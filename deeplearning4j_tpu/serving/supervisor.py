"""Supervised engine recovery: request-preserving arena rebuilds.

PR 5/6 gave the generation engine exactly one answer to a dispatch
fault: ``_break`` — fail every in-flight and queued request and refuse
new work, so a single unretried decode fault (or a transient device
error outliving its retry policy) costs the entire batch of active
streams. That is the wrong failure domain: the *device* arena is
disposable, because everything needed to reconstruct any request's
stream already lives host-side in the request/handle ledger — the
prompt, the committed tokens, the per-request numpy ``Generator``
(advanced exactly once per draw, never by the device), the sampling
config, and the deadline. The position itself is derived state:
a request holding ``ids = prompt + generated`` has fed exactly
``len(ids) - 1`` tokens (the last drawn token is pending, never yet
fed), wherever the fault landed.

So the supervisor QUARANTINES instead of breaking: on a dispatch
fault it drops the (possibly poisoned) arena wholesale — slot state,
page pool, page tables, prefix cache — rebuilds a fresh one, and
re-admits every survivor by re-priming ``ids[:-1]`` with
``pending = ids[-1]``, no draw and no rng touch. The next dispatch
then recomputes exactly the distribution the unperturbed run would
have seen, and the untouched rng draws exactly the token it would
have drawn — greedy AND sampled streams continue bit-identically
(test-pinned, slot and paged arenas, prefix cache on). Re-priming
reuses the warm prefill buckets, the arena skeleton rebuild reuses
the compiled scatter/gather shapes, so a recovery after a
full-envelope ``warmup()`` compiles nothing new.

Since ISSUE 14 the rebuild payload is the PUBLIC, versioned
``serving/request.RequestLedgerEntry`` and the quarantine travels the
same ``export_ledger`` → re-admit path the serving fleet's live
migration uses (``serving/fleet/migration.py``) — supervisor recovery
is cross-replica migration pointed back at the same engine, one code
path instead of two hand-synced copies.

Restarts are BUDGETED (``resilience.retry.RestartBudget``): a fault
burst inside the window is ridden out, but exhausting the budget means
the fault is persistent — masking it with eternal rebuilds would turn
a dead device into an invisible crash loop — so the supervisor
escalates to the engine's original terminal ``_break`` (fail-all,
health down, submits refused). Every rebuild lands on
``dl4jtpu_serving_engine_rebuilds_total{cause}`` and the engine's
``health()``.

See ARCHITECTURE.md "Serving survivability".
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from deeplearning4j_tpu.monitoring import flightrecorder
from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)
from deeplearning4j_tpu.resilience.retry import RestartBudget
from deeplearning4j_tpu.serving.health import (
    SERVING_ENGINE_ESCALATIONS, SERVING_ENGINE_REBUILDS,
    SERVING_RECOVERED_REQUESTS)

log = logging.getLogger(__name__)

__all__ = ["EngineSupervisor"]

#: cause label values (one counter child per cause, touched at bind so
#: the schema renders on an engine that never faulted)
CAUSE_DECODE = "decode_fault"
CAUSE_ADMISSION = "admission_fault"


class EngineSupervisor:
    """Recovery policy for one :class:`~.engine.GenerationEngine`.

    Pass it as ``GenerationEngine(supervisor=...)``; the engine calls
    :meth:`on_dispatch_fault` from its step-cycle failure path and the
    supervisor decides recover-vs-escalate:

    - budget has room → quarantine + rebuild the arena, re-admit every
      survivor from the host-side ledger (bit-identical continuation),
      return True (the engine keeps serving);
    - budget exhausted (or the rebuild itself fails) → return False and
      the engine falls through to its terminal ``_break`` fail-all.

    One supervisor per engine: binding resolves the metric handles to
    the engine's model label.
    """

    def __init__(self, budget: Optional[RestartBudget] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.budget = budget if budget is not None else RestartBudget()
        self._registry = registry
        self._engine = None
        self.rebuilds = 0
        self.recovered_requests = 0
        self.escalations = 0
        self.last_fault: Optional[BaseException] = None
        self.last_cause: Optional[str] = None
        self.last_rebuild_t: Optional[float] = None

    # -- engine side ---------------------------------------------------
    def _bind(self, engine, registry: Optional[MetricsRegistry]) -> None:
        if self._engine is not None and self._engine is not engine:
            raise ValueError(
                "one EngineSupervisor supervises one engine — construct "
                "a fresh supervisor per GenerationEngine")
        self._engine = engine
        r = self._registry or registry or global_registry()
        rebuilds = r.counter(
            SERVING_ENGINE_REBUILDS,
            "Arena rebuilds by the serving supervisor", ("model", "cause"))
        self._rebuild_handles = {
            c: rebuilds.labels(model=engine._label, cause=c)
            for c in (CAUSE_DECODE, CAUSE_ADMISSION)}
        # escalations are NOT rebuilds: a separate series keeps
        # sum(rebuilds_total) equal to arenas actually rebuilt
        self._escalated = r.counter(
            SERVING_ENGINE_ESCALATIONS,
            "Faults escalated to the terminal fail-all (budget "
            "exhausted or rebuild failed)", ("model",)).labels(
            model=engine._label)
        self._recovered = r.counter(
            SERVING_RECOVERED_REQUESTS,
            "In-flight requests re-admitted bit-identically after an "
            "arena rebuild", ("model",)).labels(model=engine._label)

    def on_dispatch_fault(self, engine, exc: BaseException,
                          cause: str) -> bool:
        """Called by the engine (under its step lock) when a dispatch
        cycle raised. True = recovered, keep serving; False = escalate
        to the terminal fail-all."""
        self.last_fault = exc
        self.last_cause = cause
        if not self.budget.try_acquire():
            self.escalations += 1
            self._escalated.inc()
            self._escalation_telemetry(engine, exc, "budget_exhausted")
            log.error(
                "serving supervisor: restart budget exhausted "
                "(%d rebuilds / %.0fs window) — escalating %r to "
                "fail-all", self.budget.max_restarts,
                self.budget.window_s, exc)
            return False
        try:
            survivors = engine._quarantine_rebuild()
        except Exception:  # noqa: BLE001 — a failed rebuild must escalate
            self.escalations += 1
            self._escalated.inc()
            self._escalation_telemetry(engine, exc, "rebuild_failed")
            log.exception(
                "serving supervisor: arena rebuild failed — escalating "
                "the original fault %r to fail-all", exc)
            return False
        self.rebuilds += 1
        self.recovered_requests += survivors
        self.last_rebuild_t = time.monotonic()
        self._rebuild_handles[cause].inc()
        self._recovered.inc(survivors)
        engine._emit_serving_event(
            "rebuild", cause=cause, survivors=survivors,
            budget_remaining=self.budget.remaining())
        log.warning(
            "serving supervisor: quarantined arena after %s (%r); "
            "rebuilt and re-admitted %d in-flight request(s) "
            "(%d budget restart(s) left)", cause, exc, survivors,
            self.budget.remaining())
        return True

    def _escalation_telemetry(self, engine, exc: BaseException,
                              why: str) -> None:
        """Timeline event + flight-record artifact at the moment the
        supervisor gives up — the last look at the arena before
        ``_break`` fails every handle (its own dump, fired next, is
        deduped by the per-trigger rate limit but kept as a distinct
        trigger for the unsupervised case)."""
        engine._emit_serving_event("escalate", why=why,
                                   error=repr(exc))
        flightrecorder.maybe_dump(
            "supervisor_escalation", error=exc,
            health=engine.health(),
            queue=engine.queue_snapshot(),
            traces=engine._flight_traces(),
            extra={"why": why, "supervisor": self.health()})

    # -- observability -------------------------------------------------
    def health(self) -> dict:
        return {
            "rebuilds": self.rebuilds,
            "recovered_requests": self.recovered_requests,
            "escalations": self.escalations,
            "budget_remaining": self.budget.remaining(),
            "last_cause": self.last_cause,
            "last_fault": (repr(self.last_fault)
                           if self.last_fault is not None else None),
        }
