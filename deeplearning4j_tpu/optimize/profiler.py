"""Profiling/tracing listeners.

SURVEY §5 "Tracing/profiling": the reference profiles via listener timing
(PerformanceListener ETL/iteration timing, BaseStatsListener sections) and
ND4J's OpProfiler below the repo line. The TPU-native equivalents:

- ProfilerListener: captures a JAX/XLA XPlane trace (viewable in
  TensorBoard / xprof) for a window of training iterations —
  jax.profiler.start_trace/stop_trace around the fit loop's hot section.
- TimingListener: wall-clock section timing (ETL vs step) without any
  trace overhead, mirroring PerformanceListener's lastEtlTime idea.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

import jax

from deeplearning4j_tpu.optimize.listeners import TrainingListener

log = logging.getLogger(__name__)


class ProfilerListener(TrainingListener):
    """Capture an XPlane trace for iterations [start_iteration,
    start_iteration + num_iterations). Output dir is TensorBoard-loadable.
    """

    def __init__(self, log_dir: str, start_iteration: int = 2,
                 num_iterations: int = 3):
        self.log_dir = log_dir
        self.start_iteration = start_iteration
        self.num_iterations = num_iterations
        self._active = False
        self._done = False

    def iteration_done(self, model, iteration: int, score: float):
        if self._done:
            return
        if not self._active and iteration >= self.start_iteration:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            self._stop_at = iteration + self.num_iterations
            return
        if self._active and iteration >= self._stop_at:
            self._stop()

    def on_epoch_end(self, model, epoch: int):
        # never leave a trace open across epochs
        self._stop()

    def close(self):
        """Invoked from the fit loops' finally: a fit() that raises or
        ends before _stop_at must not leak an open XPlane trace.
        Idempotent — repeated close() (or close() after the epoch
        boundary already stopped the trace) is a no-op."""
        self._stop()

    def _stop(self):
        if not self._active:
            return
        self._active = False
        self._done = True
        try:
            jax.profiler.stop_trace()
            log.info("profiler trace written to %s", self.log_dir)
        except Exception:  # noqa: BLE001 — closing a dead trace must not
            log.warning("stop_trace failed", exc_info=True)  # mask fit errors


class TimingListener(TrainingListener):
    """Wall-clock iteration timing with simple section accounting
    (ref: PerformanceListener ETL-time measurement,
    MultiLayerNetwork.java:1203-1209)."""

    def __init__(self, window: int = 50):
        self.window = window
        self.iteration_ms: List[float] = []
        self._last: Optional[float] = None

    def iteration_done(self, model, iteration: int, score: float):
        now = time.perf_counter()
        if self._last is not None:
            self.iteration_ms.append((now - self._last) * 1000.0)
            if len(self.iteration_ms) > self.window:
                self.iteration_ms.pop(0)
        self._last = now

    def summary(self) -> Dict[str, float]:
        if not self.iteration_ms:
            return {}
        arr = sorted(self.iteration_ms)
        n = len(arr)
        return {
            "mean_ms": sum(arr) / n,
            "p50_ms": arr[n // 2],
            "p95_ms": arr[min(n - 1, int(n * 0.95))],
            "iterations": n,
        }


def annotate(name: str):
    """Named trace span for host-side code (shows up in the XPlane trace):

        with annotate("etl"):
            batch = next(it)
    """
    return jax.profiler.TraceAnnotation(name)
