"""Post-training int8 weight quantization for inference (W8A16).

TPU-native post-parity serving feature (the reference's nearest hook is
the ND4J compressor row, SURVEY §2.1 — compression there serves
gradient transport; here the target is inference memory bandwidth).
Per-channel symmetric int8 weights with fp32 scales: the dequantize is
a convert+multiply that XLA fuses into the consuming matmul/conv read,
so serving reads 1 byte per weight from HBM instead of 4 (or 2 under
bf16). Memory-bound paths — token-by-token decode, large Dense/attention
projections — speed up by up to the storage ratio; compute-bound convs
keep their MXU path unchanged (weights arrive bf16/fp32 after the fused
dequant, exactly as before).

Usage:
    net = model.init()            # or a restored checkpoint
    quantize_for_inference(net)   # in place; training is then refused
    net.output(x)                 # same API, int8 weights under the hood

Persist the ORIGINAL checkpoint, not the quantized net — quantization
is an inference-time transform (re-apply after restore), mirroring how
the reference treats compression as transport encoding, not model
state.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["QuantizedTensor", "quantize_array", "quantize_params",
           "quantize_for_inference", "dequantize_tree"]


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """Symmetric per-channel int8 tensor: `q` int8, `scale` fp32 along
    `axis`. Flows through jit as a pytree; layers never see it — the
    network dequantizes at forward entry (dequantize_tree) and XLA
    fuses the convert+multiply into each consumer."""

    def __init__(self, q, scale, axis: int):
        self.q = q
        self.scale = scale
        self.axis = axis

    def tree_flatten(self):
        return (self.q, self.scale), self.axis

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def dequantize(self, dtype=jnp.float32):
        shape = [1] * self.q.ndim
        shape[self.axis] = -1
        return self.q.astype(dtype) * \
            self.scale.reshape(shape).astype(dtype)

    def __repr__(self):
        return (f"QuantizedTensor(shape={tuple(self.q.shape)}, "
                f"axis={self.axis})")


def quantize_array(w, axis: int) -> QuantizedTensor:
    """Symmetric per-channel int8: scale = max|w| / 127 along every
    non-channel axis; values round into [-127, 127] (no -128: symmetric
    range keeps dequant exactly scale-linear)."""
    w = jnp.asarray(w)
    red = tuple(i for i in range(w.ndim) if i != axis)
    amax = jnp.max(jnp.abs(w), axis=red)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    shape = [1] * w.ndim
    shape[axis] = -1
    q = jnp.clip(jnp.round(w / scale.reshape(shape)),
                 -127, 127).astype(jnp.int8)
    return QuantizedTensor(q, scale.astype(jnp.float32), axis)


def _channel_axis(arr) -> Optional[int]:
    """Quantization channel axis by this repo's weight layout
    conventions: 2-D matmul weights are [in, out] (per-output-column
    scales — Dense/LSTM/attention), 3-D conv1d kernels are [O, I, k]
    and 4-D conv2d kernels OIHW (per-output-filter scales, the
    reference's ConvolutionParamInitializer layout). 0/1-D params
    (biases, norms) stay fp."""
    if arr.ndim == 2:
        return 1
    if arr.ndim in (3, 4):
        return 0
    return None


def quantize_params(params, min_size: int = 4096):
    """Quantize every floating weight of >=2 dims and >= `min_size`
    elements in a (nested) param dict; leaves everything else alone.
    Small tensors stay fp — their HBM traffic is negligible and tiny
    channels quantize poorly."""
    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        arr = node
        if (hasattr(arr, "dtype")
                and jnp.issubdtype(arr.dtype, jnp.floating)
                and arr.ndim >= 2
                and int(np.prod(arr.shape)) >= min_size):
            axis = _channel_axis(arr)
            if axis is not None:
                return quantize_array(arr, axis)
        return arr
    return walk(params)


def dequantize_tree(params, dtype=jnp.float32):
    """Materialize QuantizedTensor leaves as `dtype` arrays (a no-op
    tree_map when none exist). Called at network forward entry; the
    converts fuse into consumers under jit."""
    return jax.tree_util.tree_map(
        lambda l: l.dequantize(dtype)
        if isinstance(l, QuantizedTensor) else l,
        params, is_leaf=lambda l: isinstance(l, QuantizedTensor))


def quantize_for_inference(net, min_size: int = 4096):
    """Quantize `net`'s weights to int8 IN PLACE for serving and return
    it. Training on a quantized net is refused (there is no int8
    gradient path — re-quantize after further fp training instead);
    output / rnn_time_step / sample_stream / evaluate work unchanged."""
    net.params = quantize_params(net.params, min_size=min_size)
    net._quantized = True
    net._jit_cache.clear()      # param treedef changed: force retrace
    return net
