"""Second-order / line-search optimizers.

Equivalent of deeplearning4j-nn optimize/solvers/ (SURVEY §2.2 "Solvers"):
ConjugateGradient.java, LBFGS.java, LineGradientDescent.java driven by
BackTrackLineSearch.java. (StochasticGradientDescent is the jitted train
step in the networks themselves.)

These are full-batch algorithms over the flattened parameter vector —
the classical use is small-data refinement (the reference defaults
them for pretrain layers). Loss and gradient come from one jitted
value_and_grad over the network's loss; the algorithm outer loop stays in
Python (data-dependent convergence checks don't belong inside jit).
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)


def _flatten(params) -> Tuple[jnp.ndarray, Callable]:
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = [l.shape for l in leaves]
    sizes = [int(np.prod(s)) for s in shapes]

    def unflatten(vec):
        outs, off = [], 0
        for s, n in zip(shapes, sizes):
            outs.append(vec[off:off + n].reshape(s))
            off += n
        return jax.tree_util.tree_unflatten(treedef, outs)

    vec = jnp.concatenate([l.reshape(-1) for l in leaves]) if leaves \
        else jnp.zeros((0,))
    return vec, unflatten


class BackTrackLineSearch:
    """Armijo backtracking (ref: BackTrackLineSearch.java — optimize()
    with c1 slope condition, step halving)."""

    def __init__(self, c1: float = 1e-4, shrink: float = 0.5,
                 max_steps: int = 20, initial_step: float = 1.0):
        self.c1 = c1
        self.shrink = shrink
        self.max_steps = max_steps
        self.initial_step = initial_step

    def search(self, f, x, fx, g, direction):
        slope = float(jnp.dot(g, direction))
        if slope >= 0:
            direction = -g  # not a descent direction: fall back to steepest
            slope = float(jnp.dot(g, direction))

        def armijo(step, f_new):
            return np.isfinite(f_new) and \
                f_new <= float(fx) + self.c1 * step * slope

        step = self.initial_step
        for k in range(self.max_steps):
            f_new = float(f(x + step * direction))
            if armijo(step, f_new):
                if k == 0:
                    # accepted at first try: expand while it keeps helping —
                    # prevents a poorly-scaled direction (e.g. LBFGS gamma
                    # poisoned by one tiny step) from crawling forever
                    best_step, best_f = step, f_new
                    for _ in range(self.max_steps):
                        trial = best_step / self.shrink
                        f_trial = float(f(x + trial * direction))
                        if armijo(trial, f_trial) and f_trial < best_f:
                            best_step, best_f = trial, f_trial
                        else:
                            break
                    return x + best_step * direction, best_f, best_step
                return x + step * direction, f_new, step
            step *= self.shrink
        return x, float(fx), 0.0  # no progress


class BaseSecondOrderOptimizer:
    """Shared outer loop (ref: BaseOptimizer.java optimize())."""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-5,
                 line_search: Optional[BackTrackLineSearch] = None):
        self.max_iterations = max_iterations
        self.tolerance = tolerance
        self.line_search = line_search or BackTrackLineSearch()
        self.score_history: List[float] = []

    # subclass hook
    def _direction(self, g, state):
        raise NotImplementedError

    def _update_memory(self, state, x_old, x_new, g_old, g_new):
        return state

    def optimize_fn(self, value_and_grad, x0):
        """Minimize a flat function. Returns (x, final_value)."""
        x = x0
        fx, g = value_and_grad(x)
        state: dict = {}
        self.score_history = [float(fx)]
        f_only = lambda v: value_and_grad(v)[0]  # noqa: E731
        just_restarted = False
        for it in range(self.max_iterations):
            d = self._direction(g, state)
            x_new, f_new, step = self.line_search.search(f_only, x, fx, g, d)
            if step == 0.0:
                if not just_restarted:  # stale memory can poison directions
                    state = {}
                    just_restarted = True
                    continue
                log.info("line search made no progress at iter %d", it)
                break
            just_restarted = False
            _, g_new = value_and_grad(x_new)
            state = self._update_memory(state, x, x_new, g, g_new)
            improved = float(fx) - f_new
            x, fx, g = x_new, f_new, g_new
            self.score_history.append(float(fx))
            if abs(improved) < self.tolerance:
                break
        return x, float(fx)

    def optimize(self, net, dataset) -> float:
        """Full-batch optimize a network's loss in place (the reference's
        Solver.optimize with this ConvexOptimizer)."""
        x = jnp.asarray(dataset.features)
        y = jnp.asarray(dataset.labels)
        fmask = None if dataset.features_mask is None \
            else jnp.asarray(dataset.features_mask)
        lmask = None if dataset.labels_mask is None \
            else jnp.asarray(dataset.labels_mask)
        vec0, unflatten = _flatten(net.params)

        @jax.jit
        def vg(vec):
            loss, _ = net._loss(unflatten(vec), net.state, x, y, None,
                                fmask, lmask, train=False)
            return loss

        value_and_grad = jax.jit(jax.value_and_grad(vg))
        vec, final = self.optimize_fn(lambda v: value_and_grad(v), vec0)
        net.params = unflatten(vec)
        net.score_value = final
        return final


class LineGradientDescent(BaseSecondOrderOptimizer):
    """Steepest descent + line search (ref: LineGradientDescent.java)."""

    def _direction(self, g, state):
        return -g


class ConjugateGradient(BaseSecondOrderOptimizer):
    """Nonlinear CG, Polak-Ribière with restart
    (ref: ConjugateGradient.java)."""

    def _direction(self, g, state):
        if "g_prev" not in state:
            d = -g
        else:
            g_prev, d_prev = state["g_prev"], state["d_prev"]
            beta = float(jnp.dot(g, g - g_prev) /
                         jnp.maximum(jnp.dot(g_prev, g_prev), 1e-20))
            beta = max(0.0, beta)  # PR+ restart
            d = -g + beta * d_prev
        state["_d_used"] = d  # cached for _update_memory
        return d

    def _update_memory(self, state, x_old, x_new, g_old, g_new):
        return {"g_prev": g_old, "d_prev": state["_d_used"]}


class LBFGS(BaseSecondOrderOptimizer):
    """Limited-memory BFGS, two-loop recursion (ref: LBFGS.java, default
    memory m=10)."""

    def __init__(self, memory: int = 10, **kwargs):
        super().__init__(**kwargs)
        self.memory = memory

    def _direction(self, g, state):
        s_list = state.get("s", [])
        y_list = state.get("y", [])
        q = g
        alphas = []
        for s, yv in zip(reversed(s_list), reversed(y_list)):
            rho = 1.0 / float(jnp.maximum(jnp.dot(yv, s), 1e-20))
            a = rho * float(jnp.dot(s, q))
            alphas.append((a, rho, s, yv))
            q = q - a * yv
        if y_list:
            y_last, s_last = y_list[-1], s_list[-1]
            gamma = float(jnp.dot(s_last, y_last) /
                          jnp.maximum(jnp.dot(y_last, y_last), 1e-20))
        else:
            gamma = 1.0
        r = gamma * q
        for a, rho, s, yv in reversed(alphas):
            b = rho * float(jnp.dot(yv, r))
            r = r + (a - b) * s
        return -r

    def _update_memory(self, state, x_old, x_new, g_old, g_new):
        s = x_new - x_old
        yv = g_new - g_old
        if float(jnp.dot(s, yv)) > 1e-10:  # curvature condition
            s_list = state.get("s", []) + [s]
            y_list = state.get("y", []) + [yv]
            state = {"s": s_list[-self.memory:],
                     "y": y_list[-self.memory:]}
        return state
