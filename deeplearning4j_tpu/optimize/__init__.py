"""Optimization-adjacent utilities: solvers, listeners, profiler, and
post-training quantization (ref layer: optimize/ Solver + listeners in
deeplearning4j-nn; quantization is the TPU-serving post-parity add)."""

from deeplearning4j_tpu.optimize.quantization import (  # noqa: F401
    QuantizedTensor, quantize_for_inference,
)
