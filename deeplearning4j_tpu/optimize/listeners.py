"""Training listeners.

TPU-native equivalent of optimize/api/IterationListener + TrainingListener and
the listener zoo in optimize/listeners/* (ScoreIterationListener,
PerformanceListener, EvaluativeListener, CollectScoresIterationListener,
TimeIterationListener, ComposableIterationListener).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

log = logging.getLogger(__name__)


class TrainingListener:
    """Observer of the training loop (ref: optimize/api/TrainingListener.java).

    `score` may arrive as a RAW device scalar, not a Python float: the fit
    loops never sync on the loss (see nn/score.py). `float(score)` works
    either way — call it only at your reporting cadence, because on a
    device value it is a host sync."""

    def iteration_done(self, model, iteration: int, score: float):
        pass

    def on_epoch_start(self, model, epoch: int):
        pass

    def on_epoch_end(self, model, epoch: int):
        pass

    def on_forward_pass(self, model, activations):
        pass

    def on_backward_pass(self, model):
        pass

    def close(self):
        """Release held resources (open traces, files). Invoked from the
        fit loops' finally — i.e. also when fit() raises — and must be
        safe to call repeatedly."""
        pass


def close_listeners(listeners) -> None:
    """Best-effort close() of every listener — the fit loops call this
    from their finally so a fit that raises (or ends inside a profiler
    window) never leaks listener resources like an open XPlane trace."""
    for lst in listeners:
        close = getattr(lst, "close", None)
        if callable(close):
            try:
                close()
            except Exception:  # noqa: BLE001 — cleanup best-effort
                log.warning("listener close() failed", exc_info=True)


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (ref: ScoreIterationListener.java)."""

    def __init__(self, print_iterations: int = 10, printer: Callable = None):
        self.print_iterations = max(1, print_iterations)
        self.printer = printer or (lambda s: log.info(s))

    def iteration_done(self, model, iteration, score):
        if iteration % self.print_iterations == 0:
            self.printer(f"Score at iteration {iteration} is {float(score)}")


class PerformanceListener(TrainingListener):
    """Throughput tracking: samples/sec, batches/sec
    (ref: PerformanceListener.java)."""

    def __init__(self, frequency: int = 1, report: Callable = None):
        self.frequency = max(1, frequency)
        self.report = report or (lambda s: log.info(s))
        self._last_time = None
        self._last_iter = None
        self._samples = 0
        self.samples_per_sec = 0.0
        self.batches_per_sec = 0.0

    def record_batch(self, num_examples: int):
        self._samples += num_examples

    def iteration_done(self, model, iteration, score):
        now = time.perf_counter()
        if self._last_time is not None and iteration % self.frequency == 0:
            dt = now - self._last_time
            iters = iteration - (self._last_iter or 0)
            if dt > 0 and iters > 0:
                self.batches_per_sec = iters / dt
                self.samples_per_sec = self._samples / dt
                self.report(
                    f"iteration {iteration}: {self.samples_per_sec:.1f} samples/sec, "
                    f"{self.batches_per_sec:.2f} batches/sec, score={score:.5f}")
            self._last_time = now
            self._last_iter = iteration
            self._samples = 0
        elif self._last_time is None:
            self._last_time = now
            self._last_iter = iteration
            self._samples = 0


class CollectScoresIterationListener(TrainingListener):
    """Collect (iteration, score) pairs (ref: CollectScoresIterationListener.java)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))


class TimeIterationListener(TrainingListener):
    """Estimate remaining time (ref: TimeIterationListener.java).

    The clock starts LAZILY on the first iteration_done, not at
    construction: any setup time between building the listener and
    calling fit() (data download, jit compile of unrelated models) must
    not inflate the per-iteration estimate."""

    def __init__(self, total_iterations: int):
        self.total = total_iterations
        self.start: Optional[float] = None
        self._first_iteration: Optional[int] = None

    def iteration_done(self, model, iteration, score):
        now = time.perf_counter()
        if self.start is None:
            self.start = now
            self._first_iteration = iteration
            return
        done = iteration - self._first_iteration
        if done > 0:
            remaining = (now - self.start) / done * (self.total - iteration)
            log.info("Remaining time estimate: %.1fs", remaining)


class EvaluativeListener(TrainingListener):
    """Periodically evaluate on a held-out iterator (ref: EvaluativeListener.java)."""

    def __init__(self, iterator, frequency: int = 1, on_epoch: bool = False):
        self.iterator = iterator
        self.frequency = max(1, frequency)
        self.on_epoch = on_epoch
        self.evaluations: List = []

    def _eval(self, model):
        e = model.evaluate(self.iterator)
        self.evaluations.append(e)
        log.info("\n%s", e.stats())

    def iteration_done(self, model, iteration, score):
        if not self.on_epoch and iteration > 0 and iteration % self.frequency == 0:
            self._eval(model)

    def on_epoch_end(self, model, epoch):
        if self.on_epoch and (epoch + 1) % self.frequency == 0:
            self._eval(model)


class ComposableIterationListener(TrainingListener):
    """Fan-out to child listeners (ref: ComposableIterationListener.java)."""

    def __init__(self, *listeners: TrainingListener):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration, score):
        for l in self.listeners:
            l.iteration_done(model, iteration, score)


class ParamAndGradientIterationListener(TrainingListener):
    """Per-iteration parameter/update statistics to the log or a
    tab-separated file (ref: ParamAndGradientIterationListener.java —
    the reference logs mean-magnitude of params and gradients; gradients
    are internal to the jitted step here, so the per-iteration param
    DELTA, i.e. the applied update, fills that column)."""

    def __init__(self, frequency: int = 1, output_file: str = None,
                 log_stats: bool = True):
        self.frequency = max(1, frequency)
        self.output_file = output_file
        self.log_stats = log_stats
        self._prev = None
        if output_file:
            with open(output_file, "w") as f:
                f.write("iteration\tscore\tparam_mean_mag\tupdate_mean_mag\n")

    @staticmethod
    def _leaves(tree, path=""):
        import numpy as np
        if isinstance(tree, dict):
            for k in sorted(tree):
                yield from ParamAndGradientIterationListener._leaves(
                    tree[k], path + "/" + str(k))
        elif tree is not None:
            yield path, np.asarray(tree)

    @classmethod
    def _mean_mag(cls, leaves):
        import numpy as np
        total = sum(float(np.abs(a).sum()) for _, a in leaves)
        count = sum(a.size for _, a in leaves)
        return total / max(1, count)

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency:
            return  # keep _prev: the update column spans the report interval
        leaves = list(self._leaves(model.params))
        pm = self._mean_mag(leaves)
        um = float("nan")
        if self._prev is not None and len(self._prev) == len(leaves):
            um = self._mean_mag([(p, a - b)
                                 for (p, a), (_, b)
                                 in zip(leaves, self._prev)])
        # safe to keep without copying: jax arrays are immutable and the
        # train step REPLACES model.params each iteration, so these
        # snapshots can't be mutated underneath us
        self._prev = leaves
        if self.log_stats:
            log.info("iter %d: score %.5f, |param| %.3e, |update| %.3e",
                     iteration, score, pm, um)
        if self.output_file:
            with open(self.output_file, "a") as f:
                f.write(f"{iteration}\t{score:.6f}\t{pm:.6e}\t{um:.6e}\n")


class SleepyTrainingListener(TrainingListener):
    """Inject sleeps into the training loop for debugging/throttling
    (ref: SleepyTrainingListener.java timerIteration/timerEpoch)."""

    def __init__(self, sleep_iteration_ms: float = 0.0,
                 sleep_epoch_ms: float = 0.0):
        self.sleep_iteration_ms = sleep_iteration_ms
        self.sleep_epoch_ms = sleep_epoch_ms

    def iteration_done(self, model, iteration, score):
        if self.sleep_iteration_ms > 0:
            time.sleep(self.sleep_iteration_ms / 1000.0)

    def on_epoch_end(self, model, epoch):
        if self.sleep_epoch_ms > 0:
            time.sleep(self.sleep_epoch_ms / 1000.0)
