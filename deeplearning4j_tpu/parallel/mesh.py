"""Device mesh construction.

TPU-native replacement for the reference's device discovery/affinity layer
(ParallelWrapper.java:124-143 attachThreadToDevice; Nd4j AffinityManager):
on TPU, devices form a logical mesh (`jax.sharding.Mesh`) with named axes and
XLA handles placement — no thread pinning, no per-device model replicas.

Axis convention (scaling-book style): "data" for batch/data parallelism,
"model" for tensor-model parallelism. Collectives ride ICI within a slice;
multi-host meshes extend over DCN via jax.distributed (see distributed.py).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = ("data", "model"),
              devices=None) -> Mesh:
    """Build a mesh over the given (or all) devices.

    shape=None → all devices on the "data" axis (pure data parallelism,
    the ParallelWrapper-equivalent default).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = (n,)
        axis_names = (axis_names[0],)
    shape = tuple(int(s) for s in shape)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(axis_names[:len(shape)]))


def default_mesh(num_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return make_mesh(devices=devices)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-dim sharding for inputs: [B, ...] split over the data axis."""
    return NamedSharding(mesh, P("data"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
