"""CLI entry point for mesh-parallel training.

Equivalent of deeplearning4j-scaleout main/ParallelWrapperMain.java:143
(JCommander args → ParallelWrapper training over a saved model + data).

Usage:
    python -m deeplearning4j_tpu.parallel.main \
        --model model.zip --data train.csv --label-index 4 \
        --num-classes 3 --batch-size 32 --epochs 5 \
        --training-mode allreduce --output trained.zip
"""

from __future__ import annotations

import argparse
import logging
import sys

log = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="deeplearning4j_tpu.parallel.main",
        description="Train a saved model data-parallel over the device "
                    "mesh (ParallelWrapperMain equivalent)")
    p.add_argument("--model", required=True,
                   help="model zip (ModelSerializer format)")
    p.add_argument("--data", required=True, help="training CSV")
    p.add_argument("--label-index", type=int, required=True)
    p.add_argument("--num-classes", type=int)
    p.add_argument("--regression", action="store_true")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--training-mode", default="allreduce",
                   choices=["allreduce", "averaging"])
    p.add_argument("--averaging-frequency", type=int, default=5)
    p.add_argument("--prefetch-buffer", type=int, default=2,
                   help="async prefetch depth (0 disables)")
    p.add_argument("--output", help="where to save the trained model zip")
    p.add_argument("--ui-port", type=int,
                   help="serve the training UI on this port")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from deeplearning4j_tpu.datasets.records import (
        CSVRecordReader, RecordReaderDataSetIterator)
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from deeplearning4j_tpu.util import model_serializer

    net = model_serializer.restore_model(args.model)
    it = RecordReaderDataSetIterator(
        CSVRecordReader(args.data), batch_size=args.batch_size,
        label_index=args.label_index, num_classes=args.num_classes,
        regression=args.regression)

    if args.ui_port is not None:
        from deeplearning4j_tpu.ui import (InMemoryStatsStorage,
                                           StatsListener, UIServer)
        storage = InMemoryStatsStorage()
        UIServer.get_instance(port=args.ui_port).attach(storage)
        net.add_listener(StatsListener(storage))

    pw = ParallelWrapper(net, training_mode=args.training_mode,
                         averaging_frequency=args.averaging_frequency,
                         prefetch_buffer=args.prefetch_buffer)
    pw.fit(it, epochs=args.epochs)
    log.info("final score: %s", net.score_value)
    if args.output:
        model_serializer.write_model(net, args.output)
        log.info("saved to %s", args.output)
    return 0


if __name__ == "__main__":
    sys.exit(main())
