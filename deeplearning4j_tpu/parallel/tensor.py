"""Tensor (model) parallelism: Megatron-style sharded transformer blocks.

Beyond the reference's data-parallel-only scale-out (SURVEY §2.5 — all
four reference strategies shard the BATCH), TPU meshes make intra-layer
model sharding first-class: this module shards attention heads and FFN
hidden units over a "model" mesh axis with the canonical Megatron
layout —

- attention: Wq/Wk/Wv column-sharded (each device owns H/n heads, runs
  its heads' attention locally), Wo row-sharded, one psum to rebuild the
  residual stream;
- MLP: W1 column-sharded (hidden/n per device), W2 row-sharded, one psum.

Two collectives per block, both riding ICI. Composes with the "data"
axis (dp x tp meshes) and with sequence parallelism (parallel/sequence)
on the same mesh. Exactness vs the single-device math is tested on the
virtual 8-device mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.util.jax_compat import shard_map

from deeplearning4j_tpu.parallel.sequence import blockwise_attention


def _gqa_kv_sharded(n_kv_heads, tp) -> bool:
    """Can the KV heads themselves be column-sharded over tp devices?
    Yes when each device owns n_kv_heads/tp whole KV heads; otherwise
    (tp > n_kv_heads) KV params stay replicated and each device slices
    its group's head locally (GQA KV params are small by design)."""
    return n_kv_heads % tp == 0


def _validate_gqa(n_heads, n_kv_heads, tp) -> None:
    if n_heads % n_kv_heads:
        raise ValueError(f"n_heads {n_heads} not divisible by n_kv_heads "
                         f"{n_kv_heads}")
    if not _gqa_kv_sharded(n_kv_heads, tp) and tp % n_kv_heads:
        raise ValueError(
            f"tensor-parallel GQA needs n_kv_heads ({n_kv_heads}) "
            f"divisible by tp ({tp}) or tp divisible by n_kv_heads "
            "(head-group replication would straddle devices otherwise)")


def shard_mha_params(params: Dict, mesh: Mesh, axis: str = "model",
                     n_kv_heads=None, n_heads=None):
    """Place MultiHeadSelfAttention-style params {wq,wk,wv,wo} (or the
    SelfAttentionLayer spelling {Wq,...,bq,...}) with the Megatron
    layout: q/k/v column-sharded, o row-sharded.

    Grouped-query attention (Wk/Wv narrower than Wq): pass `n_kv_heads`
    (+ `n_heads` for validation). KV params column-shard when each
    device owns whole KV heads (n_kv_heads % tp == 0); with tp >
    n_kv_heads the KV heads are REPLICATED and tp_mha slices each
    device's group head locally — q/o sharding is unchanged either way."""
    tp = mesh.shape[axis]
    wq = next((v for k, v in params.items() if k.lower() == "wq"), None)
    wk = next((v for k, v in params.items() if k.lower() == "wk"), None)
    gqa = (wq is not None and wk is not None and wq.shape != wk.shape)
    if gqa:
        if n_kv_heads is None:
            raise ValueError(
                "grouped-query attention params (Wk width "
                f"{wk.shape[1]} != Wq width {wq.shape[1]}): pass "
                "n_kv_heads to shard_mha_params")
        if n_heads is None:
            # infer from the widths: d = Wk_width / n_kv_heads
            d, rem = divmod(wk.shape[1], n_kv_heads)
            if rem or wq.shape[1] % d:
                raise ValueError(
                    f"Wk width {wk.shape[1]} not divisible by n_kv_heads "
                    f"{n_kv_heads} (or Wq width {wq.shape[1]} not a "
                    "multiple of the head dim)")
            n_heads = wq.shape[1] // d
        _validate_gqa(n_heads, n_kv_heads, tp)
    kv_col = (not gqa) or _gqa_kv_sharded(n_kv_heads, tp)
    col = NamedSharding(mesh, P(None, axis))
    row = NamedSharding(mesh, P(axis, None))
    vec = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())
    out = {}
    for k, v in params.items():
        lk = k.lower()
        if lk == "wq":
            out[k] = jax.device_put(v, col)
        elif lk in ("wk", "wv"):
            out[k] = jax.device_put(v, col if kv_col else rep)
        elif lk == "wo":
            out[k] = jax.device_put(v, row)
        elif lk == "bq":
            out[k] = jax.device_put(v, vec)
        elif lk in ("bk", "bv"):
            out[k] = jax.device_put(v, vec if kv_col else rep)
        else:  # bo and anything else replicated
            out[k] = jax.device_put(v, rep)
    return out


def tp_mha(params: Dict, x, mesh: Mesh, n_heads: int,
           axis: str = "model", causal: bool = True,
           block_size: int = 512, batch_axis: str = None,
           n_kv_heads: int = None):
    """Tensor-parallel multi-head self-attention.

    x: [B,T,E]; params as in shard_mha_params (keys wq/wk/wv/wo +
    optional biases, any capitalization; missing biases are treated as
    zero). Each device computes its H/n heads with the blockwise kernel;
    the row-sharded output projection psums (over the model axis only)
    back to the full residual. `batch_axis` additionally shards B over a
    data axis of the same mesh (dp x tp composition). Output == the
    unsharded math.

    Grouped-query attention: pass `n_kv_heads` < n_heads (Wk/Wv of width
    n_kv_heads*head_dim). With n_kv_heads % tp == 0 the KV heads are
    column-sharded like Q; with tp > n_kv_heads each device holds the
    replicated KV params and slices the ONE head its query group reads
    (head-group replication). Q-head blocks stay aligned with their KV
    group either way because both shards are contiguous."""
    n = mesh.shape[axis]
    if n_heads % n:
        raise ValueError(f"n_heads {n_heads} not divisible by mesh axis "
                         f"'{axis}' size {n}")
    gqa = n_kv_heads is not None and n_kv_heads != n_heads
    if gqa:
        _validate_gqa(n_heads, n_kv_heads, n)
    kv_col = (not gqa) or _gqa_kv_sharded(n_kv_heads, n)
    E = x.shape[-1]
    d = E // n_heads
    kv_width = (n_kv_heads if gqa else n_heads) * d
    keys = {k.lower(): k for k in params}

    def get(name, width):
        if name in keys:
            return params[keys[name]]
        return jnp.zeros((width,), x.dtype)  # absent bias = zero

    xspec = P(batch_axis, None, None) if batch_axis else P()
    col, row, colb, rep = P(None, axis), P(axis, None), P(axis), P()
    kvspec = col if kv_col else rep
    kvbspec = colb if kv_col else rep

    @partial(shard_map, mesh=mesh,
             in_specs=(xspec, col, kvspec, kvspec, row, colb, kvbspec,
                       kvbspec, rep),
             out_specs=xspec, check_vma=False)
    def fwd(x, wq, wk, wv, wo, bq, bk, bv, bo):
        B, T, _ = x.shape
        h_local = n_heads // n

        def heads(y):
            return y.reshape(B, T, -1, d).transpose(0, 2, 1, 3)

        q = heads(x @ wq + bq)                  # [B, h_local, T, d]
        k = heads(x @ wk + bk)                  # [B, kv_local, T, d]
        v = heads(x @ wv + bv)
        if gqa:
            if kv_col:
                # device owns n_kv_heads/n whole KV heads; its q heads
                # [i*h_local, (i+1)*h_local) group onto exactly those
                reps = n_heads // n_kv_heads
            else:
                # replicated KV: this device's whole q block reads ONE
                # head — slice it by model-axis position
                group = jax.lax.axis_index(axis) // (n // n_kv_heads)
                k = jax.lax.dynamic_slice_in_dim(k, group, 1, axis=1)
                v = jax.lax.dynamic_slice_in_dim(v, group, 1, axis=1)
                reps = h_local
            k = jnp.repeat(k, reps, axis=1)
            v = jnp.repeat(v, reps, axis=1)
        o = blockwise_attention(q, k, v, causal=causal,
                                block_size=block_size)
        o = o.transpose(0, 2, 1, 3).reshape(B, T, E // n)
        out = jax.lax.psum(o @ wo, axis)  # row-parallel projection
        return out + bo

    return fwd(x, params[keys["wq"]], params[keys["wk"]],
               params[keys["wv"]], params[keys["wo"]],
               get("bq", E), get("bk", kv_width), get("bv", kv_width),
               get("bo", E))


def tp_mlp(params: Dict, x, mesh: Mesh, axis: str = "model",
           activation=jax.nn.gelu, batch_axis: str = None):
    """Tensor-parallel position-wise MLP: W1 [E,F] column-sharded,
    W2 [F,E] row-sharded, biases b1 sharded / b2 replicated. One psum
    (over the model axis only — composes with `batch_axis` dp)."""
    xspec = P(batch_axis, None, None) if batch_axis else P()

    @partial(shard_map, mesh=mesh,
             in_specs=(xspec, P(None, axis), P(axis), P(axis, None), P()),
             out_specs=xspec, check_vma=False)
    def fwd(x, w1, b1, w2, b2):
        h = activation(x @ w1 + b1)
        return jax.lax.psum(h @ w2, axis) + b2

    return fwd(x, params["W1"], params["b1"], params["W2"], params["b2"])


def make_tp_mesh(n_data: int, n_model: int, devices=None) -> Mesh:
    """2-D dp x tp mesh ("data", "model") — the composed layout the
    dryrun exercises. Thin wrapper over parallel.mesh.make_mesh (which
    validates the device count)."""
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    devices = devices if devices is not None \
        else jax.devices()[:n_data * n_model]
    return make_mesh(shape=(n_data, n_model),
                     axis_names=("data", "model"), devices=devices)
