"""Tensor (model) parallelism: Megatron-style sharded transformer blocks.

Beyond the reference's data-parallel-only scale-out (SURVEY §2.5 — all
four reference strategies shard the BATCH), TPU meshes make intra-layer
model sharding first-class: this module shards attention heads and FFN
hidden units over a "model" mesh axis with the canonical Megatron
layout —

- attention: Wq/Wk/Wv column-sharded (each device owns H/n heads, runs
  its heads' attention locally), Wo row-sharded, one psum to rebuild the
  residual stream;
- MLP: W1 column-sharded (hidden/n per device), W2 row-sharded, one psum.

Two collectives per block, both riding ICI. Composes with the "data"
axis (dp x tp meshes) and with sequence parallelism (parallel/sequence)
on the same mesh. Exactness vs the single-device math is tested on the
virtual 8-device mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from deeplearning4j_tpu.parallel.sequence import blockwise_attention


def shard_mha_params(params: Dict, mesh: Mesh, axis: str = "model"):
    """Place MultiHeadSelfAttention-style params {wq,wk,wv,wo} (or the
    SelfAttentionLayer spelling {Wq,...,bq,...}) with the Megatron
    layout: q/k/v column-sharded, o row-sharded."""
    wq = next((v for k, v in params.items() if k.lower() == "wq"), None)
    wk = next((v for k, v in params.items() if k.lower() == "wk"), None)
    if wq is not None and wk is not None and wq.shape != wk.shape:
        raise ValueError(
            "grouped-query attention params (n_kv_heads < n_heads: Wk/Wv "
            f"width {wk.shape[1]} != {wq.shape[1]}) are not supported by "
            "the Megatron head sharding — use n_kv_heads=None for tensor "
            "parallelism")
    col = NamedSharding(mesh, P(None, axis))
    row = NamedSharding(mesh, P(axis, None))
    vec = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())
    out = {}
    for k, v in params.items():
        lk = k.lower()
        if lk in ("wq", "wk", "wv"):
            out[k] = jax.device_put(v, col)
        elif lk == "wo":
            out[k] = jax.device_put(v, row)
        elif lk in ("bq", "bk", "bv"):
            out[k] = jax.device_put(v, vec)
        else:  # bo and anything else replicated
            out[k] = jax.device_put(v, rep)
    return out


def tp_mha(params: Dict, x, mesh: Mesh, n_heads: int,
           axis: str = "model", causal: bool = True,
           block_size: int = 512, batch_axis: str = None):
    """Tensor-parallel multi-head self-attention.

    x: [B,T,E]; params as in shard_mha_params (keys wq/wk/wv/wo +
    optional biases, any capitalization; missing biases are treated as
    zero). Each device computes its H/n heads with the blockwise kernel;
    the row-sharded output projection psums (over the model axis only)
    back to the full residual. `batch_axis` additionally shards B over a
    data axis of the same mesh (dp x tp composition). Output == the
    unsharded math.
    """
    n = mesh.shape[axis]
    if n_heads % n:
        raise ValueError(f"n_heads {n_heads} not divisible by mesh axis "
                         f"'{axis}' size {n}")
    E = x.shape[-1]
    keys = {k.lower(): k for k in params}

    def get(name, width):
        if name in keys:
            return params[keys[name]]
        return jnp.zeros((width,), x.dtype)  # absent bias = zero

    xspec = P(batch_axis, None, None) if batch_axis else P()
    col, row, colb, rep = P(None, axis), P(axis, None), P(axis), P()

    @partial(shard_map, mesh=mesh,
             in_specs=(xspec, col, col, col, row, colb, colb, colb, rep),
             out_specs=xspec, check_vma=False)
    def fwd(x, wq, wk, wv, wo, bq, bk, bv, bo):
        B, T, _ = x.shape
        h_local = n_heads // n
        d = E // n_heads

        def proj(w, b):
            y = x @ w + b  # [B,T,E/n]
            return y.reshape(B, T, h_local, d).transpose(0, 2, 1, 3)

        q, k, v = proj(wq, bq), proj(wk, bk), proj(wv, bv)
        o = blockwise_attention(q, k, v, causal=causal,
                                block_size=block_size)
        o = o.transpose(0, 2, 1, 3).reshape(B, T, E // n)
        out = jax.lax.psum(o @ wo, axis)  # row-parallel projection
        return out + bo

    return fwd(x, params[keys["wq"]], params[keys["wk"]],
               params[keys["wv"]], params[keys["wo"]],
               get("bq", E), get("bk", E), get("bv", E), get("bo", E))


def tp_mlp(params: Dict, x, mesh: Mesh, axis: str = "model",
           activation=jax.nn.gelu, batch_axis: str = None):
    """Tensor-parallel position-wise MLP: W1 [E,F] column-sharded,
    W2 [F,E] row-sharded, biases b1 sharded / b2 replicated. One psum
    (over the model axis only — composes with `batch_axis` dp)."""
    xspec = P(batch_axis, None, None) if batch_axis else P()

    @partial(shard_map, mesh=mesh,
             in_specs=(xspec, P(None, axis), P(axis), P(axis, None), P()),
             out_specs=xspec, check_vma=False)
    def fwd(x, w1, b1, w2, b2):
        h = activation(x @ w1 + b1)
        return jax.lax.psum(h @ w2, axis) + b2

    return fwd(x, params["W1"], params["b1"], params["W2"], params["b2"])


def make_tp_mesh(n_data: int, n_model: int, devices=None) -> Mesh:
    """2-D dp x tp mesh ("data", "model") — the composed layout the
    dryrun exercises. Thin wrapper over parallel.mesh.make_mesh (which
    validates the device count)."""
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    devices = devices if devices is not None \
        else jax.devices()[:n_data * n_model]
    return make_mesh(shape=(n_data, n_model),
                     axis_names=("data", "model"), devices=devices)
