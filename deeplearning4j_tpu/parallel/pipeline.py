"""Pipeline (stage) parallelism: GPipe-style microbatched execution.

Completes the parallelism suite (data parallel — parallel/wrapper;
sequence parallel — parallel/sequence; tensor parallel — parallel/tensor)
with the fourth axis: each device of a "pipe" mesh axis owns ONE STAGE of
the network; microbatches stream through the stages, activations hop to
the next stage over ICI with `ppermute`. The schedule is the classic
GPipe fill-drain loop: with S stages and M microbatches, the loop runs
S+M-1 ticks, each device computing its stage on the microbatch currently
resident (or idling in the bubble); bubble fraction (S-1)/(S+M-1)
shrinks as M grows.

All stages must share one apply signature (params, x) -> y with equal
activation shapes (classic homogeneous-block pipelining, the transformer
case). Exactness vs sequentially composing the stages is tested on the
virtual mesh; gradients flow through the ppermutes so the same program
trains under jax.grad.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def shard_stage_params(stage_params: list, mesh: Mesh, axis: str = "pipe"):
    """Stack per-stage param pytrees along a new leading axis and shard it
    over the pipe axis (device s holds stage s's params)."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)
    sh = lambda a: NamedSharding(  # noqa: E731
        mesh, P(*([axis] + [None] * (a.ndim - 1))))
    return jax.tree.map(lambda a: jax.device_put(a, sh(a)), stacked)


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh: Mesh,
                   axis: str = "pipe", n_microbatches: int = None):
    """Run `stage_fn(params_s, h)` for stages s=0..S-1 over the pipe axis.

    stacked_params: pytree with leading stage axis (shard_stage_params).
    x: [B, ...] global batch; B must divide by n_microbatches (default =
    number of stages). Returns the final stage's output for the full
    batch. Differentiable (fori_loop-free: a lax.scan drives the
    schedule, ppermute moves activations stage->stage).
    """
    S = mesh.shape[axis]
    n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_stages != S:
        raise ValueError(
            f"{n_stages} stacked stages but the '{axis}' mesh axis has "
            f"{S} devices — one stage per device (a larger multiple "
            "would silently drop stages)")
    M = n_microbatches or S
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    mb = B // M
    micro = x.reshape(M, mb, *x.shape[1:])

    # params: each device sees its own stage's slice (leading axis 1)
    param_specs = jax.tree.map(
        lambda a: P(*([axis] + [None] * (a.ndim - 1))), stacked_params)

    @partial(shard_map, mesh=mesh,
             in_specs=(param_specs, P()), out_specs=P(),
             check_vma=False)
    def run(params, micro):
        me = jax.lax.axis_index(axis)
        p_local = jax.tree.map(lambda a: a[0], params)  # my stage's params
        n_ticks = S + M - 1
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            buf, outs = carry
            # which microbatch enters stage 0 this tick (garbage when
            # t >= M; masked out below)
            feed = micro[jnp.minimum(t, M - 1)]
            h_in = jnp.where(me == 0,
                             jnp.where(t < M, feed, jnp.zeros_like(feed)),
                             buf)
            h_out = stage_fn(p_local, h_in)
            # last stage finishes microbatch t-(S-1) at tick t
            done_idx = t - (S - 1)
            valid = (done_idx >= 0) & (done_idx < M)
            outs = jax.lax.cond(
                valid,
                lambda o: o.at[jnp.clip(done_idx, 0, M - 1)].set(h_out),
                lambda o: o, outs)
            buf_next = jax.lax.ppermute(h_out, axis, fwd_perm)
            return (buf_next, outs), None

        buf0 = jnp.zeros_like(micro[0])
        outs0 = jnp.zeros_like(micro)
        (buf, outs), _ = jax.lax.scan(tick, (buf0, outs0),
                                      jnp.arange(n_ticks))
        # only the LAST stage's outs are real; broadcast them to everyone
        # so the out_spec P() (replicated) holds
        last = jax.lax.psum(
            jnp.where(me == S - 1, outs, jnp.zeros_like(outs)), axis)
        return last

    outs = run(stacked_params, micro)
    return outs.reshape(B, *x.shape[1:])
