"""Pipeline (stage) parallelism: GPipe-style microbatched execution.

Completes the parallelism suite (data parallel — parallel/wrapper;
sequence parallel — parallel/sequence; tensor parallel — parallel/tensor)
with the fourth axis: each device of a "pipe" mesh axis owns ONE STAGE of
the network; microbatches stream through the stages, activations hop to
the next stage over ICI with `ppermute`. The schedule is the classic
GPipe fill-drain loop: with S stages and M microbatches, the loop runs
S+M-1 ticks, each device computing its stage on the microbatch currently
resident (or idling in the bubble); bubble fraction (S-1)/(S+M-1)
shrinks as M grows.

All stages must share one apply signature (params, x) -> y with equal
activation shapes (classic homogeneous-block pipelining, the transformer
case). Exactness vs sequentially composing the stages is tested on the
virtual mesh; gradients flow through the ppermutes so the same program
trains under jax.grad.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.util.jax_compat import shard_map


def shard_stage_params(stage_params: list, mesh: Mesh, axis: str = "pipe"):
    """Stack per-stage param pytrees along a new leading axis and shard it
    over the pipe axis (device s holds stage s's params)."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)
    sh = lambda a: NamedSharding(  # noqa: E731
        mesh, P(*([axis] + [None] * (a.ndim - 1))))
    return jax.tree.map(lambda a: jax.device_put(a, sh(a)), stacked)


def _prepare(stage_fn, stacked_params, x, mesh: Mesh, axis: str,
             n_microbatches: int):
    """Shared schedule setup: validate one-stage-per-device and the
    microbatch split; build the per-stage param sharding specs.
    Returns (S, M, micro, param_specs).

    The microbatches are cast to the STAGE OUTPUT dtype (traced
    abstractly) — the pipeline carries activations stage-to-stage, so a
    type-stable loop needs stage output dtype == stage input dtype; with
    mixed user dtypes (e.g. f64 params on f32 inputs under x64) the
    widening the math would do anyway happens once, up front."""
    S = mesh.shape[axis]
    n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_stages != S:
        raise ValueError(
            f"{n_stages} stacked stages but the '{axis}' mesh axis has "
            f"{S} devices — one stage per device (a larger multiple "
            "would silently drop stages)")
    M = n_microbatches or S
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    micro = x.reshape(M, B // M, *x.shape[1:])
    p0 = jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype),
                      stacked_params)
    h = jax.ShapeDtypeStruct(micro.shape[1:], micro.dtype)
    try:
        h_out = jax.eval_shape(stage_fn, p0, h)
        micro = micro.astype(h_out.dtype)
    except Exception:
        # stage_fn may use mesh collectives, which only trace inside the
        # shard_map body (axes unbound here) — keep the input dtype; the
        # user then owns type stability, as before
        h_out = None
    # params: each device sees its own stage's slice (leading axis 1)
    param_specs = jax.tree.map(
        lambda a: P(*([axis] + [None] * (a.ndim - 1))), stacked_params)
    return S, M, micro, param_specs, h_out


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh: Mesh,
                   axis: str = "pipe", n_microbatches: int = None):
    """Run `stage_fn(params_s, h)` for stages s=0..S-1 over the pipe axis.

    stacked_params: pytree with leading stage axis (shard_stage_params).
    x: [B, ...] global batch; B must divide by n_microbatches (default =
    number of stages). Returns the final stage's output for the full
    batch. Differentiable (fori_loop-free: a lax.scan drives the
    schedule, ppermute moves activations stage->stage).
    """
    S, M, micro, param_specs, _ = _prepare(stage_fn, stacked_params, x,
                                           mesh, axis, n_microbatches)
    B = x.shape[0]

    @partial(shard_map, mesh=mesh,
             in_specs=(param_specs, P()), out_specs=P(),
             check_vma=False)
    def run(params, micro):
        me = jax.lax.axis_index(axis)
        p_local = jax.tree.map(lambda a: a[0], params)  # my stage's params
        n_ticks = S + M - 1
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            buf, outs = carry
            # which microbatch enters stage 0 this tick (garbage when
            # t >= M; masked out below)
            feed = micro[jnp.minimum(t, M - 1)]
            h_in = jnp.where(me == 0,
                             jnp.where(t < M, feed, jnp.zeros_like(feed)),
                             buf)
            h_out = stage_fn(p_local, h_in)
            # last stage finishes microbatch t-(S-1) at tick t
            done_idx = t - (S - 1)
            valid = (done_idx >= 0) & (done_idx < M)
            outs = jax.lax.cond(
                valid,
                lambda o: o.at[jnp.clip(done_idx, 0, M - 1)].set(h_out),
                lambda o: o, outs)
            buf_next = jax.lax.ppermute(h_out, axis, fwd_perm)
            return (buf_next, outs), None

        buf0 = jnp.zeros_like(micro[0])
        outs0 = jnp.zeros_like(micro)
        (buf, outs), _ = jax.lax.scan(tick, (buf0, outs0),
                                      jnp.arange(n_ticks))
        # only the LAST stage's outs are real; broadcast them to everyone
        # so the out_spec P() (replicated) holds
        last = jax.lax.psum(
            jnp.where(me == S - 1, outs, jnp.zeros_like(outs)), axis)
        return last

    outs = run(stacked_params, micro)
    return outs.reshape(B, *x.shape[1:])


def pipeline_train_step(stage_fn: Callable, loss_fn: Callable,
                        stacked_params, x, y, mesh: Mesh,
                        axis: str = "pipe", n_microbatches: int = None):
    """One 1F1B-style pipelined train step: returns (mean loss, dparams).

    `pipeline_apply` under `jax.grad` is GPipe: the scan's autodiff saves
    residuals for every (tick, stage) — activation memory grows O(M) with
    the microbatch count. This schedule interleaves each microbatch's
    backward with later microbatches' forwards, so a device only holds
    the stage INPUTS of its in-flight microbatches: at most 2S-1 of them,
    independent of M (the 1F1B property; classic refs: PipeDream/Megatron
    one-forward-one-backward). Backward is recompute-form — a tick's
    backward re-runs stage_fn from the saved input under jax.vjp, the
    same FLOP profile as a jax.checkpoint-ed GPipe — so for long trains
    (M >> S) memory drops from O(M) to O(S) at ~S extra pipeline ticks.

    stage_fn(params_s, h) -> h (homogeneous stages, as pipeline_apply);
    loss_fn(h_out, y_mb) -> scalar mean loss of one microbatch.
    Returns (loss, dparams): loss = mean over microbatches, dparams has
    the same stage-stacked layout as `stacked_params` (device s
    contributes the grads of its own stage). Input-grads (dx) are not
    returned — this is a train step, not a general VJP.
    """
    S, M, micro_x, param_specs, h_out = _prepare(stage_fn, stacked_params,
                                                 x, mesh, axis,
                                                 n_microbatches)
    micro_y = y.reshape(M, x.shape[0] // M, *y.shape[1:])
    K = 2 * S  # residual ring: >= max in-flight stage inputs (2S-1)
    # the loss accumulator carry must match what loss_fn actually
    # returns (x64-safe): trace it abstractly on the stage-output aval
    # from _prepare; when that was untraceable (collective-using
    # stage_fn) fall back to a dtype-promotion estimate
    try:
        if h_out is None:
            raise TypeError
        loss_dtype = jax.eval_shape(
            loss_fn, h_out,
            jax.ShapeDtypeStruct(micro_y.shape[1:], micro_y.dtype)).dtype
    except Exception:
        loss_dtype = jnp.result_type(
            jnp.float32, micro_x.dtype, micro_y.dtype,
            *[a.dtype for a in jax.tree.leaves(stacked_params)])

    @partial(shard_map, mesh=mesh,
             in_specs=(param_specs, P(), P()),
             out_specs=(P(), param_specs),
             check_vma=False)
    def run(params, mx, my):
        me = jax.lax.axis_index(axis)
        p_local = jax.tree.map(lambda a: a[0], params)
        # schedule: fwd(s, m) at tick s + m; bwd(s, m) at tick
        # (2S - 1 - s) + m — the last stage's backward trails its forward
        # by one tick, cotangents ppermute upstream one stage per tick
        n_ticks = 2 * S + M - 2 + 1
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]

        def tick(carry, t):
            fbuf, bbuf, resid, dp_acc, loss_acc = carry
            # ---- forward half: microbatch m_f enters this stage
            m_f = t - me
            f_valid = (m_f >= 0) & (m_f < M)
            feed = mx[jnp.clip(m_f, 0, M - 1)]
            h_in = jnp.where(me == 0, feed, fbuf)
            h_out = stage_fn(p_local, h_in)
            resid = jax.lax.cond(
                f_valid,
                lambda r: r.at[jnp.clip(m_f, 0, M - 1) % K].set(h_in),
                lambda r: r, resid)
            fbuf_next = jax.lax.ppermute(h_out, axis, fwd_perm)

            # ---- backward half: microbatch m_b leaves this stage
            m_b = t - (2 * S - 1 - me)
            b_valid = (m_b >= 0) & (m_b < M)
            mi = jnp.clip(m_b, 0, M - 1)
            h_saved = resid[mi % K]
            h2, vjp_fn = jax.vjp(lambda p, h: stage_fn(p, h),
                                 p_local, h_saved)
            # last stage seeds the cotangent from the loss; others use
            # the cotangent ppermuted down from stage s+1
            y_mb = my[mi]
            loss_mb, g_loss = jax.value_and_grad(loss_fn)(h2, y_mb)
            cot = jnp.where(me == S - 1, g_loss, bbuf)
            dp, dh = vjp_fn(cot)
            dp_acc = jax.tree.map(
                lambda acc, g: acc + jnp.where(b_valid, g, 0.0),
                dp_acc, dp)
            loss_acc = loss_acc + jnp.where(
                b_valid & (me == S - 1), loss_mb, 0.0)
            bbuf_next = jax.lax.ppermute(dh, axis, bwd_perm)
            return (fbuf_next, bbuf_next, resid, dp_acc, loss_acc), None

        z = jnp.zeros_like(mx[0])
        resid0 = jnp.zeros((K,) + z.shape, z.dtype)
        dp0 = jax.tree.map(jnp.zeros_like, p_local)
        carry0 = (z, z, resid0, dp0, jnp.zeros((), loss_dtype))
        (_, _, _, dp_acc, loss_acc), _ = jax.lax.scan(
            tick, carry0, jnp.arange(n_ticks))
        # objective = (1/M) sum of per-microbatch mean losses, so the
        # accumulated per-microbatch grads average the same way
        loss = jax.lax.psum(loss_acc, axis) / M
        dparams = jax.tree.map(lambda a: (a / M)[None], dp_acc)
        return loss, dparams

    return run(stacked_params, micro_x, micro_y)
