"""Early stopping over mesh-parallel training.

Equivalent of deeplearning4j-scaleout EarlyStoppingParallelTrainer.java:373
(SURVEY §2.5): the early-stopping epoch loop driving a ParallelWrapper
instead of single-device fit. On TPU the "parallel" part is the sharded
train step; termination/scoring/saving semantics are identical to
earlystopping.core.
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.earlystopping.core import (
    EarlyStoppingConfiguration, EarlyStoppingResult, EarlyStoppingTrainer,
)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """ref: EarlyStoppingParallelTrainer.java — wraps the model in a
    ParallelWrapper; each early-stopping epoch trains data-parallel across
    the mesh, then scoring/termination run on the (replicated) params."""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_iterator, mesh=None,
                 training_mode: str = "allreduce",
                 averaging_frequency: int = 5,
                 prefetch_buffer: int = 2,
                 wrapper: Optional[ParallelWrapper] = None):
        super().__init__(config, model, train_iterator)
        self.wrapper = wrapper or ParallelWrapper(
            model, mesh=mesh, training_mode=training_mode,
            averaging_frequency=averaging_frequency,
            prefetch_buffer=prefetch_buffer)

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        best_score, best_epoch = None, -1
        scores = {}
        epoch = 0
        reason, details = "MaxEpochs", ""
        while True:
            self.wrapper.fit(self.train_iterator, epochs=1)
            s = self.model.score_value
            aborted = False
            for c in cfg.iteration_termination_conditions:
                if c.terminate(self.model.iteration_count, s):
                    reason = "IterationTerminationCondition"
                    details = type(c).__name__
                    aborted = True
                    break
            if aborted:
                break
            if cfg.score_calculator is not None and \
                    epoch % cfg.evaluate_every_n_epochs == 0:
                score = cfg.score_calculator.calculate_score(self.model)
            else:
                score = s
            scores[epoch] = score
            if best_score is None or score < best_score:
                best_score, best_epoch = score, epoch
                cfg.model_saver.save_best(self.model, score)
            if cfg.save_last_model:
                cfg.model_saver.save_latest(self.model, score)
            term = False
            for c in cfg.epoch_termination_conditions:
                if c.terminate(epoch, score):
                    reason = "EpochTerminationCondition"
                    details = type(c).__name__
                    term = True
                    break
            if term:
                break
            epoch += 1
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            total_epochs=epoch + 1, best_model_epoch=best_epoch,
            best_model_score=(best_score if best_score is not None
                              else float("nan")),
            score_vs_epoch=scores, best_model=cfg.model_saver.get_best())
