"""Early stopping over mesh-parallel training.

Equivalent of deeplearning4j-scaleout EarlyStoppingParallelTrainer.java:373
(SURVEY §2.5): the early-stopping epoch loop driving a ParallelWrapper
instead of single-device fit. Only the train-one-epoch step differs —
termination/scoring/saving live in earlystopping.core.
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.earlystopping.core import (
    EarlyStoppingConfiguration, EarlyStoppingTrainer,
)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """ref: EarlyStoppingParallelTrainer.java — wraps the model in a
    ParallelWrapper; each early-stopping epoch trains data-parallel across
    the mesh. Iteration termination conditions are checked once per epoch
    (the sharded step doesn't surface per-batch host callbacks)."""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_iterator, mesh=None,
                 training_mode: str = "allreduce",
                 averaging_frequency: int = 5,
                 prefetch_buffer: int = 2,
                 wrapper: Optional[ParallelWrapper] = None):
        super().__init__(config, model, train_iterator)
        self.wrapper = wrapper or ParallelWrapper(
            model, mesh=mesh, training_mode=training_mode,
            averaging_frequency=averaging_frequency,
            prefetch_buffer=prefetch_buffer)

    def _fit_epoch(self):
        self.wrapper.fit(self.train_iterator, epochs=1)
        s = self.model.score_value
        for c in self.config.iteration_termination_conditions:
            if c.terminate(self.model.iteration_count, s):
                return True, type(c).__name__
        return False, None
