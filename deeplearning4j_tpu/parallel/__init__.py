"""Parallelism: device meshes, sharded training, parallel inference.

TPU-native replacement for deeplearning4j-scaleout (SURVEY §2.5): the four
reference strategies (ParallelWrapper averaging / encoded gradient sharing,
Spark parameter averaging, Aeron async parameter server) collapse into
sharded jit over a `jax.sharding.Mesh` — gradients are allreduced densely
over ICI by XLA-inserted collectives, which is the BASELINE.json north star.
"""

from deeplearning4j_tpu.parallel.mesh import (  # noqa: F401
    default_mesh,
    make_mesh,
)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper  # noqa: F401
from deeplearning4j_tpu.parallel.inference import ParallelInference  # noqa: F401
from deeplearning4j_tpu.parallel.elastic import (  # noqa: F401
    ElasticConfig,
    ElasticTrainer,
)
from deeplearning4j_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    pipeline_train_step,
    shard_stage_params,
)
