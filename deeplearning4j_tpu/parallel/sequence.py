"""Sequence/context parallelism: ring attention + all-to-all (Ulysses).

The reference (2017-era DL4J) has no attention and no sequence parallelism
(SURVEY §5 "Long-context"): its long-sequence story is truncated BPTT +
masking, which this framework already implements. This module is the
forward-looking long-context subsystem the TPU build treats as first-class:

- **Ring attention** (blockwise attention with KV rotation over the ICI
  ring): each device holds a sequence shard; K/V blocks rotate around the
  mesh axis via ``jax.lax.ppermute`` while a streaming (online-softmax)
  accumulator keeps the attention numerically exact. Memory per device is
  O(T_local²-free): only the local Q block and one in-flight KV block live
  in HBM, so context length scales linearly with the number of devices.
- **Ulysses / all-to-all attention**: ``jax.lax.all_to_all`` reshards from
  sequence-sharded to head-sharded, runs full local attention on each
  device's head slice, then reshards back. Cheaper collectives for models
  with enough heads; attention itself is unchanged.

Both are exact — outputs match single-device attention to float tolerance
(tested on an 8-device CPU mesh).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.util.jax_compat import shard_map

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() gradients clean



def _validate_window(window, causal) -> None:
    """Shared gate for every sliding-window entry point."""
    if window is None:
        return
    if not causal:
        raise ValueError("window attention requires causal=True")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


def reference_attention(q, k, v, causal: bool = False):
    """Plain single-device scaled-dot-product attention, [B,H,T,D] layout.
    The correctness oracle for both parallel paths."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def blockwise_attention(q, k, v, causal: bool = False,
                        block_size: int = 512, key_mask=None,
                        use_pallas: Optional[bool] = None,
                        window: Optional[int] = None):
    """Single-device flash-style attention: lax.scan over KV blocks with
    an online-softmax accumulator — O(T·block) live memory instead of the
    [T,T] score matrix, so one chip handles long contexts that would OOM
    the naive path (32k+ at bf16). Exact to float tolerance vs
    reference_attention; XLA keeps each block's QK^T / PV matmuls on the
    MXU and the running (m, l, o) update fuses into their epilogue.

    On TPU, supported shapes dispatch to the Pallas flash-attention
    kernel (nn/layers/pallas_attention.py — ~4x faster at T=8k: the
    (m,l,acc) state stays in VMEM scratch across KV steps and causal
    blocks above the diagonal are skipped; see PERF.md). `use_pallas`
    None=auto, False=always scan, True=require the kernel. The kernel
    picks its own tuned block sizes; `block_size` governs the scan path.

    q,k,v: [B,H,T,D]. T is padded internally to a block multiple; padded
    keys are masked with NEG_INF so results are unaffected. `key_mask`
    [B,T] (1=valid) additionally NEG_INF-masks padded KEY positions of
    variable-length batches (zeroing K/V would still receive softmax
    mass — score 0 can exceed valid negative scores). `window=W` (causal
    only) restricts each query to its W most recent keys — Mistral-style
    local attention. On the Pallas kernel path, blocks fully outside the
    window are SKIPPED, so cost is O(T·W); the scan fallback applies the
    mask but still visits every block (O(T²) semantics-only).
    """
    from deeplearning4j_tpu.nn.layers.pallas_attention import (
        flash_attention, flash_attention_supported)
    _validate_window(window, causal)
    if use_pallas is None:
        use_pallas = (jax.default_backend() == "tpu"
                      and flash_attention_supported(q.shape))
    if use_pallas:
        return flash_attention(q, k, v, causal=causal, key_mask=key_mask,
                               window=window)
    B, H, T, D = q.shape
    Tk = k.shape[2]                     # may differ (cross attention)
    if causal and T != Tk:
        raise ValueError(f"causal attention needs Tq == Tk ({T} vs {Tk})")
    bs = int(min(block_size, Tk))
    pad = (-Tk) % bs
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    if key_mask is not None:
        km = jnp.pad(key_mask.astype(bool), ((0, 0), (0, pad)))
        kmb = km.reshape(B, -1, bs).transpose(1, 0, 2)   # [n_blocks,B,bs]
    n_blocks = (Tk + pad) // bs
    scale = jnp.float32(1.0 / np.sqrt(D))
    qf = q.astype(jnp.float32)
    kb = k.reshape(B, H, n_blocks, bs, D).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, n_blocks, bs, D).transpose(2, 0, 1, 3, 4)
    q_pos = jnp.arange(T)

    def body(carry, blk):
        m, l, o = carry
        if key_mask is not None:
            kc, vc, idx, kmc = blk
        else:
            kc, vc, idx = blk
            kmc = None
        s = jnp.einsum("bhqd,bhkd->bhqk", qf,
                       kc.astype(jnp.float32)) * scale
        k_pos = idx * bs + jnp.arange(bs)
        valid = k_pos < Tk                               # pad mask
        if causal:
            valid = valid[None, :] & (q_pos[:, None] >= k_pos[None, :])
            if window is not None:
                # sliding window: query i sees keys (i-window, i]
                valid = valid & (q_pos[:, None] - k_pos[None, :] < window)
        else:
            valid = jnp.broadcast_to(valid[None, :], (T, bs))
        s = jnp.where(valid[None, None], s, NEG_INF)
        if kmc is not None:  # variable-length key mask [B,bs]
            s = jnp.where(kmc[:, None, None, :], s, NEG_INF)
        blk_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32))
        return (m_new, l_new, o_new), None

    m0 = jnp.full((B, H, T), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    o0 = jnp.zeros((B, H, T, D), jnp.float32)
    # remat the block body: reverse-mode through a plain scan would save
    # every block's [T, block] score/softmax matrices (OOM at long T);
    # checkpointing recomputes them in backward so only the (m, l, o)
    # carries persist — the flash-attention backward memory profile.
    xs = (kb, vb, jnp.arange(n_blocks))
    if key_mask is not None:
        xs = xs + (kmb,)
    (m, l, o), _ = jax.lax.scan(jax.checkpoint(body), (m0, l0, o0), xs)
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def _ring_steps_needed(n: int, T: int, window: Optional[int]) -> int:
    """How many ring steps any device can need. Without a window: all n.
    With a sliding window W, the chunk s hops back starts (s-1)*T+1
    positions before the oldest query on every device — once that
    exceeds W-1 no device can see ANY of it, so the loop (and its
    ppermutes) stops: O(W) work and traffic per device."""
    if window is None:
        return n
    steps = 1
    while steps < n and (steps - 1) * T + 1 < window:
        steps += 1
    return steps


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, n: int,
                          window: Optional[int] = None):
    """Per-shard ring attention body (runs under shard_map).

    q,k,v: [B,H,T_local,D] — this device's sequence shard. K/V blocks
    rotate ring-wise; a streaming softmax (running max m, normalizer l,
    weighted sum o) accumulates exact attention over the full sequence.
    The step loop is a Python loop over the STATIC axis size so a sliding
    window truncates it (and its ppermutes) at _ring_steps_needed."""
    my = jax.lax.axis_index(axis_name)
    scale = jnp.float32(1.0 / np.sqrt(q.shape[-1]))
    B, H, T, D = q.shape
    qf = q.astype(jnp.float32)

    m0 = jnp.full((B, H, T), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    o0 = jnp.zeros((B, H, T, D), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    q_pos = my * T + jnp.arange(T)                     # global query positions
    steps = _ring_steps_needed(n, T, window) if causal else n

    @jax.checkpoint  # flash-style backward: recompute per-step scores
    def attend(step, k_c, v_c, m, l, o):
        src = (my - step) % n                          # origin shard of k_c
        s = jnp.einsum("bhqd,bhkd->bhqk", qf,
                       k_c.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * T + jnp.arange(T)
            mask = q_pos[:, None] >= k_pos[None, :]    # [T,T]
            if window is not None:
                mask = mask & (q_pos[:, None] - k_pos[None, :] < window)
            s = jnp.where(mask[None, None], s, NEG_INF)
        blk_max = jnp.max(s, axis=-1)                  # [B,H,T]
        m_new = jnp.maximum(m, blk_max)
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32))
        return m_new, l_new, o_new

    if steps == n:
        # full ring: the original rolled loop (one compiled body, not n)
        def body(step, carry):
            k_c, v_c, m, l, o = carry
            m, l, o = attend(step, k_c, v_c, m, l, o)
            k_r = jax.lax.ppermute(k_c, axis_name, perm)
            v_r = jax.lax.ppermute(v_c, axis_name, perm)
            return k_r, v_r, m, l, o

        _, _, m, l, o = jax.lax.fori_loop(0, n, body, (k, v, m0, l0, o0))
    else:
        # window-truncated ring: unrolled so the loop (and its
        # ppermutes) STOPS after `steps` hops — O(W) per device
        m, l, o = m0, l0, o0
        k_c, v_c = k, v
        for step in range(steps):
            m, l, o = attend(jnp.int32(step), k_c, v_c, m, l, o)
            if step < steps - 1:
                k_c = jax.lax.ppermute(k_c, axis_name, perm)
                v_c = jax.lax.ppermute(v_c, axis_name, perm)
    # fully-masked rows (can't happen for causal with step 0 = own block,
    # but guard anyway) normalize to zero
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _ring_attention_local_flash(q, k, v, *, axis_name: str, causal: bool,
                                interpret: bool, n: int,
                                window: Optional[int] = None):
    """Ring attention with the Pallas flash kernel as the per-chunk
    engine: each ring step computes (o_i, lse_i) for this device's
    queries against the visiting KV chunk and merges with the running
    accumulator by the logaddexp rule.

    The step loop is a Python loop over the STATIC axis size, so the
    per-step chunk distance is a compile-time constant: step s attends
    the chunk s hops back as BANDED attention (causal + window masks with
    q_offset = s*T — the kernel's block skip then prunes out-of-band
    blocks), devices whose chunk would wrap (future chunk) take a
    lax.cond skip, and with a sliding window the loop itself stops at
    _ring_steps_needed — O(W) compute AND ppermute traffic per device."""
    from deeplearning4j_tpu.nn.layers.pallas_attention import (
        flash_attention_lse)
    my = jax.lax.axis_index(axis_name)
    B, H, T, D = q.shape

    o0 = jnp.zeros((B, H, T, D), jnp.float32)
    lse0 = jnp.full((B, H, T), NEG_INF, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    steps = _ring_steps_needed(n, T, window) if causal else n

    def merge(o, lse, o_i, lse_i):
        lse_new = jnp.logaddexp(lse, lse_i)
        w_old = jnp.exp(lse - lse_new)[..., None]
        w_new = jnp.exp(lse_i - lse_new)[..., None]
        return o * w_old + o_i * w_new, lse_new

    if window is None:
        # full ring: rolled loop with the full/diag/skip trichotomy —
        # exactly TWO kernel specializations regardless of ring size
        def _full(ops):
            o, lse = flash_attention_lse(q, ops[0], ops[1], causal=False,
                                         interpret=interpret)
            return o.astype(jnp.float32), lse

        def _diag(ops):
            o, lse = flash_attention_lse(q, ops[0], ops[1], causal=True,
                                         interpret=interpret)
            return o.astype(jnp.float32), lse

        def _skip(ops):
            return o0, lse0

        def body(step, carry):
            k_c, v_c, o, lse = carry
            src = (my - step) % n                  # origin shard of k_c
            if causal:
                branch = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
                o_i, lse_i = jax.lax.switch(branch, [_full, _diag, _skip],
                                            (k_c, v_c))
            else:
                o_i, lse_i = _full((k_c, v_c))
            o, lse = merge(o, lse, o_i, lse_i)
            k_r = jax.lax.ppermute(k_c, axis_name, perm)
            v_r = jax.lax.ppermute(v_c, axis_name, perm)
            return k_r, v_r, o, lse

        _, _, o, lse = jax.lax.fori_loop(0, n, body, (k, v, o0, lse0))
        return o.astype(q.dtype)

    # windowed ring: unrolled over the (window-truncated) static step
    # count — each step's chunk distance is a compile-time constant, so
    # step s runs as BANDED attention with q_offset = s*T (the kernel's
    # block skip prunes out-of-band blocks) and the loop + ppermutes stop
    # at _ring_steps_needed: O(W) compute AND ring traffic per device
    o, lse = o0, lse0
    k_c, v_c = k, v
    for step in range(steps):
        if step == 0:
            o_i, lse_i = flash_attention_lse(q, k_c, v_c, causal=True,
                                             window=window,
                                             interpret=interpret)
            o_i = o_i.astype(jnp.float32)
        else:
            def _band(ops, _step=step):
                oo, ll = flash_attention_lse(
                    q, ops[0], ops[1], causal=True, window=window,
                    q_offset=_step * T, interpret=interpret)
                return oo.astype(jnp.float32), ll

            def _skipw(ops):
                return o0, lse0

            # devices whose chunk-s-back wraps around see a FUTURE chunk
            o_i, lse_i = jax.lax.cond(my >= step, _band, _skipw, (k_c, v_c))
        o, lse = merge(o, lse, o_i, lse_i)
        if step < steps - 1:
            k_c = jax.lax.ppermute(k_c, axis_name, perm)
            v_c = jax.lax.ppermute(v_c, axis_name, perm)
    return o.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "data",
                   causal: bool = False,
                   use_flash: Optional[bool] = None,
                   interpret: bool = False,
                   window: Optional[int] = None):
    """Exact attention over a sequence sharded on ``mesh[axis]``.

    q/k/v: [B,H,T,D] global arrays (T divisible by the axis size). Returns
    [B,H,T,D]. Under jit the ppermutes ride ICI neighbor links — the
    canonical ring schedule.

    `window=W` (causal only) gives Mistral-style sliding-window local
    attention under sequence parallelism: ring chunks fully outside the
    window are never visited (the step loop stops once the chunk distance
    exceeds W), making cost — compute and ring traffic — O(W) per device
    instead of O(T).

    On TPU with supported shapes the per-chunk engine is the Pallas flash
    kernel (_ring_attention_local_flash: per-chunk (o, lse) merged by
    logaddexp, with banded q_offset chunks under a window); otherwise the
    lax online-softmax body. `use_flash` None=auto, and `interpret=True`
    runs the kernel in interpreter mode (tests on CPU)."""
    from deeplearning4j_tpu.nn.layers.pallas_attention import (
        flash_attention_supported)
    _validate_window(window, causal)
    size = mesh.shape[axis]
    if use_flash is None:
        local = (q.shape[0], q.shape[1], q.shape[2] // size, q.shape[3])
        use_flash = (jax.default_backend() == "tpu"
                     and flash_attention_supported(local))
    spec = P(None, None, axis, None)
    if use_flash:
        local_fn = functools.partial(_ring_attention_local_flash,
                                     axis_name=axis, causal=causal,
                                     interpret=interpret, n=size,
                                     window=window)
    else:
        local_fn = functools.partial(_ring_attention_local, axis_name=axis,
                                     causal=causal, n=size, window=window)
    fn = shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool,
                   window: Optional[int] = None):
    """Per-shard Ulysses body: all_to_all seq→head shards, local full
    attention, all_to_all back. q,k,v: [B,H,T_local,D]; H divisible by n."""
    def seq_to_heads(x):
        # [B,H,T_local,D] -> [B,H/n,T_global,D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # blockwise core: O(T·block) memory for the full-length local
    # attention (the naive [T,T] score matrix defeats the point of
    # sharding long sequences), and the Pallas flash kernel on TPU.
    # No fp32 pre-cast: both engines accumulate in fp32 internally, and
    # bf16 inputs keep the MXU rate / halve the gathered-copy traffic.
    # A sliding window passes straight through: after the head reshard
    # each device holds the FULL sequence, so the engine's own block
    # skipping delivers the O(T·W) cost.
    out = blockwise_attention(qh, kh, vh, causal=causal, window=window)
    return heads_to_seq(out.astype(q.dtype))


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "data",
                      causal: bool = False, window: Optional[int] = None):
    """All-to-all (DeepSpeed-Ulysses-style) sequence-parallel attention.
    Requires num_heads % axis_size == 0."""
    n = mesh.shape[axis]
    if q.shape[1] % n != 0:
        raise ValueError(
            f"ulysses needs heads ({q.shape[1]}) divisible by mesh axis "
            f"'{axis}' size ({n}); use ring_attention otherwise")
    _validate_window(window, causal)
    spec = P(None, None, axis, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis, causal=causal,
                          window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


class MultiHeadSelfAttention:
    """Minimal MHA block wired for sequence parallelism: projections are
    plain (replicated) matmuls; the attention core is ring/ulysses/local.

    x: [B,T,E] → [B,T,E]. A post-parity extension (the reference has no
    attention layer); exists so long-context models can be built and the
    sequence-parallel paths exercised end-to-end in training steps.
    """

    def __init__(self, embed_dim: int, num_heads: int,
                 impl: str = "ring", causal: bool = True,
                 window: Optional[int] = None):
        if embed_dim % num_heads:
            raise ValueError("embed_dim must divide by num_heads")
        _validate_window(window, causal)
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        if impl not in ("ring", "ulysses", "local", "blockwise", "flash"):
            raise ValueError(f"unknown attention impl {impl!r}")
        self.impl = impl
        self.causal = causal
        self.window = window

    def init(self, rng: jax.Array):
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        s = 1.0 / np.sqrt(self.embed_dim)
        E = self.embed_dim
        return {
            "wq": jax.random.normal(k1, (E, E)) * s,
            "wk": jax.random.normal(k2, (E, E)) * s,
            "wv": jax.random.normal(k3, (E, E)) * s,
            "wo": jax.random.normal(k4, (E, E)) * s,
        }

    def apply(self, params, x, mesh: Optional[Mesh] = None,
              axis: str = "data"):
        B, T, E = x.shape
        H, D = self.num_heads, self.head_dim

        def heads(u):  # [B,T,E] -> [B,H,T,D]
            return u.reshape(B, T, H, D).transpose(0, 2, 1, 3)

        q, k, v = (heads(x @ params[w]) for w in ("wq", "wk", "wv"))
        # no mesh: ring/ulysses fall back to the single-device blockwise
        # kernel (exact to float tolerance; memory-safe for long T)
        if self.impl == "flash":
            from deeplearning4j_tpu.nn.layers.pallas_attention import (
                flash_attention, flash_attention_supported)
            if not flash_attention_supported(q.shape):
                raise ValueError(
                    f"impl='flash' unsupported for q shape {q.shape}: head "
                    "dim must be one of (64, 128, 256) and T >= 128")
            if jax.default_backend() != "tpu":
                o = blockwise_attention(q, k, v, causal=self.causal,
                                        use_pallas=False,  # CPU fallback
                                        window=self.window)
            else:
                o = flash_attention(q, k, v, causal=self.causal,
                                    window=self.window)
        elif self.impl == "blockwise" or \
                (mesh is None and self.impl != "local"):
            o = blockwise_attention(q, k, v, causal=self.causal,
                                    window=self.window)
        elif self.impl == "local":
            if self.window is not None:
                raise ValueError("impl='local' does not support window")
            o = reference_attention(q, k, v, causal=self.causal)
        elif self.impl == "ring":
            o = ring_attention(q, k, v, mesh, axis=axis, causal=self.causal,
                               window=self.window)
        else:
            o = ulysses_attention(q, k, v, mesh, axis=axis,
                                  causal=self.causal, window=self.window)
        o = o.transpose(0, 2, 1, 3).reshape(B, T, E)
        return o @ params["wo"]
