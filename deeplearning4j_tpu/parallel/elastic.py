"""ElasticTrainer: multi-host SPMD training that survives host loss.

The missing half of ROADMAP item 2 over PR 7's durable substrate. The
single-generation multi-host story (tests/distributed_worker.py) is:
``jax.distributed.initialize`` → global mesh → every host feeds its
shard → XLA allreduces. That world is rigid — one lost host SIGABRTs
every peer via the coordination service, and the job is gone. This
trainer wraps the same SPMD step in the elastic membership loop
(resilience/elastic.py):

    establish generation ──▶ restore from latest_committed_step
         ▲                         │
         │                         ▼
    agree gen N+1 ◀── detect ◀── train shard / heartbeat / commit
    (tear down,        (lease expiry, hung or failed
     re-initialize,     allreduce, commit timeout,
     re-mesh)           join lease at a commit boundary)

Key invariants:

- **Every survivor resumes from ``latest_committed_step``** after a
  re-mesh. Params are replicated, so any committed shard restores the
  full state; nothing a dead generation computed past its last commit
  survives — which is exactly what makes the survivor's continuation
  bit-identical to a fresh single(world)-process run resumed from the
  same committed step (the gloo suite pins this by sha256).
- **Scale-in is detected asynchronously** (a lost host can't be halfway
  through dispatching), via lease expiry before dispatch or via the
  dispatch watchdog: a peer SIGKILLed mid-allreduce leaves the
  collective hung (or erroring), the watchdog fires, and the ledger
  confirms who died. An error/timeout WITHOUT a confirmed loss
  re-raises — it was a real failure, not membership.
- **Scale-out is decided at commit boundaries only**, and ONLY by the
  generation's process 0, which publishes the successor record BEFORE
  the COMMIT marker. Every rank checks for a successor right after the
  commit barrier — the barrier is the fleet's existing rendezvous, so
  all ranks leave the generation at the same step and nobody dispatches
  an allreduce a departed peer will never join (the deadlock a
  per-step, per-rank join check would invite).
- **Deterministic sharding**: a host's rows are a pure function of
  (step, global batch, generation record) — ``host_shard_bounds``'s
  largest-even-split over the batch-cycling schedule — so any
  membership can recompute who feeds what with no negotiation.

The trainer owns a bare train-step loop (the scale-out shape of
tests/durable_worker.py), not the listener-rich ``net.fit``: elastic
membership is about the fleet around the step, and the canonical step
function (``net._get_train_step``) is shared with every other fit path.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Dict, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.monitoring.events import emit as emit_event
from deeplearning4j_tpu.resilience.chaos import fire
from deeplearning4j_tpu.resilience.durable import (
    CommitTimeoutError, latest_committed_step, read_commit)
from deeplearning4j_tpu.resilience.elastic import (
    GenerationDead, GenerationRecord, LeaseLedger, MembershipChanged,
    agree_next_generation, declare_elastic_series, detect_membership,
    free_port)

log = logging.getLogger(__name__)


@dataclasses.dataclass
class ElasticConfig:
    """Knobs for one elastic training job (all hosts must agree on
    everything except ``rank``)."""

    ledger_root: str  # shared dir for leases + generation records
    checkpoint_dir: str  # shared dir for distributed commits
    rank: int  # this host's stable GLOBAL rank
    #: gen-0 membership (global ranks). Rank min(bootstrap_members)
    #: publishes generation 0; everyone else adopts it. A host NOT in
    #: the bootstrap set joins by lease (scale-out).
    bootstrap_members: Sequence[int] = (0,)
    #: "host:port" for generation 0 (later generations pick their own);
    #: None = loopback + a free port (single-host/test fleets).
    bootstrap_coordinator: Optional[str] = None
    lease_ttl: float = 5.0
    heartbeat_interval: Optional[float] = None  # default ttl/3
    #: watchdog around each allreduce dispatch: a hung collective past
    #: this is treated as a membership event (confirmed via the ledger)
    dispatch_timeout: float = 30.0
    #: grace to wait for a lease to expire when a dispatch ERRORS before
    #: the ttl has had time to pass (gloo reports a died peer's closed
    #: socket in milliseconds); None = lease_ttl + 1
    confirm_grace: Optional[float] = None
    remesh_timeout: float = 60.0
    publish_stagger: float = 0.25
    commit_every: int = 10
    commit_timeout: float = 60.0
    advertise_host: str = "127.0.0.1"

    def __post_init__(self):
        if self.commit_every < 1:
            raise ValueError("commit_every must be >= 1")
        if int(self.rank) < 0:
            raise ValueError("rank must be >= 0")


class ElasticTrainer:
    """Train a (seed-identical on every host) network across an elastic
    multi-host fleet; see the module docstring for the protocol.

    ``step_chaos`` is the chaos seam (one ``chaos.fire`` event per
    global step BEFORE its dispatch): ``HostLossInjector`` /
    ``LeaseStallInjector`` plug in here for the gloo kill/hang suites.
    """

    def __init__(self, net, config: ElasticConfig, step_chaos=None):
        self.net = net
        self.config = config
        self.step_chaos = step_chaos
        self.ledger = LeaseLedger(
            config.ledger_root, config.rank, ttl=config.lease_ttl,
            interval=config.heartbeat_interval,
            advertise_host=config.advertise_host)
        self.record: Optional[GenerationRecord] = None
        self.remeshes = 0
        self.last_remesh_seconds: Optional[float] = None
        self.last_restored_step: Optional[int] = None
        self._step = 0
        self._runtime_live = False  # jax.distributed currently up
        self._dirty = False  # a previous generation's backend existed
        (self._g_generation, self._g_members, self._c_remesh,
         self._c_lost, self._h_remesh) = declare_elastic_series()
        if not net._initialized:
            net.init()

    # ------------------------------------------------------------------
    # membership / runtime lifecycle
    # ------------------------------------------------------------------
    def _establish(self) -> GenerationRecord:
        """Adopt (or bootstrap) the current generation; joiners wait for
        admission. Returns an activated record."""
        cfg = self.config
        rec = self.ledger.latest_generation()
        if rec is None:
            members = sorted(int(m) for m in cfg.bootstrap_members)
            if cfg.rank == members[0]:
                coord = cfg.bootstrap_coordinator or \
                    f"{cfg.advertise_host}:{free_port(cfg.advertise_host)}"
                rec = self.ledger.publish_generation(GenerationRecord(
                    generation=0, members=members, coordinator=coord,
                    published_by=cfg.rank))
            else:
                rec = self.ledger.wait_for_generation(
                    0, timeout=cfg.remesh_timeout)
        while not rec.contains(cfg.rank):
            # a join request is just our heartbeat being alive: wait for
            # the incumbents to fold us into a successor generation
            log.info("rank %d waiting for admission past generation %d",
                     cfg.rank, rec.generation)
            rec = self.ledger.wait_for_generation(
                rec.generation + 1, timeout=cfg.remesh_timeout)
        self._activate(rec)
        return rec

    def _host_park_net(self) -> None:
        """Materialize the net's training state as host numpy: every
        device array created before a backend reset is dead after it —
        this must run BEFORE any backend rebuild, whether the previous
        backend was a dead generation's or the implicit single-process
        one ``net.init()`` built before the first generation came up."""
        from deeplearning4j_tpu.resilience.durable import snapshot_tree
        net = self.net
        net.params = snapshot_tree(net.params)
        net.state = snapshot_tree(net.state)
        net.updater_state = snapshot_tree(net.updater_state)
        if getattr(net, "_rng", None) is not None:
            net._rng = np.asarray(net._rng)

    def _activate(self, rec: GenerationRecord) -> None:
        """Bring the jax runtime up for a generation. world=1 runs with
        no coordination service at all — the whole point of scale-in
        surviving the coordinator's death."""
        from deeplearning4j_tpu.parallel import distributed as dist
        cfg = self.config
        pid = rec.process_id_of(cfg.rank)
        if rec.world > 1:
            # the backend (even a fresh process's: net.init() built a
            # single-process one) predates this generation's
            # coordination service — park state on host, rebuild
            self._host_park_net()
            dist.reset_backend(collectives="gloo")
            self._dirty = True
            dist.elastic_initialize(rec.coordinator, rec.world, pid,
                                    initialization_timeout=cfg.remesh_timeout)
            self._runtime_live = True
        if self._dirty:
            # compiled steps traced against a previous backend's devices;
            # a never-reset world-of-one keeps its warm cache (steady
            # state stays zero-retrace, and so does a later fit_steps
            # call on an already-activated world — hence the reset below)
            cache = getattr(self.net, "_jit_cache", None)
            if cache is not None:
                cache.clear()
            self._dirty = False
        self.record = rec
        self.ledger.heartbeat(rec.generation)
        self._g_generation.set(rec.generation)
        self._g_members.set(rec.world)
        log.info("rank %d active in generation %d: world=%d process_id=%d "
                 "coordinator=%s", cfg.rank, rec.generation, rec.world,
                 pid, rec.coordinator)

    def _teardown(self) -> None:
        """Leave the current generation's runtime behind (never blocks
        on remote state — the peers may be dead)."""
        from deeplearning4j_tpu.parallel import distributed as dist
        self._host_park_net()
        if self._runtime_live:
            dist.teardown_dead_generation()
            self._runtime_live = False
        else:
            # world-of-one: no coordination service, but compiled traces
            # and device arrays still bind the old backend
            dist.reset_backend(collectives="none")
        self._dirty = True

    def _remesh(self, prev: GenerationRecord,
                event: MembershipChanged) -> GenerationRecord:
        """The one re-mesh path for scale-in AND scale-out: tear down,
        agree on the successor, activate it."""
        cfg = self.config
        t0 = time.perf_counter()
        if event.lost_ranks:
            self._c_lost.inc(len(event.lost_ranks))
        log.warning("re-mesh (%s): %s", event.cause, event)
        self._teardown()
        rec = prev
        deadline = time.monotonic() + cfg.remesh_timeout
        while True:
            rec = agree_next_generation(self.ledger, rec,
                                        stagger=cfg.publish_stagger,
                                        timeout=cfg.remesh_timeout)
            if not rec.contains(cfg.rank):
                # the fleet re-meshed WITHOUT us (our lease looked dead
                # — e.g. heartbeats stalled behind a slow disk). Our
                # live lease is already a join request; wait to be folded
                # into a later generation instead of fighting this one.
                log.warning("excluded from generation %d; waiting for "
                            "re-admission", rec.generation)
                rec = self.ledger.wait_for_generation(
                    rec.generation + 1,
                    timeout=max(0.0, deadline - time.monotonic()))
                continue
            # a successor published by a member that died before anyone
            # could adopt it (e.g. the committer between record and
            # marker) is dead on arrival: bump again rather than hanging
            # initialize on a dead coordinator
            delta = detect_membership(self.ledger, rec)
            if not delta.lost:
                break
            log.warning("generation %d dead on arrival (lost %s); "
                        "bumping again", rec.generation, delta.lost)
        self._activate(rec)
        self.remeshes += 1
        self.last_remesh_seconds = time.perf_counter() - t0
        self._c_remesh.inc(cause=event.cause)
        self._h_remesh.observe(self.last_remesh_seconds)
        emit_event("resilience", "remesh", cause=event.cause,
                   generation=rec.generation, world=len(rec.members),
                   lost=sorted(event.lost_ranks or ()),
                   seconds=round(self.last_remesh_seconds, 3))
        return rec

    # ------------------------------------------------------------------
    # detection helpers
    # ------------------------------------------------------------------
    def _confirm_loss(self, rec: GenerationRecord,
                      reason: str) -> Optional[MembershipChanged]:
        """A dispatch or commit failed/timed out: is it membership? Poll
        the ledger up to the confirm grace for an expired member lease —
        gloo reports a dead peer's closed socket in milliseconds, long
        before the lease ttl can elapse. Also watch for a SUCCESSOR
        generation: a peer that (wrongly — e.g. this host's heartbeat
        writes stalled behind a slow disk) declared US dead has already
        re-meshed without us, our collective will never complete, and
        the way back in is the join path, not a retry. No confirmed
        loss and no successor → None (the failure was real; the caller
        re-raises it)."""
        cfg = self.config
        grace = cfg.confirm_grace if cfg.confirm_grace is not None \
            else cfg.lease_ttl + 1.0
        deadline = time.monotonic() + grace
        while True:
            delta = detect_membership(self.ledger, rec)
            if delta.lost:
                return GenerationDead(rec.generation, delta.lost, reason,
                                      joined=delta.joined)
            nxt = self.ledger.read_generation(rec.generation + 1)
            if nxt is not None:
                return MembershipChanged(
                    rec.generation,
                    f"peers moved to generation {nxt.generation} "
                    f"({reason})", joined=delta.joined)
            if time.monotonic() > deadline:
                return None
            time.sleep(min(0.1, cfg.lease_ttl / 4))

    def _check_scale_in(self, rec: GenerationRecord) -> None:
        """Pre-dispatch lease check: only LOSSES act here (join admission
        is a commit-boundary decision by process 0 — see module doc). An
        expired lease is re-read once after a beat before it counts: a
        heartbeat briefly stalled behind a slow disk recovers on its
        next write, and a false scale-in costs the whole fleet a
        re-mesh."""
        delta = detect_membership(self.ledger, rec)
        if not delta.lost:
            return
        time.sleep(min(0.3, self.config.lease_ttl / 4))
        delta = detect_membership(self.ledger, rec)
        if delta.lost:
            raise GenerationDead(rec.generation, delta.lost,
                                 "lease expired", joined=delta.joined)

    def _check_successor(self, rec: GenerationRecord) -> None:
        """Post-commit check: process 0 published a successor record
        (scale-out admission) before the COMMIT marker, so every rank
        that passed the barrier is guaranteed to see it."""
        nxt = self.ledger.read_generation(rec.generation + 1)
        if nxt is not None:
            joined = [m for m in nxt.members if not rec.contains(m)]
            raise MembershipChanged(rec.generation,
                                    "successor generation published",
                                    joined=joined)

    # ------------------------------------------------------------------
    # the train loop
    # ------------------------------------------------------------------
    def fit_steps(self, x, y, n_steps: int,
                  global_batch_size: Optional[int] = None):
        """Train ``n_steps`` global SPMD steps over a deterministic
        batch-cycling schedule of (x, y), surviving any number of
        membership changes. Returns the net with final params applied.

        Every host passes the SAME full (x, y) (the Spark-RDD analogue:
        the dataset is addressable everywhere; which rows a host
        *materializes on device* is its shard of the current
        generation). ``global_batch_size`` defaults to ``len(x)`` and
        must divide it."""
        from deeplearning4j_tpu import monitoring
        monitoring.ensure_started()
        x = np.asarray(x)
        y = np.asarray(y)
        gbs = int(global_batch_size or x.shape[0])
        if x.shape[0] % gbs:
            raise ValueError(f"global batch {gbs} must divide the "
                             f"dataset ({x.shape[0]} rows)")
        self.ledger.start()
        try:
            rec = self._establish()
            while True:
                try:
                    self._run_generation(rec, x, y, int(n_steps), gbs)
                    return self.net
                except MembershipChanged as e:
                    rec = self._remesh(rec, e)
        finally:
            self.ledger.stop()

    def _restore_committed(self, rec: GenerationRecord) -> int:
        """Resume from ``latest_committed_step`` (0 = fresh start).
        Params are replicated, so this generation's process id picks its
        old shard when one exists and any intact shard (0) otherwise —
        a joiner that never wrote a shard restores the fleet's state all
        the same."""
        from deeplearning4j_tpu.util.checkpoint import (
            restore_distributed_checkpoint)
        cfg = self.config
        step = latest_committed_step(cfg.checkpoint_dir)
        if step is None:
            self.last_restored_step = None
            return 0
        import os
        commit = read_commit(os.path.join(cfg.checkpoint_dir,
                                          f"step_{step}")) or {}
        cw = int(commit.get("world", rec.world))
        pid = rec.process_id_of(cfg.rank)
        shard = pid if pid < cw else 0
        restored = restore_distributed_checkpoint(
            self.net, cfg.checkpoint_dir, rank=shard, world=cw, step=step)
        self.last_restored_step = restored
        log.info("rank %d restored committed step %d (shard %d of "
                 "world %d)", cfg.rank, restored, shard, cw)
        return int(restored)

    def _run_generation(self, rec: GenerationRecord, x, y,
                        n_steps: int, gbs: int) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel import distributed as dist
        cfg = self.config
        net = self.net
        pid = rec.process_id_of(cfg.rank)
        start = self._restore_committed(rec)
        if start >= n_steps:
            return
        mesh = dist.global_mesh()
        rep = NamedSharding(mesh, P())

        def replicate(tree):
            """Replicated placement WITHOUT a broadcast: every process
            holds the same host values by construction (same seed, or
            the same committed checkpoint), so each assembles the
            replicated array from its local copy. A multi-host
            ``jax.device_put(tree, P())`` would instead emit one async
            broadcast collective per leaf with no data dependencies
            between them — two processes can execute those in different
            orders and cross the gloo streams (observed as
            ``op.preamble.length <= op.nbytes`` aborts at generation
            startup)."""
            return jax.tree_util.tree_map(
                lambda a: jax.make_array_from_process_local_data(
                    rep, np.ascontiguousarray(a)), tree)

        params = replicate(net.params)
        state = replicate(net.state)
        upd = replicate(net.updater_state)
        step_fn = net._get_train_step(False)
        # NamedSharding refuses an axis the mesh doesn't divide evenly,
        # so each generation trains on the largest per-device-even prefix
        # of the batch window (the ParallelWrapper._host_trim rule:
        # remainders are DROPPED, loudly — an elastic fleet must absorb
        # a 4→3 re-mesh, not crash on 16 % 3). eff is a pure function of
        # (gbs, generation record): every member computes the same trim.
        n_dev = int(np.prod(mesh.devices.shape))
        eff = (gbs // n_dev) * n_dev
        if eff == 0:
            raise ValueError(
                f"global batch {gbs} smaller than the generation's "
                f"{n_dev} devices — nothing to shard")
        if eff != gbs:
            log.warning(
                "generation %d: global batch %d not divisible by its %d "
                "devices; training on the first %d rows of each batch "
                "window this generation", rec.generation, gbs, n_dev, eff)
        lo, hi = dist.host_shard_bounds(eff, rank=pid, world=rec.world)
        n_rows = x.shape[0]

        def _sync_net(step: int) -> None:
            net.params, net.state, net.updater_state = params, state, upd
            net.iteration_count = int(step)

        for step in range(start, n_steps):
            self._step = step
            fire(self.step_chaos, step)
            self._check_scale_in(rec)
            b0 = (step * gbs) % n_rows
            gx = dist.make_global_array(x[b0 + lo:b0 + hi], mesh)
            gy = dist.make_global_array(y[b0 + lo:b0 + hi], mesh)
            rng = net._next_rng()
            out = self._dispatch_watched(
                rec, lambda: jax.block_until_ready(
                    step_fn(params, state, upd, gx, gy, rng, None, None)))
            params, state, upd, loss = out
            net.score_value = loss
            if (step + 1) % cfg.commit_every == 0 or step + 1 == n_steps:
                _sync_net(step + 1)
                self._commit(rec, step + 1)
                self._check_successor(rec)
        _sync_net(n_steps)

    def _dispatch_watched(self, rec: GenerationRecord, dispatch):
        """Run one allreduce dispatch under the watchdog. A peer that
        dies mid-collective leaves the dispatch hung (gloo may also
        surface a closed-socket error) — map both onto the ledger:
        confirmed loss → GenerationDead; otherwise the failure is real
        and propagates. The hung thread is abandoned (daemon); the
        teardown that follows drops the backend it is blocked in."""
        cfg = self.config
        result: Dict[str, Any] = {}
        done = threading.Event()

        def _run():
            try:
                result["out"] = dispatch()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                result["err"] = e
            done.set()

        t = threading.Thread(target=_run, daemon=True,
                             name="elastic-dispatch")
        t.start()
        if not done.wait(cfg.dispatch_timeout):
            dead = self._confirm_loss(
                rec, f"allreduce hung > {cfg.dispatch_timeout}s")
            if dead is not None:
                raise dead
            raise TimeoutError(
                f"dispatch exceeded {cfg.dispatch_timeout}s with every "
                f"member lease live — not a membership event")
        if "err" in result:
            dead = self._confirm_loss(
                rec, f"allreduce failed: {result['err']!r}")
            if dead is not None:
                raise dead from result["err"]
            raise result["err"]
        return result["out"]

    def _commit(self, rec: GenerationRecord, step: int) -> None:
        """Distributed commit at a step boundary; process 0 additionally
        folds pending join leases into a successor generation record,
        published BEFORE the COMMIT marker (see _check_successor)."""
        from deeplearning4j_tpu.util.checkpoint import (
            save_distributed_checkpoint)
        from deeplearning4j_tpu.resilience.elastic import (
            plan_next_generation)
        cfg = self.config
        pid = rec.process_id_of(cfg.rank)
        try:
            if pid == 0:
                # write our shard + barrier on the others, but delay the
                # marker until the scale-out decision is on disk
                save_distributed_checkpoint(
                    self.net, cfg.checkpoint_dir, step=step, rank=0,
                    world=rec.world, timeout=cfg.commit_timeout,
                    wait=False, publish=False)
                delta = detect_membership(self.ledger, rec)
                if delta.joined:
                    lease = self.ledger.read_lease(
                        min(set(delta.joined) | set(rec.members))) or {}
                    self.ledger.publish_generation(plan_next_generation(
                        rec, sorted(set(rec.members) | set(delta.joined)),
                        cfg.rank,
                        advertise_host=lease.get("host") or
                        cfg.advertise_host))
                from deeplearning4j_tpu.resilience.durable import (
                    publish_commit)
                import os
                publish_commit(os.path.join(cfg.checkpoint_dir,
                                            f"step_{step}"),
                               step=step, world=rec.world,
                               timeout=cfg.commit_timeout)
            else:
                save_distributed_checkpoint(
                    self.net, cfg.checkpoint_dir, step=step, rank=pid,
                    world=rec.world, timeout=cfg.commit_timeout,
                    wait=True)
        except CommitTimeoutError as e:
            dead = self._confirm_loss(rec, f"commit barrier timeout "
                                           f"at step {step}")
            if dead is not None:
                raise dead from e
            raise
        log.info("rank %d committed step %d (generation %d)",
                 cfg.rank, step, rec.generation)

    # ------------------------------------------------------------------
    def health(self) -> Dict[str, Any]:
        """Ops surface: current membership + re-mesh history (the
        dl4jtpu_elastic_* series carry the same facts registry-side)."""
        rec = self.record
        return {
            "rank": self.config.rank,
            "generation": None if rec is None else rec.generation,
            "world": None if rec is None else rec.world,
            "members": None if rec is None else list(rec.members),
            "process_id": None if rec is None
            else rec.process_id_of(self.config.rank),
            "step": self._step,
            "remeshes": self.remeshes,
            "last_remesh_seconds": self.last_remesh_seconds,
            "last_restored_step": self.last_restored_step,
            "lease_stalled": self.ledger.stalled,
        }
