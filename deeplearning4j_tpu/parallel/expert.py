"""Expert parallelism: mixture-of-experts FFN with experts sharded over
an "expert" mesh axis.

The fifth parallelism axis (dp — parallel/wrapper, sp — parallel/sequence,
tp — parallel/tensor, pp — parallel/pipeline): each device owns ONE
expert's FFN parameters (the memory-scaling point of ep — total expert
capacity grows linearly with devices), a shared router picks the top-1
expert per token, every device computes its expert on the tokens routed
to it (gate-masked), and one psum combines the expert outputs. The
load-balancing auxiliary loss follows the standard Switch-Transformer
recipe (routing itself is deterministic — no router jitter).

Correctness-first formulation: computation per device is dense over the
token batch with routed-token masking (capacity == batch; the classic
all_to_all capacity-C dispatch is a throughput refinement on top of the
same math). Exactness vs the unsharded all-experts reference and
gradient equality are tested on the virtual mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.util.jax_compat import shard_map


def init_moe_params(key, embed_dim: int, ffn_dim: int, n_experts: int,
                    scale: float = 0.1) -> Dict:
    """Router + stacked expert FFN params (leading expert axis)."""
    ks = jax.random.split(key, 3)
    return {
        "Wg": (jax.random.normal(ks[0], (embed_dim, n_experts))
               * scale).astype(jnp.float32),
        "W1": (jax.random.normal(ks[1], (n_experts, embed_dim, ffn_dim))
               * scale).astype(jnp.float32),
        "b1": jnp.zeros((n_experts, ffn_dim), jnp.float32),
        "W2": (jax.random.normal(ks[2], (n_experts, ffn_dim, embed_dim))
               * scale).astype(jnp.float32),
        "b2": jnp.zeros((n_experts, embed_dim), jnp.float32),
    }


def shard_moe_params(params: Dict, mesh: Mesh, axis: str = "expert"):
    """Experts sharded over the axis; router replicated."""
    out = {}
    for k, v in params.items():
        if k == "Wg":
            out[k] = jax.device_put(v, NamedSharding(mesh, P()))
        else:
            out[k] = jax.device_put(v, NamedSharding(
                mesh, P(*([axis] + [None] * (v.ndim - 1)))))
    return out


def moe_reference(params: Dict, x, activation=jax.nn.gelu):
    """Unsharded top-1 MoE (the correctness oracle): every expert runs,
    each token takes its argmax expert's output scaled by the gate."""
    logits = x @ params["Wg"]                         # [B,T,N]
    probs = jax.nn.softmax(logits, axis=-1)
    best = jnp.argmax(probs, axis=-1)                 # [B,T]
    gate = jnp.take_along_axis(probs, best[..., None], -1)[..., 0]
    h = activation(jnp.einsum("bte,nef->btnf", x, params["W1"])
                   + params["b1"])
    y = jnp.einsum("btnf,nfe->btne", h, params["W2"]) + params["b2"]
    sel = jax.nn.one_hot(best, probs.shape[-1], dtype=x.dtype)
    return jnp.einsum("btne,btn->bte", y, sel) * gate[..., None]


def moe_mlp(params: Dict, x, mesh: Mesh, axis: str = "expert",
            activation=jax.nn.gelu, batch_axis: str = None):
    """Expert-parallel top-1 MoE FFN. x: [B,T,E]; params as in
    init_moe_params/shard_moe_params with n_experts == axis size.
    Returns (y, aux_loss) — aux is the Switch load-balance term
    (n_experts * sum_e fraction_e * prob_e)."""
    n = mesh.shape[axis]
    n_exp = params["W1"].shape[0]
    if n_exp != n:
        raise ValueError(f"{n_exp} experts but mesh axis '{axis}' has "
                         f"{n} devices (one expert per device)")
    xspec = P(batch_axis, None, None) if batch_axis else P()
    espec = lambda v: P(*([axis] + [None] * (v.ndim - 1)))  # noqa: E731

    @partial(shard_map, mesh=mesh,
             in_specs=(xspec, P(), espec(params["W1"]),
                       espec(params["b1"]), espec(params["W2"]),
                       espec(params["b2"])),
             out_specs=(xspec, P()), check_vma=False)
    def fwd(x, wg, w1, b1, w2, b2):
        me = jax.lax.axis_index(axis)
        logits = x @ wg                               # [b,T,N] (global N)
        probs = jax.nn.softmax(logits, axis=-1)
        best = jnp.argmax(probs, axis=-1)             # [b,T]
        gate = jnp.take_along_axis(probs, best[..., None], -1)[..., 0]
        mine = (best == me).astype(x.dtype)           # routed to my expert
        h = activation(x @ w1[0] + b1[0])
        y = (h @ w2[0] + b2[0]) * (gate * mine)[..., None]
        y = jax.lax.psum(y, axis)
        # Switch aux loss: n * sum_e (token fraction to e) * (mean prob e)
        frac = jax.lax.psum(
            jnp.mean(mine) * jax.nn.one_hot(me, n_exp), axis)
        mean_p = jnp.mean(probs, axis=(0, 1))
        if batch_axis:
            frac = jax.lax.pmean(frac, batch_axis)
            mean_p = jax.lax.pmean(mean_p, batch_axis)
        aux = n_exp * jnp.sum(frac * mean_p)
        return y, aux

    return fwd(x, params["Wg"], params["W1"], params["b1"],
               params["W2"], params["b2"])
