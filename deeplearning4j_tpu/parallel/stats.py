"""Distributed-training phase statistics + HTML timeline export.

Equivalent of deeplearning4j-scaleout spark/api/stats/
CommonSparkTrainingStats.java and spark/stats/StatsUtils.exportStatsAsHtml
(SURVEY §2.5 "Spark stats"): wall-clock accounting of the training phases
(data feed / ETL vs device step vs host sync) with an HTML timeline export.

On TPU the phases differ from Spark's (no broadcast/repartition), so the
categories are the ones that matter here: etl (host batch prep + transfer),
step (jitted train step), listener (host callbacks).
"""

from __future__ import annotations

import html
import json
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class PhaseEvent:
    phase: str
    start: float
    duration_ms: float


@dataclass
class TrainingStats:
    """Collects (phase, start, duration) events
    (ref: CommonSparkTrainingStats collects per-phase timing lists).

    Every completed phase ALSO lands in the process-wide metrics registry
    (`dl4jtpu_span_seconds{span=<phase>}`) so ParallelWrapper timings show
    up at /metrics alongside the fit-loop spans; set `registry` to target
    a non-global MetricsRegistry."""
    events: List[PhaseEvent] = field(default_factory=list)
    registry: Optional[object] = None
    _open: Dict[str, float] = field(default_factory=dict)

    def start_phase(self, phase: str) -> None:
        self._open[phase] = time.perf_counter()

    def end_phase(self, phase: str) -> None:
        t0 = self._open.pop(phase, None)
        if t0 is not None:
            now = time.perf_counter()
            self.events.append(PhaseEvent(phase, t0, (now - t0) * 1000.0))
            from deeplearning4j_tpu.monitoring.tracing import record_span
            record_span(phase, now - t0, self.registry)

    class _Timer:
        def __init__(self, stats, phase):
            self.stats, self.phase = stats, phase

        def __enter__(self):
            self.stats.start_phase(self.phase)

        def __exit__(self, *exc):
            self.stats.end_phase(self.phase)

    def time_phase(self, phase: str) -> "TrainingStats._Timer":
        return TrainingStats._Timer(self, phase)

    def summary(self) -> Dict[str, Dict[str, float]]:
        agg: Dict[str, List[float]] = defaultdict(list)
        for e in self.events:
            agg[e.phase].append(e.duration_ms)
        out = {}
        for phase, ds in agg.items():
            ds_sorted = sorted(ds)
            n = len(ds_sorted)
            out[phase] = {
                "count": n,
                "total_ms": sum(ds_sorted),
                "mean_ms": sum(ds_sorted) / n,
                "p50_ms": ds_sorted[n // 2],
                "max_ms": ds_sorted[-1],
            }
        return out

    def export_html(self, path: str) -> None:
        """Standalone HTML: per-phase summary table + SVG timeline
        (ref: StatsUtils.exportStatsAsHtml timeline chart)."""
        summ = self.summary()
        colors = {"etl": "#fb8c00", "step": "#1976d2", "listener": "#43a047"}
        rows = "".join(
            f"<tr><td>{html.escape(p)}</td><td>{s['count']}</td>"
            f"<td>{s['total_ms']:.1f}</td><td>{s['mean_ms']:.2f}</td>"
            f"<td>{s['p50_ms']:.2f}</td><td>{s['max_ms']:.2f}</td></tr>"
            for p, s in sorted(summ.items()))
        svg = self._timeline_svg(colors)
        with open(path, "w") as f:
            f.write(f"""<!DOCTYPE html><html><head><title>Training stats</title>
<style>body{{font-family:sans-serif;margin:20px}}
table{{border-collapse:collapse;font-size:13px}}
td,th{{border:1px solid #ccc;padding:4px 10px;text-align:right}}
th{{background:#f4f4f4}}</style></head><body>
<h1>Training phase stats</h1>
<table><tr><th>phase</th><th>count</th><th>total ms</th><th>mean ms</th>
<th>p50 ms</th><th>max ms</th></tr>{rows}</table>
<h2>Timeline</h2>{svg}</body></html>""")

    def _timeline_svg(self, colors: Dict[str, str], width: int = 1000,
                      row_h: int = 26) -> str:
        if not self.events:
            return "<p>no events</p>"
        t0 = min(e.start for e in self.events)
        t1 = max(e.start + e.duration_ms / 1000.0 for e in self.events)
        span = max(t1 - t0, 1e-9)
        phases = sorted({e.phase for e in self.events})
        h = row_h * len(phases) + 30
        parts = [f'<svg width="{width}" height="{h}" '
                 f'xmlns="http://www.w3.org/2000/svg">']
        for ri, p in enumerate(phases):
            y = ri * row_h + 20
            parts.append(f'<text x="2" y="{y + 14}" font-size="12">'
                         f'{html.escape(p)}</text>')
            col = colors.get(p, "#8e24aa")
            for e in self.events:
                if e.phase != p:
                    continue
                x = 80 + (e.start - t0) / span * (width - 90)
                w = max(1.0, e.duration_ms / 1000.0 / span * (width - 90))
                parts.append(f'<rect x="{x:.1f}" y="{y}" width="{w:.1f}" '
                             f'height="{row_h - 6}" fill="{col}"/>')
        parts.append("</svg>")
        return "".join(parts)

    def to_json(self) -> str:
        return json.dumps({"events": [
            {"phase": e.phase, "start": e.start,
             "durationMs": e.duration_ms} for e in self.events]})
