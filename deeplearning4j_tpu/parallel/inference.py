"""Parallel / batched inference.

TPU-native equivalent of ParallelInference
(deeplearning4j-scaleout-parallelwrapper/.../ParallelInference.java:32-401):
the reference keeps per-device model replicas fed by an observable batching
queue; here ONE jitted forward serves the whole mesh — large batches are
sharded across devices (XLA SPMD), and a background batching thread provides
the same dynamic request-coalescing (InferenceMode.BATCHED, :52) for many
small concurrent requests.

Serving robustness (resilience layer):

- **Deadlines**: ``output(x, timeout=s)`` bounds the request end-to-end
  on the host side — queue admission, coalescing wait, and result wait
  all draw from one budget; expiry raises ``InferenceTimeout`` and
  increments ``dl4jtpu_serving_deadline_exceeded_total``. The device
  dispatch itself is not preempted (XLA programs run to completion) —
  an abandoned request's result is simply dropped.
- **Queue-full policy**: ``queue_policy="block"`` (default — callers
  wait for space, bounded by their deadline) or ``"fail_fast"``
  (``ServingQueueFull`` immediately; the load-shedding mode a
  latency-SLO front end wants).
- **Health/readiness**: ``health()`` plus registry gauges
  ``dl4jtpu_serving_healthy`` / ``dl4jtpu_serving_ready`` /
  ``dl4jtpu_serving_queue_depth`` (scrape-time callbacks — a crashed
  worker flips them with no event needed) and request/error counters.
- **No hung callers**: a model exception fails every coalesced waiter
  with the original error; a dying worker thread fail-fasts everything
  queued; requests arriving after shutdown are refused.
- **Fleet-backed mode** (``replicas=[model2, ...]``): extra model
  replicas (identically parameterized — the serving-fleet homogeneity
  contract) each get their own dispatch lock and, in batched mode,
  their own serving worker draining the SHARED queue — coalesced
  batches run concurrently across replicas instead of serializing on
  one model lock, and a single crashed worker degrades capacity
  instead of failing the pool (fail-all happens only when the LAST
  worker exits). The generation-side analog is
  ``serving.fleet.FleetRouter``.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, List, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry)
from deeplearning4j_tpu.parallel.mesh import default_mesh
# canonical serving error types + metric names live in serving/ (shared
# with GenerationEngine); re-exported here for back-compat
from deeplearning4j_tpu.serving.errors import (  # noqa: F401
    InferenceTimeout, ServingQueueFull)
from deeplearning4j_tpu.serving.health import (  # noqa: F401
    SERVING_DEADLINE_EXCEEDED, SERVING_ERRORS, SERVING_HEALTHY,
    SERVING_QUEUE_DEPTH, SERVING_QUEUE_REJECTED, SERVING_READY,
    SERVING_REQUESTS, register_serving_metrics)

log = logging.getLogger(__name__)


class _Request:
    __slots__ = ("x", "event", "result", "abandoned")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.abandoned = False  # deadline expired; worker may skip it


class ParallelInference:
    """Batched multi-device serving (ref: ParallelInference.java).

    output() is thread-safe: concurrent callers' inputs are coalesced into
    one device batch (dynamic batching, ref InferenceMode.BATCHED) up to
    `max_batch_size`, run once, and scattered back.
    """

    def __init__(self, model, mesh=None, max_batch_size: int = 64,
                 queue_limit: int = 64, batch_timeout_ms: float = 2.0,
                 inference_mode: str = "batched",
                 queue_policy: str = "block",
                 registry: Optional[MetricsRegistry] = None,
                 replicas=()):
        if inference_mode not in ("batched", "sequential"):
            raise ValueError(
                f"inference_mode must be 'batched' or 'sequential', got "
                f"{inference_mode!r} (ref: ParallelInference.InferenceMode)")
        if queue_policy not in ("block", "fail_fast"):
            raise ValueError(f"queue_policy must be 'block' or 'fail_fast', "
                             f"got {queue_policy!r}")
        self.model = model
        # fleet-backed mode: model + replicas, each with its own lock
        # (and, batched, its own worker). Replica 0 is the primary —
        # output_direct() and all single-model back-compat paths use it.
        self._models = [model] + list(replicas)
        for m in self._models:
            if not m._initialized:
                m.init()
        self.mesh = mesh if mesh is not None else default_mesh()
        self.n_devices = int(np.prod(self.mesh.devices.shape))
        self.max_batch_size = max_batch_size
        self.batch_timeout = batch_timeout_ms / 1000.0
        self.inference_mode = inference_mode
        self.queue_policy = queue_policy
        self._registry = registry
        # stop signal is an Event (atomic, visible cross-thread), not a
        # bare bool mutated from the caller thread
        self._stop = threading.Event()
        # ONE lock PER MODEL serializes every touch of it: a wrapped
        # model is not thread-safe (output() mutates _jit_cache and
        # _rng), and callers may race the batching workers via
        # output_direct()/sequential mode. _seq_lock stays as the
        # primary's alias (pre-fleet name).
        self._locks = [threading.Lock() for _ in self._models]
        self._seq_lock = self._locks[0]
        self._rr = 0                       # sequential-mode round robin
        self._rr_lock = threading.Lock()
        if inference_mode == "batched":
            self._queue: "queue.Queue[_Request]" = \
                queue.Queue(maxsize=queue_limit)
            self._live_workers = len(self._models)
            self._workers = [
                threading.Thread(target=self._serve_loop, args=(i,),
                                 daemon=True)
                for i in range(len(self._models))]
            for w in self._workers:
                w.start()
            self._worker = self._workers[0]    # back-compat alias
        else:
            # SEQUENTIAL (ParallelInference.java:136-216): each request
            # runs immediately, one at a time — no coalescing window, so
            # single-stream latency is one dispatch, not dispatch+timeout
            self._queue = None
            self._workers = []
            self._worker = None
        self._register_health_gauges()

    # ------------------------------------------------------------------
    # health / readiness
    # ------------------------------------------------------------------
    def is_healthy(self) -> bool:
        """The serving loop can still produce results (fleet-backed:
        at least one replica worker is still draining the queue)."""
        if self._stop.is_set():
            return False
        if self.inference_mode == "sequential":
            return True
        return any(w.is_alive() for w in self._workers)

    def is_ready(self) -> bool:
        """Healthy AND able to admit a request right now."""
        if not self.is_healthy():
            return False
        return self._queue is None or not self._queue.full()

    def queue_depth(self) -> int:
        return 0 if self._queue is None else self._queue.qsize()

    def health(self) -> dict:
        """Readiness-probe payload (the UIServer /metrics companion)."""
        out = {"healthy": self.is_healthy(), "ready": self.is_ready(),
               "queue_depth": self.queue_depth(),
               "mode": self.inference_mode,
               "replicas": len(self._models)}
        if self.inference_mode == "batched":
            out["live_workers"] = sum(
                1 for w in self._workers if w.is_alive())
        return out

    def _register_health_gauges(self) -> None:
        # the shared serving-telemetry path (serving/health.py): counter
        # handles resolved ONCE (the hot path must not re-enter the
        # registry's get-or-create lock per request) and weakref
        # scrape-time health gauges — one code path with GenerationEngine
        self._counter_handles = register_serving_metrics(
            self, type(self.model).__name__, self._registry)

    def _counter(self, metric: str) -> None:
        self._counter_handles[metric].inc()

    # ------------------------------------------------------------------
    def _run_batch(self, x: np.ndarray, deadline: Optional[float] = None,
                   idx: int = 0):
        n = x.shape[0]
        rem = n % self.n_devices
        if rem:
            pad = self.n_devices - rem
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)
        sh = NamedSharding(self.mesh, P("data", *([None] * (x.ndim - 1))))
        lock = self._locks[idx]
        if deadline is None:
            acquired = lock.acquire()
        else:
            # the lock wait (another caller's dispatch) draws from the
            # request budget; the device program itself runs to completion
            acquired = lock.acquire(
                timeout=max(0.0, deadline - time.monotonic()))
        if not acquired:
            self._counter(SERVING_DEADLINE_EXCEEDED)
            raise InferenceTimeout(
                "deadline expired waiting for the model lock")
        try:
            # request batches arrive as host arrays from submitters; the
            # sharded put IS the request's one staging step, not a
            # missed prefetch (there is no iterator to prefetch from)
            # tpulint: disable=device-transfer-in-hot-loop
            out = self._models[idx].output(jax.device_put(x, sh))
        finally:
            lock.release()
        # host materialization is the serving response contract here, not
        # a pipeline stall: the caller blocks on this result by design
        # tpulint: disable=host-sync-in-hot-loop
        return np.asarray(out)[:n]

    def _serve_loop(self, idx: int = 0):
        try:
            while not self._stop.is_set():
                try:
                    first = self._queue.get(timeout=0.1)
                except queue.Empty:
                    continue
                batch: List[_Request] = [first]
                total = first.x.shape[0]
                # coalesce whatever arrives within the timeout window
                deadline = self.batch_timeout
                while total < self.max_batch_size:
                    try:
                        nxt = self._queue.get(timeout=deadline)
                        batch.append(nxt)
                        total += nxt.x.shape[0]
                    except queue.Empty:
                        break
                # deadline-expired waiters are gone; don't burn a
                # dispatch on a batch nobody is waiting for
                batch = [r for r in batch if not r.abandoned]
                if not batch:
                    continue
                try:
                    # assembly INSIDE the guard: one malformed request
                    # (mismatched shapes) fails ITS batch's waiters, it
                    # must not kill the serving loop for everyone after
                    x = np.concatenate([r.x for r in batch], axis=0)
                    out = self._run_batch(x, idx=idx)
                    s = 0
                    for r in batch:
                        k = r.x.shape[0]
                        r.result = out[s:s + k]
                        s += k
                except BaseException as e:  # propagate to all waiters
                    self._counter(SERVING_ERRORS)
                    for r in batch:
                        r.result = e
                        r.event.set()
                    if not isinstance(e, Exception):
                        # a worker-killing signal: die AFTER answering
                        # this batch's waiters — with replica workers
                        # still alive they would otherwise block
                        # forever on a batch nobody holds
                        raise
                    continue
                for r in batch:
                    r.event.set()
        finally:
            # worker exiting for ANY reason (shutdown or crash): with
            # replica workers still draining the queue this is a
            # capacity loss, not an outage — only the LAST worker out
            # fail-fasts the leftovers (nobody would answer them)
            with self._rr_lock:
                self._live_workers -= 1
                last = self._live_workers <= 0
            if last:
                self._stop.set()
                self._fail_pending(RuntimeError(
                    "ParallelInference worker stopped"))

    def _fail_pending(self, exc: Exception) -> None:
        if self._queue is None:
            return
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            req.result = exc
            req.event.set()

    # ------------------------------------------------------------------
    def output(self, x, timeout: Optional[float] = None) -> np.ndarray:
        """Synchronous inference through the batching queue, or immediate
        one-at-a-time execution in SEQUENTIAL mode
        (ref: ParallelInference.output :97-121).

        ``timeout`` (seconds) is the per-request deadline; None preserves
        the wait-forever contract. On expiry raises InferenceTimeout."""
        x = np.asarray(x)
        self._counter(SERVING_REQUESTS)
        deadline = None if timeout is None else time.monotonic() + timeout
        if self.inference_mode == "sequential":
            if self._stop.is_set():
                raise RuntimeError("ParallelInference shut down")
            with self._rr_lock:
                # fleet-backed: spread immediate dispatches round-robin
                # over the replica locks so concurrent sequential
                # callers don't serialize on one model
                idx = self._rr % len(self._models)
                self._rr += 1
            try:
                return self._run_batch(x, deadline, idx=idx)
            except InferenceTimeout:
                raise  # already counted as a deadline, not a model error
            except Exception:
                self._counter(SERVING_ERRORS)
                raise
        if self._stop.is_set():
            raise RuntimeError("ParallelInference shut down")
        req = _Request(x)
        self._enqueue(req, deadline)
        # stop-aware wait: a request enqueued after shutdown()'s drain pass
        # has no worker left to answer it, so don't block on the event
        # unconditionally — the poll only ever loops on a dead server
        # poll clamped to the remaining budget: a 20ms deadline must be
        # enforced at ~20ms, not at the end of a full 200ms poll
        while not req.event.wait(
                0.2 if deadline is None else
                max(0.001, min(0.2, deadline - time.monotonic()))):
            if deadline is not None and time.monotonic() >= deadline:
                req.abandoned = True
                self._counter(SERVING_DEADLINE_EXCEEDED)
                raise InferenceTimeout(
                    f"no result within {timeout:g}s "
                    f"(queue_depth={self.queue_depth()})")
            # give up only when EVERY worker is GONE: during a graceful
            # shutdown (_stop set, workers draining in-flight batches)
            # the result is still coming and must be delivered
            if not any(w.is_alive() for w in self._workers) \
                    and not req.event.is_set():
                raise RuntimeError("ParallelInference shut down")
        if isinstance(req.result, BaseException):
            raise req.result
        return req.result

    def _enqueue(self, req: _Request, deadline: Optional[float]) -> None:
        if self.queue_policy == "fail_fast":
            try:
                self._queue.put_nowait(req)
            except queue.Full:
                self._counter(SERVING_QUEUE_REJECTED)
                raise ServingQueueFull(
                    f"batching queue at limit "
                    f"({self._queue.maxsize} requests)") from None
            return
        # block policy: wait for space, bounded by the deadline (forever
        # with none — the legacy contract)
        while True:
            budget = 0.2 if deadline is None else \
                min(0.2, deadline - time.monotonic())
            if budget <= 0:
                self._counter(SERVING_DEADLINE_EXCEEDED)
                raise InferenceTimeout(
                    "deadline expired waiting for queue space")
            try:
                self._queue.put(req, timeout=budget)
                return
            except queue.Full:
                if self._stop.is_set():
                    raise RuntimeError("ParallelInference shut down") \
                        from None

    def output_direct(self, x) -> np.ndarray:
        """Bypass the queue: one big sharded batch (for bulk scoring)."""
        return self._run_batch(np.asarray(x))

    def shutdown(self):
        """Stop the batching worker and wait for it to drain (bounded by
        one poll interval + the in-flight batch). Requests still queued
        when the worker exits are failed over to their waiters — nobody
        blocks forever on a dead server."""
        self._stop.set()
        for w in self._workers:
            if w.is_alive():
                w.join(timeout=5.0)
        self._fail_pending(RuntimeError("ParallelInference shut down"))
