"""Parallel / batched inference.

TPU-native equivalent of ParallelInference
(deeplearning4j-scaleout-parallelwrapper/.../ParallelInference.java:32-401):
the reference keeps per-device model replicas fed by an observable batching
queue; here ONE jitted forward serves the whole mesh — large batches are
sharded across devices (XLA SPMD), and a background batching thread provides
the same dynamic request-coalescing (InferenceMode.BATCHED, :52) for many
small concurrent requests.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, List, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import default_mesh


class _Request:
    __slots__ = ("x", "event", "result")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None


class ParallelInference:
    """Batched multi-device serving (ref: ParallelInference.java).

    output() is thread-safe: concurrent callers' inputs are coalesced into
    one device batch (dynamic batching, ref InferenceMode.BATCHED) up to
    `max_batch_size`, run once, and scattered back.
    """

    def __init__(self, model, mesh=None, max_batch_size: int = 64,
                 queue_limit: int = 64, batch_timeout_ms: float = 2.0,
                 inference_mode: str = "batched"):
        if inference_mode not in ("batched", "sequential"):
            raise ValueError(
                f"inference_mode must be 'batched' or 'sequential', got "
                f"{inference_mode!r} (ref: ParallelInference.InferenceMode)")
        self.model = model
        if not model._initialized:
            model.init()
        self.mesh = mesh if mesh is not None else default_mesh()
        self.n_devices = int(np.prod(self.mesh.devices.shape))
        self.max_batch_size = max_batch_size
        self.batch_timeout = batch_timeout_ms / 1000.0
        self.inference_mode = inference_mode
        # stop signal is an Event (atomic, visible cross-thread), not a
        # bare bool mutated from the caller thread
        self._stop = threading.Event()
        # ONE lock serializes every model touch: the wrapped model is not
        # thread-safe (output() mutates _jit_cache and _rng), and callers
        # may race the batching worker via output_direct()/sequential mode
        self._seq_lock = threading.Lock()
        if inference_mode == "batched":
            self._queue: "queue.Queue[_Request]" = \
                queue.Queue(maxsize=queue_limit)
            self._worker = threading.Thread(target=self._serve_loop,
                                            daemon=True)
            self._worker.start()
        else:
            # SEQUENTIAL (ParallelInference.java:136-216): each request
            # runs immediately, one at a time — no coalescing window, so
            # single-stream latency is one dispatch, not dispatch+timeout
            self._queue = None
            self._worker = None

    # ------------------------------------------------------------------
    def _run_batch(self, x: np.ndarray):
        n = x.shape[0]
        rem = n % self.n_devices
        if rem:
            pad = self.n_devices - rem
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)
        sh = NamedSharding(self.mesh, P("data", *([None] * (x.ndim - 1))))
        with self._seq_lock:
            out = self.model.output(jax.device_put(x, sh))
        # host materialization is the serving response contract here, not
        # a pipeline stall: the caller blocks on this result by design
        return np.asarray(out)[:n]

    def _serve_loop(self):
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch: List[_Request] = [first]
            total = first.x.shape[0]
            # coalesce whatever arrives within the timeout window
            deadline = self.batch_timeout
            while total < self.max_batch_size:
                try:
                    nxt = self._queue.get(timeout=deadline)
                    batch.append(nxt)
                    total += nxt.x.shape[0]
                except queue.Empty:
                    break
            x = np.concatenate([r.x for r in batch], axis=0)
            try:
                out = self._run_batch(x)
                s = 0
                for r in batch:
                    k = r.x.shape[0]
                    r.result = out[s:s + k]
                    s += k
            except Exception as e:  # propagate to all waiters
                for r in batch:
                    r.result = e
            for r in batch:
                r.event.set()

    # ------------------------------------------------------------------
    def output(self, x) -> np.ndarray:
        """Synchronous inference through the batching queue, or immediate
        one-at-a-time execution in SEQUENTIAL mode
        (ref: ParallelInference.output :97-121)."""
        x = np.asarray(x)
        if self.inference_mode == "sequential":
            return self._run_batch(x)  # _run_batch holds the model lock
        if self._stop.is_set():
            raise RuntimeError("ParallelInference shut down")
        req = _Request(x)
        self._queue.put(req)
        # stop-aware wait: a request enqueued after shutdown()'s drain pass
        # has no worker left to answer it, so don't block on the event
        # unconditionally — the poll only ever loops on a dead server
        while not req.event.wait(0.2):
            if self._stop.is_set() and not (
                    self._worker is not None and self._worker.is_alive()):
                raise RuntimeError("ParallelInference shut down")
        if isinstance(req.result, Exception):
            raise req.result
        return req.result

    def output_direct(self, x) -> np.ndarray:
        """Bypass the queue: one big sharded batch (for bulk scoring)."""
        return self._run_batch(np.asarray(x))

    def shutdown(self):
        """Stop the batching worker and wait for it to drain (bounded by
        one poll interval + the in-flight batch). Requests still queued
        when the worker exits are failed over to their waiters — nobody
        blocks forever on a dead server."""
        self._stop.set()
        if self._worker is not None and self._worker.is_alive():
            self._worker.join(timeout=5.0)
        if self._queue is not None:
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                req.result = RuntimeError("ParallelInference shut down")
                req.event.set()
