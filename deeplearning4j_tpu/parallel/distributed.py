"""Multi-host distributed backend.

TPU-native replacement for the reference's multi-node stacks (SURVEY §2.5
strategies 3-4): Spark parameter averaging (ParameterAveragingTrainingMaster)
and the Aeron UDP VoidParameterServer (SharedTrainingMaster/
SharedTrainingWrapper.java:206-244, SilentTrainingDriver threshold-compressed
async updates).

On TPU both collapse to the same synchronous SPMD program: `jax.distributed`
brings up the gRPC coordination service over DCN; every host runs the SAME
jitted train step over a global mesh whose "data" axis spans all chips in the
job; XLA routes gradient allreduce over ICI within a slice and DCN across
slices. Gradient compression (EncodingHandler thresholdEncode) is dropped by
design — dense bf16/fp32 allreduce over ICI is faster than the reference's
sparse codec over UDP (BASELINE.json north star).

Spark's remaining role — data sharding — maps to per-host input pipelines:
each host feeds only its local shard of the global batch
(`host_local_batch` / `host_shard_bounds`), like Spark executors reading
their RDD partitions.

**Elastic lifecycle** (resilience/elastic.py + parallel/elastic.py): the
coordination service reacts to a lost peer by *terminating every other
task* — the exact cascade an elastic trainer must survive. Three
primitives here make the runtime survivable:

- ``elastic_initialize``: bring up jax.distributed with jax's own
  failure detector stood down (heartbeat windows pushed out to hours via
  the internal ``State.initialize`` knobs the public wrapper hides) so
  the lease ledger — not the gRPC service — owns failure detection.
- ``abandon_distributed``: detach from a DEAD generation without ever
  calling ``client.shutdown()`` (it blocks on a shutdown barrier the
  dead peer will never reach, and a clean shutdown attempt can itself
  trigger the terminate-everyone error path). The old client/service are
  parked on a module-level zombie list so their destructors never run;
  the distributed State fields are reset to single-process.
- ``reset_backend``: drop every live backend + compiled trace and flip
  the CPU collectives implementation (gloo needs a distributed client;
  a world-of-one must build without one) so the next jax call builds a
  fresh client against the CURRENT distributed state.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np

log = logging.getLogger(__name__)

_ENV_COORD = "JAX_COORDINATOR_ADDRESS"
_ENV_NPROC = "JAX_NUM_PROCESSES"
_ENV_PID = "JAX_PROCESS_ID"


@dataclass
class VoidConfiguration:
    """Connection info for the coordination service — name kept for API
    parity with the reference's VoidConfiguration (SharedTrainingMaster.java:58),
    but it configures jax.distributed (gRPC over DCN), not Aeron UDP."""

    coordinator_address: Optional[str] = None  # "host:port" of process 0
    num_processes: int = 1
    process_id: int = 0
    local_device_ids: Optional[Sequence[int]] = None

    @classmethod
    def from_env(cls) -> "VoidConfiguration":
        """Explicit parse of the standard jax.distributed env vars
        (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).

        All three unset → a single-process configuration. Anything else
        must be COMPLETE and VALID: a partial or malformed set raises
        ``ValueError`` naming exactly what is wrong, instead of the old
        silent single-process fallback that turned a typo'd coordinator
        address into a 1/N-throughput job that "worked"."""
        raw = {k: os.environ.get(k)
               for k in (_ENV_COORD, _ENV_NPROC, _ENV_PID)}
        present = {k: v for k, v in raw.items() if v not in (None, "")}
        if not present:
            return cls()
        missing = [k for k, v in raw.items() if v in (None, "")]
        if missing:
            raise ValueError(
                f"partial jax.distributed environment: "
                f"{sorted(present)} set but {sorted(missing)} unset — "
                f"set all three of {_ENV_COORD}/{_ENV_NPROC}/{_ENV_PID} "
                f"or none")
        coord = raw[_ENV_COORD]
        host, sep, port = coord.rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ValueError(
                f"{_ENV_COORD}={coord!r} is not host:port")
        try:
            nproc = int(raw[_ENV_NPROC])
        except ValueError:
            raise ValueError(
                f"{_ENV_NPROC}={raw[_ENV_NPROC]!r} is not an integer"
            ) from None
        try:
            pid = int(raw[_ENV_PID])
        except ValueError:
            raise ValueError(
                f"{_ENV_PID}={raw[_ENV_PID]!r} is not an integer"
            ) from None
        if nproc < 1:
            raise ValueError(f"{_ENV_NPROC}={nproc} must be >= 1")
        if not 0 <= pid < nproc:
            raise ValueError(
                f"{_ENV_PID}={pid} out of range for "
                f"{_ENV_NPROC}={nproc} (need 0 <= id < processes)")
        return cls(coordinator_address=coord, num_processes=nproc,
                   process_id=pid)


_initialized = False


def initialize(config: Optional[VoidConfiguration] = None) -> None:
    """Bring up the multi-host runtime (ref equivalent: VoidParameterServer
    .init at SharedTrainingWrapper.java:206-214 / Spark context setup).

    With config=None, settings come from the standard env vars (parsed
    and VALIDATED by ``VoidConfiguration.from_env`` — a partial or
    malformed set raises instead of silently running single-process) or
    the cloud TPU metadata that jax.distributed auto-detects.
    """
    global _initialized
    if _initialized:
        return
    if config is None or config.coordinator_address is None:
        if config is None:
            config = VoidConfiguration.from_env()  # raises on bad env
        if config.coordinator_address is None and _on_cloud_tpu():
            try:
                jax.distributed.initialize()
                _initialized = True
            except (ValueError, RuntimeError) as e:
                # TPU-ish env vars present but no resolvable coordinator
                # (e.g. a single tunneled chip) — run single-process
                log.info("multi-host auto-init unavailable (%s); "
                         "single-process mode", e)
            return
        if config.coordinator_address is None:
            log.info("single-process mode (no coordinator configured)")
            return
    jax.distributed.initialize(
        coordinator_address=config.coordinator_address,
        num_processes=config.num_processes,
        process_id=config.process_id,
        local_device_ids=config.local_device_ids,
    )
    _initialized = True


def _on_cloud_tpu() -> bool:
    return bool(os.environ.get("TPU_WORKER_HOSTNAMES") or
                os.environ.get("TPU_NAME"))


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def is_initialized() -> bool:
    return _initialized


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def global_mesh(axis_names: Sequence[str] = ("data",),
                shape: Optional[Sequence[int]] = None):
    """Mesh over ALL devices in the job (every host's chips). With the
    default shape, the "data" axis spans the whole pod — the multi-host
    analogue of SparkDl4jMultiLayer's cluster-wide data parallelism."""
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    return make_mesh(shape=shape, axis_names=axis_names, devices=jax.devices())


def host_local_batch(global_batch_size: int,
                     rank: Optional[int] = None,
                     world: Optional[int] = None,
                     strict: bool = False) -> int:
    """Per-host share of a global batch (Spark-executor-partition
    analogue).

    Elastic world sizes rarely divide the global batch evenly (a 1024
    batch over a 3-survivor generation), so the default split is the
    LARGEST EVEN SPLIT with the remainder assigned one extra example to
    the lowest ranks: ``base = g // world`` everywhere, ranks
    ``0..(g % world)-1`` take ``base + 1``. Every example is consumed,
    shards differ by at most one, and the assignment is a pure function
    of (g, rank, world) — deterministic across re-meshes, which is what
    lets a survivor recompute its shard from the generation record
    alone. ``strict=True`` restores the pre-elastic contract: raise on
    any non-divisible batch (jobs that size batches to the pod and want
    loud failure when that invariant breaks).

    ``rank``/``world`` default to the live runtime (call-time reads —
    module-scope snapshots of either go stale after a re-mesh; tpulint
    rule ``stale-world-snapshot``)."""
    n = jax.process_count() if world is None else int(world)
    r = jax.process_index() if rank is None else int(rank)
    if not 0 <= r < n:
        raise ValueError(f"rank {r} out of range for world {n}")
    g = int(global_batch_size)
    rem = g % n
    if rem and strict:
        raise ValueError(f"global batch {g} not divisible by "
                         f"{n} processes")
    return g // n + (1 if r < rem else 0)


def host_shard_bounds(global_batch_size: int,
                      rank: Optional[int] = None,
                      world: Optional[int] = None,
                      strict: bool = False) -> Tuple[int, int]:
    """Contiguous ``[lo, hi)`` row range of this host's shard under the
    ``host_local_batch`` split: lo = sum of the shard sizes below this
    rank. Shards tile the global batch exactly (no gaps, no overlap) for
    every (batch, world) combination."""
    n = jax.process_count() if world is None else int(world)
    r = jax.process_index() if rank is None else int(rank)
    sizes = [host_local_batch(global_batch_size, rank=i, world=n,
                              strict=strict) for i in range(r + 1)]
    hi = sum(sizes)
    return hi - sizes[-1], hi


def make_global_array(local_batch: np.ndarray, mesh, spec=None):
    """Assemble a globally-sharded array from per-host local shards
    (jax.make_array_from_process_local_data) — the DCN-era equivalent of
    Spark broadcasting/partitioning DataSets to executors."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, spec if spec is not None
                             else P("data", *([None] * (local_batch.ndim - 1))))
    return jax.make_array_from_process_local_data(sharding, local_batch)


# ---------------------------------------------------------------------------
# elastic runtime lifecycle (resilience/elastic.py's jax-facing half)
# ---------------------------------------------------------------------------
#: abandoned coordination clients/services from dead generations. Their
#: destructors are never safe to run (a DistributedRuntimeClient
#: destructor attempts the shutdown barrier a dead peer will never
#: reach), so they are parked here for the life of the process. Elastic
#: worker processes should exit via os._exit so interpreter teardown
#: never walks this list.
_zombie_runtimes: List[object] = []


def elastic_initialize(coordinator_address: str, num_processes: int,
                       process_id: int,
                       initialization_timeout: float = 60.0,
                       heartbeat_interval_seconds: int = 100,
                       max_missing_heartbeats: int = 100) -> None:
    """``jax.distributed.initialize`` with jax's own failure detector
    stood down.

    The default coordination-service reaction to a missed heartbeat is
    to TERMINATE every remaining task (client.h: "Terminating process
    because the JAX distributed service detected fatal errors") — the
    opposite of elastic. The public ``jax.distributed.initialize``
    doesn't expose the heartbeat knobs, so this goes through the
    internal ``State.initialize`` and pushes the detection horizon out
    to ``interval * max_missing`` seconds (default ~2.7 hours): the
    lease ledger detects a lost host in seconds and tears the runtime
    down long before jax's own detector ever fires."""
    global _initialized
    from jax._src import distributed as _jdist
    if _cpu_platform():
        # the CPU backend's cross-process collectives implementation
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    _jdist.global_state.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes), process_id=int(process_id),
        local_device_ids=None,
        cluster_detection_method="deactivate",
        initialization_timeout=int(initialization_timeout),
        service_heartbeat_interval_seconds=int(heartbeat_interval_seconds),
        service_max_missing_heartbeats=int(max_missing_heartbeats),
        client_heartbeat_interval_seconds=int(heartbeat_interval_seconds),
        client_max_missing_heartbeats=int(max_missing_heartbeats))
    _initialized = True


def _cpu_platform() -> bool:
    try:
        return jax.config.jax_platforms in ("cpu",)
    except AttributeError:  # pragma: no cover - very old jax
        return False


def abandon_distributed() -> None:
    """Detach from a DEAD generation's coordination runtime without
    shutting it down.

    ``client.shutdown()`` blocks on the shutdown barrier until every
    registered task arrives — a SIGKILLed peer never will — and error
    propagation during the wait can terminate this process. Instead the
    live client/service objects are parked on the zombie list (keeping
    them referenced so no destructor ever runs) and the distributed
    State is reset to single-process, so the next backend build sees a
    clean world. Pair with ``reset_backend``."""
    global _initialized
    from jax._src import distributed as _jdist
    state = _jdist.global_state
    if state.client is not None:
        _zombie_runtimes.append(state.client)
    if state.service is not None:
        _zombie_runtimes.append(state.service)
    state.client = None
    state.service = None
    state.preemption_sync_manager = None
    state.process_id = 0
    state.num_processes = 1
    state.coordinator_address = None
    _initialized = False


def reset_backend(collectives: Optional[str] = None) -> None:
    """Drop every live backend, compiled trace, and device array binding
    so the next jax call rebuilds against the CURRENT distributed state.

    ``collectives`` sets ``jax_cpu_collectives_implementation`` first
    ("gloo" before re-joining a multi-process world, "none" before
    running world-of-one: the gloo CPU client refuses to build without a
    distributed client). Every jax.Array created before the reset is
    dead after it — restore state from host copies (the committed
    checkpoint) before touching the mesh again."""
    if collectives is not None and _cpu_platform():
        jax.config.update("jax_cpu_collectives_implementation",
                          collectives)
    import jax.extend.backend as _xb
    _xb.clear_backends()
    jax.clear_caches()


_teardown_lock = threading.Lock()


def teardown_dead_generation() -> None:
    """The survivor-side teardown: abandon the dead generation's
    coordination runtime and reset to a single-process CPU/TPU world.
    Idempotent; safe to call with a peer hung mid-collective (nothing
    here blocks on remote state)."""
    with _teardown_lock:
        abandon_distributed()
        reset_backend(collectives="none")
