"""Multi-host distributed backend.

TPU-native replacement for the reference's multi-node stacks (SURVEY §2.5
strategies 3-4): Spark parameter averaging (ParameterAveragingTrainingMaster)
and the Aeron UDP VoidParameterServer (SharedTrainingMaster/
SharedTrainingWrapper.java:206-244, SilentTrainingDriver threshold-compressed
async updates).

On TPU both collapse to the same synchronous SPMD program: `jax.distributed`
brings up the gRPC coordination service over DCN; every host runs the SAME
jitted train step over a global mesh whose "data" axis spans all chips in the
job; XLA routes gradient allreduce over ICI within a slice and DCN across
slices. Gradient compression (EncodingHandler thresholdEncode) is dropped by
design — dense bf16/fp32 allreduce over ICI is faster than the reference's
sparse codec over UDP (BASELINE.json north star).

Spark's remaining role — data sharding — maps to per-host input pipelines:
each host feeds only its local shard of the global batch
(`host_local_batch`), like Spark executors reading their RDD partitions.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np

log = logging.getLogger(__name__)


@dataclass
class VoidConfiguration:
    """Connection info for the coordination service — name kept for API
    parity with the reference's VoidConfiguration (SharedTrainingMaster.java:58),
    but it configures jax.distributed (gRPC over DCN), not Aeron UDP."""

    coordinator_address: Optional[str] = None  # "host:port" of process 0
    num_processes: int = 1
    process_id: int = 0
    local_device_ids: Optional[Sequence[int]] = None


_initialized = False


def initialize(config: Optional[VoidConfiguration] = None) -> None:
    """Bring up the multi-host runtime (ref equivalent: VoidParameterServer
    .init at SharedTrainingWrapper.java:206-214 / Spark context setup).

    With config=None, settings come from the standard env vars
    (JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES, JAX_PROCESS_ID) or the
    cloud TPU metadata that jax.distributed auto-detects.
    """
    global _initialized
    if _initialized:
        return
    if config is None or config.coordinator_address is None:
        if os.environ.get("JAX_COORDINATOR_ADDRESS") or _on_cloud_tpu():
            try:
                jax.distributed.initialize()
                _initialized = True
            except (ValueError, RuntimeError) as e:
                # TPU-ish env vars present but no resolvable coordinator
                # (e.g. a single tunneled chip) — run single-process
                log.info("multi-host auto-init unavailable (%s); "
                         "single-process mode", e)
        else:
            log.info("single-process mode (no coordinator configured)")
        return
    jax.distributed.initialize(
        coordinator_address=config.coordinator_address,
        num_processes=config.num_processes,
        process_id=config.process_id,
        local_device_ids=config.local_device_ids,
    )
    _initialized = True


def _on_cloud_tpu() -> bool:
    return bool(os.environ.get("TPU_WORKER_HOSTNAMES") or
                os.environ.get("TPU_NAME"))


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def global_mesh(axis_names: Sequence[str] = ("data",),
                shape: Optional[Sequence[int]] = None):
    """Mesh over ALL devices in the job (every host's chips). With the
    default shape, the "data" axis spans the whole pod — the multi-host
    analogue of SparkDl4jMultiLayer's cluster-wide data parallelism."""
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    return make_mesh(shape=shape, axis_names=axis_names, devices=jax.devices())


def host_local_batch(global_batch_size: int) -> int:
    """Per-host share of a global batch (Spark-executor-partition analogue)."""
    n = jax.process_count()
    if global_batch_size % n:
        raise ValueError(f"global batch {global_batch_size} not divisible by "
                         f"{n} processes")
    return global_batch_size // n


def make_global_array(local_batch: np.ndarray, mesh, spec=None):
    """Assemble a globally-sharded array from per-host local shards
    (jax.make_array_from_process_local_data) — the DCN-era equivalent of
    Spark broadcasting/partitioning DataSets to executors."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, spec if spec is not None
                             else P("data", *([None] * (local_batch.ndim - 1))))
    return jax.make_array_from_process_local_data(sharding, local_batch)
