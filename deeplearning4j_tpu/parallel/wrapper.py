"""Data-parallel training over a device mesh.

TPU-native replacement for deeplearning4j-scaleout's ParallelWrapper
(deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java:58-898) and
its two training modes:

- TrainingMode.SHARED_GRADIENTS (:68, EncodedGradientsAccumulator /
  EncodingHandler threshold-compressed async exchange) → here the NORTH STAR
  (BASELINE.json): ONE jitted SPMD train step with the batch sharded over the
  mesh "data" axis and params replicated; XLA inserts a dense allreduce
  (psum) of gradients over ICI. No worker threads, no replicas, no
  compression — ICI bandwidth makes dense exchange faster than the
  reference's sparse codec path.

- TrainingMode.AVERAGING (:59-74, averageModels every averagingFrequency
  iters :251-257) → `shard_map` formulation: each mesh shard runs
  `averaging_frequency` LOCAL updater steps on its own microbatches
  (lax.scan), then params/updater-state are psum-averaged. Kept for parity
  testing (the reference invariant
  TestCompareParameterAveragingSparkVsSingleMachine: freq=1 averaging ==
  single-machine result holds here exactly for SGD).

The reference's worker thread pool, device pinning (attachThreadToDevice
:137) and MagicQueue feeding disappear: SPMD partitioning is the scheduler.
"""

from __future__ import annotations

import logging
import time
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.util.jax_compat import shard_map
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator, AsyncDataSetIterator
from deeplearning4j_tpu.monitoring.listener import (
    finalize_fit_telemetry, maybe_record_fit_iteration)
from deeplearning4j_tpu.nn.updater import normalize_gradients
from deeplearning4j_tpu.optimize.listeners import close_listeners
from deeplearning4j_tpu.parallel.mesh import default_mesh
from deeplearning4j_tpu.resilience.durable import (
    capture_cursor_pass, consume_restored_cursor, dispatch_boundary)
from deeplearning4j_tpu.resilience.sentinel import (
    apply_step, effective_policy, guard_updates, tree_finite)

log = logging.getLogger(__name__)


def _strip_rnn_state(state):
    """Remove per-batch RNN carries (h/c) so pytree structure is stable
    across shard_map in/out specs."""
    return {k: {kk: vv for kk, vv in v.items() if kk not in ("h", "c")}
            if isinstance(v, dict) else v for k, v in state.items()}


class ParallelWrapper:
    """Multi-device trainer wrapping a MultiLayerNetwork or ComputationGraph
    (ref: ParallelWrapper.Builder / fit :468)."""

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 training_mode: str = "allreduce",
                 averaging_frequency: int = 5,
                 prefetch_buffer: int = 2,
                 report_score_after_averaging: bool = True,
                 collect_stats: bool = False,
                 steps_per_dispatch: int = 1,
                 device_prefetch: bool = False):
        self.model = model
        self.mesh = mesh if mesh is not None else default_mesh()
        self.training_mode = training_mode
        self.averaging_frequency = max(1, averaging_frequency)
        self.prefetch_buffer = prefetch_buffer
        #: allreduce mode: fuse K same-shape batches into one lax.scan
        #: dispatch of the wrapped model's scan train step (SPMD: batch
        #: axis 1 sharded over the mesh). Epoch tails fall back to the
        #: per-batch allreduce step.
        self.steps_per_dispatch = max(1, int(steps_per_dispatch))
        #: replace the host-side AsyncDataSetIterator stage with a
        #: DevicePrefetchIterator that lands batches PRE-SHARDED on the
        #: mesh (NamedSharding over "data"), so the H2D copy overlaps
        #: compute instead of happening inside the fit step.
        self.device_prefetch = bool(device_prefetch)
        self.n_devices = int(np.prod(self.mesh.devices.shape))
        self._jit_cache: Dict[Any, Any] = {}
        self._warned_small_batch = False
        self._warned_remainder_drop = False
        # phase timing (ref: CommonSparkTrainingStats role)
        self.stats = None
        if collect_stats:
            from deeplearning4j_tpu.parallel.stats import TrainingStats
            self.stats = TrainingStats()
        if not model._initialized:
            model.init()

    # ------------------------------------------------------------------
    def _host_trim(self, arr):
        """Host half of batch sharding: make the batch divisible by
        n_devices. Non-divisible remainders are DROPPED (the reference
        drops/queues leftovers rather than duplicating examples —
        duplicate-padding would silently over-weight the repeated sample in
        the gradient). Batches smaller than the mesh still pad by repetition
        as the only way to occupy every device; that case is logged once."""
        # host-only by caller contract: _shard_batch/_shard_stack return
        # device (prefetched) arrays untouched before reaching this, so
        # this asarray never sees a device value
        # tpulint: disable=host-sync-in-hot-loop
        arr = np.asarray(arr)
        n = arr.shape[0]
        rem = n % self.n_devices
        if rem:
            if n >= self.n_devices:
                if not self._warned_remainder_drop:
                    log.warning(
                        "batch of %d not divisible by %d devices: dropping "
                        "the %d trailing example(s) each step (size batches "
                        "to a multiple of the mesh to use all data)",
                        n, self.n_devices, rem)
                    self._warned_remainder_drop = True
                arr = arr[:n - rem]
            else:
                if not self._warned_small_batch:
                    log.warning(
                        "batch of %d < %d devices: padding by repetition "
                        "(repeated examples are over-weighted this step)",
                        n, self.n_devices)
                    self._warned_small_batch = True
                pad = self.n_devices - n
                arr = np.concatenate(
                    [arr, np.repeat(arr[-1:], pad, axis=0)], axis=0)
        return arr

    def _trim_batch(self, ds: DataSet) -> DataSet:
        """DataSet-level _host_trim (DevicePrefetchIterator transform:
        the worker trims before the background device_put). Stashes the
        pre-transform effective count so listener/throughput stats match
        the unprefetched path (a below-mesh batch padded by repetition
        must still report its REAL rows)."""
        out = DataSet(
            self._host_trim(ds.features),
            None if ds.labels is None else self._host_trim(ds.labels),
            None if ds.features_mask is None
            else self._host_trim(ds.features_mask),
            None if ds.labels_mask is None
            else self._host_trim(ds.labels_mask))
        out.real_examples = self._effective_examples(ds)
        return out

    def _shard_batch(self, arr):
        """Trim to mesh divisibility and device_put sharded on the data
        axis. Batches already staged by the device-prefetch pipeline
        (committed jax.Arrays, pre-trimmed and pre-sharded by the
        worker) pass through untouched — np.asarray on them would be a
        D2H round-trip."""
        if isinstance(arr, jax.Array):
            return arr
        arr = self._host_trim(arr)
        sh = NamedSharding(self.mesh, P("data", *([None] * (arr.ndim - 1))))
        # the SPMD jit-boundary copy of the UNPREFETCHED compat path:
        # fit(device_prefetch=True) moves this into the background worker
        # tpulint: disable=device-transfer-in-hot-loop
        return jax.device_put(arr, sh)

    def _shard_stack(self, arrs):
        """Stack K same-shape batches to [K, B, ...] sharded
        P(None, "data", ...) for the fused scan step. Device-resident
        (prefetched) batches stack on device; host batches trim and
        transfer as ONE put."""
        if isinstance(arrs[0], jax.Array):
            return jnp.stack(arrs)
        a = np.stack([self._host_trim(x) for x in arrs])
        sh = NamedSharding(self.mesh,
                           P(None, "data", *([None] * (a.ndim - 2))))
        # same unprefetched-compat jit-boundary copy as _shard_batch,
        # fused to ONE put for the K-step group
        # tpulint: disable=device-transfer-in-hot-loop
        return jax.device_put(a, sh)

    def _effective_examples(self, ds: DataSet) -> int:
        """Examples that actually contribute to the step after the
        divisibility trim (listener stats must not count dropped or
        repetition-padded rows). Prefetched batches carry the count
        computed BEFORE the worker's trim/pad (see _trim_batch)."""
        pre = getattr(ds, "real_examples", None)
        if pre is not None:
            return int(pre)
        n = ds.num_examples()
        if n >= self.n_devices:
            return (n // self.n_devices) * self.n_devices
        return n

    def _replicate(self, tree):
        sh = NamedSharding(self.mesh, P())
        return jax.device_put(tree, sh)

    def _timer(self, phase: str):
        """Phase timer. With collect_stats the TrainingStats event list
        records (and forwards to the metrics registry itself); otherwise a
        monitoring span lands the phase directly in the registry — either
        way every ParallelWrapper phase shows up at /metrics."""
        if self.stats is not None:
            return self.stats.time_phase(phase)
        from deeplearning4j_tpu.monitoring.tracing import span
        return span(phase)

    def _stash_batch_for_viz(self, ds: DataSet):
        m = self.model
        # hoisted capability flag (set at fit start); falls back to the
        # per-call scan when the batch path is driven directly
        stash = getattr(m, "_stash_features", None)
        if stash is None:
            stash = any(getattr(l, "needs_batch_features", False)
                        for l in m.listeners)
        if stash:
            m._last_batch_features = ds.features

    # ------------------------------------------------------------------
    # allreduce mode (north star)
    # ------------------------------------------------------------------
    def _fit_batch_allreduce(self, ds: DataSet):
        """One global SPMD step: inputs sharded, params replicated — the
        jitted step from the wrapped model works unchanged, XLA partitions
        it and inserts the ICI allreduce."""
        t0 = time.perf_counter()
        m = self.model
        policy = effective_policy(m)
        step = m._get_train_step(False, policy)
        rng = m._next_rng()
        self._stash_batch_for_viz(ds)
        with self._timer("step"):
            x = self._shard_batch(ds.features)
            y = self._shard_batch(ds.labels)
            fmask = None if ds.features_mask is None else self._shard_batch(ds.features_mask)
            lmask = None if ds.labels_mask is None else self._shard_batch(ds.labels_mask)
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            if isinstance(m, MultiLayerNetwork):
                args = (x, y, rng, fmask, lmask)
            else:
                inputs = {m.conf.network_inputs[0]: x}
                labels = {m.conf.network_outputs[0]: y}
                fmasks = None if fmask is None else {m.conf.network_inputs[0]: fmask}
                lmasks = None if lmask is None else {m.conf.network_outputs[0]: lmask}
                args = (inputs, labels, rng, fmasks, lmasks)
            m.params, m.state, m.updater_state, loss = apply_step(
                m, policy, step, m.params, m.state, m.updater_state, *args)
            m.score_value = loss  # raw device scalar, float() on access
        with self._timer("listener"):
            for lst in m.listeners:
                if hasattr(lst, "record_batch"):
                    lst.record_batch(self._effective_examples(ds))
                # raw score: see multilayer's listener loop
                lst.iteration_done(m, m.iteration_count, m._score_raw)
        m.iteration_count += 1
        maybe_record_fit_iteration(m, self._effective_examples(ds),
                                   time.perf_counter() - t0)

    def _fit_group_allreduce(self, batches):
        """Fused multi-step SPMD dispatch: K batches stacked to
        [K, B, ...] (batch axis sharded over the mesh) through the
        wrapped model's scan train step — K allreduce steps, ONE
        Python→XLA round-trip. Listeners fire per logical step with
        lazy slices of the per-step loss vector."""
        t0 = time.perf_counter()
        m = self.model
        k = len(batches)
        policy = effective_policy(m)
        step = m._get_scan_train_step(k, policy)
        with self._timer("step"):
            rngs = jnp.stack([m._next_rng() for _ in range(k)])
            xs = self._shard_stack([b.features for b in batches])
            ys = self._shard_stack([b.labels for b in batches])
            fm = None if batches[0].features_mask is None else \
                self._shard_stack([b.features_mask for b in batches])
            lm = None if batches[0].labels_mask is None else \
                self._shard_stack([b.labels_mask for b in batches])
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            if isinstance(m, MultiLayerNetwork):
                args = (xs, ys, rngs, fm, lm)
            else:
                inputs = {m.conf.network_inputs[0]: xs}
                labels = {m.conf.network_outputs[0]: ys}
                fms = None if fm is None else {m.conf.network_inputs[0]: fm}
                lms = None if lm is None else {m.conf.network_outputs[0]: lm}
                args = (inputs, labels, rngs, fms, lms)
            m.params, m.state, m.updater_state, losses = apply_step(
                m, policy, step, m.params, m.state, m.updater_state, *args)
            m.score_value = losses[-1]  # raw device scalar
        with self._timer("listener"):
            for i, b in enumerate(batches):
                loss_i = losses[i]  # lazy device slice, no sync
                # per LOGICAL step, so viz listeners pair each
                # iteration_done with its own batch's features
                self._stash_batch_for_viz(b)
                for lst in m.listeners:
                    if hasattr(lst, "record_batch"):
                        lst.record_batch(self._effective_examples(b))
                    lst.iteration_done(m, m.iteration_count, loss_i)
                m.iteration_count += 1
        maybe_record_fit_iteration(
            m, sum(self._effective_examples(b) for b in batches),
            time.perf_counter() - t0, n_batches=k)

    # ------------------------------------------------------------------
    # averaging mode (parity with ParameterAveraging semantics)
    # ------------------------------------------------------------------
    def _get_averaging_step(self, policy: str = "off"):
        key = ("avg", policy)
        if key in self._jit_cache:
            return self._jit_cache[key]
        m = self.model
        conf = m.conf
        mesh = self.mesh
        freq = self.averaging_frequency
        nd = self.n_devices

        def local_round(params, state, upd_state, xs, ys, rngs):
            """Runs on ONE shard: `freq` sequential local steps over the
            leading microbatch axis, then cross-shard param average. The
            non-finite sentinel skips per (shard, local step): a shard
            whose microbatch NaNs contributes its PRE-step params to the
            average instead of a poisoned tree."""

            def one(carry, inp):
                p, s, u = carry
                x, y, rng = inp
                rng = rng.reshape(2)  # per-shard slice [1,2] -> legacy key (2,)
                (loss, s2), grads = jax.value_and_grad(
                    lambda pp: m._loss(pp, s, x, y, rng, None, None, train=True),
                    has_aux=True)(p)
                ok = None if policy == "off" else tree_finite(loss, grads)
                grads = normalize_gradients(grads, conf.gradient_normalization,
                                            conf.gradient_normalization_threshold)
                steps, u2 = conf.updater.update(grads, u, p)
                p2 = jax.tree_util.tree_map(lambda a, b: a - b, p, steps)
                s2 = _strip_rnn_state(s2)
                if policy != "off":
                    p2, u2, s2 = guard_updates(
                        ok, policy, (p2, p), (u2, u), (s2, s))
                out = loss if policy == "off" else (loss, ok)
                return (p2, s2, u2), out

            (p_f, s_f, u_f), out = jax.lax.scan(one, (params, state, upd_state),
                                                (xs, ys, rngs))
            s_f = _strip_rnn_state(s_f)
            # parameter averaging across the mesh (ref: averageModels :339)
            p_avg = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, "data"), p_f)
            u_avg = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a.astype(jnp.float32), "data").astype(a.dtype)
                if jnp.issubdtype(a.dtype, jnp.integer) else jax.lax.pmean(a, "data"),
                u_f)
            s_avg = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, "data"), s_f)
            if policy == "off":
                return p_avg, s_avg, u_avg, jnp.mean(out)
            losses, oks = out
            # per-local-step flag, ANDed over shards (replicated output)
            oks_all = jax.lax.pmin(oks.astype(jnp.int32), "data")
            return p_avg, s_avg, u_avg, jnp.mean(losses), oks_all

        def rep(x):
            return jax.tree_util.tree_map(lambda _: P(), x)

        def rounds(params, state, upd_state, xs, ys, rngs):
            outs = (rep(params), rep(state), rep(upd_state), P())
            if policy != "off":
                outs = outs + (P(),)
            fn = shard_map(
                local_round, mesh=mesh,
                in_specs=(rep(params), rep(state), rep(upd_state),
                          P(None, "data"), P(None, "data"), P(None, "data")),
                out_specs=outs,
                check_vma=False)
            return fn(params, state, upd_state, xs, ys, rngs)

        self._jit_cache[key] = jax.jit(rounds)
        return self._jit_cache[key]

    def _fit_round_averaging(self, batches):
        """Consume `averaging_frequency * n_devices` microbatches as one
        round (ref: ParameterAveragingTrainingMaster split sizing :287-298)."""
        t0 = time.perf_counter()
        m = self.model
        self._stash_batch_for_viz(batches[-1])
        freq = len(batches) // self.n_devices
        xs = np.stack([np.stack([b.features for b in
                                 batches[f * self.n_devices:(f + 1) * self.n_devices]],
                                axis=0) for f in range(freq)], axis=0)
        ys = np.stack([np.stack([b.labels for b in
                                 batches[f * self.n_devices:(f + 1) * self.n_devices]],
                                axis=0) for f in range(freq)], axis=0)
        # xs: [freq, n_dev, B, ...] — shard axis 1, scan axis 0, flatten device dim
        xs = xs.reshape((freq, self.n_devices * xs.shape[2]) + xs.shape[3:])
        ys = ys.reshape((freq, self.n_devices * ys.shape[2]) + ys.shape[3:])
        # one rng per (scan step, shard): [freq, n_dev, 2], shard axis = 1
        # (reshaped on device — round-tripping the keys through numpy was
        # a host sync in the per-round hot path)
        rngs = jax.random.split(
            m._next_rng(), freq * self.n_devices
        ).reshape(freq, self.n_devices, -1)
        policy = effective_policy(m)
        step = self._get_averaging_step(policy)
        with self._timer("step"):
            m.state = _strip_rnn_state(m.state)
            m.params, m.state, m.updater_state, loss = apply_step(
                m, policy, step, m.params, m.state, m.updater_state,
                jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(rngs))
            m.score_value = loss  # raw device scalar, float() on access
        round_examples = sum(b.num_examples() for b in batches)
        with self._timer("listener"):
            for lst in m.listeners:
                if hasattr(lst, "record_batch"):
                    # the whole round's examples: a MetricsListener (or
                    # PerformanceListener) must see the true throughput,
                    # not zero samples per round
                    lst.record_batch(round_examples)
                # raw score: see multilayer's listener loop
                lst.iteration_done(m, m.iteration_count, m._score_raw)
        m.iteration_count += freq
        maybe_record_fit_iteration(m, round_examples,
                                   time.perf_counter() - t0, n_batches=freq)

    # ------------------------------------------------------------------
    def fit(self, data, labels=None, epochs: int = 1, batch_size: int = 32,
            *, execution_plan=None):
        """Train across the mesh (ref: ParallelWrapper.fit :468). The
        iterator is wrapped in async prefetch like the reference's
        ADSI-per-device feeding — host-side by default, or the
        device-side pipeline stage when ``device_prefetch=True``
        (batches land pre-trimmed and pre-sharded on the mesh). With
        ``steps_per_dispatch=K``, allreduce mode fuses runs of K
        same-shape batches into single scan dispatches.

        ``execution_plan`` ("auto" | "fused" | "xla") resolves the fused
        training-kernel plan onto the wrapped model ONCE per fit, same
        seam as the single-device fit loops (tuning/plan.py)."""
        from deeplearning4j_tpu.monitoring import ensure_started
        from deeplearning4j_tpu.pipeline.padding import group_signature
        ensure_started()
        m = self.model
        if execution_plan is not None:
            from deeplearning4j_tpu.tuning.plan import apply_execution_plan
            sig0 = (getattr(m, "fuse_bn_act_conv", None),
                    getattr(m, "_fuse_stem", None),
                    getattr(m, "_fusion_only", None))
            apply_execution_plan(m, execution_plan)
            if sig0 != (getattr(m, "fuse_bn_act_conv", None),
                        getattr(m, "_fuse_stem", None),
                        getattr(m, "_fusion_only", None)):
                # averaging mode traces m._loss into the WRAPPER's
                # cache — a changed plan must rebuild it (allreduce
                # mode uses the model's cache, which set_fusion clears)
                self._jit_cache.clear()
        if labels is not None:
            it = ArrayDataSetIterator(data, labels, batch_size)
        elif isinstance(data, DataSet):
            it = ArrayDataSetIterator(data.features, data.labels, batch_size)
        else:
            it = data
        if it is not data:
            # align the internal iterator's pass counter with the
            # absolute epoch count — see MultiLayerNetwork.fit
            it.restore_state({"epoch": m.epoch_count, "pos": 0})
        # listener capability scan hoisted out of the per-batch path
        m._stash_features = any(getattr(l, "needs_batch_features", False)
                                for l in m.listeners)
        # restored data-pipeline cursor applies to the BASE iterator —
        # the per-epoch prefetch wrapper below is a fresh 1:1 stage each
        # pass, so fast-forwarding the base fast-forwards the stream
        consume_restored_cursor(m, it)
        capture_cursor_pass(m, it)
        try:
            for _ in range(epochs):
                # device prefetch serves the allreduce (SPMD) path only:
                # the averaging round builds its [freq, dev*B] stack
                # host-side, so pre-sharded device batches would force a
                # D2H gather per round, and the divisibility trim would
                # silently drop rows the averaging path trains on
                if self.device_prefetch and \
                        self.training_mode != "averaging":
                    from deeplearning4j_tpu.pipeline.prefetch import \
                        DevicePrefetchIterator
                    src = DevicePrefetchIterator(
                        it, prefetch=max(1, self.prefetch_buffer),
                        mesh=self.mesh, data_axis="data",
                        transform=self._trim_batch)
                elif self.prefetch_buffer:
                    src = AsyncDataSetIterator(it,
                                               prefetch=self.prefetch_buffer)
                else:
                    src = it
                averaging = self.training_mode == "averaging"
                round_size = self.averaging_frequency * self.n_devices
                k = self.steps_per_dispatch
                pend = []
                group, sig = [], None
                src_it = iter(src)
                while True:
                    with self._timer("etl"):
                        ds = next(src_it, None)
                    if ds is None:
                        break
                    if averaging:
                        pend.append(ds)
                        if len(pend) == round_size:
                            self._fit_round_averaging(pend)  # times itself
                            m._dispatched_in_epoch += round_size
                            dispatch_boundary(m)
                            pend = []
                    elif k > 1:
                        s = group_signature(ds)
                        if group and s != sig:
                            for b in group:  # unfusable run: per-batch
                                self._fit_batch_allreduce(b)
                                m._dispatched_in_epoch += 1
                                dispatch_boundary(m)
                            group = []
                        sig = s
                        group.append(ds)
                        if len(group) == k:
                            self._fit_group_allreduce(group)  # times itself
                            m._dispatched_in_epoch += k
                            dispatch_boundary(m)
                            group = []
                    else:
                        self._fit_batch_allreduce(ds)  # times itself
                        m._dispatched_in_epoch += 1
                        dispatch_boundary(m)
                # trailing partial averaging round / scan group:
                # allreduce per-batch steps
                for ds in pend + group:
                    self._fit_batch_allreduce(ds)
                    m._dispatched_in_epoch += 1
                    dispatch_boundary(m)
                m.epoch_count += 1
                m._dispatched_in_epoch = 0
                m._cursor_pass += 1
            # one allowed sync, after the final batch (see multilayer.fit)
            finalize_fit_telemetry(m)
        finally:
            m._stash_features = None
            m._cursor_pass = None
            close_listeners(m.listeners)
        return m
