"""Data-parallel training over a device mesh.

TPU-native replacement for deeplearning4j-scaleout's ParallelWrapper
(deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java:58-898) and
its two training modes:

- TrainingMode.SHARED_GRADIENTS (:68, EncodedGradientsAccumulator /
  EncodingHandler threshold-compressed async exchange) → here the NORTH STAR
  (BASELINE.json): ONE jitted SPMD train step with the batch sharded over the
  mesh "data" axis and params replicated; XLA inserts a dense allreduce
  (psum) of gradients over ICI. No worker threads, no replicas, no
  compression — ICI bandwidth makes dense exchange faster than the
  reference's sparse codec path.

- TrainingMode.AVERAGING (:59-74, averageModels every averagingFrequency
  iters :251-257) → `shard_map` formulation: each mesh shard runs
  `averaging_frequency` LOCAL updater steps on its own microbatches
  (lax.scan), then params/updater-state are psum-averaged. Kept for parity
  testing (the reference invariant
  TestCompareParameterAveragingSparkVsSingleMachine: freq=1 averaging ==
  single-machine result holds here exactly for SGD).

The reference's worker thread pool, device pinning (attachThreadToDevice
:137) and MagicQueue feeding disappear: SPMD partitioning is the scheduler.
"""

from __future__ import annotations

import logging
import time
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.util.jax_compat import shard_map
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator, AsyncDataSetIterator
from deeplearning4j_tpu.monitoring.listener import (
    finalize_fit_telemetry, maybe_record_fit_iteration)
from deeplearning4j_tpu.nn.updater import normalize_gradients
from deeplearning4j_tpu.optimize.listeners import close_listeners
from deeplearning4j_tpu.parallel.mesh import default_mesh

log = logging.getLogger(__name__)


def _strip_rnn_state(state):
    """Remove per-batch RNN carries (h/c) so pytree structure is stable
    across shard_map in/out specs."""
    return {k: {kk: vv for kk, vv in v.items() if kk not in ("h", "c")}
            if isinstance(v, dict) else v for k, v in state.items()}


class ParallelWrapper:
    """Multi-device trainer wrapping a MultiLayerNetwork or ComputationGraph
    (ref: ParallelWrapper.Builder / fit :468)."""

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 training_mode: str = "allreduce",
                 averaging_frequency: int = 5,
                 prefetch_buffer: int = 2,
                 report_score_after_averaging: bool = True,
                 collect_stats: bool = False):
        self.model = model
        self.mesh = mesh if mesh is not None else default_mesh()
        self.training_mode = training_mode
        self.averaging_frequency = max(1, averaging_frequency)
        self.prefetch_buffer = prefetch_buffer
        self.n_devices = int(np.prod(self.mesh.devices.shape))
        self._jit_cache: Dict[Any, Any] = {}
        self._warned_small_batch = False
        self._warned_remainder_drop = False
        # phase timing (ref: CommonSparkTrainingStats role)
        self.stats = None
        if collect_stats:
            from deeplearning4j_tpu.parallel.stats import TrainingStats
            self.stats = TrainingStats()
        if not model._initialized:
            model.init()

    # ------------------------------------------------------------------
    def _shard_batch(self, arr):
        """Make the batch divisible by n_devices and device_put sharded on
        the data axis. Non-divisible remainders are DROPPED (the reference
        drops/queues leftovers rather than duplicating examples —
        duplicate-padding would silently over-weight the repeated sample in
        the gradient). Batches smaller than the mesh still pad by repetition
        as the only way to occupy every device; that case is logged once."""
        arr = np.asarray(arr)
        n = arr.shape[0]
        rem = n % self.n_devices
        if rem:
            if n >= self.n_devices:
                if not self._warned_remainder_drop:
                    log.warning(
                        "batch of %d not divisible by %d devices: dropping "
                        "the %d trailing example(s) each step (size batches "
                        "to a multiple of the mesh to use all data)",
                        n, self.n_devices, rem)
                    self._warned_remainder_drop = True
                arr = arr[:n - rem]
            else:
                if not self._warned_small_batch:
                    log.warning(
                        "batch of %d < %d devices: padding by repetition "
                        "(repeated examples are over-weighted this step)",
                        n, self.n_devices)
                    self._warned_small_batch = True
                pad = self.n_devices - n
                arr = np.concatenate(
                    [arr, np.repeat(arr[-1:], pad, axis=0)], axis=0)
        sh = NamedSharding(self.mesh, P("data", *([None] * (arr.ndim - 1))))
        return jax.device_put(arr, sh)

    def _effective_examples(self, ds: DataSet) -> int:
        """Examples that actually contribute to the step after the
        divisibility trim (listener stats must not count dropped rows)."""
        n = ds.num_examples()
        if n >= self.n_devices:
            return (n // self.n_devices) * self.n_devices
        return n

    def _replicate(self, tree):
        sh = NamedSharding(self.mesh, P())
        return jax.device_put(tree, sh)

    def _timer(self, phase: str):
        """Phase timer. With collect_stats the TrainingStats event list
        records (and forwards to the metrics registry itself); otherwise a
        monitoring span lands the phase directly in the registry — either
        way every ParallelWrapper phase shows up at /metrics."""
        if self.stats is not None:
            return self.stats.time_phase(phase)
        from deeplearning4j_tpu.monitoring.tracing import span
        return span(phase)

    def _stash_batch_for_viz(self, ds: DataSet):
        m = self.model
        if any(getattr(l, "needs_batch_features", False)
               for l in m.listeners):
            m._last_batch_features = ds.features

    # ------------------------------------------------------------------
    # allreduce mode (north star)
    # ------------------------------------------------------------------
    def _fit_batch_allreduce(self, ds: DataSet):
        """One global SPMD step: inputs sharded, params replicated — the
        jitted step from the wrapped model works unchanged, XLA partitions
        it and inserts the ICI allreduce."""
        t0 = time.perf_counter()
        m = self.model
        step = m._get_train_step(False)
        rng = m._next_rng()
        self._stash_batch_for_viz(ds)
        with self._timer("step"):
            x = self._shard_batch(ds.features)
            y = self._shard_batch(ds.labels)
            fmask = None if ds.features_mask is None else self._shard_batch(ds.features_mask)
            lmask = None if ds.labels_mask is None else self._shard_batch(ds.labels_mask)
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            if isinstance(m, MultiLayerNetwork):
                m.params, m.state, m.updater_state, loss = step(
                    m.params, m.state, m.updater_state, x, y, rng, fmask, lmask)
            else:
                inputs = {m.conf.network_inputs[0]: x}
                labels = {m.conf.network_outputs[0]: y}
                fmasks = None if fmask is None else {m.conf.network_inputs[0]: fmask}
                lmasks = None if lmask is None else {m.conf.network_outputs[0]: lmask}
                m.params, m.state, m.updater_state, loss = step(
                    m.params, m.state, m.updater_state, inputs, labels, rng,
                    fmasks, lmasks)
            m.score_value = loss  # raw device scalar, float() on access
        with self._timer("listener"):
            for lst in m.listeners:
                if hasattr(lst, "record_batch"):
                    lst.record_batch(self._effective_examples(ds))
                # raw score: see multilayer's listener loop
                lst.iteration_done(m, m.iteration_count, m._score_raw)
        m.iteration_count += 1
        maybe_record_fit_iteration(m, self._effective_examples(ds),
                                   time.perf_counter() - t0)

    # ------------------------------------------------------------------
    # averaging mode (parity with ParameterAveraging semantics)
    # ------------------------------------------------------------------
    def _get_averaging_step(self):
        if "avg" in self._jit_cache:
            return self._jit_cache["avg"]
        m = self.model
        conf = m.conf
        mesh = self.mesh
        freq = self.averaging_frequency
        nd = self.n_devices

        def local_round(params, state, upd_state, xs, ys, rngs):
            """Runs on ONE shard: `freq` sequential local steps over the
            leading microbatch axis, then cross-shard param average."""

            def one(carry, inp):
                p, s, u = carry
                x, y, rng = inp
                rng = rng.reshape(2)  # per-shard slice [1,2] -> legacy key (2,)
                (loss, s2), grads = jax.value_and_grad(
                    lambda pp: m._loss(pp, s, x, y, rng, None, None, train=True),
                    has_aux=True)(p)
                grads = normalize_gradients(grads, conf.gradient_normalization,
                                            conf.gradient_normalization_threshold)
                steps, u2 = conf.updater.update(grads, u, p)
                p2 = jax.tree_util.tree_map(lambda a, b: a - b, p, steps)
                return (p2, _strip_rnn_state(s2), u2), loss

            (p_f, s_f, u_f), losses = jax.lax.scan(one, (params, state, upd_state),
                                                   (xs, ys, rngs))
            s_f = _strip_rnn_state(s_f)
            # parameter averaging across the mesh (ref: averageModels :339)
            p_avg = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, "data"), p_f)
            u_avg = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a.astype(jnp.float32), "data").astype(a.dtype)
                if jnp.issubdtype(a.dtype, jnp.integer) else jax.lax.pmean(a, "data"),
                u_f)
            s_avg = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, "data"), s_f)
            return p_avg, s_avg, u_avg, jnp.mean(losses)

        def rep(x):
            return jax.tree_util.tree_map(lambda _: P(), x)

        def rounds(params, state, upd_state, xs, ys, rngs):
            fn = shard_map(
                local_round, mesh=mesh,
                in_specs=(rep(params), rep(state), rep(upd_state),
                          P(None, "data"), P(None, "data"), P(None, "data")),
                out_specs=(rep(params), rep(state), rep(upd_state), P()),
                check_vma=False)
            return fn(params, state, upd_state, xs, ys, rngs)

        self._jit_cache["avg"] = jax.jit(rounds)
        return self._jit_cache["avg"]

    def _fit_round_averaging(self, batches):
        """Consume `averaging_frequency * n_devices` microbatches as one
        round (ref: ParameterAveragingTrainingMaster split sizing :287-298)."""
        t0 = time.perf_counter()
        m = self.model
        self._stash_batch_for_viz(batches[-1])
        freq = len(batches) // self.n_devices
        xs = np.stack([np.stack([b.features for b in
                                 batches[f * self.n_devices:(f + 1) * self.n_devices]],
                                axis=0) for f in range(freq)], axis=0)
        ys = np.stack([np.stack([b.labels for b in
                                 batches[f * self.n_devices:(f + 1) * self.n_devices]],
                                axis=0) for f in range(freq)], axis=0)
        # xs: [freq, n_dev, B, ...] — shard axis 1, scan axis 0, flatten device dim
        xs = xs.reshape((freq, self.n_devices * xs.shape[2]) + xs.shape[3:])
        ys = ys.reshape((freq, self.n_devices * ys.shape[2]) + ys.shape[3:])
        # one rng per (scan step, shard): [freq, n_dev, 2], shard axis = 1
        # (reshaped on device — round-tripping the keys through numpy was
        # a host sync in the per-round hot path)
        rngs = jax.random.split(
            m._next_rng(), freq * self.n_devices
        ).reshape(freq, self.n_devices, -1)
        step = self._get_averaging_step()
        with self._timer("step"):
            m.state = _strip_rnn_state(m.state)
            m.params, m.state, m.updater_state, loss = step(
                m.params, m.state, m.updater_state, jnp.asarray(xs),
                jnp.asarray(ys), jnp.asarray(rngs))
            m.score_value = loss  # raw device scalar, float() on access
        round_examples = sum(b.num_examples() for b in batches)
        with self._timer("listener"):
            for lst in m.listeners:
                if hasattr(lst, "record_batch"):
                    # the whole round's examples: a MetricsListener (or
                    # PerformanceListener) must see the true throughput,
                    # not zero samples per round
                    lst.record_batch(round_examples)
                # raw score: see multilayer's listener loop
                lst.iteration_done(m, m.iteration_count, m._score_raw)
        m.iteration_count += freq
        maybe_record_fit_iteration(m, round_examples,
                                   time.perf_counter() - t0, n_batches=freq)

    # ------------------------------------------------------------------
    def fit(self, data, labels=None, epochs: int = 1, batch_size: int = 32):
        """Train across the mesh (ref: ParallelWrapper.fit :468). The
        iterator is wrapped in async prefetch like the reference's
        ADSI-per-device feeding."""
        from deeplearning4j_tpu.monitoring import ensure_started
        ensure_started()
        m = self.model
        if labels is not None:
            it = ArrayDataSetIterator(data, labels, batch_size)
        elif isinstance(data, DataSet):
            it = ArrayDataSetIterator(data.features, data.labels, batch_size)
        else:
            it = data

        try:
            for _ in range(epochs):
                src = AsyncDataSetIterator(it, prefetch=self.prefetch_buffer) \
                    if self.prefetch_buffer else it
                averaging = self.training_mode == "averaging"
                round_size = self.averaging_frequency * self.n_devices
                pend = []
                src_it = iter(src)
                while True:
                    with self._timer("etl"):
                        ds = next(src_it, None)
                    if ds is None:
                        break
                    if averaging:
                        pend.append(ds)
                        if len(pend) == round_size:
                            self._fit_round_averaging(pend)  # times itself
                            pend = []
                    else:
                        self._fit_batch_allreduce(ds)  # times itself
                # trailing partial averaging round: allreduce steps
                for ds in pend:
                    self._fit_batch_allreduce(ds)
                m.epoch_count += 1
            # one allowed sync, after the final batch (see multilayer.fit)
            finalize_fit_telemetry(m)
        finally:
            close_listeners(m.listeners)
        return m
