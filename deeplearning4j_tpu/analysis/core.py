"""tpulint core: findings, module model, suppressions, and the scan engine.

The analyzer is the static half of the performance-observability story:
PR 1's runtime recompile watcher catches dispatch pathologies *while they
happen*; tpulint catches the same classes of defect *at review time* by
walking the AST — host syncs in fit hot paths, tracer leaks out of jitted
functions, recompile hazards, f64 promotion, unlocked cross-thread state,
and plain hygiene. Rules are pure functions over a `ModuleInfo` (parsed
tree + import-alias resolution + parent links); the engine handles file
discovery, inline suppressions, and severity plumbing. No third-party
dependencies — stdlib `ast` only, so the lint lane runs anywhere the
package imports.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

#: rule id reserved for files the engine itself cannot parse
PARSE_ERROR_RULE = "parse-error"

_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([A-Za-z0-9_\-, ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location.

    `chain` carries the interprocedural evidence for promoted findings
    (the callee chain from the flagged call site down to the function
    owning the effect, rendered ``module.qualname`` per hop, with the
    effect site appended) — empty for purely lexical findings. It is
    display/JSON payload only and deliberately NOT part of the
    fingerprint: refactoring an intermediate helper must not churn the
    baseline while the contract violation is unchanged."""

    rule: str
    severity: str
    path: str  # posix-style path relative to the scan root
    line: int
    message: str
    snippet: str = ""
    chain: Tuple[str, ...] = ()

    def fingerprint(self) -> str:
        """Location-tolerant identity for baseline matching: rule + path +
        whitespace-normalized source line. Line numbers are deliberately
        excluded so unrelated edits above a grandfathered finding don't
        invalidate the baseline."""
        norm = re.sub(r"\s+", "", self.snippet)
        raw = f"{self.rule}|{self.path}|{norm}".encode("utf-8")
        return hashlib.sha1(raw).hexdigest()[:16]

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["chain"] = list(self.chain)
        d["fingerprint"] = self.fingerprint()
        return d

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        out = f"{loc}: [{self.rule}] {self.severity}: {self.message}"
        if self.snippet:
            out += f"\n    {self.snippet}"
        if self.chain:
            out += f"\n    via: {' -> '.join(self.chain)}"
        return out


def _parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """Map line number -> set of suppressed rule ids ('all' wildcards).

    `# tpulint: disable=rule-a,rule-b` at the end of a code line suppresses
    on that line; on a standalone comment line it suppresses the next
    non-blank, non-comment line (so multi-rule suppressions can carry a
    justification sentence alongside).
    """
    out: Dict[int, Set[str]] = {}
    pending: Set[str] = set()
    for lineno, line in enumerate(source.splitlines(), 1):
        stripped = line.strip()
        m = _SUPPRESS_RE.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            if stripped.startswith("#"):
                pending |= rules
                continue
            out.setdefault(lineno, set()).update(rules)
        if stripped and not stripped.startswith("#"):
            if pending:
                out.setdefault(lineno, set()).update(pending)
                pending = set()
    return out


class ModuleInfo:
    """A parsed module plus the cross-cutting facts every rule needs:
    parent links, enclosing-scope queries, and import-alias resolution
    (`jnp.asarray` -> `jax.numpy.asarray` regardless of local spelling)."""

    def __init__(self, path: str, rel_path: str, source: str):
        self.path = path
        self.rel_path = rel_path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)  # SyntaxError propagates to the engine
        self.suppressions = _parse_suppressions(source)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        # local name -> canonical dotted prefix ("np" -> "numpy",
        # "jnp" -> "jax.numpy", "jit" -> "jax.jit")
        self.aliases: Dict[str, str] = {}
        self._collect_imports()
        #: memo for derived per-module facts (donation maps, jit-staged
        #: function sets, ...): several rules need the same expensive
        #: whole-tree walks, and a module is immutable once parsed
        self._facts: Dict[str, object] = {}

    def fact(self, key: str, compute):
        """Memoized derived fact: `compute(self)` runs once per module."""
        if key not in self._facts:
            self._facts[key] = compute(self)
        return self._facts[key]

    # -- imports ------------------------------------------------------
    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def imports_module(self, root: str) -> bool:
        """True if any import resolves under the dotted prefix `root`."""
        for canon in self.aliases.values():
            if canon == root or canon.startswith(root + "."):
                return True
        return False

    # -- name resolution ----------------------------------------------
    def resolve(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name for a Name/Attribute chain, resolving
        import aliases at the root; None for non-static expressions."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = self.aliases.get(cur.id, cur.id)
        parts.append(root)
        return ".".join(reversed(parts))

    # -- tree queries -------------------------------------------------
    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Enclosing FunctionDef/AsyncFunctionDef nodes, innermost first."""
        return [a for a in self.ancestors(node)
                if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def inside_loop(self, node: ast.AST,
                    within: Optional[ast.AST] = None) -> bool:
        """True if a for/while/comprehension sits between `node` and
        `within` (or the nearest enclosing function when omitted)."""
        loops = (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
                 ast.SetComp, ast.DictComp, ast.GeneratorExp)
        for a in self.ancestors(node):
            if within is not None and a is within:
                return False
            if within is None and isinstance(
                    a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            if isinstance(a, loops):
                return True
        return False

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()[:160]
        return ""

    def segment(self, node: ast.AST) -> str:
        return ast.get_source_segment(self.source, node) or ""


class Rule:
    """Base class: subclasses set `id`/`severity`/`description` and yield
    findings from `check(module)`.

    Project-aware rules additionally define
    ``check_project(module, project)`` — the engine calls it (instead of
    `check`) whenever a whole-program `ProjectInfo` is available, so the
    same rule object degrades gracefully to its lexical behavior on a
    bare single-file scan."""

    id: str = ""
    severity: str = SEVERITY_WARNING
    description: str = ""

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, mod: ModuleInfo, node, message: str,
                chain: Tuple[str, ...] = ()) -> Finding:
        line = node if isinstance(node, int) else getattr(node, "lineno", 0)
        return Finding(self.id, self.severity, mod.rel_path, line,
                       message, mod.line_text(line), chain)


# ---------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------
def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        yield os.path.join(dirpath, f)


def scan_file(path: str, rules: Sequence[Rule],
              root: Optional[str] = None,
              project=None) -> List[Finding]:
    rel = os.path.relpath(path, root) if root else path
    rel = rel.replace(os.sep, "/")
    mod = project.module_for_path(rel) if project is not None else None
    if mod is None:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        try:
            mod = ModuleInfo(path, rel, source)
        except SyntaxError as e:
            return [Finding(PARSE_ERROR_RULE, SEVERITY_ERROR, rel,
                            e.lineno or 0, f"cannot parse: {e.msg}")]
    findings: List[Finding] = []
    for rule in rules:
        checker = getattr(rule, "check_project", None)
        it = checker(mod, project) if (checker is not None
                                       and project is not None) \
            else rule.check(mod)
        for f_ in it:
            suppressed = mod.suppressions.get(f_.line, ())
            if f_.rule in suppressed or "all" in suppressed:
                continue
            findings.append(f_)
    findings.sort(key=lambda f_: (f_.path, f_.line, f_.rule))
    return findings


def scan_paths(paths: Sequence[str], rules: Optional[Sequence[Rule]] = None,
               root: Optional[str] = None, project=None,
               files: Optional[Sequence[str]] = None) -> List[Finding]:
    """Scan files/directories with the given rules (default: all).

    A whole-program `ProjectInfo` is built over `paths` once (parse
    shared with the per-file scan) so project-aware rules see cross-
    module facts; pass `project` to reuse one already built. `files`
    (an explicit pre-computed subset of the walk) is the diff lane's
    O(diff) seam: rules run only on those modules while the project
    layer still spans everything, so a changed caller keeps seeing
    unchanged callees' summaries.

    `root` defaults to the cwd and is applied to BOTH the project layer
    and the per-file scan — the two must key modules by the same
    relative paths or cross-module resolution silently degrades."""
    if rules is None:
        from deeplearning4j_tpu.analysis.rules import ALL_RULES
        rules = ALL_RULES
    root = root or os.getcwd()
    if project is None:
        from deeplearning4j_tpu.analysis.project import ProjectInfo
        project = ProjectInfo.build(paths, root)
    out: List[Finding] = []
    for path in (files if files is not None else iter_python_files(paths)):
        out.extend(scan_file(path, rules, root=root, project=project))
    return out
