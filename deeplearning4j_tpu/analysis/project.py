"""ProjectInfo: the whole-program layer under tpulint's interprocedural
rules.

PR 2's engine was strictly per-module: every rule was a pure function
over one `ModuleInfo`, so any defect that crossed a module boundary — a
helper that syncs called from a fit loop two files away, a retried
dispatch re-reading donated buffers, a builder snapshotting a
process-wide flag — was invisible. `ProjectInfo` parses every module
under the scan root ONCE, derives module names from their paths, and
answers the cross-cutting questions rules need:

- which project module a canonical dotted name lives in (longest-prefix
  match over the module table);
- what a name resolves to ACROSS modules, following import-alias and
  re-export chains (``from pkg.sub import helper`` in ``pkg/__init__``
  then ``from pkg import helper`` elsewhere) with a bounded hop count so
  a re-export cycle cannot loop;
- the lazily-built call graph with per-function effect summaries
  (`analysis.callgraph.CallGraph`).

Soundness caveats (documented, deliberate): resolution follows static
names only — dynamic dispatch (``obj.method()`` on a non-``self``
receiver, callables stored in containers, listener protocols) breaks
the chain, so interprocedural findings are under- not over-approximate;
relative imports and ``import *`` are not followed; unparsable modules
are skipped here (the scan itself still reports them as parse-error
findings). Everything stays stdlib-`ast` so the lint lane runs anywhere
the package imports.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.core import (
    ModuleInfo, iter_python_files)

#: maximum import-alias / re-export hops followed while resolving one
#: name — bounds work on pathological re-export cycles
MAX_RESOLVE_HOPS = 6


def module_name_for(rel_path: str) -> str:
    """Dotted module name for a posix rel path: ``pkg/sub/mod.py`` ->
    ``pkg.sub.mod``; a package ``__init__.py`` names the package."""
    p = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    parts = [s for s in p.split("/") if s]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class ProjectInfo:
    """Parsed view of every module under the scan root."""

    def __init__(self, root: str):
        self.root = root
        #: dotted module name -> ModuleInfo
        self.modules: Dict[str, ModuleInfo] = {}
        #: posix rel path -> dotted module name
        self.by_rel_path: Dict[str, str] = {}
        self._callgraph = None

    # -- construction --------------------------------------------------
    @classmethod
    def build(cls, paths: Sequence[str],
              root: Optional[str] = None) -> "ProjectInfo":
        """Parse every .py under `paths` (skipping unparsable files —
        the scan reports those as parse-error findings on its own)."""
        root = root or os.getcwd()
        proj = cls(root)
        for path in iter_python_files(paths):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, "r", encoding="utf-8") as f:
                    mod = ModuleInfo(path, rel, f.read())
            except (SyntaxError, OSError, UnicodeDecodeError):
                continue
            proj.add_module(mod)
        return proj

    def add_module(self, mod: ModuleInfo) -> None:
        name = module_name_for(mod.rel_path)
        self.modules[name] = mod
        self.by_rel_path[mod.rel_path] = name

    def module_for_path(self, rel_path: str) -> Optional[ModuleInfo]:
        name = self.by_rel_path.get(rel_path)
        return self.modules.get(name) if name else None

    # -- import graph --------------------------------------------------
    def imported_project_modules(self, mod: ModuleInfo) -> Set[str]:
        """Project modules this module's imports resolve under."""
        out: Set[str] = set()
        for canon in mod.aliases.values():
            hit = self.split_module_prefix(canon)
            if hit is not None:
                out.add(hit[0])
        return out

    def import_graph(self) -> Dict[str, Set[str]]:
        return {name: self.imported_project_modules(mod)
                for name, mod in self.modules.items()}

    # -- name resolution -----------------------------------------------
    def split_module_prefix(
            self, canonical: str) -> Optional[Tuple[str, str]]:
        """Longest project-module prefix of a canonical dotted name:
        ``pkg.sub.mod.Class.method`` -> (``pkg.sub.mod``,
        ``Class.method``)."""
        parts = canonical.split(".")
        for i in range(len(parts), 0, -1):
            name = ".".join(parts[:i])
            if name in self.modules:
                return name, ".".join(parts[i:])
        return None

    def resolve_name(self, canonical: str,
                     _hops: int = 0) -> Optional[Tuple[str, str]]:
        """Resolve a canonical dotted name to (module_name, qualname) of
        an actual def/class, following re-export alias chains up to
        MAX_RESOLVE_HOPS. None when the name leaves the project or the
        definition cannot be found statically."""
        if _hops > MAX_RESOLVE_HOPS:
            return None
        hit = self.split_module_prefix(canonical)
        if hit is None:
            return None
        mod_name, qual = hit
        if not qual:
            return mod_name, ""
        mod = self.modules[mod_name]
        if self._find_def(mod, qual) is not None:
            return mod_name, qual
        # re-export: the first segment is an import alias in mod
        head, _, rest = qual.partition(".")
        target = mod.aliases.get(head)
        if target is not None and target != head:
            chained = target + ("." + rest if rest else "")
            return self.resolve_name(chained, _hops + 1)
        return None

    def lookup_function(self, module_name: str,
                        qualname: str) -> Optional[ast.AST]:
        """The FunctionDef/AsyncFunctionDef for module:qualname, walking
        Class.method paths; None when absent or not a function."""
        mod = self.modules.get(module_name)
        if mod is None:
            return None
        node = self._find_def(mod, qualname)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node
        return None

    @staticmethod
    def _find_def(mod: ModuleInfo, qualname: str) -> Optional[ast.AST]:
        """Walk a dotted qualname through class bodies to its def."""
        scope: List[ast.stmt] = mod.tree.body
        node: Optional[ast.AST] = None
        for part in qualname.split("."):
            node = None
            for stmt in scope:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)) and stmt.name == part:
                    node = stmt
                    break
            if node is None:
                return None
            scope = node.body if isinstance(node, ast.ClassDef) else []
        return node

    def resolve_call(self, mod: ModuleInfo,
                     call: ast.Call) -> Optional[Tuple[str, str]]:
        """(module_name, qualname) for a call's target when it resolves
        to a project function: module-level names / dotted attributes
        through import aliases, and ``self.method(...)`` within the
        enclosing class. None for anything dynamic."""
        func = call.func
        # self.method(...): same-class lookup in the same module
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name) \
                and func.value.id == "self":
            cls = next((a for a in mod.ancestors(call)
                        if isinstance(a, ast.ClassDef)), None)
            if cls is None:
                return None
            for stmt in cls.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and stmt.name == func.attr:
                    mod_name = self.by_rel_path.get(mod.rel_path)
                    if mod_name is None:
                        return None
                    return mod_name, f"{cls.name}.{func.attr}"
            return None
        canonical = mod.resolve(func)
        if canonical is None:
            return None
        resolved = self.resolve_name(canonical)
        if resolved is not None and resolved[1]:
            return resolved
        # same-module bare-name call (`helper(x)` with helper defined
        # here): no project-module prefix to strip, look it up directly
        if isinstance(func, ast.Name) and func.id == canonical:
            own = self.by_rel_path.get(mod.rel_path)
            if own is not None and isinstance(
                    self._find_def(mod, canonical),
                    (ast.FunctionDef, ast.AsyncFunctionDef)):
                return own, canonical
        return None

    # -- call graph ----------------------------------------------------
    @property
    def callgraph(self):
        if self._callgraph is None:
            from deeplearning4j_tpu.analysis.callgraph import CallGraph
            self._callgraph = CallGraph(self)
        return self._callgraph

    # -- mutable process-wide state (jit-key-drift support) ------------
    def mutable_globals(self, module_name: str) -> Set[str]:
        """Module-scope names that some function in the module rebinds
        via a ``global`` statement — the set_*-seam shape
        (`set_paged_decode_impl` & friends). A global only ever bound at
        import time is configuration, not mutable process state."""
        mod = self.modules.get(module_name)
        if mod is None:
            return set()
        return module_mutable_globals(mod)


def module_mutable_globals(mod: ModuleInfo) -> Set[str]:
    """Same as ProjectInfo.mutable_globals for a standalone module.
    Memoized per module."""
    return mod.fact("mutable_globals", _compute_mutable_globals)


def _compute_mutable_globals(mod: ModuleInfo) -> Set[str]:
    bound: Set[str] = set()
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    bound.add(t.id)
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name):
            bound.add(stmt.target.id)
    written: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Global):
            written.update(node.names)
    return bound & written


def iter_functions(mod: ModuleInfo) -> Iterator[Tuple[str, ast.AST]]:
    """(qualname, def-node) for every function in a module, nested defs
    included (``outer.<locals>.inner`` style qualnames)."""

    def walk(scope: List[ast.stmt], prefix: str, in_func: bool):
        for stmt in scope:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{stmt.name}"
                yield qual, stmt
                yield from walk(stmt.body, f"{qual}.<locals>.", True)
            elif isinstance(stmt, ast.ClassDef):
                sep = ".<locals>." if in_func else "."
                yield from walk(stmt.body, f"{prefix}{stmt.name}{sep}"
                                if prefix else f"{stmt.name}.", in_func)

    yield from walk(mod.tree.body, "", False)
