"""tpulint CLI: `python -m deeplearning4j_tpu.analysis [paths] ...`.

Exit-code contract (also in --help):
  0  clean — no new findings, no stale baseline entries
  1  gate failure — new findings (incl. parse errors), stale baseline
     entries (debt paid off but not ratcheted), or a refused
     --update-baseline (error-severity additions need
     --allow-grandfather)
  2  usage error — unknown rule id, missing path, bad --diff ref, or
     --write-baseline/--update-baseline under --diff or a rule subset
     (a partial scan must never become the baseline)

`--diff <ref>` is the CI-lane mode: rules run ONLY on modules changed
vs the merge-base with <ref> (working tree included, untracked files
counted as fully changed) PLUS their reverse-import closure bounded by
the callgraph depth — a changed callee that grew an effect surfaces
its interprocedural finding in an UNCHANGED caller, so importers must
be scanned too. The gate stays O(impacted diff) while the ProjectInfo
layer spans the whole tree, so a changed caller keeps seeing unchanged
callees' summaries. Baseline matching and staleness are restricted to
the scanned modules.
`--format=json` emits a machine round-trippable report (interprocedural
findings carry their callee `chain`); the full scan plus
TPULINT_BASELINE.json ratchet stays the nightly/verify path.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis import baseline as bl
from deeplearning4j_tpu.analysis.core import (
    Finding, iter_python_files, scan_paths)
from deeplearning4j_tpu.analysis.project import ProjectInfo
from deeplearning4j_tpu.analysis.rules import ALL_RULES, RULES_BY_ID

_EPILOG = """\
exit codes:
  0  clean: no new findings and no stale baseline entries
  1  gate failure: new findings (incl. parse errors), stale baseline
     entries, or a refused --update-baseline
  2  usage error: unknown rule, missing path, bad --diff ref, or
     baseline writes combined with --diff / a rule subset
"""


def _default_paths() -> List[str]:
    """Scan the installed package when no path is given."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="tpulint: whole-program AST analyzer for JAX/TPU "
                    "anti-patterns (host syncs / device transfers in hot "
                    "paths — incl. through helper calls, donation "
                    "use-after-consume, jit-key drift, tracer leaks, "
                    "recompile hazards, f64 promotion, unlocked thread "
                    "state, hygiene).",
        epilog=_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: the "
                        "deeplearning4j_tpu package)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", metavar="PATH",
                   help=f"baseline file (default: {bl.BASELINE_NAME} in "
                        f"cwd, then the repo root)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline: every finding is new")
    p.add_argument("--write-baseline", action="store_true",
                   help="overwrite the baseline with the current "
                        "findings and exit 0 (unguarded; prefer "
                        "--update-baseline)")
    p.add_argument("--update-baseline", action="store_true",
                   help="ratchet the baseline from the current scan: "
                        "stale entries drop, but ADDING error-severity "
                        "findings is refused without --allow-grandfather")
    p.add_argument("--allow-grandfather", action="store_true",
                   help="let --update-baseline grandfather error-"
                        "severity findings (a reviewed decision)")
    p.add_argument("--diff", metavar="REF",
                   help="scan only modules changed vs the merge-base "
                        "with REF (working tree included); the project "
                        "layer still spans everything")
    p.add_argument("--rules", metavar="ID[,ID...]",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--rule", metavar="ID", action="append", default=[],
                   help="run a single rule (repeatable; combines with "
                        "--rules)")
    p.add_argument("--list-rules", action="store_true",
                   help="print rule ids and descriptions, then exit")
    return p


def _select_rules(spec: Optional[str], singles: Sequence[str]):
    ids = [s.strip() for s in (spec or "").split(",") if s.strip()]
    ids += [s.strip() for s in singles if s.strip()]
    if not ids:
        return ALL_RULES
    unknown = [i for i in ids if i not in RULES_BY_ID]
    if unknown:
        raise ValueError(
            f"tpulint: unknown rule id(s): {', '.join(unknown)} "
            f"(see --list-rules)")
    seen: Dict[str, None] = {}
    for i in ids:
        seen.setdefault(i)
    return [RULES_BY_ID[i] for i in seen]


# ---------------------------------------------------------------------
# --diff plumbing
# ---------------------------------------------------------------------
_HUNK_RE = re.compile(r"^@@ -\d+(?:,\d+)? \+(\d+)(?:,(\d+))? @@")


def _git(root: str, *args: str) -> str:
    proc = subprocess.run(["git", "-C", root, *args],
                          capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(proc.stderr.strip()
                           or f"git {' '.join(args)} failed")
    return proc.stdout


def diff_changed_py(root: str, ref: str
                    ) -> Tuple[Set[str], Dict[str, List[Tuple[int, int]]]]:
    """(changed .py ABSOLUTE paths, root-relative path -> added/changed
    line ranges) for the working tree vs the merge-base with `ref`.
    git emits repo-toplevel-relative paths, which need not coincide
    with the baseline-dir `root` findings are keyed on — so files are
    resolved against the toplevel and ranges re-keyed against `root`.
    Untracked (not-yet-added) .py files count as fully changed; deleted
    files are naturally absent (nothing to scan)."""
    top = _git(root, "rev-parse", "--show-toplevel").strip()
    try:
        base = _git(top, "merge-base", ref, "HEAD").strip()
    except RuntimeError:
        # ref exists but shares no history (shallow clones): diff
        # straight against it
        base = _git(top, "rev-parse", "--verify",
                    f"{ref}^{{commit}}").strip()

    def rel_to_root(git_path: str) -> str:
        return os.path.relpath(os.path.join(top, git_path),
                               root).replace(os.sep, "/")

    files: Set[str] = set()
    ranges: Dict[str, List[Tuple[int, int]]] = {}
    for f in _git(top, "-c", "diff.noprefix=false", "diff",
                  "--no-ext-diff", "--name-only", base,
                  "--", "*.py").splitlines():
        if f.strip():
            files.add(os.path.abspath(os.path.join(top, f)))
    # a brand-new module is invisible to `git diff <base>` until added:
    # treat untracked .py files as changed end to end
    for f in _git(top, "ls-files", "--others", "--exclude-standard",
                  "--", "*.py").splitlines():
        if f.strip():
            files.add(os.path.abspath(os.path.join(top, f)))
            ranges.setdefault(rel_to_root(f), []).append((1, 10 ** 9))
    current: Optional[str] = None
    # user diff config (noprefix/mnemonicPrefix/external drivers) must
    # not change the parseable hunk format the range extraction expects
    for line in _git(top, "-c", "diff.noprefix=false",
                     "-c", "diff.mnemonicPrefix=false", "diff",
                     "--no-ext-diff", "--unified=0", base,
                     "--", "*.py").splitlines():
        if line.startswith("+++ b/"):
            current = rel_to_root(line[6:].strip())
        elif line.startswith("@@") and current is not None:
            m = _HUNK_RE.match(line)
            if m:
                start = int(m.group(1))
                count = int(m.group(2)) if m.group(2) is not None else 1
                if count > 0:
                    ranges.setdefault(current, []).append(
                        (start, start + count - 1))
    return files, ranges


def _on_changed_line(f_: Finding,
                     ranges: Dict[str, List[Tuple[int, int]]]) -> bool:
    return any(a <= f_.line <= b for a, b in ranges.get(f_.path, ()))


def _importer_closure(project: ProjectInfo, root: str,
                      changed: Set[str]) -> Set[str]:
    """Absolute paths of modules that (transitively, up to the
    callgraph depth bound) import a changed module: where a changed
    callee's new effect surfaces as an interprocedural finding."""
    from deeplearning4j_tpu.analysis.callgraph import MAX_DEPTH
    importers: Dict[str, Set[str]] = {}
    for mod_name, deps in project.import_graph().items():
        for dep in deps:
            importers.setdefault(dep, set()).add(mod_name)
    frontier = {project.by_rel_path[rel]
                for rel in (os.path.relpath(f, root).replace(os.sep, "/")
                            for f in changed)
                if rel in project.by_rel_path}
    seen: Set[str] = set()
    for _ in range(MAX_DEPTH):
        frontier = {imp for m in frontier
                    for imp in importers.get(m, ())} - seen
        if not frontier:
            break
        seen |= frontier
    return {os.path.abspath(os.path.join(
                root, project.modules[m].rel_path)) for m in seen}


# ---------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------
def _emit_text(new: List[Finding], matched: int, stale: List[str],
               total: int, scanned: int, total_modules: int,
               diff_ref: Optional[str]) -> None:
    for f_ in new:
        print(f_.render())
    bits = [f"{total} finding(s)", f"{len(new)} new",
            f"{matched} baselined"]
    if stale:
        bits.append(f"{len(stale)} stale baseline entr"
                    f"{'y' if len(stale) == 1 else 'ies'} "
                    f"(HARD failure — ratchet with --update-baseline)")
    print("tpulint: " + ", ".join(bits))
    scope = f"diff vs {diff_ref}" if diff_ref else "full scan"
    print(f"tpulint: scanned {scanned} of {total_modules} modules "
          f"({scope})")


def _emit_json(new: List[Finding], matched: int, stale: List[str],
               total: int, root: str, scanned: int, total_modules: int,
               diff_ref: Optional[str],
               ranges: Dict[str, List[Tuple[int, int]]]) -> None:
    out = []
    for f_ in new:
        d = f_.to_dict()
        if diff_ref is not None:
            d["on_changed_line"] = _on_changed_line(f_, ranges)
        out.append(d)
    print(json.dumps({
        "tool": "tpulint",
        "root": root,
        "total": total,
        "baselined": matched,
        "stale_baseline": stale,
        "scanned_modules": scanned,
        "total_modules": total_modules,
        "diff_base": diff_ref,
        "new": out,
    }, indent=2))


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_parser()
    try:
        args = parser.parse_args(argv)
        rules = _select_rules(args.rules, args.rule)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    except SystemExit as e:  # argparse already printed help/usage
        return 0 if e.code in (0, None) else 2

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id:28s} [{r.severity}] {r.description}")
        return 0

    if args.write_baseline or args.update_baseline:
        # a partial scan must never become the baseline: it would wipe
        # every out-of-scope grandfathered entry
        if args.diff:
            print("tpulint: refusing to (re)write the baseline from a "
                  "--diff scan: a partial scan must never become the "
                  "baseline", file=sys.stderr)
            return 2
        if len(rules) != len(ALL_RULES):
            print("tpulint: refusing to (re)write the baseline from a "
                  "rule-subset scan (--rule/--rules): the other rules' "
                  "grandfathered entries would be wiped", file=sys.stderr)
            return 2

    baseline_path = args.baseline or bl.default_baseline_path()
    # paths in findings/baseline are relative to the baseline's directory
    # so the report is stable no matter where the scan is launched from
    root = os.path.dirname(os.path.abspath(baseline_path)) or os.getcwd()
    paths = args.paths or _default_paths()
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"tpulint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    project = ProjectInfo.build(paths, root)
    total_modules = len(project.modules)

    only_files: Optional[Set[str]] = None
    ranges: Dict[str, List[Tuple[int, int]]] = {}
    if args.diff:
        try:
            changed, ranges = diff_changed_py(root, args.diff)
        except RuntimeError as e:
            print(f"tpulint: --diff {args.diff}: {e}", file=sys.stderr)
            return 2
        # impact closure: a changed CALLEE that grew an effect produces
        # its interprocedural finding in an UNCHANGED caller, so the
        # scan set must include the reverse-import closure of the
        # changed modules — bounded by the callgraph depth (each call
        # hop crosses at most one import edge)
        only_files = set(changed) | _importer_closure(project, root,
                                                      changed)
    scanned_files = [p for p in iter_python_files(paths)
                     if only_files is None
                     or os.path.abspath(p) in only_files]
    scanned = len(scanned_files)

    findings = scan_paths(paths, rules=rules, root=root, project=project,
                          files=scanned_files)

    if args.write_baseline:
        bl.write_baseline(baseline_path, findings)
        print(f"tpulint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0
    if args.update_baseline:
        refused = bl.update_baseline(baseline_path, findings,
                                     allow_grandfather=args.allow_grandfather)
        if refused:
            print("tpulint: --update-baseline refused — these findings "
                  "are at severity error and would be newly "
                  "grandfathered (fix them, or pass --allow-grandfather "
                  "after review):", file=sys.stderr)
            for f_ in refused:
                print("  " + f_.render().splitlines()[0], file=sys.stderr)
            return 1
        print(f"tpulint: ratcheted baseline to {len(findings)} "
              f"finding(s) at {baseline_path}")
        return 0

    baseline = {} if args.no_baseline else bl.load_baseline(baseline_path)
    if only_files is not None:
        # a diff scan sees only changed modules: entries for unscanned
        # modules are out of scope, not stale
        scanned_rel = {os.path.relpath(p, root).replace(os.sep, "/")
                       for p in scanned_files}
        baseline = {fp: e for fp, e in baseline.items()
                    if e.get("path") in scanned_rel}
    if len(rules) != len(ALL_RULES):
        # a rule-subset run leaves the other rules' entries out of
        # scope, not stale
        selected = {r.id for r in rules}
        baseline = {fp: e for fp, e in baseline.items()
                    if e.get("rule") in selected}
    new, matched, stale = bl.split_new(findings, baseline)

    if args.format == "json":
        _emit_json(new, matched, stale, len(findings), root, scanned,
                   total_modules, args.diff, ranges)
    else:
        _emit_text(new, matched, stale, len(findings), scanned,
                   total_modules, args.diff)
    return 1 if (new or stale) else 0
