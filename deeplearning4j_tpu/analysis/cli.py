"""tpulint CLI: `python -m deeplearning4j_tpu.analysis [paths] ...`.

Exit codes: 0 = clean against the baseline, 1 = new findings (or parse
errors), 2 = usage error. `--format=json` emits a machine round-trippable
report for the CI lane; `--write-baseline` (re)grandfathers the current
scan.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

from deeplearning4j_tpu.analysis import baseline as bl
from deeplearning4j_tpu.analysis.core import Finding, scan_paths
from deeplearning4j_tpu.analysis.rules import ALL_RULES, RULES_BY_ID


def _default_paths() -> List[str]:
    """Scan the installed package when no path is given."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="tpulint: AST analyzer for JAX/TPU anti-patterns "
                    "(host syncs in hot loops, tracer leaks, recompile "
                    "hazards, f64 promotion, unlocked thread state, "
                    "hygiene).")
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: the "
                        "deeplearning4j_tpu package)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", metavar="PATH",
                   help=f"baseline file (default: {bl.BASELINE_NAME} in "
                        f"cwd, then the repo root)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline: every finding is new")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--rules", metavar="ID[,ID...]",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print rule ids and descriptions, then exit")
    return p


def _select_rules(spec: Optional[str]):
    if not spec:
        return ALL_RULES
    ids = [s.strip() for s in spec.split(",") if s.strip()]
    unknown = [i for i in ids if i not in RULES_BY_ID]
    if unknown:
        raise ValueError(
            f"tpulint: unknown rule id(s): {', '.join(unknown)} "
            f"(see --list-rules)")
    return [RULES_BY_ID[i] for i in ids]


def _emit_text(new: List[Finding], matched: int, stale: List[str],
               total: int) -> None:
    for f_ in new:
        print(f_.render())
    bits = [f"{total} finding(s)", f"{len(new)} new",
            f"{matched} baselined"]
    if stale:
        bits.append(f"{len(stale)} stale baseline entr"
                    f"{'y' if len(stale) == 1 else 'ies'} "
                    f"(re-run --write-baseline to ratchet down)")
    print("tpulint: " + ", ".join(bits))


def _emit_json(new: List[Finding], matched: int, stale: List[str],
               total: int, root: str) -> None:
    print(json.dumps({
        "tool": "tpulint",
        "root": root,
        "total": total,
        "baselined": matched,
        "stale_baseline": stale,
        "new": [f_.to_dict() for f_ in new],
    }, indent=2))


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_parser()
    try:
        args = parser.parse_args(argv)
        rules = _select_rules(args.rules)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    except SystemExit as e:  # argparse already printed help/usage
        return 0 if e.code in (0, None) else 2

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id:24s} [{r.severity}] {r.description}")
        return 0

    baseline_path = args.baseline or bl.default_baseline_path()
    # paths in findings/baseline are relative to the baseline's directory
    # so the report is stable no matter where the scan is launched from
    root = os.path.dirname(os.path.abspath(baseline_path)) or os.getcwd()
    paths = args.paths or _default_paths()
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"tpulint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    findings = scan_paths(paths, rules=rules, root=root)

    if args.write_baseline:
        bl.write_baseline(baseline_path, findings)
        print(f"tpulint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    baseline = {} if args.no_baseline else bl.load_baseline(baseline_path)
    new, matched, stale = bl.split_new(findings, baseline)

    if args.format == "json":
        _emit_json(new, matched, stale, len(findings), root)
    else:
        _emit_text(new, matched, stale, len(findings))
    return 1 if new else 0
