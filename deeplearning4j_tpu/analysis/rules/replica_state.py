"""replica-local-state-in-router: fleet code probing engine internals.

The fleet layer (``serving/fleet/``) makes placement, migration, and
scaling decisions ABOUT engines while those engines' step loops run
concurrently. Engine-internal mutable state — ``_slots``, ``_pending``,
``_pool``, ``_seating``, ``_page_tables`` — is guarded by the ENGINE's
lock and mutates mid-step: a router reading it directly races the step
cycle (a half-updated slot scan scores a phantom load), and couples the
fleet to internals the next refactors (prefill/decode disaggregation,
sharded replicas) will move. The sanctioned seams are the public
accessors — ``health()``, ``queue_snapshot()``, ``is_healthy()`` /
``is_ready()`` / ``queue_depth()`` / ``active_slots()``, and the
request-ledger trio ``export_ledger()`` / ``admit_from_ledger()`` /
``detach_ledger()`` — which take the engine lock and hand back
immutable copies.

The rule is structural rather than name-listed: inside a
``serving/fleet/`` module, ANY read of a single-underscore attribute on
an object other than ``self``/``cls`` is a foreign-private probe and is
flagged (dunders exempt). That catches tomorrow's private attribute as
well as today's, and keeps the fleet layer honest about its own
abstractions — private state of fleet classes is reached through
``self``, everything else through a public seam.
"""

from __future__ import annotations

import ast
from typing import Iterator

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)

#: the path fragment that scopes the rule to the fleet layer
_FLEET_PATH = "serving/fleet/"


class ReplicaLocalStateInRouterRule(Rule):
    id = "replica-local-state-in-router"
    severity = SEVERITY_WARNING
    description = ("fleet router/autoscale/migration code reading "
                   "engine-internal (foreign private) mutable state "
                   "instead of the public health()/queue_snapshot()/"
                   "ledger accessors")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if _FLEET_PATH not in mod.rel_path:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Attribute):
                continue
            attr = node.attr
            if not attr.startswith("_") or attr.startswith("__"):
                continue
            base = node.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                continue
            yield self.finding(
                mod, node,
                f"foreign private state `.{attr}` read in fleet code — "
                f"engine internals are lock-guarded and mid-step "
                f"mutable; go through the public accessors "
                f"(health(), queue_snapshot(), export_ledger()/"
                f"admit_from_ledger()/detach_ledger()) or carry a "
                f"justified suppression")
