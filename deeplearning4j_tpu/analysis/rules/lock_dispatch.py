"""lock-held-across-dispatch: device work inside a `with <lock>:` block.

The serving/parallel hot paths hand work between threads under
``threading.Lock``s. A jitted dispatch — or worse, a blocking device
sync — made while HOLDING such a lock couples every other waiter to
the device's latency: a stalled TPU call (dead tunnel, preempted core,
a multi-second compile) under the engine lock freezes ``submit()``,
health probes, and metrics scrapes along with it, turning one slow
dispatch into a process-wide stall. The sanctioned shapes are (a)
snapshot state under the lock, dispatch outside it, or (b) a
deliberately single-threaded dispatcher whose lock guards ONLY the
dispatch path while submit/health/metrics read lock-free — the serving
engine's design, carried as justified inline suppressions.

Flagged inside a lock-holding ``with`` block:

- calls to module-local functions decorated ``@jax.jit`` (directly or
  via ``partial(jax.jit, ...)``);
- the repo's canonical dispatch entry points (``rnn_time_step``,
  ``util.decoding.prime_prompt/step_tokens/verify_tokens``,
  ``serving.paging.gather_pages/scatter_pages``);
- blocking device syncs: ``block_until_ready`` (function or method),
  ``jax.device_get``, ``jax.effects_barrier``.

Condition variables (`cond`) are exempt: a ``Condition.wait`` park is
the queue idiom, not a device-latency coupling.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Set

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)

#: lock-like context expressions (cond/sem deliberately absent: waiting
#: on a Condition is the handoff idiom, not a device stall under a lock)
_LOCKISH = re.compile(r"lock|mutex", re.IGNORECASE)

#: canonical dotted names of repo dispatch entry points + jax syncs
_DISPATCH_CALLS = {
    "deeplearning4j_tpu.util.decoding.prime_prompt",
    "deeplearning4j_tpu.util.decoding.step_tokens",
    "deeplearning4j_tpu.util.decoding.verify_tokens",
    "deeplearning4j_tpu.serving.paging.gather_pages",
    "deeplearning4j_tpu.serving.paging.scatter_pages",
}
_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready",
               "jax.effects_barrier"}
#: method names that are dispatches/syncs wherever they appear
_DISPATCH_ATTRS = {"rnn_time_step"}
_SYNC_ATTRS = {"block_until_ready"}


def _is_jax_jit(mod: ModuleInfo, node: ast.AST) -> bool:
    """True for a decorator expression meaning jax.jit: bare ``jax.jit``,
    ``jax.jit(...)``, or ``partial(jax.jit, ...)``."""
    if mod.resolve(node) == "jax.jit":
        return True
    if isinstance(node, ast.Call):
        fn = mod.resolve(node.func)
        if fn == "jax.jit":
            return True
        if fn == "functools.partial" and node.args \
                and mod.resolve(node.args[0]) == "jax.jit":
            return True
    return False


def _jitted_locals(mod: ModuleInfo) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and any(_is_jax_jit(mod, d) for d in node.decorator_list):
            out.add(node.name)
    return out


def _lock_with(mod: ModuleInfo, node: ast.With) -> bool:
    return any(_LOCKISH.search(mod.segment(item.context_expr))
               for item in node.items)


class LockHeldAcrossDispatchRule(Rule):
    id = "lock-held-across-dispatch"
    severity = SEVERITY_WARNING
    description = ("jitted dispatch or blocking device sync while "
                   "holding a threading lock — a stalled device call "
                   "freezes every other waiter on the lock")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not mod.imports_module("jax") and \
                not mod.imports_module("deeplearning4j_tpu"):
            return
        jitted = _jitted_locals(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            what = self._classify(mod, node, jitted)
            if what is None:
                continue
            holder = self._enclosing_lock_with(mod, node)
            if holder is None:
                continue
            yield self.finding(
                mod, node,
                f"{what} inside `with "
                f"{mod.segment(holder.items[0].context_expr)}:` — a "
                f"stalled device call here blocks every thread waiting "
                f"on the lock; snapshot under the lock and dispatch "
                f"outside it (or carry a justified suppression)")

    # -- helpers -------------------------------------------------------
    @staticmethod
    def _enclosing_lock_with(mod: ModuleInfo, node: ast.AST):
        """Nearest lock-guarded With between `node` and its enclosing
        function (a lock taken in an OUTER function is that function's
        finding, not this one's)."""
        for a in mod.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return None
            if isinstance(a, ast.With) and _lock_with(mod, a):
                return a
        return None

    def _classify(self, mod: ModuleInfo, call: ast.Call,
                  jitted: Set[str]):
        name = mod.resolve(call.func)
        if name is not None:
            if name in _SYNC_CALLS:
                return f"blocking device sync `{name}`"
            if name in _DISPATCH_CALLS:
                return f"jitted dispatch `{name.rsplit('.', 1)[-1]}`"
            if name in jitted:
                return f"locally-jitted dispatch `{name}`"
        if isinstance(call.func, ast.Attribute):
            if call.func.attr in _SYNC_ATTRS:
                return f"blocking device sync `.{call.func.attr}()`"
            if call.func.attr in _DISPATCH_ATTRS:
                return f"jitted dispatch `.{call.func.attr}()`"
        return None
