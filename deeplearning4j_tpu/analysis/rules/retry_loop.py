"""unbounded-retry: a retry loop that can spin forever.

The shape ``while True: try: ... except ...: time.sleep(k)`` turns a
dead dependency into a hung process: no attempt ceiling, usually no
backoff, and on a serving or ETL thread it pins the worker exactly when
the operator needs it to fail loudly. The resilience layer's
``resilience.retry.retry_call`` is the sanctioned replacement — bounded
attempts, exponential backoff with jitter, and retry metrics.

A loop is flagged when ALL of:

- it is a ``while`` with a constant-true test (``while True:`` /
  ``while 1:``) — condition-bounded loops (``while attempt < n``,
  ``while not stop.is_set()``) and ``for`` loops over ``range`` are
  bounded by construction;
- it calls ``time.sleep`` somewhere in its body (the hallmark of a
  wait-and-try-again loop, as opposed to a consumer poll);
- it contains an exception handler that swallows and loops — no
  ``raise``, ``break``, or ``return`` anywhere in the handler, which is
  precisely the missing attempt bound (a handler that re-raises after
  ``if attempts > limit`` is the bound). Only handlers whose NEAREST
  enclosing loop is the while-True itself count: a bounded inner
  ``for attempt in range(n)`` retry nested inside a legitimate daemon
  loop belongs to the ``for``, not the daemon loop.
"""

from __future__ import annotations

import ast
from typing import Iterator

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)


def _const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _calls_sleep(mod: ModuleInfo, node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and \
                mod.resolve(sub.func) == "time.sleep":
            return True
    return False


def _bounds_the_loop(mod: ModuleInfo, stmt: ast.AST,
                     handler: ast.ExceptHandler) -> bool:
    """True if `stmt` actually bounds the retry loop the handler serves:
    a ``break`` that exits the retry loop itself (not a nested for), a
    ``return`` from the loop's own function (not a nested def), a
    ``raise`` that propagates (not one inside a nested try that may
    swallow it locally). Ownership = nothing of the capturing kind
    between the statement and the handler."""
    if isinstance(stmt, ast.Break):
        blockers = (ast.For, ast.While, ast.AsyncFor)
    elif isinstance(stmt, ast.Return):
        blockers = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    elif isinstance(stmt, ast.Raise):
        blockers = (ast.Try,)
    else:
        return False
    for a in mod.ancestors(stmt):
        if a is handler:
            return True
        if isinstance(a, blockers):
            return False
    return False


def _swallowing_handler(mod: ModuleInfo, loop: ast.While):
    """First except handler BELONGING TO `loop` (nearest enclosing loop
    is `loop` itself — a handler inside a nested bounded ``for`` is that
    loop's business) with no raise/break/return that bounds the loop."""
    for sub in ast.walk(loop):
        if not isinstance(sub, ast.ExceptHandler):
            continue
        nearest = None
        for a in mod.ancestors(sub):
            if isinstance(a, (ast.For, ast.While, ast.AsyncFor)):
                nearest = a
                break
        if nearest is not loop:
            continue
        if not any(_bounds_the_loop(mod, s, sub)
                   for body in sub.body for s in ast.walk(body)):
            return sub
    return None


class UnboundedRetryRule(Rule):
    id = "unbounded-retry"
    severity = SEVERITY_WARNING
    description = ("while-True retry loop with time.sleep but no attempt "
                   "bound; use resilience.retry.retry_call (bounded "
                   "backoff + jitter)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.While) or not _const_true(node.test):
                continue
            if not _calls_sleep(mod, node):
                continue
            handler = _swallowing_handler(mod, node)
            if handler is None:
                continue
            yield self.finding(
                mod, node,
                "unbounded retry: `while True` + time.sleep with an "
                "except handler that never raises/breaks — a dead "
                "dependency hangs this thread forever; bound it with "
                "resilience.retry.retry_call (max_attempts + "
                "exponential backoff + jitter)")
