"""Hygiene rules: bare `except:` and mutable default arguments.

Small, classic, and disproportionately painful in an accelerator
codebase: a bare except swallows `KeyboardInterrupt` in a fit loop that
takes hours, and a mutable default on a layer/config constructor aliases
state across every model built in the process.
"""

from __future__ import annotations

import ast
from typing import Iterator

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)


class BareExceptRule(Rule):
    id = "bare-except"
    severity = SEVERITY_WARNING
    description = ("bare `except:` swallows KeyboardInterrupt/SystemExit; "
                   "catch Exception or narrower")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield self.finding(
                    mod, node,
                    "bare `except:` also catches KeyboardInterrupt and "
                    "SystemExit; use `except Exception:` or narrower")


class MutableDefaultRule(Rule):
    id = "mutable-default-arg"
    severity = SEVERITY_WARNING
    description = "mutable default argument is shared across all calls"

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            defaults = list(node.args.defaults) + \
                [d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                bad = None
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    bad = {ast.List: "list", ast.Dict: "dict",
                           ast.Set: "set"}[type(d)]
                elif isinstance(d, ast.Call) and isinstance(d.func, ast.Name) \
                        and d.func.id in ("list", "dict", "set", "bytearray"):
                    bad = d.func.id
                if bad:
                    yield self.finding(
                        mod, d,
                        f"mutable default ({bad}) on '{node.name}' is "
                        f"evaluated once and shared across calls; default "
                        f"to None and create inside")
