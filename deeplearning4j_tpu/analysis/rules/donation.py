"""donation-use-after-consume: a donated buffer read after the dispatch
that consumed it.

`donate_argnums` / the streaming `donate_state=True` protocol hand an
argument's buffers to XLA for in-place reuse: after the dispatch the
Python-side array is DELETED — touching it again is an error on real
accelerators (and silently fine on CPU, which is exactly why review
keeps missing it). The PR 10 `decode_retry` bug was this class: a
retried dispatch re-ran against state buffers its first attempt had
already consumed. The repo's contract (serving/engine.py `_donate`):
donation and re-execution are mutually exclusive — a consumed value must
be reassigned from the dispatch result before ANY later read, return, or
re-dispatch on every path.

Three statically checkable shapes:

1. sequence — a name (or ``self.attr`` chain) passed at a donated
   position is loaded, returned, or re-dispatched later in the same
   function on some path that did not unconditionally reassign it first;
2. loop — a donating dispatch inside a for/while whose consumed argument
   is never rebound in the loop body: iteration 2 re-reads the buffer
   iteration 1 consumed;
3. retried callable — a donating dispatch (including literal
   ``donate_state=True``) inside a nested def/lambda handed to a
   ``retry``-shaped call: every retry attempt after the first re-runs
   against consumed buffers (the PR 10 shape; fix like the engine —
   donation OFF whenever a retry policy is configured, or re-stage
   inputs per attempt).

Donating callables are recognized from ``@partial(jax.jit,
donate_argnums=...)`` decorations and ``g = jax.jit(f,
donate_argnums=...)`` module assignments, locally and — with a
`ProjectInfo` — across module boundaries through import aliases.
Dynamic aliasing (jits stored in dicts, passed as parameters) is out of
scope: under-approximate, never noisy.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple, Union

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_ERROR)
from deeplearning4j_tpu.analysis.rules._common import (
    _is_tracing_wrapper, walk_no_defs as _walk_no_defs)

#: call names that re-run their callable argument (the retry shape)
_RETRY_NAME = re.compile(r"retry", re.IGNORECASE)

#: sentinel for donate_state=True dispatches (no positional key tracked:
#: the consumed buffers are the callee's internal streaming state)
STATE = "state"


@dataclasses.dataclass(frozen=True)
class DonatingCall:
    label: str                                  # display name of the callee
    positions: Union[FrozenSet[int], str]       # donated argnums, or STATE


def _literal_argnums(val: ast.AST) -> Optional[FrozenSet[int]]:
    if isinstance(val, ast.Constant) and isinstance(val.value, int):
        return frozenset({val.value})
    if isinstance(val, (ast.Tuple, ast.List)):
        out = {e.value for e in val.elts
               if isinstance(e, ast.Constant) and isinstance(e.value, int)}
        return frozenset(out) if out else None
    return None


def _donating_jit_call(mod: ModuleInfo,
                       call: ast.Call) -> Optional[FrozenSet[int]]:
    """donate_argnums of a `jax.jit(...)`/`partial(jax.jit, ...)` call
    expression, when literal and non-empty."""
    if not (isinstance(call, ast.Call) and _is_tracing_wrapper(mod, call)):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _literal_argnums(kw.value)
    return None


def module_donation_map(mod: ModuleInfo) -> Dict[str, FrozenSet[int]]:
    """key -> donated positions for every statically visible donating
    callable at MODULE scope: decorated defs and ``name = jax.jit(f,
    donate_argnums=...)`` bindings. Class members are keyed
    ``Class.name`` ONLY and nested (function-local) callables are NOT
    recorded here at all — either form of bare-name sharing would let
    an unrelated same-named callable inherit donation (an
    error-severity false positive). Function-local donating callables
    come from `function_donation_map`. Memoized per module."""
    return mod.fact("donation_map", _compute_donation_map)


def _scope_donations(mod: ModuleInfo, scope,
                     cls_prefix: str,
                     out: Dict[str, FrozenSet[int]],
                     recurse_classes: bool) -> None:
    for node in scope:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    nums = _donating_jit_call(mod, dec)
                    if nums:
                        out[f"{cls_prefix}{node.name}"] = nums
            # nested defs are a narrower scope: not recorded here
        elif isinstance(node, ast.ClassDef) and recurse_classes:
            _scope_donations(mod, node.body, f"{node.name}.", out, True)
        elif isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call):
            nums = _donating_jit_call(mod, node.value)
            if nums:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[f"{cls_prefix}{t.id}"] = nums


def _compute_donation_map(mod: ModuleInfo) -> Dict[str, FrozenSet[int]]:
    out: Dict[str, FrozenSet[int]] = {}
    _scope_donations(mod, mod.tree.body, "", out, recurse_classes=True)
    return out


def function_donation_map(mod: ModuleInfo,
                          fn: ast.AST) -> Dict[str, FrozenSet[int]]:
    """Donating callables bound in `fn`'s own body (its immediate
    nested defs and local jit-assignments) — visible to calls within
    `fn` only; deeper nested defs are their own scope."""
    out: Dict[str, FrozenSet[int]] = {}
    _scope_donations(mod, fn.body, "", out, recurse_classes=False)
    return out


def classify_donating_call(mod: ModuleInfo, call: ast.Call,
                           donation_map: Dict[str, FrozenSet[int]],
                           project=None) -> Optional[DonatingCall]:
    """DonatingCall when `call` dispatches a donating jit (local map,
    cross-module via project, or a literal ``donate_state=True``)."""
    for kw in call.keywords:
        if kw.arg == "donate_state" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            label = _callee_label(call)
            return DonatingCall(label, STATE)
    func = call.func
    if isinstance(func, ast.Name) and func.id in donation_map:
        return DonatingCall(func.id, donation_map[func.id])
    canonical = mod.resolve(func)
    if canonical is not None and project is not None:
        resolved = project.resolve_name(canonical)
        if resolved is not None and resolved[1]:
            target_mod = project.modules.get(resolved[0])
            if target_mod is not None and target_mod is not mod:
                dmap = _project_donation_map(project, resolved[0],
                                             target_mod)
                # exact qualname only: a bare-name fallback would let
                # B.step inherit A.step's donation (error-severity FP)
                nums = dmap.get(resolved[1])
                if nums:
                    return DonatingCall(resolved[1], nums)
    return None


def _project_donation_map(project, mod_name: str,
                          mod: ModuleInfo) -> Dict[str, FrozenSet[int]]:
    cache = getattr(project, "_donation_maps", None)
    if cache is None:
        cache = {}
        project._donation_maps = cache
    if mod_name not in cache:
        cache[mod_name] = module_donation_map(mod)
    return cache[mod_name]


def _callee_label(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return "<dispatch>"


def _key_of(node: ast.AST) -> Optional[str]:
    """Dotted key for a Name / self-rooted Attribute chain, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    parts.append(cur.id)
    return ".".join(reversed(parts))


def _loads_of(node: ast.AST, key: str) -> Optional[ast.AST]:
    """First Load of `key` inside `node` (nested defs excluded)."""
    for sub in _walk_no_defs(node):
        if isinstance(sub, ast.Name) and "." not in key \
                and sub.id == key and isinstance(sub.ctx, ast.Load):
            return sub
        if isinstance(sub, ast.Attribute) and "." in key \
                and isinstance(sub.ctx, ast.Load) \
                and _key_of(sub) == key:
            return sub
    return None


def _target_is_key(t: ast.AST, key: str) -> bool:
    if isinstance(t, ast.Tuple):
        return any(_target_is_key(e, key) for e in t.elts)
    return _key_of(t) == key


class _PathScan:
    """Ordered use-before-kill scan over statement blocks."""

    def scan_block(self, stmts: List[ast.stmt],
                   key: str) -> Tuple[Optional[ast.AST], bool]:
        """(first use, killed-on-all-paths) for a statement sequence."""
        for s in stmts:
            use, killed = self.scan_stmt(s, key)
            if use is not None:
                return use, False
            if killed:
                return None, True
        return None, False

    def scan_stmt(self, s: ast.stmt,
                  key: str) -> Tuple[Optional[ast.AST], bool]:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return None, False
        if isinstance(s, ast.Assign):
            use = _loads_of(s.value, key)
            if use is None:
                for t in s.targets:  # a[key] = ... reads key
                    for sub in ast.walk(t):
                        if isinstance(sub, (ast.Subscript, ast.Call)):
                            use = _loads_of(sub, key)
                            if use is not None:
                                break
                    if use is not None:
                        break
            killed = any(_target_is_key(t, key) for t in s.targets)
            return use, (killed and use is None)
        if isinstance(s, ast.AnnAssign):
            use = _loads_of(s.value, key) if s.value is not None else None
            return use, (use is None and _target_is_key(s.target, key))
        if isinstance(s, ast.AugAssign):
            if _target_is_key(s.target, key):
                return s.target, False  # read-modify-write: a use
            return _loads_of(s.value, key), False
        if isinstance(s, ast.If):
            use = _loads_of(s.test, key)
            if use is not None:
                return use, False
            u1, k1 = self.scan_block(s.body, key)
            u2, k2 = self.scan_block(s.orelse, key)
            use = u1 if u1 is not None else u2
            return use, (use is None and k1 and k2 and bool(s.orelse))
        if isinstance(s, (ast.For, ast.AsyncFor)):
            use = _loads_of(s.iter, key)
            if use is not None:
                return use, False
            if _target_is_key(s.target, key):
                return None, False  # rebound each iteration
            u, _k = self.scan_block(s.body, key)
            if u is None:
                u, _k = self.scan_block(s.orelse, key)
            return u, False  # loop may run zero times: never a kill
        if isinstance(s, ast.While):
            use = _loads_of(s.test, key)
            if use is not None:
                return use, False
            u, _k = self.scan_block(s.body, key)
            return u, False
        if isinstance(s, (ast.With, ast.AsyncWith)):
            killed = False
            for item in s.items:
                use = _loads_of(item.context_expr, key)
                if use is not None:
                    return use, False
                if item.optional_vars is not None \
                        and _target_is_key(item.optional_vars, key):
                    killed = True
            if killed:
                return None, True
            return self.scan_block(s.body, key)
        if isinstance(s, ast.Try):
            u_body, k_body = self.scan_block(s.body, key)
            if u_body is not None:
                return u_body, False
            handlers_ok = True
            for h in self.handlers_of(s):
                u, k = self.scan_block(h.body, key)
                if u is not None:
                    return u, False
                # a handler path needs no kill if it cannot fall
                # through (raise/return/continue/break terminal)
                if not (k or self._terminates(h.body)):
                    handlers_ok = False
            u_else, k_else = self.scan_block(s.orelse, key)
            if u_else is not None:
                return u_else, False
            u_fin, k_fin = self.scan_block(s.finalbody, key)
            if u_fin is not None:
                return u_fin, False
            if k_fin:
                return None, True   # finally runs on every path
            # the success path kills via the body or its else; the
            # exception path needs every handler to kill or be unable
            # to fall through (the exception may have fired BEFORE the
            # body's kill completed)
            return None, ((k_body or k_else) and handlers_ok)

        # Return / Expr / Raise / Assert / Delete / ...
        return _loads_of(s, key), False

    @staticmethod
    def handlers_of(s: ast.Try):
        return s.handlers

    @staticmethod
    def _terminates(stmts: List[ast.stmt]) -> bool:
        return bool(stmts) and isinstance(
            stmts[-1], (ast.Raise, ast.Return, ast.Continue, ast.Break))


def _iter_blocks(fn: ast.AST) -> Iterator[List[ast.stmt]]:
    """Every statement list lexically inside `fn` (nested defs excluded),
    outermost first."""
    queue: List[List[ast.stmt]] = [fn.body]
    while queue:
        block = queue.pop(0)
        yield block
        for s in block:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(s, attr, None)
                if sub:
                    queue.append(sub)
            for h in getattr(s, "handlers", []):
                queue.append(h.body)


class DonationUseAfterConsumeRule(Rule):
    id = "donation-use-after-consume"
    severity = SEVERITY_ERROR
    description = ("a value passed to a donate_argnums/donate_state=True "
                   "dispatch is read, returned, or re-dispatched after "
                   "the dispatch consumed its buffers (the PR 10 "
                   "decode_retry class)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        yield from self.check_project(mod, None)

    def check_project(self, mod: ModuleInfo, project) -> Iterator[Finding]:
        from deeplearning4j_tpu.analysis.project import iter_functions
        dmap = module_donation_map(mod)
        scanner = _PathScan()
        for _qual, fn in iter_functions(mod):
            # names bound in enclosing functions are visible here
            # (closure scoping), innermost binding shadowing outward
            merged = dict(dmap)
            for scope in reversed(list(mod.enclosing_functions(fn))):
                merged.update(function_donation_map(mod, scope))
            merged.update(function_donation_map(mod, fn))
            yield from self._check_function(mod, fn, merged, scanner,
                                            project)

    # -- per-function shapes -------------------------------------------
    def _check_function(self, mod: ModuleInfo, fn: ast.AST,
                        dmap: Dict[str, FrozenSet[int]],
                        scanner: _PathScan, project) -> Iterator[Finding]:
        flagged_keys: Set[str] = set()
        donating: List[Tuple[ast.Call, DonatingCall, ast.stmt]] = []
        # shape 1: sequence scan per block. Only SIMPLE statements are
        # consumption points here: a donating call nested in a compound
        # statement is processed when its own (inner) block comes up, so
        # a rebinding inside the compound (``for x in xs: state =
        # step(state, x)``) cannot be misread as a use-after-consume by
        # the outer sequence. Calls in compound HEADERS (an ``if
        # step(...):`` test) are out of scope — documented
        # under-approximation.
        simple = (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr,
                  ast.Return, ast.Raise, ast.Assert, ast.Delete)
        for block in _iter_blocks(fn):
            for i, stmt in enumerate(block):
                if not isinstance(stmt, simple):
                    continue
                for call in self._calls_in(stmt):
                    don = classify_donating_call(mod, call, dmap,
                                                 project=project)
                    if don is None:
                        continue
                    donating.append((call, don, stmt))
                    if don.positions == STATE:
                        continue
                    for pos in sorted(don.positions):
                        if pos >= len(call.args):
                            continue
                        key = _key_of(call.args[pos])
                        if key is None or key in flagged_keys:
                            continue
                        if self._stmt_rebinds(stmt, key):
                            continue  # x = dispatch(x): the refresh idiom
                        use, _killed = scanner.scan_block(
                            block[i + 1:], key)
                        if use is not None:
                            flagged_keys.add(key)
                            yield self.finding(
                                mod, use,
                                f"'{key}' read after being donated to "
                                f"{don.label}() (donate_argnums={pos}): "
                                f"the dispatch consumed its buffers — "
                                f"reassign '{key}' from the dispatch "
                                f"result before any later use, or copy "
                                f"before donating")
        # shape 2: re-dispatch in a loop without rebinding
        for call, don, _stmt in donating:
            if don.positions == STATE:
                continue
            loop = self._innermost_loop(mod, call, fn)
            if loop is None:
                continue
            for pos in sorted(don.positions):
                if pos >= len(call.args):
                    continue
                key = _key_of(call.args[pos])
                if key is None or key in flagged_keys:
                    continue
                if not self._rebound_in(loop, key):
                    flagged_keys.add(key)
                    yield self.finding(
                        mod, call,
                        f"donating dispatch {don.label}() re-reads "
                        f"'{key}' on the next loop iteration: the first "
                        f"iteration consumed its buffers and '{key}' is "
                        f"never rebound in the loop body")
        # shape 3: donating dispatch inside a retried callable
        yield from self._retry_shape(mod, fn, dmap, project)

    # -- helpers -------------------------------------------------------
    @staticmethod
    def _calls_in(stmt: ast.stmt) -> Iterator[ast.Call]:
        for sub in _walk_no_defs(stmt):
            if isinstance(sub, ast.Call):
                yield sub

    @staticmethod
    def _stmt_rebinds(stmt: ast.stmt, key: str) -> bool:
        if isinstance(stmt, ast.Assign):
            return any(_target_is_key(t, key) for t in stmt.targets)
        if isinstance(stmt, ast.AnnAssign):
            return _target_is_key(stmt.target, key)
        return False

    @staticmethod
    def _innermost_loop(mod: ModuleInfo, node: ast.AST,
                        fn: ast.AST) -> Optional[ast.AST]:
        for anc in mod.ancestors(node):
            if anc is fn:
                return None
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return None  # nested def: a different execution context
            if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                return anc
        return None

    @staticmethod
    def _rebound_in(loop: ast.AST, key: str) -> bool:
        for sub in _walk_no_defs(loop):
            if isinstance(sub, ast.Assign) \
                    and any(_target_is_key(t, key) for t in sub.targets):
                return True
            if isinstance(sub, (ast.For, ast.AsyncFor)) \
                    and _target_is_key(sub.target, key):
                return True
            if isinstance(sub, ast.withitem) \
                    and sub.optional_vars is not None \
                    and _target_is_key(sub.optional_vars, key):
                return True
        return False

    def _retry_shape(self, mod: ModuleInfo, fn: ast.AST,
                     dmap: Dict[str, FrozenSet[int]],
                     project) -> Iterator[Finding]:
        # nested callables defined anywhere in this function
        nested: Dict[str, ast.AST] = {}
        for child in ast.walk(fn):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and child is not fn:
                nested[child.name] = child
        for call in self._calls_in_fn(fn):
            name = _callee_label(call)
            if not _RETRY_NAME.search(name):
                continue
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                cand: Optional[ast.AST] = None
                if isinstance(arg, ast.Lambda):
                    cand = arg
                elif isinstance(arg, ast.Name) and arg.id in nested:
                    cand = nested[arg.id]
                if cand is None:
                    continue
                don = self._donating_inside(mod, cand, dmap, project)
                if don is None:
                    continue
                don_call, don_info = don
                yield self.finding(
                    mod, call,
                    f"donating dispatch {don_info.label}() (line "
                    f"{don_call.lineno}) runs inside a callable passed "
                    f"to {name}(): a retried attempt re-runs against "
                    f"buffers the first attempt already consumed (the "
                    f"PR 10 decode_retry bug) — disable donation "
                    f"whenever a retry policy is configured, or "
                    f"re-stage the donated inputs per attempt",
                    chain=(f"{name}() at {mod.rel_path}:{call.lineno}",
                           f"{don_info.label}() at "
                           f"{mod.rel_path}:{don_call.lineno}"))
                break

    @staticmethod
    def _calls_in_fn(fn: ast.AST) -> Iterator[ast.Call]:
        for sub in _walk_no_defs(fn, include_self=False):
            if isinstance(sub, ast.Call):
                yield sub

    def _donating_inside(self, mod: ModuleInfo, callable_node: ast.AST,
                         dmap, project):
        """A donating dispatch lexically inside a nested callable (its
        own further-nested defs excluded), or reached through one
        resolved project call (bounded: retries wrap thin closures)."""
        body = callable_node.body
        stmts = body if isinstance(body, list) else None
        subs: List[ast.AST] = []
        if stmts is not None:
            for stmt in stmts:
                subs.extend(_walk_no_defs(stmt))
        else:  # Lambda: body is a bare expression
            subs.extend(_walk_no_defs(body))
        for sub in subs:
            if not isinstance(sub, ast.Call):
                continue
            don = classify_donating_call(mod, sub, dmap, project=project)
            if don is not None:
                return sub, don
            if project is not None:
                target = project.resolve_call(mod, sub)
                if target is not None:
                    ev = project.callgraph.reaches(
                        f"{target[0]}:{target[1]}",
                        frozenset({"donating_dispatch"}), max_depth=2)
                    if ev is not None:
                        eff, _chain = ev
                        return sub, DonatingCall(eff.what, frozenset())
        return None
