"""tpulint rule registry.

Rule families: host-sync + device-transfer (ISSUE 3; interprocedurally
promoted in ISSUE 13), tracer-leak, recompile-hazard, dtype-promotion,
concurrency, hygiene, retry (ISSUE 4), state-write (ISSUE 7),
world-snapshot (ISSUE 8), lock-dispatch (ISSUE 9),
int8-promotion-in-dispatch (ISSUE 18 — quantized-pool reads must
explicitly widen before arithmetic), the ISSUE 13
exactness-contract families: donation-use-after-consume and
jit-key-drift, replica-state (ISSUE 14 — the fleet layer reads
engines only through public accessors), and wall-clock (ISSUE 15 —
clock reads inside traced/step-builder bodies bake trace-time
constants). Adding a rule = subclass
`analysis.core.Rule` (optionally with a ``check_project`` for
whole-program facts), instantiate it here.
"""

from __future__ import annotations

from typing import Dict, List

from deeplearning4j_tpu.analysis.core import Rule
from deeplearning4j_tpu.analysis.rules.host_sync import HostSyncRule
from deeplearning4j_tpu.analysis.rules.device_transfer import (
    DeviceTransferRule)
from deeplearning4j_tpu.analysis.rules.tracer_leak import TracerLeakRule
from deeplearning4j_tpu.analysis.rules.recompile import RecompileHazardRule
from deeplearning4j_tpu.analysis.rules.dtype import (
    DtypePromotionRule, Int8PromotionRule)
from deeplearning4j_tpu.analysis.rules.concurrency import ThreadSharedStateRule
from deeplearning4j_tpu.analysis.rules.hygiene import (
    BareExceptRule, MutableDefaultRule)
from deeplearning4j_tpu.analysis.rules.lock_dispatch import (
    LockHeldAcrossDispatchRule)
from deeplearning4j_tpu.analysis.rules.retry_loop import UnboundedRetryRule
from deeplearning4j_tpu.analysis.rules.state_write import (
    NonAtomicStateWriteRule)
from deeplearning4j_tpu.analysis.rules.world_snapshot import (
    WorldSnapshotRule)
from deeplearning4j_tpu.analysis.rules.donation import (
    DonationUseAfterConsumeRule)
from deeplearning4j_tpu.analysis.rules.jit_key import JitKeyDriftRule
from deeplearning4j_tpu.analysis.rules.replica_state import (
    ReplicaLocalStateInRouterRule)
from deeplearning4j_tpu.analysis.rules.wall_clock import (
    WallClockInTracedBodyRule)

ALL_RULES: List[Rule] = [
    HostSyncRule(),
    DeviceTransferRule(),
    DonationUseAfterConsumeRule(),
    JitKeyDriftRule(),
    TracerLeakRule(),
    RecompileHazardRule(),
    DtypePromotionRule(),
    Int8PromotionRule(),
    ThreadSharedStateRule(),
    LockHeldAcrossDispatchRule(),
    BareExceptRule(),
    MutableDefaultRule(),
    UnboundedRetryRule(),
    NonAtomicStateWriteRule(),
    WorldSnapshotRule(),
    ReplicaLocalStateInRouterRule(),
    WallClockInTracedBodyRule(),
]

RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in ALL_RULES}

__all__ = ["ALL_RULES", "RULES_BY_ID"]
