"""non-atomic-state-write: state serialized straight onto its final path.

``open(path, "w"/"wb")`` + ``json.dump``/``pickle.dump`` (or
``f.write(json.dumps(...))``, or a ``zipfile.ZipFile(path, "w")`` model
save) truncates the ONLY copy of the state before the new bytes are
durable: a crash mid-write — preemption, disk-full, SIGKILL — leaves a
torn file where a loadable one used to be, and the next load fails (or
worse, half-parses). The sanctioned shape is tmp-in-same-dir → flush →
fsync → ``os.replace`` — ``resilience.durable.atomic_write_json`` /
``atomic_write_bytes`` for JSON/blob state, or writing the zip/npz to a
tmp path and renaming it into place.

A write is flagged when ALL of:

- the sink is ``open(path, "w"|"wb")`` (append-mode sinks are logs, not
  replace-writes) or ``zipfile.ZipFile(path, "w")``;
- serialized STATE flows into it: ``json.dump(obj, f)``,
  ``pickle.dump(obj, f)``, ``f.write(json.dumps(...))`` anywhere in the
  ``with`` body — or, for ZipFile, the zip itself (a whole-model
  archive is state by construction);
- the target path shows no sign of the tmp-rename idiom: any ``tmp`` in
  the path expression (``tmp = path + ".tmp"``, ``mktemp``,
  ``tmp_path``) marks the write as the tmp half of an atomic replace
  and exempts it.

``resilience/durable.py`` — the helper the rule points at — is exempt
wholesale. Plain-text report/HTML exports (``f.write(html)``) are out of
scope: losing a report to a crash is an inconvenience, not a recovery
failure.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)

_DUMPERS = {"json.dump", "pickle.dump"}
_SERIALIZERS = {"json.dumps", "pickle.dumps"}


def _call_mode(call: ast.Call, default: str = "r") -> Optional[str]:
    """The literal mode of an open()/ZipFile() call; None when dynamic."""
    arg = None
    if len(call.args) >= 2:
        arg = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            arg = kw.value
    if arg is None:
        return default
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


def _is_tmp_target(mod: ModuleInfo, call: ast.Call) -> bool:
    """True when the path expression carries the tmp-rename idiom."""
    if not call.args:
        return False
    seg = mod.segment(call.args[0]).lower()
    return "tmp" in seg


def _dump_into(mod: ModuleInfo, with_node: ast.With,
               handle: Optional[str]) -> Optional[ast.AST]:
    """First statement in the with-body that serializes state into the
    opened handle."""
    for sub in ast.walk(with_node):
        if not isinstance(sub, ast.Call):
            continue
        name = mod.resolve(sub.func)
        if name in _DUMPERS:
            sink = None
            if len(sub.args) >= 2:
                sink = sub.args[1]
            for kw in sub.keywords:
                if kw.arg in ("fp", "file"):
                    sink = kw.value
            if handle is None or (isinstance(sink, ast.Name)
                                  and sink.id == handle):
                return sub
        # f.write(json.dumps(...) [+ ...])
        if isinstance(sub.func, ast.Attribute) and sub.func.attr == "write" \
                and isinstance(sub.func.value, ast.Name) \
                and (handle is None or sub.func.value.id == handle):
            for inner in ast.walk(sub):
                if isinstance(inner, ast.Call) and \
                        mod.resolve(inner.func) in _SERIALIZERS:
                    return sub
    return None


class NonAtomicStateWriteRule(Rule):
    id = "non-atomic-state-write"
    severity = SEVERITY_WARNING
    description = ("state serialized directly onto its final path "
                   "(open(w/wb)+json/pickle.dump or ZipFile(path,'w')); "
                   "a crash mid-write destroys the only copy — use the "
                   "tmp-write-fsync-rename helper "
                   "(resilience.durable.atomic_write_*)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if mod.rel_path.endswith("resilience/durable.py"):
            return  # the atomic helper itself
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                call = item.context_expr
                if not isinstance(call, ast.Call):
                    continue
                name = mod.resolve(call.func)
                if name == "open":
                    if _call_mode(call) not in ("w", "wb"):
                        continue
                    if _is_tmp_target(mod, call):
                        continue
                    handle = item.optional_vars.id \
                        if isinstance(item.optional_vars, ast.Name) else None
                    hit = _dump_into(mod, node, handle)
                    if hit is not None:
                        yield self.finding(
                            mod, hit,
                            "state dumped straight onto its final path: "
                            "a crash mid-write leaves a torn file where "
                            "a loadable one was — write tmp-in-same-dir "
                            "then fsync + os.replace (resilience.durable"
                            ".atomic_write_json/_bytes)")
                elif name == "zipfile.ZipFile":
                    if _call_mode(call) != "w":
                        continue
                    if _is_tmp_target(mod, call):
                        continue
                    yield self.finding(
                        mod, call,
                        "model zip written straight onto its final "
                        "path: a crash mid-write destroys the previous "
                        "save — build the archive at a tmp path and "
                        "os.replace it into place")
