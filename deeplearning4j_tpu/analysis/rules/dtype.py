"""dtype rules: float64 creeping into jax program modules, and int8
buffers reaching arithmetic without an explicit widen.

TPUs execute f64 in slow software emulation (or jax silently truncates
to f32 with `jax_enable_x64` off, masking the intent). Either way a
float64 literal or dtype in a module that builds jax computations is a
hazard — except in the finite-difference gradient checker, whose whole
point is f64 reference arithmetic, and the central x64 shim in
util/jax_compat that gates it.

The int8 rule is the ISSUE 18 companion: a quantized KV pool hands
int8 arrays to dispatch code, and jax's type promotion silently widens
`int8 op float` to whatever the lattice says — or worse, `int8 @ int8`
runs an integer dot whose accumulator semantics differ between the
interpreter and the MXU. The quant kernel's contract is that every
int8 read is EXPLICITLY widened (`.astype(jnp.float32)`) before any
arithmetic; this rule flags the spots where an int8-typed local slips
into a BinOp or a dot/einsum bare.
"""

from __future__ import annotations

import ast
from typing import Iterator

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)

_EXEMPT_PATH_PARTS = ("gradient_check", "jax_compat")
_F64_OWNERS = ("numpy", "jax.numpy", "jax")


def _is_exempt(mod: ModuleInfo) -> bool:
    return any(part in mod.rel_path for part in _EXEMPT_PATH_PARTS)


class DtypePromotionRule(Rule):
    id = "dtype-promotion"
    severity = SEVERITY_WARNING
    description = ("float64 dtype in a jax-importing module outside the "
                   "gradient checker risks x64 emulation or silent "
                   "truncation")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if _is_exempt(mod) or not mod.imports_module("jax"):
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                owner = mod.resolve(node.value)
                if owner in _F64_OWNERS:
                    yield self.finding(
                        mod, node,
                        f"{owner}.float64 in a jax module: f64 emulates "
                        f"slowly on TPU (or truncates silently with x64 "
                        f"off); keep device math in f32/bf16")
            elif isinstance(node, ast.keyword) and node.arg == "dtype" \
                    and isinstance(node.value, ast.Constant) \
                    and node.value.value == "float64":
                yield self.finding(
                    mod, node.value,
                    "dtype='float64' in a jax module: keep device math "
                    "in f32/bf16")
            elif isinstance(node, ast.Call):
                fn = mod.resolve(node.func)
                if fn and fn.endswith("config.update") and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and node.args[0].value == "jax_enable_x64":
                    yield self.finding(
                        mod, node,
                        "jax_enable_x64 toggled outside util/jax_compat: "
                        "route through the central shim so the flag can't "
                        "leak into production paths")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "astype" and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and node.args[0].value == "float64":
                    yield self.finding(
                        mod, node,
                        ".astype('float64') in a jax module: keep device "
                        "math in f32/bf16")


_INT8_OWNERS = ("numpy", "jax.numpy", "jax")
_DOT_FNS = ("dot", "einsum", "matmul", "dot_general", "tensordot")


def _is_int8_dtype(mod: ModuleInfo, node: ast.AST) -> bool:
    """`jnp.int8` / `np.int8` / the string 'int8'."""
    if isinstance(node, ast.Constant):
        return node.value == "int8"
    if isinstance(node, ast.Attribute) and node.attr == "int8":
        return mod.resolve(node.value) in _INT8_OWNERS
    return False


def _int8_producer(mod: ModuleInfo, node: ast.AST) -> bool:
    """Does this expression syntactically yield an int8 array?
    `.astype(int8)` or any call carrying `dtype=int8`."""
    if not isinstance(node, ast.Call):
        return False
    if isinstance(node.func, ast.Attribute) and node.func.attr == "astype" \
            and node.args and _is_int8_dtype(mod, node.args[0]):
        return True
    return any(kw.arg == "dtype" and _is_int8_dtype(mod, kw.value)
               for kw in node.keywords)


class Int8PromotionRule(Rule):
    id = "int8-promotion-in-dispatch"
    severity = SEVERITY_WARNING
    description = ("arithmetic on an int8-typed local without an explicit "
                   "widen silently promotes (or runs an integer dot) — "
                   "quantized-pool reads must .astype() before math")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not mod.imports_module("jax"):
            return
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # last-assignment-wins, in line order: `q = x.astype(int8)`
            # marks q; a later `q = q.astype(f32)` clears it
            assigns = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    assigns.append((node.lineno, node.targets[0].id,
                                    _int8_producer(mod, node.value)))
            assigns.sort()
            if not any(is8 for _, _, is8 in assigns):
                continue

            def int8_at(name: str, lineno: int) -> bool:
                last = None
                for aline, aname, is8 in assigns:
                    if aname == name and aline <= lineno:
                        last = is8
                return bool(last)

            for node in ast.walk(fn):
                operands = ()
                what = "arithmetic"
                if isinstance(node, ast.BinOp):
                    operands = (node.left, node.right)
                elif isinstance(node, ast.Call):
                    f = node.func
                    name = f.attr if isinstance(f, ast.Attribute) else \
                        (f.id if isinstance(f, ast.Name) else None)
                    if name in _DOT_FNS:
                        operands, what = tuple(node.args), name
                for op in operands:
                    if isinstance(op, ast.Name) \
                            and int8_at(op.id, op.lineno):
                        yield self.finding(
                            mod, node,
                            f"int8 local '{op.id}' used in {what} without "
                            f"an explicit widen: promotion is silent and "
                            f"integer-dot accumulator semantics differ "
                            f"across backends; .astype(jnp.float32) first")
