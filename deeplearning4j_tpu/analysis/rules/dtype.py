"""dtype-promotion: float64 creeping into jax program modules.

TPUs execute f64 in slow software emulation (or jax silently truncates
to f32 with `jax_enable_x64` off, masking the intent). Either way a
float64 literal or dtype in a module that builds jax computations is a
hazard — except in the finite-difference gradient checker, whose whole
point is f64 reference arithmetic, and the central x64 shim in
util/jax_compat that gates it.
"""

from __future__ import annotations

import ast
from typing import Iterator

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)

_EXEMPT_PATH_PARTS = ("gradient_check", "jax_compat")
_F64_OWNERS = ("numpy", "jax.numpy", "jax")


def _is_exempt(mod: ModuleInfo) -> bool:
    return any(part in mod.rel_path for part in _EXEMPT_PATH_PARTS)


class DtypePromotionRule(Rule):
    id = "dtype-promotion"
    severity = SEVERITY_WARNING
    description = ("float64 dtype in a jax-importing module outside the "
                   "gradient checker risks x64 emulation or silent "
                   "truncation")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if _is_exempt(mod) or not mod.imports_module("jax"):
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                owner = mod.resolve(node.value)
                if owner in _F64_OWNERS:
                    yield self.finding(
                        mod, node,
                        f"{owner}.float64 in a jax module: f64 emulates "
                        f"slowly on TPU (or truncates silently with x64 "
                        f"off); keep device math in f32/bf16")
            elif isinstance(node, ast.keyword) and node.arg == "dtype" \
                    and isinstance(node.value, ast.Constant) \
                    and node.value.value == "float64":
                yield self.finding(
                    mod, node.value,
                    "dtype='float64' in a jax module: keep device math "
                    "in f32/bf16")
            elif isinstance(node, ast.Call):
                fn = mod.resolve(node.func)
                if fn and fn.endswith("config.update") and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and node.args[0].value == "jax_enable_x64":
                    yield self.finding(
                        mod, node,
                        "jax_enable_x64 toggled outside util/jax_compat: "
                        "route through the central shim so the flag can't "
                        "leak into production paths")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "astype" and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and node.args[0].value == "float64":
                    yield self.finding(
                        mod, node,
                        ".astype('float64') in a jax module: keep device "
                        "math in f32/bf16")
