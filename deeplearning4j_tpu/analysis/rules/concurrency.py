"""unlocked-thread-state: shared state mutated from a thread target
without a visible lock.

The serving/ETL surfaces (`parallel/`, async iterators, streaming) run
background `threading.Thread`s. A target function that assigns `self.*`
or module globals without holding a lock races its owner thread — the
classic lost-update on counters, caches, and queues-by-hand. The rule
looks for mutations inside thread-target functions that are not wrapped
in a `with <something lock-like>:` block; `queue.Queue`/`Event`-mediated
handoffs (the sanctioned pattern) don't trip it because they mutate no
shared attribute.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Set

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)

_LOCKISH = re.compile(r"lock|mutex|cond|sem", re.IGNORECASE)


def _thread_targets(mod: ModuleInfo) -> Set[str]:
    """Names of functions/methods handed to threading.Thread(target=...)."""
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if mod.resolve(node.func) != "threading.Thread":
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            if isinstance(kw.value, ast.Name):
                out.add(kw.value.id)
            elif isinstance(kw.value, ast.Attribute):
                out.add(kw.value.attr)
    return out


def _under_lock(mod: ModuleInfo, node: ast.AST, fn: ast.AST) -> bool:
    for a in mod.ancestors(node):
        if a is fn:
            return False
        if isinstance(a, ast.With):
            for item in a.items:
                if _LOCKISH.search(mod.segment(item.context_expr)):
                    return True
    return False


class ThreadSharedStateRule(Rule):
    id = "unlocked-thread-state"
    severity = SEVERITY_WARNING
    description = ("thread-target function mutates self.*/global state "
                   "without a visible lock")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        targets = _thread_targets(mod)
        if not targets:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in targets:
                continue
            globals_: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Global):
                    globals_.update(sub.names)
            for sub in ast.walk(node):
                if isinstance(sub, ast.AugAssign):
                    tgts = [sub.target]
                elif isinstance(sub, ast.Assign):
                    tgts = sub.targets
                else:
                    continue
                for t in tgts:
                    leaked = None
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        leaked = f"self.{t.attr}"
                    elif isinstance(t, ast.Name) and t.id in globals_:
                        leaked = f"global '{t.id}'"
                    if leaked and not _under_lock(mod, sub, node):
                        yield self.finding(
                            mod, sub,
                            f"thread target '{node.name}' mutates {leaked} "
                            f"without holding a lock; guard it or hand off "
                            f"through a Queue/Event")
