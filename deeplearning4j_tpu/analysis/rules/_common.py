"""Shared AST helpers for rules that reason about jit-staged functions."""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from deeplearning4j_tpu.analysis.core import ModuleInfo


def walk_no_defs(node: ast.AST,
                 include_self: bool = True) -> Iterator[ast.AST]:
    """Walk an AST WITHOUT descending into nested function / lambda
    definitions — those are separate analysis scopes (and often
    jit-staged bodies with different semantics). `include_self=False`
    walks a function's own body (the def node itself excluded)."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        if include_self:
            return
    elif include_self:
        yield node
    for child in ast.iter_child_nodes(node):
        yield from walk_no_defs(child, include_self=True)


def module_calls(mod: ModuleInfo) -> List[ast.Call]:
    """Every Call node in the module, in walk order (memoized): the
    hot-loop rules and their interprocedural promotions iterate calls
    several times per scan."""
    return mod.fact("all_calls", lambda m: [
        n for n in ast.walk(m.tree) if isinstance(n, ast.Call)])


def norm_source(node: ast.AST) -> str:
    """Whitespace-stripped source form of a node, for textual matching
    (memo-guard targets, jit-key flow)."""
    try:
        return re.sub(r"\s+", "", ast.unparse(node))
    except Exception:  # pragma: no cover - unparse is total on 3.9+
        return ""

#: call targets that stage a Python function for tracing: assigning
#: tracers to Python state inside any of these leaks, and value-dependent
#: branches inside any of these concretize
_TRACING_WRAPPERS = ("jit", "pmap", "shard_map")


def _is_tracing_wrapper(mod: ModuleInfo, node: ast.AST) -> bool:
    """True for expressions like `jax.jit`, `jit` (from-imported), or
    `partial(jax.jit, ...)` used as decorator or wrapper callee."""
    if isinstance(node, ast.Call):
        # @partial(jax.jit, static_argnums=...) / functools.partial(...)
        fn = mod.resolve(node.func)
        if fn is not None and fn.rsplit(".", 1)[-1] == "partial" and node.args:
            return _is_tracing_wrapper(mod, node.args[0])
        node = node.func
    name = mod.resolve(node)
    if name is None:
        return False
    return name.rsplit(".", 1)[-1] in _TRACING_WRAPPERS


def jit_call_static_names(mod: ModuleInfo,
                         call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """Static argnums/argnames declared on a jit(...) call, when literal."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        val = kw.value
        if kw.arg == "static_argnums":
            if isinstance(val, ast.Constant) and isinstance(val.value, int):
                nums.add(val.value)
            elif isinstance(val, (ast.Tuple, ast.List)):
                nums.update(e.value for e in val.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, int))
        elif kw.arg == "static_argnames":
            if isinstance(val, ast.Constant) and isinstance(val.value, str):
                names.add(val.value)
            elif isinstance(val, (ast.Tuple, ast.List)):
                names.update(e.value for e in val.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str))
    return nums, names


def collect_jit_functions(
        mod: ModuleInfo) -> Dict[ast.FunctionDef, Optional[ast.Call]]:
    """FunctionDefs staged for tracing in this module: decorated with a
    tracing wrapper, or named as the wrapped argument of a `jax.jit(f)` /
    `partial(jax.jit, ...)(f)`-style call. Maps each def to the jit call
    that wraps it (None when the decorator form carries no call).
    Memoized per module."""
    return mod.fact("jit_functions", _compute_jit_functions)


def tracing_calls(mod: ModuleInfo) -> List[ast.Call]:
    """Every tracing-wrapper construction in the module (memoized):
    rules that only need "does this function build a jit?" intersect
    these with ancestry instead of re-walking subtrees."""
    return mod.fact("tracing_calls", lambda m: [
        n for n in ast.walk(m.tree)
        if isinstance(n, ast.Call) and _is_tracing_wrapper(m, n)])


def functions_building_jit(mod: ModuleInfo) -> Set[ast.AST]:
    """Function defs that lexically contain a tracing-wrapper
    construction anywhere in their subtree (memoized)."""

    def compute(m: ModuleInfo) -> Set[ast.AST]:
        out: Set[ast.AST] = set()
        for call in tracing_calls(m):
            out.update(m.enclosing_functions(call))
        return out

    return mod.fact("functions_building_jit", compute)


def _compute_jit_functions(
        mod: ModuleInfo) -> Dict[ast.FunctionDef, Optional[ast.Call]]:
    defs_by_name: Dict[str, List[ast.FunctionDef]] = {}
    out: Dict[ast.FunctionDef, Optional[ast.Call]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
            for dec in node.decorator_list:
                if _is_tracing_wrapper(mod, dec):
                    out[node] = dec if isinstance(dec, ast.Call) else None
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if not _is_tracing_wrapper(mod, node.func):
            continue
        for arg in node.args[:1]:
            if isinstance(arg, ast.Name):
                for fd in defs_by_name.get(arg.id, ()):  # same-module defs
                    out.setdefault(fd, node)
    return out


def traced_param_names(mod: ModuleInfo, fn: ast.FunctionDef,
                       jit_call: Optional[ast.Call]) -> Set[str]:
    """Parameter names of a jitted function that carry tracers (all
    params minus `self` and declared static args)."""
    args = fn.args
    ordered = [a.arg for a in (args.posonlyargs + args.args)]
    names = set(ordered + [a.arg for a in args.kwonlyargs])
    names.discard("self")
    if jit_call is not None:
        nums, static_names = jit_call_static_names(mod, jit_call)
        names -= static_names
        for i in nums:
            if 0 <= i < len(ordered):
                names.discard(ordered[i])
    return names
