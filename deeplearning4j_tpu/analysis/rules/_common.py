"""Shared AST helpers for rules that reason about jit-staged functions."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from deeplearning4j_tpu.analysis.core import ModuleInfo

#: call targets that stage a Python function for tracing: assigning
#: tracers to Python state inside any of these leaks, and value-dependent
#: branches inside any of these concretize
_TRACING_WRAPPERS = ("jit", "pmap", "shard_map")


def _is_tracing_wrapper(mod: ModuleInfo, node: ast.AST) -> bool:
    """True for expressions like `jax.jit`, `jit` (from-imported), or
    `partial(jax.jit, ...)` used as decorator or wrapper callee."""
    if isinstance(node, ast.Call):
        # @partial(jax.jit, static_argnums=...) / functools.partial(...)
        fn = mod.resolve(node.func)
        if fn is not None and fn.rsplit(".", 1)[-1] == "partial" and node.args:
            return _is_tracing_wrapper(mod, node.args[0])
        node = node.func
    name = mod.resolve(node)
    if name is None:
        return False
    return name.rsplit(".", 1)[-1] in _TRACING_WRAPPERS


def jit_call_static_names(mod: ModuleInfo,
                         call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """Static argnums/argnames declared on a jit(...) call, when literal."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        val = kw.value
        if kw.arg == "static_argnums":
            if isinstance(val, ast.Constant) and isinstance(val.value, int):
                nums.add(val.value)
            elif isinstance(val, (ast.Tuple, ast.List)):
                nums.update(e.value for e in val.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, int))
        elif kw.arg == "static_argnames":
            if isinstance(val, ast.Constant) and isinstance(val.value, str):
                names.add(val.value)
            elif isinstance(val, (ast.Tuple, ast.List)):
                names.update(e.value for e in val.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str))
    return nums, names


def collect_jit_functions(
        mod: ModuleInfo) -> Dict[ast.FunctionDef, Optional[ast.Call]]:
    """FunctionDefs staged for tracing in this module: decorated with a
    tracing wrapper, or named as the wrapped argument of a `jax.jit(f)` /
    `partial(jax.jit, ...)(f)`-style call. Maps each def to the jit call
    that wraps it (None when the decorator form carries no call)."""
    defs_by_name: Dict[str, List[ast.FunctionDef]] = {}
    out: Dict[ast.FunctionDef, Optional[ast.Call]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
            for dec in node.decorator_list:
                if _is_tracing_wrapper(mod, dec):
                    out[node] = dec if isinstance(dec, ast.Call) else None
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if not _is_tracing_wrapper(mod, node.func):
            continue
        for arg in node.args[:1]:
            if isinstance(arg, ast.Name):
                for fd in defs_by_name.get(arg.id, ()):  # same-module defs
                    out.setdefault(fd, node)
    return out


def traced_param_names(mod: ModuleInfo, fn: ast.FunctionDef,
                       jit_call: Optional[ast.Call]) -> Set[str]:
    """Parameter names of a jitted function that carry tracers (all
    params minus `self` and declared static args)."""
    args = fn.args
    ordered = [a.arg for a in (args.posonlyargs + args.args)]
    names = set(ordered + [a.arg for a in args.kwonlyargs])
    names.discard("self")
    if jit_call is not None:
        nums, static_names = jit_call_static_names(mod, jit_call)
        names -= static_names
        for i in nums:
            if 0 <= i < len(ordered):
                names.discard(ordered[i])
    return names
