"""host-sync-in-hot-loop: device->host synchronization inside fit/serve
hot paths.

JAX dispatch is asynchronous: the Python thread should race ahead
enqueueing steps while the accelerator executes. Any host materialization
of a device value — `.item()`, `float()`, `np.asarray`, `device_get`,
`block_until_ready` — inside the per-batch path stalls that pipeline to
one-batch-at-a-time lockstep, the exact failure mode the dispatch-
pipelining literature (cuDNN-era stacks) warns about. Keep the steady
state sync-free; materialize lazily, periodically, or after the final
batch.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_ERROR)

#: function bodies that ARE the per-batch hot path: any sync in them runs
#: once per training batch even though the loop lives in the caller
_PER_BATCH_FN = re.compile(
    r"^(_fit\w*|partial_fit|train_on_batch|_train_batch\w*|train_step|_step)$")

#: functions where only code lexically inside a loop is hot
_LOOP_FN = re.compile(r"^(fit|train|predict|_serve_loop)$")

_SYNC_CALLS = {
    "jax.device_get": "copies device values to host",
    "jax.block_until_ready": "blocks dispatch until the device drains",
    "numpy.asarray": "forces a device->host transfer",
    "numpy.array": "forces a device->host transfer",
}

_SYNC_METHODS = {
    "item": "materializes a device scalar on host",
    "tolist": "materializes a device array on host",
    "block_until_ready": "blocks dispatch until the device drains",
}


_HOST_CONTAINERS = (ast.List, ast.ListComp, ast.Tuple, ast.Set,
                    ast.SetComp, ast.GeneratorExp, ast.Dict, ast.DictComp)


def _scalar_cast_is_benign(arg: ast.AST) -> bool:
    """float()/int() of literals, len()/range() results, or shape metadata
    is host arithmetic, not a device sync."""
    if isinstance(arg, ast.Constant):
        return True
    for sub in ast.walk(arg):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim",
                                                           "size", "dtype"):
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id in ("len", "range", "perf_counter"):
            return True
    return False


class HostSyncRule(Rule):
    id = "host-sync-in-hot-loop"
    severity = SEVERITY_ERROR
    description = ("device->host sync (.item()/float()/np.asarray/"
                   "device_get/block_until_ready) inside a fit/serve hot "
                   "path serializes async dispatch")

    def _classify(self, mod: ModuleInfo, node: ast.Call):
        resolved = mod.resolve(node.func)
        if resolved in _SYNC_CALLS:
            # np.asarray of a literal host container builds a host array
            # from host data — no device value can be involved
            if resolved.startswith("numpy.") and node.args \
                    and isinstance(node.args[0], _HOST_CONTAINERS):
                return None, None
            return f"{resolved}()", _SYNC_CALLS[resolved]
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SYNC_METHODS and not node.args:
            return f".{node.func.attr}()", _SYNC_METHODS[node.func.attr]
        if resolved in ("float", "int") and len(node.args) == 1 \
                and not node.keywords \
                and not _scalar_cast_is_benign(node.args[0]):
            return f"{resolved}()", "materializes a device scalar on host"
        return None, None

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not mod.imports_module("jax"):
            return  # pure-host module: np.asarray/float() cannot sync
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            what, why = self._classify(mod, node)
            if what is None:
                continue
            for fn in mod.enclosing_functions(node):
                if _PER_BATCH_FN.match(fn.name):
                    hot, where = True, f"per-batch path '{fn.name}'"
                elif _LOOP_FN.match(fn.name) and mod.inside_loop(node,
                                                                 within=fn):
                    hot, where = True, f"loop in '{fn.name}'"
                else:
                    continue
                if hot:
                    yield self.finding(
                        mod, node,
                        f"{what} in {where} {why}; keep the steady state "
                        f"sync-free (defer to access / every N batches / "
                        f"after the final batch)")
                    break
