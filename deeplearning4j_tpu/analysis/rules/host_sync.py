"""host-sync-in-hot-loop: device->host synchronization inside fit/serve
hot paths.

JAX dispatch is asynchronous: the Python thread should race ahead
enqueueing steps while the accelerator executes. Any host materialization
of a device value — `.item()`, `float()`, `np.asarray`, `device_get`,
`block_until_ready` — inside the per-batch path stalls that pipeline to
one-batch-at-a-time lockstep, the exact failure mode the dispatch-
pipelining literature (cuDNN-era stacks) warns about. Keep the steady
state sync-free; materialize lazily, periodically, or after the final
batch.

Interprocedural promotion (ISSUE 13): the lexical check only sees syncs
spelled INSIDE the hot body, but the ones that survive review hide two
helper calls down. With a `ProjectInfo` available, a call in a hot
region whose resolved callee (bounded-depth, see analysis/callgraph.py)
transitively performs a sync is flagged AT THE CALL SITE with the callee
chain in the message — the caller owns the hot loop, so the caller's
line is where the fix (hoist / defer / cadence) lands. A justified
inline suppression on the callee's sync line kills propagation for
every caller; callees that are themselves hot-named are skipped here
(they get their own body finding instead of one per caller).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Tuple

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_ERROR)
from deeplearning4j_tpu.analysis.rules._common import module_calls

#: function bodies that ARE the per-batch hot path: any sync in them runs
#: once per training batch even though the loop lives in the caller
_PER_BATCH_FN = re.compile(
    r"^(_fit\w*|partial_fit|train_on_batch|_train_batch\w*|train_step|_step)$")

#: functions where only code lexically inside a loop is hot
_LOOP_FN = re.compile(r"^(fit|train|predict|_serve_loop)$")

_SYNC_CALLS = {
    "jax.device_get": "copies device values to host",
    "jax.block_until_ready": "blocks dispatch until the device drains",
    "numpy.asarray": "forces a device->host transfer",
    "numpy.array": "forces a device->host transfer",
}

_SYNC_METHODS = {
    "item": "materializes a device scalar on host",
    "tolist": "materializes a device array on host",
    "block_until_ready": "blocks dispatch until the device drains",
}


_HOST_CONTAINERS = (ast.List, ast.ListComp, ast.Tuple, ast.Set,
                    ast.SetComp, ast.GeneratorExp, ast.Dict, ast.DictComp)


def _scalar_cast_is_benign(arg: ast.AST) -> bool:
    """float()/int() of literals, len()/range() results, or shape metadata
    is host arithmetic, not a device sync."""
    if isinstance(arg, ast.Constant):
        return True
    for sub in ast.walk(arg):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim",
                                                           "size", "dtype"):
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id in ("len", "range", "perf_counter"):
            return True
    return False


def classify_sync(mod: ModuleInfo, node: ast.Call,
                  strong_only: bool = False
                  ) -> Tuple[Optional[str], Optional[str]]:
    """(what, why) when a call is a device->host sync, (None, None)
    otherwise. Shared by the lexical rule and the call-graph effect
    summaries so both halves agree on what a sync IS.

    `strong_only=True` (the summary mode) keeps only the unambiguous
    signals — device_get / block_until_ready / .item() / .tolist() /
    np.asarray — and drops the bare ``float()``/``int()`` cast
    heuristic: inside a hot body the common operand is a device loss,
    but across arbitrary helper bodies a float cast is usually plain
    host arithmetic, and propagating that guess to every caller would
    drown the signal."""
    resolved = mod.resolve(node.func)
    if resolved in _SYNC_CALLS:
        # np.asarray of a literal host container builds a host array
        # from host data — no device value can be involved
        if resolved.startswith("numpy.") and node.args \
                and isinstance(node.args[0], _HOST_CONTAINERS):
            return None, None
        return f"{resolved}()", _SYNC_CALLS[resolved]
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _SYNC_METHODS and not node.args:
        return f".{node.func.attr}()", _SYNC_METHODS[node.func.attr]
    if not strong_only and resolved in ("float", "int") \
            and len(node.args) == 1 and not node.keywords \
            and not _scalar_cast_is_benign(node.args[0]):
        return f"{resolved}()", "materializes a device scalar on host"
    return None, None


def hot_region(mod: ModuleInfo, node: ast.AST) -> Optional[str]:
    """The hot region a node sits in (fit/serve heat model), or None:
    per-batch-named bodies are hot everywhere; in fit/train-shaped
    functions only code lexically inside a loop is hot."""
    for fn in mod.enclosing_functions(node):
        if _PER_BATCH_FN.match(fn.name):
            return f"per-batch path '{fn.name}'"
        if _LOOP_FN.match(fn.name) and mod.inside_loop(node, within=fn):
            return f"loop in '{fn.name}'"
    return None


def is_hot_named(name: str) -> bool:
    return bool(_PER_BATCH_FN.match(name) or _LOOP_FN.match(name))


class HostSyncRule(Rule):
    id = "host-sync-in-hot-loop"
    severity = SEVERITY_ERROR
    description = ("device->host sync (.item()/float()/np.asarray/"
                   "device_get/block_until_ready) inside a fit/serve hot "
                   "path serializes async dispatch — including syncs "
                   "reached through helper calls (project mode)")

    def _classify(self, mod: ModuleInfo, node: ast.Call):
        return classify_sync(mod, node)

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not mod.imports_module("jax"):
            return  # pure-host module: np.asarray/float() cannot sync
        for node in module_calls(mod):
            what, why = self._classify(mod, node)
            if what is None:
                continue
            where = hot_region(mod, node)
            if where is None:
                continue
            yield self.finding(
                mod, node,
                f"{what} in {where} {why}; keep the steady state "
                f"sync-free (defer to access / every N batches / "
                f"after the final batch)")

    # -- interprocedural promotion -------------------------------------
    def check_project(self, mod: ModuleInfo, project) -> Iterator[Finding]:
        yield from self.check(mod)
        if project is None:
            return
        from deeplearning4j_tpu.analysis.callgraph import EFFECT_HOST_SYNC
        cg = project.callgraph
        kinds = frozenset({EFFECT_HOST_SYNC})
        for node in module_calls(mod):
            if classify_sync(mod, node)[0] is not None:
                continue  # lexical finding already covers it
            where = hot_region(mod, node)
            if where is None:
                continue
            target = project.resolve_call(mod, node)
            if target is None:
                continue
            mod_name, qual = target
            if is_hot_named(qual.rsplit(".", 1)[-1]):
                continue  # the callee body is hot itself: flagged there
            evidence = cg.reaches(f"{mod_name}:{qual}", kinds)
            if evidence is None:
                continue
            effect, chain = evidence
            yield self.finding(
                mod, node,
                f"call to '{qual}' in {where} reaches a device->host "
                f"sync: {cg.render_chain(chain, effect)} — "
                f"{effect.why}; hoist the sync out of the hot path or "
                f"run it at a cadence (suppress at the callee's sync "
                f"line if the contract is deliberate)",
                chain=chain + (f"{effect.what} at "
                               f"{effect.path}:{effect.line}",))
