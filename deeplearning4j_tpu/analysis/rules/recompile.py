"""recompile-hazard: patterns that defeat jit's compilation cache.

Four statically visible shapes of the same disease (the runtime half —
counting actual recompiles — is the PR 1 jit watcher):

- `jax.jit(...)` lexically inside a loop builds a fresh wrapper (and a
  fresh cache) per iteration, so nothing is ever a cache hit;
- unhashable `static_argnums`/`static_argnames` specs (list literals)
  and non-literal specs that may vary call-to-call;
- value-dependent Python control flow (`if x > 0:`, f-strings on traced
  params) inside a staged function either concretizes the tracer or
  recompiles per value when the arg is marked static.

The PR 11 env-read-in-step-builder check moved to `jit-key-drift`
(rules/jit_key.py), which generalizes it to every kind of process-wide
mutable state read outside the jit cache key.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)
from deeplearning4j_tpu.analysis.rules._common import (
    _is_tracing_wrapper, collect_jit_functions, traced_param_names)

_BENIGN_TEST_CALLS = ("len", "isinstance", "getattr", "hasattr",
                      "callable", "issubclass")


class _TracedNameFinder(ast.NodeVisitor):
    """Collect bare traced-param Names in an expression, skipping
    attribute access (x.shape / x.ndim are static metadata) and calls
    that are concrete at trace time."""

    def __init__(self, params: Set[str]):
        self.params = params
        self.hits: Set[str] = set()

    def visit_Attribute(self, node: ast.Attribute) -> None:
        pass  # metadata access: static under tracing

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Name) \
                and node.func.id in _BENIGN_TEST_CALLS:
            return
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)

    def visit_Name(self, node: ast.Name) -> None:
        if node.id in self.params:
            self.hits.add(node.id)


def _test_is_identity_check(test: ast.AST) -> bool:
    return isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)


class RecompileHazardRule(Rule):
    id = "recompile-hazard"
    severity = SEVERITY_WARNING
    description = ("jit-in-loop, unstable static_argnums, or value-"
                   "dependent Python control flow on traced args defeats "
                   "the jit cache")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        yield from self._jit_in_loop(mod)
        yield from self._static_specs(mod)
        yield from self._traced_branches(mod)

    # -- jit built inside a loop --------------------------------------
    def _jit_in_loop(self, mod: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            # classify the whole Call so `jit(f)(x)` counts the
            # construction once, not also the immediate invocation
            if isinstance(node, ast.Call) \
                    and _is_tracing_wrapper(mod, node) \
                    and mod.inside_loop(node):
                yield self.finding(
                    mod, node,
                    "jit wrapper constructed inside a loop: each iteration "
                    "gets a fresh compilation cache, so every call "
                    "retraces; hoist the jit out of the loop")

    # -- static_argnums hygiene ---------------------------------------
    def _static_specs(self, mod: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and _is_tracing_wrapper(mod, node)):
                continue
            for kw in node.keywords:
                if kw.arg not in ("static_argnums", "static_argnames"):
                    continue
                val = kw.value
                if isinstance(val, (ast.List, ast.ListComp, ast.Set,
                                    ast.SetComp, ast.Dict, ast.DictComp)):
                    yield self.finding(
                        mod, node,
                        f"{kw.arg} given as an unhashable container "
                        f"literal; use a tuple of ints/strs so the spec "
                        f"itself is cacheable")
                elif not isinstance(val, (ast.Constant, ast.Tuple)):
                    yield self.finding(
                        mod, node,
                        f"{kw.arg} computed at call time ({type(val).__name__}); "
                        f"a spec that varies call-to-call recompiles per "
                        f"value — prefer a literal tuple")

    def _traced_branches(self, mod: ModuleInfo) -> Iterator[Finding]:
        for fn, jit_call in collect_jit_functions(mod).items():
            params = traced_param_names(mod, fn, jit_call)
            if not params:
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    if _test_is_identity_check(node.test):
                        continue  # `x is None` is concrete at trace time
                    finder = _TracedNameFinder(params)
                    finder.visit(node.test)
                    for name in sorted(finder.hits):
                        yield self.finding(
                            mod, node,
                            f"branch on traced arg '{name}' in staged "
                            f"'{fn.name}': concretization error, or one "
                            f"recompile per value if marked static; use "
                            f"lax.cond/jnp.where")
                elif isinstance(node, ast.FormattedValue):
                    finder = _TracedNameFinder(params)
                    finder.visit(node.value)
                    for name in sorted(finder.hits):
                        yield self.finding(
                            mod, node,
                            f"f-string on traced arg '{name}' in staged "
                            f"'{fn.name}' captures the tracer repr at "
                            f"trace time, not the runtime value")
