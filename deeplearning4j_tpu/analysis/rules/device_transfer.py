"""device-transfer-in-hot-loop: synchronous host->device staging inside
fit/epoch hot paths.

`jnp.asarray` / `jnp.array` / `jax.device_put` on host data inside the
per-batch path stages the H2D copy on the CONSUMER thread: the fit loop
blocks preparing batch N+1's transfer while the device sits between
steps — serial transfer/compute instead of the overlap the hardware
supports. The device-side pipeline stage
(`pipeline.prefetch.DevicePrefetchIterator`) moves the copy into a
bounded background worker so it overlaps compute; this rule flags the
pattern that stage exists to remove. Remnants that are justified — the
jit-boundary copy of the unprefetched compat path — live in
TPULINT_BASELINE.json or carry an inline suppression with the why.

Heat model matches host-sync-in-hot-loop: function bodies that ARE the
per-batch path (`_fit*`, `partial_fit`, ...) are hot everywhere; in
`fit`/`train`-shaped functions only code lexically inside a loop is hot.
Literal-constant arguments (e.g. ``jnp.asarray(3)``) are exempt — a
scalar constant is not a batch transfer.

Serving extension (PR 10): per-STEP paths are hot too — `step`,
`_step_*`, `_dispatch_step`, `_run_dispatch`, `_decode_step` method
bodies, the decode-loop shape where the engine used to rebuild and
re-upload the whole [S, n_max] page table every generated token even
when no table had changed. The fix shape this rule points at is the
engine's cached-table path: stage the transfer in a cache helper
outside the hot names and invalidate it on MUTATION, so steady-state
steps re-upload nothing. Only top-level (method) bodies count: a
nested ``def step(carry, ...)`` is a jitted/scan body whose
``jnp.asarray`` is a trace-time constant, not a per-step H2D copy.

Interprocedural promotion (ISSUE 13): with a `ProjectInfo`, a call in a
hot region whose resolved callee transitively stages an H2D copy
(bounded depth, analysis/callgraph.py) is flagged at the call site with
the callee chain — the cached-table helpers stay exempt because the
cache-hit path means the transfer is NOT per-step; when a cache helper
is hit every step because invalidation is wrong, that is a runtime
(PR 1 watcher) story, not a lexical one.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Tuple

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)
from deeplearning4j_tpu.analysis.rules.host_sync import (
    _LOOP_FN, _PER_BATCH_FN, is_hot_named)
from deeplearning4j_tpu.analysis.rules._common import module_calls

_TRANSFER_CALLS = {
    "jax.numpy.asarray": "jnp.asarray",
    "jax.numpy.array": "jnp.array",
    "jax.device_put": "jax.device_put",
}

#: serving per-step hot paths (the decode dispatch cycle): hot only as
#: TOP-LEVEL function/method bodies — nested defs with these names are
#: jit/scan step bodies where a transfer is a trace-time constant
_PER_STEP_FN = re.compile(
    r"^(step|_step_\w+|_dispatch_step|_run_dispatch|_decode_step)$")


def classify_transfer(mod: ModuleInfo,
                      node: ast.Call) -> Tuple[Optional[str],
                                               Optional[str]]:
    """(label, why) when a call stages a synchronous H2D copy of
    non-constant data, else (None, None). Shared with the call-graph
    effect summaries."""
    resolved = mod.resolve(node.func)
    label = _TRANSFER_CALLS.get(resolved)
    if label is None:
        return None, None
    # a literal scalar/constant is shape plumbing, not a batch
    if node.args and isinstance(node.args[0], ast.Constant):
        return None, None
    return f"{label}()", "stages a host->device copy on the caller"


def hot_transfer_region(mod: ModuleInfo,
                        node: ast.AST) -> Optional[Tuple[str, bool]]:
    """(where, is_per_step) for the transfer heat model, or None."""
    for fn in mod.enclosing_functions(node):
        if _PER_BATCH_FN.match(fn.name):
            return f"per-batch path '{fn.name}'", False
        if _PER_STEP_FN.match(fn.name) and not mod.enclosing_functions(fn):
            return f"per-step path '{fn.name}'", True
        if _LOOP_FN.match(fn.name) and mod.inside_loop(node, within=fn):
            return f"loop in '{fn.name}'", False
    return None


class DeviceTransferRule(Rule):
    id = "device-transfer-in-hot-loop"
    severity = SEVERITY_WARNING
    description = ("jnp.asarray/jax.device_put on host data inside a "
                   "fit/epoch loop stages the H2D copy on the consumer "
                   "thread; prefetch it (pipeline.DevicePrefetchIterator) "
                   "so the transfer overlaps device compute — including "
                   "transfers reached through helper calls (project mode)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not mod.imports_module("jax"):
            return
        for node in module_calls(mod):
            label, _why = classify_transfer(mod, node)
            if label is None:
                continue
            region = hot_transfer_region(mod, node)
            if region is None:
                continue
            where, per_step = region
            if per_step:
                yield self.finding(
                    mod, node,
                    f"{label} in {where} re-stages a host->device "
                    f"copy every decode step even when the host data "
                    f"did not change; cache the device array outside "
                    f"the step and invalidate it on mutation (the "
                    f"serving engine's cached page-table path)")
            else:
                yield self.finding(
                    mod, node,
                    f"{label} in {where} stages a host->device "
                    f"copy on the consumer thread each batch; move "
                    f"it into a device prefetch stage "
                    f"(pipeline.DevicePrefetchIterator) so the "
                    f"transfer overlaps compute")

    # -- interprocedural promotion -------------------------------------
    def check_project(self, mod: ModuleInfo, project) -> Iterator[Finding]:
        yield from self.check(mod)
        if project is None:
            return
        from deeplearning4j_tpu.analysis.callgraph import (
            EFFECT_DEVICE_TRANSFER)
        cg = project.callgraph
        kinds = frozenset({EFFECT_DEVICE_TRANSFER})
        for node in module_calls(mod):
            if classify_transfer(mod, node)[0] is not None:
                continue
            region = hot_transfer_region(mod, node)
            if region is None:
                continue
            where, _per_step = region
            target = project.resolve_call(mod, node)
            if target is None:
                continue
            mod_name, qual = target
            last = qual.rsplit(".", 1)[-1]
            if is_hot_named(last) or _PER_STEP_FN.match(last):
                continue  # the callee body is hot itself: flagged there
            evidence = cg.reaches(f"{mod_name}:{qual}", kinds)
            if evidence is None:
                continue
            effect, chain = evidence
            yield self.finding(
                mod, node,
                f"call to '{qual}' in {where} reaches a host->device "
                f"transfer: {cg.render_chain(chain, effect)}; stage it "
                f"once outside the hot path (or prefetch it) so the "
                f"copy overlaps compute",
                chain=chain + (f"{effect.what} at "
                               f"{effect.path}:{effect.line}",))
