"""device-transfer-in-hot-loop: synchronous host->device staging inside
fit/epoch hot paths.

`jnp.asarray` / `jnp.array` / `jax.device_put` on host data inside the
per-batch path stages the H2D copy on the CONSUMER thread: the fit loop
blocks preparing batch N+1's transfer while the device sits between
steps — serial transfer/compute instead of the overlap the hardware
supports. The device-side pipeline stage
(`pipeline.prefetch.DevicePrefetchIterator`) moves the copy into a
bounded background worker so it overlaps compute; this rule flags the
pattern that stage exists to remove. Remnants that are justified — the
jit-boundary copy of the unprefetched compat path — live in
TPULINT_BASELINE.json or carry an inline suppression with the why.

Heat model matches host-sync-in-hot-loop: function bodies that ARE the
per-batch path (`_fit*`, `partial_fit`, ...) are hot everywhere; in
`fit`/`train`-shaped functions only code lexically inside a loop is hot.
Literal-constant arguments (e.g. ``jnp.asarray(3)``) are exempt — a
scalar constant is not a batch transfer.

Serving extension (PR 10): per-STEP paths are hot too — `step`,
`_step_*`, `_dispatch_step`, `_run_dispatch`, `_decode_step` method
bodies, the decode-loop shape where the engine used to rebuild and
re-upload the whole [S, n_max] page table every generated token even
when no table had changed. The fix shape this rule points at is the
engine's cached-table path: stage the transfer in a cache helper
outside the hot names and invalidate it on MUTATION, so steady-state
steps re-upload nothing. Only top-level (method) bodies count: a
nested ``def step(carry, ...)`` is a jitted/scan body whose
``jnp.asarray`` is a trace-time constant, not a per-step H2D copy.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)
from deeplearning4j_tpu.analysis.rules.host_sync import (
    _LOOP_FN, _PER_BATCH_FN)

_TRANSFER_CALLS = {
    "jax.numpy.asarray": "jnp.asarray",
    "jax.numpy.array": "jnp.array",
    "jax.device_put": "jax.device_put",
}

#: serving per-step hot paths (the decode dispatch cycle): hot only as
#: TOP-LEVEL function/method bodies — nested defs with these names are
#: jit/scan step bodies where a transfer is a trace-time constant
_PER_STEP_FN = re.compile(
    r"^(step|_step_\w+|_dispatch_step|_run_dispatch|_decode_step)$")


class DeviceTransferRule(Rule):
    id = "device-transfer-in-hot-loop"
    severity = SEVERITY_WARNING
    description = ("jnp.asarray/jax.device_put on host data inside a "
                   "fit/epoch loop stages the H2D copy on the consumer "
                   "thread; prefetch it (pipeline.DevicePrefetchIterator) "
                   "so the transfer overlaps device compute")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not mod.imports_module("jax"):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func)
            label = _TRANSFER_CALLS.get(resolved)
            if label is None:
                continue
            # a literal scalar/constant is shape plumbing, not a batch
            if node.args and isinstance(node.args[0], ast.Constant):
                continue
            for fn in mod.enclosing_functions(node):
                per_step = False
                if _PER_BATCH_FN.match(fn.name):
                    where = f"per-batch path '{fn.name}'"
                elif _PER_STEP_FN.match(fn.name) and \
                        not mod.enclosing_functions(fn):
                    per_step = True
                    where = f"per-step path '{fn.name}'"
                elif _LOOP_FN.match(fn.name) and mod.inside_loop(node,
                                                                 within=fn):
                    where = f"loop in '{fn.name}'"
                else:
                    continue
                if per_step:
                    yield self.finding(
                        mod, node,
                        f"{label}() in {where} re-stages a host->device "
                        f"copy every decode step even when the host data "
                        f"did not change; cache the device array outside "
                        f"the step and invalidate it on mutation (the "
                        f"serving engine's cached page-table path)")
                else:
                    yield self.finding(
                        mod, node,
                        f"{label}() in {where} stages a host->device "
                        f"copy on the consumer thread each batch; move "
                        f"it into a device prefetch stage "
                        f"(pipeline.DevicePrefetchIterator) so the "
                        f"transfer overlaps compute")
                break
