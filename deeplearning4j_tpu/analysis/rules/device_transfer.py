"""device-transfer-in-hot-loop: synchronous host->device staging inside
fit/epoch hot paths.

`jnp.asarray` / `jnp.array` / `jax.device_put` on host data inside the
per-batch path stages the H2D copy on the CONSUMER thread: the fit loop
blocks preparing batch N+1's transfer while the device sits between
steps — serial transfer/compute instead of the overlap the hardware
supports. The device-side pipeline stage
(`pipeline.prefetch.DevicePrefetchIterator`) moves the copy into a
bounded background worker so it overlaps compute; this rule flags the
pattern that stage exists to remove. Remnants that are justified — the
jit-boundary copy of the unprefetched compat path — live in
TPULINT_BASELINE.json or carry an inline suppression with the why.

Heat model matches host-sync-in-hot-loop: function bodies that ARE the
per-batch path (`_fit*`, `partial_fit`, ...) are hot everywhere; in
`fit`/`train`-shaped functions only code lexically inside a loop is hot.
Literal-constant arguments (e.g. ``jnp.asarray(3)``) are exempt — a
scalar constant is not a batch transfer.
"""

from __future__ import annotations

import ast
from typing import Iterator

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)
from deeplearning4j_tpu.analysis.rules.host_sync import (
    _LOOP_FN, _PER_BATCH_FN)

_TRANSFER_CALLS = {
    "jax.numpy.asarray": "jnp.asarray",
    "jax.numpy.array": "jnp.array",
    "jax.device_put": "jax.device_put",
}


class DeviceTransferRule(Rule):
    id = "device-transfer-in-hot-loop"
    severity = SEVERITY_WARNING
    description = ("jnp.asarray/jax.device_put on host data inside a "
                   "fit/epoch loop stages the H2D copy on the consumer "
                   "thread; prefetch it (pipeline.DevicePrefetchIterator) "
                   "so the transfer overlaps device compute")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not mod.imports_module("jax"):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func)
            label = _TRANSFER_CALLS.get(resolved)
            if label is None:
                continue
            # a literal scalar/constant is shape plumbing, not a batch
            if node.args and isinstance(node.args[0], ast.Constant):
                continue
            for fn in mod.enclosing_functions(node):
                if _PER_BATCH_FN.match(fn.name):
                    where = f"per-batch path '{fn.name}'"
                elif _LOOP_FN.match(fn.name) and mod.inside_loop(node,
                                                                 within=fn):
                    where = f"loop in '{fn.name}'"
                else:
                    continue
                yield self.finding(
                    mod, node,
                    f"{label}() in {where} stages a host->device copy on "
                    f"the consumer thread each batch; move it into a "
                    f"device prefetch stage "
                    f"(pipeline.DevicePrefetchIterator) so the transfer "
                    f"overlaps compute")
                break
