"""wall-clock-in-traced-body: host clock reads baked into a trace.

The ISSUE 15 event layer put ``time.time()`` / ``time.monotonic()`` /
``time.perf_counter()`` calls all over the serving and resilience hot
paths — which is fine exactly because those paths are HOST code. The
same call inside a TRACED body is a silent bug: jit stages the Python
function once, the clock is read once at trace time, and the "current
time" the compiled step computes with is a frozen constant from the
day it compiled (the temporal cousin of jit-key-drift's stale-global
class). The failure is invisible — no error, no retrace, just every
subsequent dispatch reasoning about a timestamp that never advances.

Two flagged shapes, both innermost-scope-resolved so ordinary host
code around a dispatch stays clean:

1. a clock read whose innermost enclosing function is jit-STAGED
   (``@jax.jit``-decorated or wrapped by a ``jit(f)`` call) — the read
   happens at trace time, full stop;
2. a clock read whose innermost enclosing function lexically CONSTRUCTS
   a jit (or is step-builder-named, the ``_get_*_step`` /
   ``resolve_*`` family): build-time code runs once, so the value is a
   per-build constant any nested traced closure would freeze.

A clock read inside a nested def that is NOT itself staged or
jit-building (e.g. a retry thunk defined inside a dispatch wrapper) is
runtime host code and is exempt — the innermost scope decides.
Measure-around-the-dispatch timing (``t0 = perf_counter()`` BEFORE the
jitted call, outside any staged body) is the sanctioned idiom and
never fires.
"""

from __future__ import annotations

import ast
from typing import Iterator

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)
from deeplearning4j_tpu.analysis.rules._common import (
    collect_jit_functions, functions_building_jit)
from deeplearning4j_tpu.analysis.rules.jit_key import STEP_BUILDER_NAME

#: clock calls whose value is only meaningful when read at RUN time
_CLOCK_FNS = frozenset({
    "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "time.process_time", "time.process_time_ns",
})


class WallClockInTracedBodyRule(Rule):
    id = "wall-clock-in-traced-body"
    severity = SEVERITY_WARNING
    description = ("time.time()/time.monotonic()/perf_counter() inside "
                   "a jit-staged or jit-constructing (step-builder) "
                   "body: the clock is read once at trace/build time "
                   "and the compiled step carries a frozen timestamp "
                   "constant forever after")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        staged = collect_jit_functions(mod)      # traced bodies
        builders = functions_building_jit(mod)   # build-time bodies
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = mod.resolve(node.func)
            if target not in _CLOCK_FNS:
                continue
            enclosing = mod.enclosing_functions(node)
            if not enclosing:
                continue          # module scope: import-time host code
            fn = enclosing[0]     # INNERMOST scope decides
            if fn in staged:
                yield self.finding(
                    mod, node,
                    f"`{target}()` inside jit-staged '{fn.name}': the "
                    f"clock is read once at trace time and every "
                    f"compiled dispatch reuses that frozen value — "
                    f"read the clock OUTSIDE the staged body and pass "
                    f"the result in as an argument")
            elif fn in builders or STEP_BUILDER_NAME.match(fn.name):
                yield self.finding(
                    mod, node,
                    f"`{target}()` inside jit-constructing "
                    f"'{fn.name}': build-time code runs once, so this "
                    f"timestamp is a per-build constant any traced "
                    f"closure it reaches would bake in — move the read "
                    f"to the per-call path (or pass timestamps as step "
                    f"arguments)")
