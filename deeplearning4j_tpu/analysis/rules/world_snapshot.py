"""stale-world-snapshot: world-topology reads captured at import time.

``jax.process_count()`` / ``jax.process_index()`` / ``jax.device_count()``
(and friends) answer "what does the CURRENT runtime look like" — under
elastic membership (resilience/elastic.py) the answer changes every
re-mesh: a survivor tears down jax.distributed, re-initializes with a
new world size, and its process id is re-assigned. A value captured at
module scope (``WORLD = jax.process_count()``), in a class body, or in a
function's default argument is evaluated ONCE at import/definition time
and silently wrong for the rest of the process after the first re-mesh —
the worst kind of wrong: shard math that still adds up, on the wrong
rows.

Flagged: a call to one of the world-topology reads whose evaluation
happens at import/definition time —

- at module scope or class-body scope (no enclosing function), or
- inside the default-argument expressions of a module/class-level
  ``def`` or ``lambda`` (defaults evaluate when the definition runs,
  not per call).

Call-time reads — inside a function body, a method, a lambda body —
are exactly right (``parallel/distributed.py``'s helpers re-read the
runtime on every call) and never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)

#: world-topology reads whose value a re-mesh invalidates
_WORLD_READS = {
    "jax.process_count",
    "jax.process_index",
    "jax.device_count",
    "jax.local_device_count",
    "jax.devices",
    "jax.local_devices",
    "deeplearning4j_tpu.parallel.distributed.process_count",
    "deeplearning4j_tpu.parallel.distributed.process_index",
}


def _nearest_function(mod: ModuleInfo,
                      node: ast.AST) -> Optional[ast.AST]:
    for a in mod.ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return a
    return None


def _in_defaults(fn: ast.AST, node: ast.AST) -> bool:
    """True if ``node`` sits in the default-argument expressions of
    ``fn`` (evaluated at definition time, not call time)."""
    args = getattr(fn, "args", None)
    if args is None:
        return False
    for d in list(args.defaults) + [d for d in args.kw_defaults
                                    if d is not None]:
        for sub in ast.walk(d):
            if sub is node:
                return True
    return False


class WorldSnapshotRule(Rule):
    id = "stale-world-snapshot"
    severity = SEVERITY_WARNING
    description = ("jax.process_count()/process_index()/device_count() "
                   "captured at module/class scope or in argument "
                   "defaults — stale after an elastic re-mesh; read the "
                   "runtime at call time instead")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not (mod.imports_module("jax") or
                mod.imports_module("deeplearning4j_tpu.parallel")):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = mod.resolve(node.func)
            if name not in _WORLD_READS:
                continue
            fn = _nearest_function(mod, node)
            if fn is None:
                where = "module/class scope"
            elif _in_defaults(fn, node) \
                    and _nearest_function(mod, fn) is None:
                # defaults evaluate when the def/lambda expression runs
                # — import time for a module/class-level definition
                where = "argument defaults"
            else:
                continue  # call-time read: correct
            yield self.finding(
                mod, node,
                f"`{name}()` captured at {where}: evaluated once at "
                f"import/definition time and stale after the first "
                f"elastic re-mesh (world size and process ids change "
                f"per membership generation) — move the read to call "
                f"time")
