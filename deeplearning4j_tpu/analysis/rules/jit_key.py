"""jit-key-drift: process-wide mutable state baked into a trace without
being part of the jit cache key.

The repo's exactness contract for process-wide knobs (ISSUE 13,
generalizing PR 11's env-read special case): anything mutable at process
scope that a step-builder or dispatch-construction body reads — an
``os.environ`` value, a module global flipped through a documented
``set_*`` seam (``_STREAM_CACHE_SHARDING``, ``_PAGED_DECODE_IMPL``), or
an accessor function over one (``paged_decode_impl()``) — MUST either
enter the jit cache key (flipping it then retraces, the correct
behavior) or be resolved to an explicit argument at the API boundary.
Otherwise the value bakes into the compiled step at trace time and a
later flip silently keeps the stale trace — or, when a caller keys its
own cache on it, retraces on every flip. The PR 10 health-accounting bug
was the construction-time variant: an engine snapshotted
``paged_decode_impl()`` into ``self`` at __init__ while dispatches
followed the LIVE process-wide setting.

Shapes:

1. env — ``os.environ`` / ``os.getenv`` read inside a step-builder-named
   or jit-constructing body (moved here from recompile-hazard, PR 11);
2. mutable-global / accessor read inside a jit-CONSTRUCTING top-level
   body (nested defs included — those are the traced bodies) without the
   value flowing into the jit cache key. "Mutable global" means a
   module-scope name some function rebinds via ``global`` (the set_*
   seam shape); an accessor is a project function whose own body loads
   one. The key-flow exemption recognizes the sanctioned pattern: the
   read lands in an assignment to a ``key``-named target, a ``*key*``
   call, or a ``*cache*``/``*key*`` subscript — and once one read of a
   global is keyed in a function, other reads of the SAME global there
   are exempt too (building the key next to using the value is how the
   pattern is written).
3. construction snapshot — ``self.X = <accessor()/global>`` inside
   ``__init__`` outside the global's own module: dispatch-time consumers
   must read the live accessor (the PR 10 fix shape).

Stays stdlib-ast and degrades gracefully: without a ProjectInfo only the
same-module shapes fire.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, Optional, Set

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_WARNING)
from deeplearning4j_tpu.analysis.rules._common import (
    functions_building_jit, norm_source as _norm)

#: function names that ARE plan-resolution / step-builder seams even
#: when the jit construction lives in a helper they call
STEP_BUILDER_NAME = re.compile(
    r"^(_get_\w*_(step|steps|fn)|resolve_\w+|apply_execution_plan"
    r"|set_fusion\w*)$")

_KEYISH = re.compile(r"key", re.IGNORECASE)
_CACHEISH = re.compile(r"cache|key", re.IGNORECASE)


def _is_env_read(mod: ModuleInfo, node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        fn = mod.resolve(node.func)
        if fn == "os.getenv":
            return True
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" \
                and mod.resolve(node.func.value) == "os.environ":
            return True
    if isinstance(node, ast.Subscript) \
            and mod.resolve(node.value) == "os.environ":
        return True
    return False


def _flows_into_key(mod: ModuleInfo, node: ast.AST) -> bool:
    """True when a read's value lands in the jit-cache-key idiom."""
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        if isinstance(anc, ast.Assign):
            if any(_KEYISH.search(_norm(t)) for t in anc.targets):
                return True
        elif isinstance(anc, ast.AnnAssign):
            if _KEYISH.search(_norm(anc.target)):
                return True
        elif isinstance(anc, ast.Subscript):
            if _CACHEISH.search(_norm(anc.value)):
                return True
        elif isinstance(anc, ast.Call):
            if _KEYISH.search(_norm(anc.func)):
                return True
    return False


class JitKeyDriftRule(Rule):
    id = "jit-key-drift"
    severity = SEVERITY_WARNING
    description = ("process-wide mutable state (os.environ, set_*-seam "
                   "module globals, accessors over them) read inside a "
                   "step-builder/jit-constructing body without entering "
                   "the jit cache key: the trace bakes the value in and "
                   "a later flip keeps the stale compiled step")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        yield from self.check_project(mod, None)

    def check_project(self, mod: ModuleInfo, project) -> Iterator[Finding]:
        yield from self._env_shape(mod)
        yield from self._mutable_read_shape(mod, project)
        yield from self._construction_snapshot_shape(mod, project)

    # -- shape 1: env reads (the PR 11 class, migrated) ----------------
    def _env_shape(self, mod: ModuleInfo) -> Iterator[Finding]:
        env_nodes = [n for n in ast.walk(mod.tree)
                     if _is_env_read(mod, n)]
        if not env_nodes:
            return
        env_by_fn: Dict[int, list] = {}
        fns = []
        for n in env_nodes:
            for fn in mod.enclosing_functions(n):
                if id(fn) not in env_by_fn:
                    fns.append(fn)
                env_by_fn.setdefault(id(fn), []).append(n)
        builders = functions_building_jit(mod)
        seen: Set[int] = set()   # a nested jit-building closure inside a
        # named builder is walked from both functions — one finding per
        # read, not two
        # outermost-first (matches pre-order walk): the named builder
        # claims the read before its nested closure can
        for fn in sorted(fns, key=lambda f: f.lineno):
            named = bool(STEP_BUILDER_NAME.match(fn.name))
            if not (named or fn in builders):
                continue
            for n in sorted(env_by_fn[id(fn)],
                            key=lambda x: getattr(x, "lineno", 0)):
                if id(n) in seen:
                    continue
                seen.add(id(n))
                yield self.finding(
                    mod, n,
                    f"os.environ read inside step-builder "
                    f"'{fn.name}': the value bakes into the trace "
                    f"but is not part of any jit key — flipping it "
                    f"keeps a stale compiled step (or retraces per "
                    f"flip); resolve it to an explicit argument at "
                    f"the API boundary")
                break  # one finding per builder is enough signal

    # -- mutable-global machinery --------------------------------------
    def _local_mutable(self, mod: ModuleInfo) -> Set[str]:
        from deeplearning4j_tpu.analysis.project import (
            module_mutable_globals)
        return module_mutable_globals(mod)

    def _canonical_mutable(self, mod: ModuleInfo, node: ast.AST,
                           project, local: Set[str]) -> Optional[str]:
        """'module.GLOBAL' when `node` loads a mutable module global —
        locally, through an alias, or (with a project) in another
        project module."""
        if not isinstance(node, (ast.Name, ast.Attribute)):
            return None
        if not isinstance(getattr(node, "ctx", None), ast.Load):
            return None
        if isinstance(node, ast.Name) and node.id in local \
                and node.id not in mod.aliases:
            return f"{mod.rel_path}:{node.id}" if project is None else \
                self._own_canonical(mod, project, node.id)
        canonical = mod.resolve(node)
        if canonical is None or project is None:
            return None
        hit = project.split_module_prefix(canonical)
        if hit is None:
            return None
        mod_name, rest = hit
        if rest and "." not in rest \
                and rest in project.mutable_globals(mod_name):
            return f"{mod_name}.{rest}"
        return None

    @staticmethod
    def _own_canonical(mod: ModuleInfo, project, name: str) -> str:
        own = project.by_rel_path.get(mod.rel_path, mod.rel_path)
        return f"{own}.{name}"

    def _accessor_reads(self, project, mod_name: str,
                        qualname: str) -> Set[str]:
        """Canonical mutable globals an accessor function's own body
        loads (depth 1 — accessors are thin by convention)."""
        cache: Dict = getattr(project, "_accessor_reads", None)
        if cache is None:
            cache = {}
            project._accessor_reads = cache
        key = f"{mod_name}:{qualname}"
        if key in cache:
            return cache[key]
        out: Set[str] = set()
        fn = project.lookup_function(mod_name, qualname)
        target_mod = project.modules.get(mod_name)
        if fn is not None and target_mod is not None:
            mut = project.mutable_globals(mod_name)
            for n in ast.walk(fn):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                        and n.id in mut:
                    out.add(f"{mod_name}.{n.id}")
        cache[key] = out
        return out

    # -- shape 2: reads inside jit-constructing bodies ------------------
    def _mutable_read_shape(self, mod: ModuleInfo,
                            project) -> Iterator[Finding]:
        local = self._local_mutable(mod)
        builders = functions_building_jit(mod)
        for fn in self._top_fns(mod):
            if fn not in builders:
                continue
            # pass 1: globals whose reads are keyed somewhere in fn
            keyed: Set[str] = set()
            reads = []
            for n in ast.walk(fn):
                canon = self._canonical_mutable(mod, n, project, local)
                if canon is not None:
                    if _flows_into_key(mod, n):
                        keyed.add(canon)
                    else:
                        reads.append((n, canon, None))
                    continue
                if isinstance(n, ast.Call) and project is not None:
                    target = project.resolve_call(mod, n)
                    if target is None:
                        continue
                    accessed = self._accessor_reads(project, *target)
                    if not accessed:
                        continue
                    canon = sorted(accessed)[0]
                    if _flows_into_key(mod, n):
                        keyed.add(canon)
                    else:
                        reads.append((n, canon, target[1]))
            for n, canon, accessor in reads:
                if canon in keyed:
                    continue
                if accessor is not None:
                    yield self.finding(
                        mod, n,
                        f"process-wide accessor '{accessor}()' (reads "
                        f"'{canon}') called inside jit-constructing "
                        f"'{fn.name}' without entering the jit cache "
                        f"key: the live value bakes into the trace and "
                        f"a later set_* flip keeps the stale compiled "
                        f"step — add it to the cache key (the "
                        f"_STREAM_CACHE_SHARDING pattern) or take it as "
                        f"an explicit argument")
                else:
                    yield self.finding(
                        mod, n,
                        f"process-wide mutable global '{canon}' read "
                        f"inside jit-constructing '{fn.name}' without "
                        f"entering the jit cache key: the value bakes "
                        f"into the trace and a later set_* flip keeps "
                        f"the stale compiled step — add it to the cache "
                        f"key (the _STREAM_CACHE_SHARDING pattern) or "
                        f"take it as an explicit argument")

    @staticmethod
    def _top_fns(mod: ModuleInfo):
        """Top-level functions and methods (no enclosing function):
        nested builders are walked from their top-level owner so one
        read yields one finding. Memoized per module."""
        return mod.fact("top_level_functions", lambda m: [
            node for node in ast.walk(m.tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and not m.enclosing_functions(node)])

    # -- shape 3: construction-time snapshot (the PR 10 health bug) ----
    def _construction_snapshot_shape(self, mod: ModuleInfo,
                                     project) -> Iterator[Finding]:
        if project is None:
            return
        own_name = project.by_rel_path.get(mod.rel_path)
        local = self._local_mutable(mod)
        for fn in self._top_fns(mod):
            if fn.name != "__init__":
                continue
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.Assign):
                    continue
                if not any(isinstance(t, ast.Attribute)
                           and isinstance(t.value, ast.Name)
                           and t.value.id == "self"
                           for t in stmt.targets):
                    continue
                for n in ast.walk(stmt.value):
                    canon = self._canonical_mutable(mod, n, project, local)
                    accessor = None
                    if canon is None and isinstance(n, ast.Call):
                        target = project.resolve_call(mod, n)
                        if target is not None:
                            accessed = self._accessor_reads(project,
                                                            *target)
                            if accessed:
                                canon = sorted(accessed)[0]
                                accessor = target[1]
                    if canon is None:
                        continue
                    # the owning module wiring its own seam is the
                    # documented pattern, not drift
                    if own_name is not None \
                            and canon.startswith(own_name + "."):
                        continue
                    what = f"accessor '{accessor}()'" if accessor \
                        else f"global '{canon}'"
                    yield self.finding(
                        mod, n,
                        f"construction-time snapshot of process-wide "
                        f"{what} stored on self: dispatch-time behavior "
                        f"follows the LIVE setting, which a later "
                        f"set_* call can flip (the PR 10 "
                        f"paged_decode_impl() health-accounting bug) — "
                        f"read the accessor at use time or key the jit "
                        f"cache on it")
                    break
