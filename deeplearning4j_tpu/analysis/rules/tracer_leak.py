"""tracer-leak: traced values escaping a jit-staged function into Python
state.

Assigning to `self.*` or a `global` inside a function that jax traces
stores a Tracer object, not an array: the side effect happens once at
trace time, silently goes stale across calls, and raises
`UnexpectedTracerError` the moment the leaked value is used in a later
trace. Thread state through the function's return value instead.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, SEVERITY_ERROR)
from deeplearning4j_tpu.analysis.rules._common import collect_jit_functions


def _global_names(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


class TracerLeakRule(Rule):
    id = "tracer-leak"
    severity = SEVERITY_ERROR
    description = ("assignment to self.*/global inside a jit/pmap/"
                   "shard_map-staged function stores a Tracer, not an "
                   "array")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for fn in collect_jit_functions(mod):
            globals_ = _global_names(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.AugAssign):
                    targets = [node.target]
                elif isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets = [node.target]
                else:
                    continue
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        yield self.finding(
                            mod, node,
                            f"assignment to self.{t.attr} inside traced "
                            f"'{fn.name}' leaks a Tracer into Python state; "
                            f"return the value instead")
                    elif isinstance(t, ast.Name) and t.id in globals_:
                        yield self.finding(
                            mod, node,
                            f"assignment to global '{t.id}' inside traced "
                            f"'{fn.name}' leaks a Tracer into Python state; "
                            f"return the value instead")
