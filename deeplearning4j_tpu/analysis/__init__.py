"""tpulint — whole-program AST static analysis for JAX/TPU
anti-patterns.

The static half of the performance-defect story (the PR 1 monitoring
subsystem is the runtime half): catches host syncs and device transfers
in fit/serve hot paths — including ones reached through helper calls
(the ProjectInfo/CallGraph layer, ISSUE 13) — donation use-after-consume
(the PR 10 decode_retry class), jit-key drift, tracer leaks, recompile
hazards, f64 promotion, unlocked cross-thread mutation, and hygiene
defects at review time, before they reach a TPU.

CLI:   python -m deeplearning4j_tpu.analysis [paths] \
           [--format=text|json] [--baseline=PATH] [--diff REF] \
           [--rule ID] [--update-baseline [--allow-grandfather]]
API:   scan_paths(paths) -> List[Finding]
Suppress inline with `# tpulint: disable=<rule-id>` (same line, or a
standalone comment on the line above carrying the justification); a
suppression at a helper's effect line also stops interprocedural
propagation to its callers.
"""

from deeplearning4j_tpu.analysis.core import (  # noqa: F401
    Finding, ModuleInfo, Rule, scan_file, scan_paths)
from deeplearning4j_tpu.analysis.cli import main  # noqa: F401
from deeplearning4j_tpu.analysis.rules import (  # noqa: F401
    ALL_RULES, RULES_BY_ID)

__all__ = ["Finding", "ModuleInfo", "Rule", "scan_file", "scan_paths",
           "main", "ALL_RULES", "RULES_BY_ID"]
