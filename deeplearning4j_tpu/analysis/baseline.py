"""Baseline handling: grandfathered findings that don't fail the lane.

The committed `TPULINT_BASELINE.json` records the fingerprints of
findings that were judged acceptable when the analyzer landed (host-side
f64 math in the t-SNE plotter, etc.). A scan is clean when every finding
is consumed by a baseline entry; anything beyond the recorded count is
NEW and fails CI. Fingerprints hash (rule, path, normalized source
line), not line numbers, so edits elsewhere in a file don't churn the
baseline — but touching a baselined line itself re-opens the finding,
which is the desired ratchet.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, List, Sequence, Tuple

from deeplearning4j_tpu.analysis.core import Finding, SEVERITY_ERROR

BASELINE_NAME = "TPULINT_BASELINE.json"
BASELINE_VERSION = 1


def repo_root() -> str:
    """The directory holding the package (where the baseline lives)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_baseline_path() -> str:
    for cand in (os.path.join(os.getcwd(), BASELINE_NAME),
                 os.path.join(repo_root(), BASELINE_NAME)):
        if os.path.exists(cand):
            return cand
    return os.path.join(repo_root(), BASELINE_NAME)


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry ({rule, path, count, snippet})."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return dict(data.get("findings", {}))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries: Dict[str, dict] = {}
    for f_ in findings:
        fp = f_.fingerprint()
        if fp in entries:
            entries[fp]["count"] += 1
        else:
            entries[fp] = {"rule": f_.rule, "path": f_.path,
                           "count": 1, "snippet": f_.snippet}
    payload = {"version": BASELINE_VERSION,
               "tool": "tpulint",
               "findings": dict(sorted(entries.items()))}
    # tmp + rename (stdlib-only — this package must import anywhere):
    # a crash mid-write must not leave CI gating on a torn baseline
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def update_baseline(path: str, findings: Sequence[Finding],
                    allow_grandfather: bool = False) -> List[Finding]:
    """The hardened ratchet (`--update-baseline`): rewrite the baseline
    from the current scan — which silently DROPS stale entries (debt
    paid off ratchets down for free) — but REFUSE to add entries for
    findings at severity error unless `allow_grandfather` is passed.
    Grandfathering an error-severity finding is a deliberate reviewed
    decision, not a side effect of refreshing the file.

    Returns the refused findings (non-empty means nothing was written);
    an empty list means the baseline was updated."""
    if not allow_grandfather:
        budget = Counter({fp: e.get("count", 1)
                          for fp, e in load_baseline(path).items()})
        refused: List[Finding] = []
        for f_ in findings:
            fp = f_.fingerprint()
            if budget.get(fp, 0) > 0:
                budget[fp] -= 1   # already grandfathered: re-recording ok
                continue
            if f_.severity == SEVERITY_ERROR:
                refused.append(f_)
        if refused:
            return refused
    write_baseline(path, findings)
    return []


def split_new(findings: Sequence[Finding], baseline: Dict[str, dict]
              ) -> Tuple[List[Finding], int, List[str]]:
    """Partition findings into (new, baselined_count, stale_fingerprints).

    Stale fingerprints — baseline entries no longer observed — are
    reported so the baseline can ratchet down as debt is paid."""
    budget = Counter({fp: e.get("count", 1) for fp, e in baseline.items()})
    new: List[Finding] = []
    matched = 0
    for f_ in findings:
        fp = f_.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            matched += 1
        else:
            new.append(f_)
    stale = sorted(fp for fp, left in budget.items() if left > 0)
    return new, matched, stale
