"""``python -m deeplearning4j_tpu.analysis`` — the tpulint entry point.

Exit-code contract (also printed by ``--help``):
  0  clean (no new findings, no stale baseline entries)
  1  gate failure (new findings incl. parse errors, stale baseline
     entries, or a refused ``--update-baseline``)
  2  usage error (unknown rule, missing path, bad ``--diff`` ref, or
     baseline writes combined with ``--diff`` / a rule subset)
"""

import sys

from deeplearning4j_tpu.analysis.cli import main

sys.exit(main())
