"""Call graph + bounded-depth per-function effect summaries.

Each project function gets a `FunctionSummary`: the effects its OWN body
performs (host sync, device transfer, donating dispatch — classified by
the same predicates the local rules use, so the interprocedural story
can never disagree with the lexical one) plus the project calls it
makes. `reaches()` answers "does this callee, within N call hops,
perform effect X?" with the shortest evidence chain, which promoted
rules render into their call-site messages.

Design points:

- Effects belong to their INNERMOST enclosing function: a nested
  ``def step(...)`` inside a builder is its own summary node, so a
  trace-time constant in a jit body never bleeds into the builder's
  summary.
- Inline suppressions in the CALLEE kill propagation: a justified
  ``# tpulint: disable=host-sync-in-hot-loop`` on the helper's sync line
  means callers don't get flagged for it either — one suppression per
  contract, not one per caller.
- Depth is bounded (`MAX_DEPTH` call hops) and cycles are cut by a
  visited set, so a recursive pair of modules costs one visit each.
- Resolution is static-name-only (see project.py soundness caveats):
  dynamic dispatch breaks the chain, making this an under-approximation.
"""

from __future__ import annotations

import ast
import dataclasses
from collections import deque
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.analysis.core import ModuleInfo
from deeplearning4j_tpu.analysis.project import (
    ProjectInfo, iter_functions)

#: call-hop bound for transitive summaries: effects more than this many
#: resolved calls below a hot call site are not attributed to it
MAX_DEPTH = 3

EFFECT_HOST_SYNC = "host_sync"
EFFECT_DEVICE_TRANSFER = "device_transfer"
EFFECT_DONATING_DISPATCH = "donating_dispatch"

#: effect kind -> rule id whose inline suppression kills propagation
_SUPPRESSING_RULE = {
    EFFECT_HOST_SYNC: "host-sync-in-hot-loop",
    EFFECT_DEVICE_TRANSFER: "device-transfer-in-hot-loop",
    EFFECT_DONATING_DISPATCH: "donation-use-after-consume",
}


@dataclasses.dataclass(frozen=True)
class Effect:
    kind: str
    line: int
    what: str    # e.g. "jax.device_get()"
    why: str     # one-phrase consequence, from the classifying rule
    path: str    # rel path of the module owning the effect


@dataclasses.dataclass
class FunctionSummary:
    module: str                        # dotted module name
    qualname: str
    node: ast.AST
    effects: List[Effect]
    calls: List[Tuple[str, int]]       # (callee key, call line)

    @property
    def key(self) -> str:
        return f"{self.module}:{self.qualname}"


def _memo_guarded(mod: ModuleInfo, call: ast.Call) -> bool:
    """True when a call's result feeds a memoized slot: the nearest
    enclosing assignment's target also appears in an enclosing ``if``
    test of the ``is None`` / ``not in`` shape — the cached-table /
    cached-jit idiom, where the effect runs once per invalidation, not
    once per caller invocation. Such effects are NOT propagated to
    callers (the steady state is effect-free by construction)."""
    from deeplearning4j_tpu.analysis.rules._common import norm_source

    assign = None
    for anc in mod.ancestors(call):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        if isinstance(anc, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            assign = anc
            break
    if assign is None:
        return False
    targets = assign.targets if isinstance(assign, ast.Assign) \
        else [assign.target]
    target_txt = {norm_source(t) for t in targets}
    for anc in mod.ancestors(assign):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        if isinstance(anc, ast.If):
            test = norm_source(anc.test)
            if any(t and t in test for t in target_txt) \
                    and ("isNone" in test or "notin" in test):
                return True
    return False


def own_body_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """A function's own body, nested defs/lambdas excluded (they are
    separate summary nodes). Thin façade over the shared walker."""
    from deeplearning4j_tpu.analysis.rules._common import walk_no_defs
    return walk_no_defs(fn, include_self=False)


class CallGraph:
    """Per-function summaries over a ProjectInfo + bounded reachability."""

    def __init__(self, project: ProjectInfo, max_depth: int = MAX_DEPTH):
        self.project = project
        self.max_depth = max_depth
        self.summaries: Dict[str, FunctionSummary] = {}
        for mod_name, mod in project.modules.items():
            self._summarize_module(mod_name, mod)

    # -- construction --------------------------------------------------
    def _summarize_module(self, mod_name: str, mod: ModuleInfo) -> None:
        # lazy imports: the rule modules import core, not callgraph
        from deeplearning4j_tpu.analysis.rules.host_sync import (
            classify_sync)
        from deeplearning4j_tpu.analysis.rules.device_transfer import (
            classify_transfer)
        from deeplearning4j_tpu.analysis.rules.donation import (
            classify_donating_call, module_donation_map)

        uses_jax = mod.imports_module("jax")
        donation_map = module_donation_map(mod)
        for qualname, fn in iter_functions(mod):
            effects: List[Effect] = []
            calls: List[Tuple[str, int]] = []
            for node in own_body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                line = getattr(node, "lineno", 0)
                if uses_jax:
                    what, why = classify_sync(mod, node, strong_only=True)
                    if what is not None and not self._suppressed(
                            mod, line, EFFECT_HOST_SYNC) \
                            and not _memo_guarded(mod, node):
                        effects.append(Effect(
                            EFFECT_HOST_SYNC, line, what, why,
                            mod.rel_path))
                    what, why = classify_transfer(mod, node)
                    if what is not None and not self._suppressed(
                            mod, line, EFFECT_DEVICE_TRANSFER) \
                            and not _memo_guarded(mod, node):
                        effects.append(Effect(
                            EFFECT_DEVICE_TRANSFER, line, what, why,
                            mod.rel_path))
                don = classify_donating_call(mod, node, donation_map,
                                             project=self.project)
                if don is not None and not self._suppressed(
                        mod, line, EFFECT_DONATING_DISPATCH):
                    effects.append(Effect(
                        EFFECT_DONATING_DISPATCH, line, don.label,
                        "consumes its donated argument buffers",
                        mod.rel_path))
                target = self.project.resolve_call(mod, node)
                if target is not None:
                    calls.append((f"{target[0]}:{target[1]}", line))
            s = FunctionSummary(mod_name, qualname, fn, effects, calls)
            self.summaries[s.key] = s

    @staticmethod
    def _suppressed(mod: ModuleInfo, line: int, kind: str) -> bool:
        sup = mod.suppressions.get(line, ())
        return _SUPPRESSING_RULE[kind] in sup or "all" in sup

    # -- queries -------------------------------------------------------
    def summary(self, key: str) -> Optional[FunctionSummary]:
        return self.summaries.get(key)

    def reaches(self, key: str, kinds: FrozenSet[str],
                max_depth: Optional[int] = None
                ) -> Optional[Tuple[Effect, Tuple[str, ...]]]:
        """Shortest evidence that `key` performs one of `kinds` within
        the hop bound: (effect, chain-of-keys ending at the owner).
        BFS, so the returned chain is minimal; within one depth, code
        order wins. None when nothing is reachable."""
        if max_depth is None:
            max_depth = self.max_depth
        if key not in self.summaries:
            return None
        queue = deque([(key, (key,), 1)])
        seen = {key}
        while queue:
            k, chain, depth = queue.popleft()
            for eff in self.summaries[k].effects:
                if eff.kind in kinds:
                    return eff, chain
            if depth >= max_depth:
                continue
            for callee, _line in self.summaries[k].calls:
                if callee in self.summaries and callee not in seen:
                    seen.add(callee)
                    queue.append((callee, chain + (callee,), depth + 1))
        return None

    @staticmethod
    def render_chain(chain: Sequence[str], effect: Effect) -> str:
        """Human form of an evidence chain for rule messages:
        ``a.helper -> b.deeper (jax.device_get() at pkg/b.py:12)``."""
        names = " -> ".join(k.split(":", 1)[1] or k for k in chain)
        return f"{names} ({effect.what} at {effect.path}:{effect.line})"
