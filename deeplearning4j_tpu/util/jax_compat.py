"""Version-compat shims for jax APIs that moved between releases.

`shard_map` graduated from `jax.experimental.shard_map` to `jax.shard_map`
(and its replication-check kwarg was renamed `check_rep` -> `check_vma`).
Call sites across parallel/ and nlp/ use the modern spelling; this module
makes that spelling work on older runtimes too.
"""

from __future__ import annotations

try:  # jax >= 0.6
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, **kw):
        kw["check_rep"] = kw.pop("check_vma", False)
        return _shard_map_legacy(f, **kw)

# enable_x64 likewise graduated from jax.experimental to the jax namespace
try:  # jax >= 0.7
    from jax import enable_x64
except ImportError:
    from jax.experimental import enable_x64 as _enable_x64_legacy

    def enable_x64(new_val: bool = True):
        return _enable_x64_legacy(new_val)

__all__ = ["shard_map", "enable_x64"]
