"""Model checkpointing.

TPU-native equivalent of deeplearning4j-nn/.../util/ModelSerializer.java:37-214:
a zip containing `configuration.json` (full config JSON :90) plus parameter
and updater-state arrays. The reference stores ONE flat float vector
(`coefficients.bin` :95, `updaterState.bin` :107); here each pytree leaf is a
named .npy entry (params/<layer>/<name>.npy) — same information, but
shard-friendly and layout-independent (no flat-view ordering to get wrong).

`restore_multi_layer_network` / `restore_computation_graph` mirror
ModelSerializer.restoreMultiLayerNetwork :137. A separate DL4J-zip importer
(modelimport/dl4j.py) reads the reference's own flat-vector format.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

CONFIG_JSON = "configuration.json"
MODEL_TYPE_KEY = "model_type"


def _write_tree(zf: zipfile.ZipFile, prefix: str, tree) -> None:
    flat = _flatten_with_paths(tree)
    for path, arr in flat.items():
        buf = io.BytesIO()
        np.save(buf, np.asarray(arr))
        zf.writestr(f"{prefix}/{path}.npy", buf.getvalue())


def _flatten_with_paths(tree, prefix="") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_with_paths(v, f"{prefix}{k}/"))
    elif tree is None:
        pass
    elif hasattr(tree, "shape"):
        out[prefix[:-1]] = tree
    else:
        out[prefix[:-1]] = np.asarray(tree)
    return out


def _read_tree(zf: zipfile.ZipFile, prefix: str) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    plen = len(prefix) + 1
    for name in zf.namelist():
        if not name.startswith(prefix + "/") or not name.endswith(".npy"):
            continue
        path = name[plen:-4].split("/")
        arr = np.load(io.BytesIO(zf.read(name)))
        d = out
        for seg in path[:-1]:
            d = d.setdefault(seg, {})
        d[path[-1]] = jnp.asarray(arr)
    return out


def write_model(model, path: str, save_updater: bool = True) -> None:
    """Save a MultiLayerNetwork or ComputationGraph
    (ref: ModelSerializer.writeModel :79)."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    if isinstance(model, MultiLayerNetwork):
        mtype = "MultiLayerNetwork"
    elif isinstance(model, ComputationGraph):
        mtype = "ComputationGraph"
    else:
        raise ValueError(f"cannot serialize {type(model)}")

    meta = {
        MODEL_TYPE_KEY: mtype,
        "iteration_count": model.iteration_count,
        "epoch_count": model.epoch_count,
        "framework": "deeplearning4j_tpu",
    }
    # atomic: the zip is assembled at a tmp path and renamed into place,
    # so a crash mid-save can't destroy an existing model file
    from deeplearning4j_tpu.resilience.durable import atomic_replace_path
    with atomic_replace_path(path) as tmp:
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(CONFIG_JSON, model.conf.to_json())
            zf.writestr("meta.json", json.dumps(meta))
            _write_tree(zf, "params", model.params)
            _write_tree(zf, "state", model.state)
            if save_updater:
                _write_tree(zf, "updater", model.updater_state)


NORMALIZER_JSON = "normalizer.json"


def add_normalizer_to_model(path: str, normalizer) -> None:
    """Embed a fitted normalizer in an existing checkpoint zip
    (ref: ModelSerializer.addNormalizerToModel — inference then applies
    identical preprocessing)."""
    with zipfile.ZipFile(path, "a", zipfile.ZIP_DEFLATED) as zf:
        if NORMALIZER_JSON in zf.namelist():
            raise ValueError(f"{path} already contains a normalizer")
        zf.writestr(NORMALIZER_JSON, normalizer.to_json())


def restore_normalizer_from_file(path: str):
    """ref: ModelSerializer.restoreNormalizerFromFile — None when the
    checkpoint has no embedded normalizer."""
    from deeplearning4j_tpu.datasets.normalizers import normalizer_from_dict
    with zipfile.ZipFile(path, "r") as zf:
        if NORMALIZER_JSON not in zf.namelist():
            return None
        return normalizer_from_dict(json.loads(zf.read(NORMALIZER_JSON)))


def _merge_state(init_state, loaded):
    """Use loaded state where present, else initialized values (handles
    checkpoints written without updater state)."""
    if not loaded:
        return init_state
    return loaded


def restore_multi_layer_network(path: str, load_updater: bool = True):
    """ref: ModelSerializer.restoreMultiLayerNetwork :137."""
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path) as zf:
        conf = MultiLayerConfiguration.from_json(zf.read(CONFIG_JSON).decode())
        net = MultiLayerNetwork(conf)
        net.init()
        # merge over init: parameterless layers' empty dicts produce no zip
        # entries, but the forward pass still indexes them
        net.params = {**net.params, **_read_tree(zf, "params")}
        net.state = _merge_state(net.state, _read_tree(zf, "state"))
        meta = json.loads(zf.read("meta.json"))
        net.iteration_count = meta.get("iteration_count", 0)
        net.epoch_count = meta.get("epoch_count", 0)
        if load_updater:
            upd = _read_tree(zf, "updater")
            if upd:
                net.updater_state = upd
    return net


def restore_computation_graph(path: str, load_updater: bool = True):
    """ref: ModelSerializer.restoreComputationGraph."""
    from deeplearning4j_tpu.nn.conf.network import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    with zipfile.ZipFile(path) as zf:
        conf = ComputationGraphConfiguration.from_json(zf.read(CONFIG_JSON).decode())
        net = ComputationGraph(conf)
        net.init()
        net.params = {**net.params, **_read_tree(zf, "params")}
        net.state = _merge_state(net.state, _read_tree(zf, "state"))
        meta = json.loads(zf.read("meta.json"))
        net.iteration_count = meta.get("iteration_count", 0)
        net.epoch_count = meta.get("epoch_count", 0)
        if load_updater:
            upd = _read_tree(zf, "updater")
            if upd:
                net.updater_state = upd
    return net


def restore_model(path: str, load_updater: bool = True):
    """Sniff model type and restore (ref: core ModelGuesser)."""
    with zipfile.ZipFile(path) as zf:
        meta = json.loads(zf.read("meta.json"))
    if meta[MODEL_TYPE_KEY] == "MultiLayerNetwork":
        return restore_multi_layer_network(path, load_updater)
    return restore_computation_graph(path, load_updater)
