"""Decoding strategies over the streaming rnn_time_step machinery.

Model-agnostic: works for ANY network whose rnn_time_step carries
batch-leading streaming state — LSTM h/c (the reference's
rnnTimeStep-based generation, MultiLayerNetwork.java rnnTimeStep) and
attention KV caches alike. Beams ride the batch dimension; pruning
gathers the carried state with reorder_stream_state so surviving beams
continue from their parent's caches.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nn.conf.layers import reorder_stream_state


def _one_hot(rows: np.ndarray, vocab: int) -> np.ndarray:
    rows = np.asarray(rows)
    b, t = rows.shape
    x = np.zeros((b, vocab, t), np.float32)
    x[np.arange(b)[:, None], rows, np.arange(t)[None, :]] = 1.0
    return x


def _probs(out) -> np.ndarray:
    return np.asarray(out[0] if isinstance(out, (list, tuple)) else out)


def filter_probs(probs, temperature,
                 top_k=None, top_p=None) -> np.ndarray:
    """The sampling distribution actually drawn from: temperature
    rescales first, then `top_k` keeps exactly the k most probable
    tokens, then `top_p` (nucleus) keeps the smallest prefix of the
    sorted distribution whose mass reaches p (always at least one
    token); survivors renormalize. Shared by draw() and the
    speculative-decoding acceptance rule (which needs the filtered
    distributions themselves, not just a sample).

    `probs` is one row [V] or a batch [B, V]. For a batch,
    `temperature`/`top_k`/`top_p` may each be a scalar (shared) or a
    [B] array (PER-ROW — one serving arena can hold requests with
    mixed sampling configs). Per-row `top_k`/`top_p` entries <= 0
    disable that filter for that row; per-row temperature entries must
    be positive. The single-row form is the batch form at B=1, so
    batched filtering is row-for-row identical to the scalar path
    (test-pinned)."""
    probs = np.asarray(probs)
    if probs.ndim == 1:
        return _filter_rows(probs[None, :], temperature, top_k, top_p)[0]
    if probs.ndim != 2:
        raise ValueError(f"probs must be [V] or [B, V], got shape "
                         f"{probs.shape}")
    return _filter_rows(probs, temperature, top_k, top_p)


def _row_array(v, B: int, name: str):
    """Validate a scalar-or-[B] sampling parameter; returns (array or
    None, is_per_row)."""
    if v is None:
        return None, False
    a = np.asarray(v)
    if a.ndim == 0:
        return a, False
    if a.shape != (B,):
        raise ValueError(f"{name} must be a scalar or one value per row "
                         f"({a.shape} != ({B},))")
    return a, True


def _filter_rows(p2, temperature, top_k, top_p):
    """Vectorized filter over [B, V] rows (see filter_probs)."""
    B, V = p2.shape
    logits = np.log(np.clip(p2, 1e-9, None))
    t, t_rows = _row_array(temperature, B, "temperature")
    if t_rows:
        if (np.asarray(t) <= 0).any():
            raise ValueError("per-row temperature entries must be > 0")
        logits = logits / t.astype(logits.dtype)[:, None]
    else:
        logits = logits / t.astype(logits.dtype)
    p = np.exp(logits - logits.max(axis=-1, keepdims=True))
    p = p / p.sum(axis=-1, keepdims=True)
    if top_k is not None:
        k, k_rows = _row_array(top_k, B, "top_k")
        if not k_rows and int(k) < 1:
            raise ValueError(f"top_k must be >= 1, got {int(k)}")
        krow = (np.where(k > 0, k, V) if k_rows
                else np.full(B, int(k))).astype(np.int64)
        row_on = krow < V
        if row_on.any():
            # exactly k indices per row (a value threshold would keep
            # every token TIED with the kth — e.g. a clipped flat tail —
            # and sample the whole vocab precisely when users reach for
            # top_k). This runs once per sampled token on the serving
            # hot path, so stay O(V): partition out the top kmax
            # candidates, sort only that slice, then cut each row at its
            # own k. Off rows bypass bit-exactly: keep all, divide by 1.
            kmax = int(krow[row_on].max())
            part = np.argpartition(p, V - kmax, axis=-1)[:, V - kmax:]
            vals = np.take_along_axis(p, part, axis=-1)
            order = np.take_along_axis(
                part, np.argsort(vals, axis=-1)[:, ::-1], axis=-1)
            keep = np.zeros((B, V), bool)
            np.put_along_axis(
                keep, order,
                np.arange(kmax)[None, :] < krow[:, None], axis=-1)
            keep |= ~row_on[:, None]
            p = np.where(keep, p, 0.0)
            denom = np.where(row_on, p.sum(axis=-1), 1.0)
            p = p / denom[:, None]
    if top_p is not None:
        tp, tp_rows = _row_array(top_p, B, "top_p")
        tp = np.asarray(tp, np.float64)
        if tp_rows:
            if (tp > 1.0).any():
                raise ValueError(f"top_p entries must be <= 1, got "
                                 f"{tp.max()}")
            row_on = tp > 0                           # <= 0: filter off
            prow = np.where(row_on, tp, 1.0)
        else:
            if not 0.0 < float(tp) <= 1.0:
                raise ValueError(f"top_p must be in (0, 1], got "
                                 f"{float(tp)}")
            row_on = np.ones(B, bool)
            prow = np.full(B, float(tp))
        if row_on.any():
            order = np.argsort(p, axis=-1)[:, ::-1]
            ps = np.take_along_axis(p, order, axis=-1)
            csum = np.cumsum(ps, axis=-1)
            # keep the smallest prefix whose mass reaches p, never
            # empty: a sorted token survives iff the mass STRICTLY
            # BEFORE it is under top_p (the exact searchsorted-left
            # rule, shifted-cumsum form). Off rows bypass bit-exactly.
            before = np.concatenate(
                [np.zeros((B, 1), csum.dtype), csum[:, :-1]], axis=1)
            keep = np.zeros((B, V), bool)
            np.put_along_axis(keep, order, before < prow[:, None],
                              axis=-1)
            keep |= ~row_on[:, None]
            p = np.where(keep, p, 0.0)
            denom = np.where(row_on, p.sum(axis=-1), 1.0)
            p = p / denom[:, None]
    return p


def per_row_param(v, b: int):
    """Row `b`'s value of a scalar-or-per-row `top_k`/`top_p` parameter
    (per-row array entries <= 0 mean the filter is off for that row —
    returned as None, the scalar-API spelling of off)."""
    if v is None:
        return None
    a = np.asarray(v)
    if a.ndim == 0:
        return v
    x = a[b]
    if x <= 0:
        return None
    return int(x) if np.issubdtype(a.dtype, np.integer) else float(x)


def draw(probs, temperature, rng,
         top_k=None, top_p=None):
    """Sample token ids from softmax distributions (the single draw
    implementation shared by every sampler); see filter_probs for the
    temperature/top_k/top_p semantics (incl. the per-row array forms).
    top_k=1 is greedy decoding regardless of temperature.

    One row [V] returns an int. A batch [B, V] returns a list of ints;
    `rng` is then either one Generator (consumed row-major) or a
    sequence of one Generator per row — independent per-request
    streams. (The serving engine itself draws row-by-row through the
    single-row form so each request's rng consumption is positionally
    identical to its one-shot sample_stream run; both forms share ONE
    filter kernel, `_filter_rows`.)"""
    probs = np.asarray(probs)
    if probs.ndim == 2:
        p = filter_probs(probs, temperature, top_k, top_p)
        rngs = (list(rng) if isinstance(rng, (list, tuple))
                else [rng] * len(p))
        if len(rngs) != len(p):
            raise ValueError(f"need one rng per row "
                             f"({len(rngs)} != {len(p)})")
        return [int(r.choice(p.shape[1], p=row))
                for r, row in zip(rngs, p)]
    p = filter_probs(probs, temperature, top_k, top_p)
    return int(rng.choice(len(p), p=p))


def _check_seed(seed_ids, steps, max_length):
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if len(seed_ids) == 0:
        raise ValueError("seed_ids must contain at least one token")
    if max_length is not None and len(seed_ids) >= max_length:
        raise ValueError(f"seed of {len(seed_ids)} tokens leaves no room "
                         f"under max_length {max_length}")


#: largest priming chunk; every prompt decomposes into descending
#: powers of two <= this, so ALL prompt lengths share at most
#: log2(PRIME_CHUNK_MAX)+1 distinct jit shapes (vs one trace per length)
PRIME_CHUNK_MAX = 64


def set_prime_chunk_max(n: int) -> None:
    """Raise (or lower) the largest priming chunk. Long-prompt serving
    wants this high — a 1000-token prompt primes in 6 dispatches at 1024
    vs 17 at the default 64 — at the cost of one extra compile per new
    power-of-two shape the deployment actually sees. Exactness is
    unaffected: chunks are exact prompt slices (never padded), and
    stateful streaming makes any chunking == one-shot priming."""
    if n < 1 or (n & (n - 1)) != 0:
        raise ValueError(f"prime chunk max must be a power of two, got {n}")
    global PRIME_CHUNK_MAX
    PRIME_CHUNK_MAX = n


def _prime_chunks(n: int, chunk_max: int = None):
    """Greedy power-of-two decomposition of a prompt length, largest
    chunk first (serving-friendly: a new prompt length never costs a new
    compile once the shared chunk shapes are warm)."""
    out = []
    c = chunk_max or PRIME_CHUNK_MAX
    if c < 1 or (c & (c - 1)) != 0:
        raise ValueError(f"prime chunk max must be a power of two, got {c}")
    while n > 0:
        while c > n:
            c //= 2
        out.append(c)
        n -= c
    return out


def _prime(net, ids, vocab: int, chunk_max: int = None):
    """Feed the seed through rnn_time_step in bucketed chunks; returns
    the final chunk's output (its last position is the next-token
    distribution). Stateful streaming makes chunked == one-shot priming
    (pinned by the streaming-vs-full-forward tests)."""
    at, out = 0, None
    for c in _prime_chunks(len(ids), chunk_max):
        out = net.rnn_time_step(
            _one_hot(np.asarray(ids[at:at + c])[None, :], vocab))
        at += c
    return out


def _width_bucket(w: int) -> int:
    """Round up to the next power of two — jit shapes are per-bucket,
    not per-value (beam widths for the decode step; prompt lengths for
    the padded prime)."""
    b = 1
    while b < w:
        b *= 2
    return b


def _stream_layers(net):
    """Every layer of `net` that may carry streaming state: the layer
    list of a MultiLayerNetwork, or the vertex-wrapped layers of a
    ComputationGraph."""
    for l in getattr(net, "layers", None) or []:
        yield l
    vertices = getattr(getattr(net, "conf", None), "vertices", None) or {}
    for v in vertices.values():
        l = getattr(v, "layer", None)
        if l is not None:
            yield l


def _prime_bucket_cap(net):
    """Largest safe padded-prime bucket: the smallest streaming capacity
    over the net's layers, counting a windowed (rolling-cache) layer's
    cache_length too — its FRESH priming chunk must fit the cache even
    though its stream is otherwise unbounded. None = uncapped (no
    capacity-bearing layers)."""
    cap = None
    for l in _stream_layers(net):
        if not getattr(l, "supports_streaming", False):
            continue
        for a in ("max_length", "cache_length"):
            v = getattr(l, a, 0)
            if v:
                cap = v if cap is None else min(cap, v)
    return cap


def _prime_padded(net, ids, vocab: int, chunk_max: int = None):
    """Single-dispatch priming: LEFT-pad the prompt to its power-of-two
    bucket and feed ONE rnn_time_step(pad_left=...) with packed pad
    accounting — pads never enter the streaming caches nor consume
    positions, so results are identical to chunked priming while every
    prompt length shares at most log2(max bucket) jit shapes and exactly
    one dispatch. The bucket is capped at the net's smallest streaming
    capacity (padding past it would trip static capacity checks); a
    prompt longer than that capacity — legal for rolling-window streams,
    whose length is unbounded — falls back to chunked priming, which has
    no minimum chunk shape."""
    L = len(ids)
    P = _width_bucket(L)
    cap = _prime_bucket_cap(net)
    if cap is not None and P > cap:
        if cap < L:            # no padded bucket can hold this prompt
            return _prime(net, ids, vocab, chunk_max)
        P = cap                # pad exactly to capacity: still one shape
    pad = P - L
    x = _one_hot(np.asarray([0] * pad + list(ids))[None, :], vocab)
    x[:, :, :pad] = 0.0       # pads carry no token (masked anyway)
    return net.rnn_time_step(x, pad_left=pad)


def prime_prompt(net, ids, vocab_size: int, padded: bool = False,
                 chunk_max: Optional[int] = None) -> np.ndarray:
    """Prefill: feed the whole prompt through the carried streaming
    state and return the next-token distribution [V]. `padded=True`
    primes in ONE left-padded bucketed dispatch (_prime_padded);
    otherwise chunked priming (_prime) — exactness is identical, pinned
    by the padded-prime tests. Does NOT clear previous state: the
    caller owns the stream lifecycle (sample_stream clears first; the
    serving engine primes into a fresh state it then joins to its slot
    arena)."""
    out = (_prime_padded(net, ids, vocab_size, chunk_max) if padded
           else _prime(net, ids, vocab_size, chunk_max))
    return _probs(out)[0, :, -1]


def step_tokens(net, tokens, vocab_size: int,
                donate_state: bool = False) -> np.ndarray:
    """One incremental decode step for a batch of rows: feed one token
    per row in a single dispatch, return the next-token distributions
    [B, V]. The per-step unit shared by sample_stream (B=1),
    sample_stream_batch, and the serving engine's slot arena (B=S,
    canonical shape, zero retraces after the first step).

    ``donate_state=True`` is the paged-state protocol: the serving
    engine's direct-paged decode installs the KV page pools in
    ``net.state`` and donates them into the dispatch, so the one-token
    append updates the pool IN PLACE (TPU/GPU; a no-op on CPU). The
    caller must treat the pre-call state as consumed — the state the
    net carries after the call is the only live copy."""
    out = net.rnn_time_step(
        _one_hot(np.asarray(tokens, np.int64)[:, None], vocab_size),
        donate_state=donate_state)
    return _probs(out)[:, :, -1]


def verify_tokens(net, chunks, vocab_size: int,
                  donate_state: bool = False) -> np.ndarray:
    """One widened verify forward for a batch of token chunks: feed
    `chunks` [B, W] (W = 1 + gamma for engine speculation) in a single
    dispatch and return ALL per-position next-token distributions
    [B, V, W]. The speculative counterpart of step_tokens — position j's
    row is the distribution AFTER consuming chunk[:, :j+1]; causality
    makes trailing dummy tokens invisible to earlier positions, so a
    fixed-width chunk serves rows with fewer real proposals (the
    uniform-chunk trick of speculative_sample_batch). `donate_state`
    follows step_tokens' paged-state protocol — the widened chunk runs
    the same paged append/attend path at width W."""
    out = net.rnn_time_step(
        _one_hot(np.asarray(chunks, np.int64), vocab_size),
        donate_state=donate_state)
    return _probs(out)


def accept_proposals(proposals, p_dists, q_dists, p_bonus, rng
                     ) -> Tuple[int, int]:
    """The Leviathan et al. 2023 rejection walk, extracted as the ONE
    acceptance rule shared by speculative_sample,
    speculative_sample_batch, and the serving engine's in-engine
    speculation: accept proposal i with prob min(1, p_i[d]/q_i[d]); on
    the first rejection draw the replacement from the clipped residual
    max(p_i - q_i, 0) (falling back to p_i when q subsumes p); with
    every proposal accepted draw the bonus token from `p_bonus` (the
    target's distribution one past the proposals). Returns
    ``(accepted, next_token)`` — the committed tokens are
    ``proposals[:accepted] + [next_token]`` and the target's sampling
    distribution is exactly preserved.

    A ``q_dists`` entry of None means the proposer was DETERMINISTIC —
    a one-hot draft at the proposal under the rejection rule — handled
    without materializing the [V] one-hot: q_i[d] == 1, and the
    rejection residual is p_i with entry d zeroed. rng consumption
    order (one uniform per walked proposal, then exactly one choice) is
    part of the contract: per-row engine speculation must consume each
    request's rng identically to a per-prompt run."""
    for i, d in enumerate(proposals):
        p_i, q_i = p_dists[i], q_dists[i]
        qd = 1.0 if q_i is None else float(q_i[d])
        if rng.random() < min(1.0, float(p_i[d]) / max(qd, 1e-12)):
            continue
        if q_i is None:
            resid = np.array(p_i)
            resid[d] = 0.0
        else:
            resid = np.maximum(p_i - q_i, 0.0)
        total = resid.sum()
        if total <= 0:            # p subsumed by q: fall back to p_i
            resid, total = p_i, p_i.sum()
        return i, int(rng.choice(len(resid), p=resid / total))
    return len(proposals), int(rng.choice(len(p_bonus), p=p_bonus))


def stop_reason(token: int, n_ids: int, want: int,
                stop_set) -> Optional[str]:
    """Why generation ends after appending `token` as the n_ids-th id
    (None = keep going). EOS wins over length when both hit — the stop
    token is kept as the final id either way. The single copy of the
    retirement rule shared by sample_stream and the serving engine."""
    if token in stop_set:
        return "stop"
    if n_ids >= want:
        return "length"
    return None


def sample_stream(net, seed_ids, steps: int, vocab_size: int,
                  temperature: float = 1.0,
                  rng: Optional[np.random.Generator] = None,
                  max_length: Optional[int] = None,
                  prime_chunk_max: Optional[int] = None,
                  prime_padded: bool = False,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None,
                  stop_tokens=()) -> List[int]:
    """Temperature sampling with KV-cache / stored-state incremental
    decoding: prime once with the seed, then one single-position forward
    per generated token (the reference's rnnTimeStep generation loop;
    identical distribution to a padded full forward — tested).
    `prime_chunk_max` overrides the process default (set_prime_chunk_max)
    for this call only; `prime_padded=True` instead primes the whole
    prompt in ONE left-padded dispatch (see _prime_padded). `top_k` /
    `top_p` filter each draw (see `draw`; top_k=1 is greedy).
    Generation ends early when a `stop_tokens` member is drawn (the stop
    token is kept as the final id — EOS semantics)."""
    _check_seed(seed_ids, steps, max_length)
    rng = rng or np.random.default_rng(0)
    stop_tokens = set(stop_tokens)
    ids = list(seed_ids)
    want = len(ids) + steps
    if max_length is not None:
        want = min(want, max_length)
    net.rnn_clear_previous_state()
    p = prime_prompt(net, ids, vocab_size, padded=prime_padded,
                     chunk_max=prime_chunk_max)
    for i in range(steps):
        if max_length is not None and len(ids) >= max_length:
            break
        nxt = draw(p, temperature, rng, top_k=top_k, top_p=top_p)
        ids.append(nxt)
        if stop_reason(nxt, len(ids), want, stop_tokens):
            break
        if i + 1 < steps:
            p = step_tokens(net, [nxt], vocab_size)[0]
    return ids


def prompt_lookup_proposer(ngram: int = 3):
    """Draft-FREE speculation proposer (prompt-lookup decoding): propose
    the continuation of the most recent earlier occurrence of the
    context's trailing n-gram. Costs zero device dispatches, so it wins
    even on dispatch-latency-bound serving paths whenever generation
    revisits earlier text (extraction, quoting, code, repetition);
    elsewhere it degrades gracefully to ~plain decoding. Pass the
    returned callable as speculative_sample's `draft`."""
    if ngram < 1:
        raise ValueError(f"ngram must be >= 1, got {ngram}")

    def propose(ids, gamma):
        if len(ids) <= ngram:
            return []
        tail = list(ids[-ngram:])
        for s in range(len(ids) - ngram - 1, -1, -1):
            if list(ids[s:s + ngram]) == tail:
                return list(ids[s + ngram:s + ngram + gamma])
        return []

    return propose


def sample_stream_batch(net, prompts, steps: int, vocab_size: int,
                        temperature: float = 1.0,
                        rng: Optional[np.random.Generator] = None,
                        max_length: Optional[int] = None,
                        top_k: Optional[int] = None,
                        top_p: Optional[float] = None,
                        stop_tokens=()) -> List[List[int]]:
    """Decode a BATCH of prompts simultaneously: mixed-length prompts
    LEFT-pad to the longest and prime in one masked forward (the carried
    kv_mask keeps pad keys invisible on every later step), then every
    decode step advances ALL rows in one dispatch — B times the serving
    throughput of per-prompt sample_stream for the same dispatch count.
    Shapes are bucketed like the rest of this module: the priming length
    pads to its power-of-two bucket (extra columns are fully masked) and
    the batch pads to a power-of-two row count, so serving reuses warm
    compiled shapes across request mixes.

    Per-row results match per-prompt sample_stream for greedy decoding
    (top_k=1 — test-pinned) for recurrences (masked pad steps pass h/c
    through) and attention with rope or no positions (a contiguous
    left-pad shifts a row's absolute positions uniformly; rope scores
    depend only on relative offsets). Under temperature SAMPLING the
    per-row distributions are the same but the shared rng interleaves
    draws across rows, so sequences differ from a per-prompt run with
    the same seed. Models with LEARNED positional tables need
    equal-length prompts (pads would shift the table lookups) —
    enforced here.

    `temperature`/`top_k`/`top_p` may each be PER-ROW [B] arrays (see
    filter_probs): one batch serves prompts with mixed sampling
    configs. Per-row top_k/top_p entries <= 0 switch that filter off
    for that row.

    The batch shares stream positions: every row consumes the padded
    prompt length plus one position per step, so rows stop early (with
    fewer than `steps` tokens) when the net's smallest streaming
    capacity fills — per-prompt decoding of a SHORT prompt can go
    further. A row also ends when it draws a `stop_tokens` member (kept
    as its final id — EOS semantics); other rows continue. Returns one
    continued token list per prompt."""
    if not prompts:
        return []
    rng = rng or np.random.default_rng(0)
    stop_tokens = set(stop_tokens)
    for p in prompts:
        _check_seed(p, steps, max_length)
    B, V = len(prompts), vocab_size
    for name, v in (("temperature", temperature), ("top_k", top_k),
                    ("top_p", top_p)):
        _row_array(v, B, name)         # validate per-row shapes early
    temp_rows = np.ndim(temperature) > 0
    if temp_rows and (np.asarray(temperature) <= 0).any():
        raise ValueError("per-row temperature entries must be > 0")
    out, T, B, Bb, cap = _batch_prime(net, prompts, V)
    probs = _probs(out)[:, :, -1]                           # [Bb, V]
    ids = [list(p) for p in prompts]
    stopped = [False] * B
    done = (lambda b: stopped[b] or (max_length is not None
                                     and len(ids[b]) >= max_length))
    for i in range(steps):
        tok = np.zeros(Bb, np.int64)
        for b in range(B):
            if done(b):
                continue
            tok[b] = draw(
                probs[b],
                float(np.asarray(temperature)[b]) if temp_rows
                else temperature,
                rng, top_k=per_row_param(top_k, b),
                top_p=per_row_param(top_p, b))
            ids[b].append(int(tok[b]))
            if tok[b] in stop_tokens:
                stopped[b] = True
        if all(done(b) for b in range(B)):
            break
        if i + 1 < steps:
            if cap is not None and T + i + 1 > cap:
                break                  # shared stream positions full
            probs = step_tokens(net, tok, V)
    return ids


def speculative_sample(net, draft, seed_ids, steps: int,
                       vocab_size: int,
                       gamma: int = 4,
                       temperature: float = 1.0,
                       rng: Optional[np.random.Generator] = None,
                       max_length: Optional[int] = None,
                       top_k: Optional[int] = None,
                       top_p: Optional[float] = None,
                       prime_padded: bool = False,
                       prime_chunk_max: Optional[int] = None,
                       stop_tokens=()) -> List[int]:
    """Speculative decoding (Leviathan et al. 2023 rejection scheme):
    `draft` proposes up to `gamma` tokens, the target `net` scores ALL
    of them in ONE forward, and the longest accepted prefix is kept —
    the target's sampling DISTRIBUTION is exactly preserved (with
    top_k=1 the output is bit-identical to greedy sample_stream,
    test-pinned), while the target runs once per ~(accepted+1) tokens
    instead of once per token.

    `draft` is either a same-vocab streaming net (model-based drafting —
    wins when the target's forward is much more expensive than the
    draft's, i.e. compute-bound serving) or a host callable
    `(ids, gamma) -> proposals` such as prompt_lookup_proposer()
    (draft-free — zero extra dispatches, wins whenever proposals are
    often right, even on dispatch-latency-bound paths; a deterministic
    proposer is a one-hot draft distribution under the rejection rule).

    Rollback of rejected positions uses rewind_stream_state, so the
    nets involved must carry only position-indexed streaming state
    (attention KV caches + positional offsets — LSTMs are rejected
    there). Acceptance compares the temperature/top_k/top_p-FILTERED
    distributions (standard practice, so the filters stay meaningful).
    Generation ends at the first `stop_tokens` member among the
    committed tokens (kept as the final id — identical cut to plain
    decoding with the same stops)."""
    from deeplearning4j_tpu.nn.conf.layers import (check_rewindable,
                                                   rewind_stream_state)
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    _check_seed(seed_ids, steps, max_length)
    rng = rng or np.random.default_rng(0)
    V = vocab_size
    ids = list(seed_ids)
    draft_is_fn = not hasattr(draft, "rnn_time_step")
    if draft_is_fn and not callable(draft):
        raise TypeError("draft must be a streaming net or a callable "
                        "(ids, gamma) -> proposals")
    # fail fast: a non-rewindable net would otherwise only error at the
    # first data-dependent rejection, mid-generation
    check_rewindable(net, gamma)
    if not draft_is_fn:
        check_rewindable(draft, gamma)
    net.rnn_clear_previous_state()
    prime = _prime_padded if prime_padded else _prime
    out_t = prime(net, ids, V, prime_chunk_max)
    # p_next: target's (filtered) distribution for the NEXT token given
    # everything its cache has consumed so far
    p_next = filter_probs(_probs(out_t)[0, :, -1], temperature,
                          top_k, top_p)
    if not draft_is_fn:
        draft.rnn_clear_previous_state()
        out_d = prime(draft, ids, V, prime_chunk_max)
        q_next = filter_probs(_probs(out_d)[0, :, -1], temperature,
                              top_k, top_p)
    want = len(seed_ids) + steps
    if max_length is not None:
        want = min(want, max_length)
    stop_set = set(stop_tokens)

    def _stop_cut(start):
        """Index just past the first stop token at/after `start`, or -1."""
        for j in range(start, len(ids)):
            if ids[j] in stop_set:
                return j + 1
        return -1

    # the committed-but-not-yet-consumed LAST token of `ids` rides at
    # the FRONT of the next verify chunk instead of costing its own
    # dispatch: every round is exactly ONE target forward, so even at
    # zero acceptance the dispatch count never exceeds plain decoding's
    pending = None
    while len(ids) < want:
        g = min(gamma, want - len(ids))
        # --- draft proposes up to g tokens + its distributions --------
        if draft_is_fn:
            proposals = [int(t) for t in draft(ids, g)][:g]
            g = len(proposals)
            # deterministic proposer == one-hot draft distribution
            # (None entries — accept_proposals' materialization-free path)
            q_dists = [None] * g
        else:
            proposals, q_dists = [], []
            if pending is not None:
                out_d = draft.rnn_time_step(
                    _one_hot(np.asarray([[pending]]), V))
                q_next = filter_probs(_probs(out_d)[0, :, -1],
                                      temperature, top_k, top_p)
            q = q_next
            for _ in range(g):
                d = int(rng.choice(V, p=q))
                proposals.append(d)
                q_dists.append(q)
                out_d = draft.rnn_time_step(
                    _one_hot(np.asarray([[d]]), V))
                q = filter_probs(_probs(out_d)[0, :, -1], temperature,
                                 top_k, top_p)
        # --- target scores pending + all proposals in ONE forward -----
        chunk = ([] if pending is None else [pending]) + proposals
        if not chunk:                 # g == 0 and nothing pending
            nxt = int(rng.choice(V, p=p_next))
            ids.append(nxt)
            if stop_set and nxt in stop_set:
                return ids
            pending = nxt
            # p_next for the round after this comes from the verify
            # forward that consumes `pending` next round
            p_next = None
            continue
        out_t = net.rnn_time_step(
            _one_hot(np.asarray(chunk)[None, :], V))
        tp = _probs(out_t)[0]                      # [V, len(chunk)]
        off = len(chunk) - g                       # 1 when pending rode
        if pending is not None:
            # pending is already IN ids (committed last round); the
            # forward above just consumed it into the caches
            pending = None
            p_next = filter_probs(tp[:, off - 1], temperature,
                                  top_k, top_p)
        if g == 0:                    # plain step: sample from p_next
            nxt = int(rng.choice(V, p=p_next))
            ids.append(nxt)
            if stop_set and nxt in stop_set:
                return ids
            pending = nxt
            p_next = None
            continue
        p_dists = [p_next] + [
            filter_probs(tp[:, off + i], temperature, top_k, top_p)
            for i in range(g - 1)]
        p_bonus = filter_probs(tp[:, off + g - 1], temperature,
                               top_k, top_p)
        # --- standard acceptance walk (the shared rejection rule) -----
        accepted, nxt = accept_proposals(proposals, p_dists, q_dists,
                                         p_bonus, rng)
        base = len(ids)
        ids.extend(proposals[:accepted])
        ids.append(nxt)
        if stop_set:
            cut = _stop_cut(base)
            if cut >= 0:
                # cap at `want`: plain decoding would have stopped at
                # steps before ever reaching a later stop token
                return ids[:min(cut, want)]
        pending = nxt
        p_next = None
        # --- rollback rejected positions (pending rides the next
        # round's verify forward instead of a commit dispatch) ---------
        rewind_stream_state(net, g - accepted)
        if not draft_is_fn:
            rewind_stream_state(draft, g - accepted)
    return ids[:want]


def _batch_prime(net, prompts, vocab_size: int):
    """Shared masked left-padded batch prime (see sample_stream_batch for
    the exactness conditions): returns (out, T, B, Bb, cap)."""
    lens = [len(p) for p in prompts]
    from deeplearning4j_tpu.nn.conf.layers import PositionalEmbeddingLayer
    has_learned_pos = any(isinstance(l, PositionalEmbeddingLayer)
                          for l in _stream_layers(net))
    if len(set(lens)) > 1 and has_learned_pos:
        raise ValueError(
            "mixed-length batched decoding is not exact for "
            "learned positional tables (left-pads shift the "
            "lookups) — pad prompts to equal length, use a rope "
            "model, or decode per prompt")
    cap = _prime_bucket_cap(net)
    if has_learned_pos:
        T = max(lens)      # ANY left pad would shift the table lookups
    else:
        T = _width_bucket(max(lens))             # bucketed prime length
        if cap is not None and T > cap >= max(lens):
            T = cap
    B, V = len(prompts), vocab_size
    Bb = _width_bucket(B)                        # bucketed batch rows
    x = np.zeros((Bb, V, T), np.float32)
    mask = np.zeros((Bb, T), np.float32)
    for b, p in enumerate(prompts):
        pad = T - len(p)
        x[b, list(p), pad + np.arange(len(p))] = 1.0
        mask[b, pad:] = 1.0
    net.rnn_clear_previous_state()
    if hasattr(net, "layers"):                   # MultiLayerNetwork
        out = net.rnn_time_step(x, mask=mask)
    else:                                        # ComputationGraph
        out = net.rnn_time_step(
            x, masks={net.conf.network_inputs[0]: mask})
    return out, T, B, Bb, cap


def _check_per_row_speculable(net, n: int) -> None:
    """Entry validation for batched speculation: everything per-row
    rewind needs, checked BEFORE any state is mutated (the fail-fast
    spirit of speculative_sample's check_rewindable call). `n` is the
    worst-case per-round rewind — the full uniform chunk, gamma + 1."""
    from deeplearning4j_tpu.nn.conf.layers import (
        PositionalEmbeddingLayer, check_rewindable,
    )
    check_rewindable(net, n)
    for l in _stream_layers(net):
        if isinstance(l, PositionalEmbeddingLayer):
            raise ValueError(
                "batched speculative decoding is attention-only: learned "
                "positional tables carry a shared pos_offset that cannot "
                "rewind per row (use a rope or position-free model)")
        # windowed (rolling-cache) attention is fine: per-row positions
        # write each row's own modular slots and kv_abs promotes to
        # [N, L] (SelfAttentionLayer._stream_attend_rolling vec branch);
        # check_rewindable above already enforced
        # cache_length >= window + gamma + 1


def speculative_sample_batch(net, draft, prompts, steps: int,
                             vocab_size: int,
                             gamma: int = 4,
                             temperature: float = 1.0,
                             rngs=None,
                             max_length: Optional[int] = None,
                             top_k: Optional[int] = None,
                             top_p: Optional[float] = None,
                             stop_tokens=()) -> List[List[int]]:
    """Batched speculative decoding: every prompt speculates
    simultaneously with PER-ROW acceptance — each round is one batched
    draft phase plus ONE batched target verify forward, and each row
    rewinds only its own rejected positions (rewind_stream_state with an
    array promotes the attention kv_pos to a per-row vector; subsequent
    cache writes land at each row's own slots). Composes the two serving
    multipliers: speculation's (accepted+1):1 dispatch ratio × batching's
    B rows per dispatch.

    `draft` is a host proposer callable `(ids, gamma) -> proposals`
    (e.g. prompt_lookup_proposer(); applied per row, zero dispatches) or
    a same-vocab streaming net (model drafting: the draft streams the
    same batch, g dispatches per round). `rngs` is one np Generator per
    prompt (default: fresh per-row default_rng(row)); each row consumes
    its own stream in the same order as a per-prompt speculative_sample
    run, so with top_k=1 (greedy — every accept/replace/bonus is
    deterministic) each row's output EQUALS its per-prompt
    speculative_sample output for rope / position-free models
    (test-pinned, both draft kinds). Under temperature sampling rows
    still draw from their own rngs, but float-level batch-vs-single
    differences can flip individual acceptance draws.

    Like sample_stream_batch, rows share stream capacity from the padded
    prompt length; per-row rewind is attention-only (LSTMs cannot
    rewind; learned positional tables are rejected by the layer checks).
    Windowed (rolling-cache) attention IS supported: each row writes its
    own modular slots and the slot->absolute-position map promotes to
    per-row on the first rewind (cache_length >= window + gamma + 1
    enforced at entry)."""
    from deeplearning4j_tpu.nn.conf.layers import rewind_stream_state
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if not prompts:
        return []
    for p in prompts:
        _check_seed(p, steps, max_length)
    B, V = len(prompts), vocab_size
    if rngs is None:
        rngs = [np.random.default_rng(b) for b in range(B)]
    if len(rngs) != B:
        raise ValueError(f"need one rng per prompt ({len(rngs)} != {B})")
    draft_is_fn = not hasattr(draft, "rnn_time_step")
    if draft_is_fn and not callable(draft):
        raise TypeError("draft must be a streaming net or a callable "
                        "(ids, gamma) -> proposals")
    # fail fast at entry: rounds rewind up to the FULL uniform chunk
    # (gamma + 1 — a frozen/zero-acceptance row keeps nothing), and the
    # per-row machinery is attention-only
    _check_per_row_speculable(net, gamma + 1)
    if not draft_is_fn:
        _check_per_row_speculable(draft, gamma + 1)

    out_t, T, B, Bb, cap = _batch_prime(net, prompts, V)
    if not draft_is_fn:
        out_d, *_ = _batch_prime(draft, prompts, V)
        q_next = [filter_probs(_probs(out_d)[b, :, -1], temperature,
                               top_k, top_p) for b in range(B)]
    p_next: List[Optional[np.ndarray]] = [
        filter_probs(_probs(out_t)[b, :, -1], temperature, top_k, top_p)
        for b in range(B)]

    ids = [list(p) for p in prompts]
    want = [len(p) + steps for p in prompts]
    if max_length is not None:
        want = [min(w, max_length) for w in want]
    stop_set = set(stop_tokens)
    done = [False] * B
    # positions consumed per row (for the shared-capacity guard): all
    # rows consumed T at prime; per-row rewinds subtract independently
    row_pos = [T] * B
    pending: List[Optional[int]] = [None] * B

    def _finish(b, cut=None):
        done[b] = True
        if cut is not None:
            ids[b] = ids[b][:cut]

    first_round = True
    while not all(done):
        g = gamma
        # --- draft proposes per row -----------------------------------
        # row b proposes at most min(g, room_b) tokens; the verify chunk
        # stays UNIFORM at 1+g slots (short rows pad with 0s, which sit
        # after their real tokens — causal attention means the dummies
        # never influence earlier positions — and are rewound)
        proposals: List[List[int]] = [[] for _ in range(B)]
        q_dists: List[List[np.ndarray]] = [[] for _ in range(B)]
        room = [max(0, want[b] - len(ids[b])) for b in range(B)]
        draft_writes = 0                    # positions the draft consumed
        if draft_is_fn:
            for b in range(B):
                if done[b]:
                    continue
                props = [int(x) for x in draft(ids[b], min(g, room[b]))]
                proposals[b] = props[:min(g, room[b])]
                q_dists[b] = [None] * len(proposals[b])
        else:
            # rounds >= 2: one dispatch consumes every row's pending
            # token into the draft cache (round 1 has no pendings — the
            # prime already produced q_next)
            if not first_round:
                toks = np.zeros(Bb, np.int64)
                for b in range(B):
                    if not done[b] and pending[b] is not None:
                        toks[b] = pending[b]
                out_d = draft.rnn_time_step(_one_hot(toks[:, None], V))
                draft_writes += 1
                for b in range(B):
                    if not done[b]:
                        q_next[b] = filter_probs(_probs(out_d)[b, :, -1],
                                                 temperature, top_k,
                                                 top_p)
            qs = list(q_next)
            # g batched sampling dispatches advance every row together
            for _ in range(g):
                toks = np.zeros(Bb, np.int64)
                for b in range(B):
                    if done[b] or len(proposals[b]) >= min(g, room[b]):
                        continue
                    d = int(rngs[b].choice(V, p=qs[b]))
                    proposals[b].append(d)
                    q_dists[b].append(qs[b])
                    toks[b] = d
                out_d = draft.rnn_time_step(_one_hot(toks[:, None], V))
                draft_writes += 1
                for b in range(B):
                    if not done[b]:
                        qs[b] = filter_probs(_probs(out_d)[b, :, -1],
                                             temperature, top_k, top_p)
        first_round = False
        # --- ONE batched target verify forward ------------------------
        chunk_len = 1 + g
        chunk = np.zeros((Bb, chunk_len), np.int64)
        offs = np.zeros(B, np.int32)        # 1 when pending rides slot 0
        for b in range(B):
            if done[b]:
                continue
            row = ([] if pending[b] is None else [pending[b]]) + \
                proposals[b]
            offs[b] = 0 if pending[b] is None else 1
            chunk[b, :len(row)] = row
        if cap is not None and max(row_pos) + chunk_len > cap:
            # shared stream capacity exhausted: stop everyone honestly
            if not draft_is_fn and draft_writes:
                rewind_stream_state(
                    draft, np.full(Bb, draft_writes, np.int32))
            break
        out_t = net.rnn_time_step(_one_hot(chunk, V))
        tp_all = _probs(out_t)               # [Bb, V, chunk_len]
        rew = np.zeros(B, np.int32)          # target rollback per row
        draft_keep = np.zeros(B, np.int32)   # draft slots to keep per row
        for b in range(B):
            if done[b]:
                rew[b] = chunk_len           # frozen rows keep no writes
                continue
            row_pos[b] += chunk_len
            tp = tp_all[b]
            g_b = len(proposals[b])
            off = int(offs[b])
            if off:                          # pending consumed into cache
                pending[b] = None
                p_next[b] = filter_probs(tp[:, off - 1], temperature,
                                         top_k, top_p)
            if g_b == 0:                     # plain step from p_next
                nxt = int(rngs[b].choice(V, p=p_next[b]))
                ids[b].append(nxt)
                rew[b] = chunk_len - off     # drop all proposal slots
                if (stop_set and nxt in stop_set) or \
                        len(ids[b]) >= want[b]:
                    _finish(b)
                else:
                    pending[b] = nxt
                    p_next[b] = None
                continue
            p_dists = [p_next[b]] + [
                filter_probs(tp[:, off + i], temperature, top_k, top_p)
                for i in range(g_b - 1)]
            p_bonus = filter_probs(tp[:, off + g_b - 1], temperature,
                                   top_k, top_p)
            accepted, nxt = accept_proposals(proposals[b], p_dists,
                                             q_dists[b], p_bonus, rngs[b])
            base = len(ids[b])
            ids[b].extend(proposals[b][:accepted])
            ids[b].append(nxt)
            rew[b] = chunk_len - off - accepted
            draft_keep[b] = accepted
            if stop_set:
                cut = next((j + 1 for j in range(base, len(ids[b]))
                            if ids[b][j] in stop_set), -1)
                if cut >= 0:
                    _finish(b, cut=min(cut, want[b]))
            if not done[b] and len(ids[b]) >= want[b]:
                ids[b] = ids[b][:want[b]]
                _finish(b)
            if not done[b]:
                pending[b] = ids[b][-1]
                p_next[b] = None
        # --- per-row rollback (one dispatch for all counters) ---------
        amounts = np.zeros(Bb, np.int32)
        amounts[:B] = rew
        amounts[B:] = chunk_len              # bucket-pad rows keep nothing
        for b in range(B):
            row_pos[b] -= int(rew[b])
        rewind_stream_state(net, amounts)
        if not draft_is_fn:
            d_am = np.full(Bb, draft_writes, np.int32)
            for b in range(B):
                if not done[b] or draft_keep[b]:
                    d_am[b] = draft_writes - int(draft_keep[b]) - \
                        int(offs[b])
            rewind_stream_state(draft, np.maximum(d_am, 0))
    return ids


def beam_search_batch(net, prompts, steps: int, vocab_size: int,
                      beam_width: int = 4,
                      max_length: Optional[int] = None,
                      stop_tokens=()
                      ) -> List[Tuple[List[int], float]]:
    """Beam search over a BATCH of prompts: the [prompts x beams] grid
    flattens onto the batch axis, so every decode step advances all
    prompts' beams in ONE dispatch (per-prompt beam_search costs a
    dispatch per prompt per step). Each prompt's search is independent —
    per-prompt results equal beam_search (test-pinned for rope /
    position-free models; the exactness conditions are
    sample_stream_batch's, since priming left-pads mixed-length prompts
    to a shared bucket). Returns [(best_sequence, log_prob)] per prompt,
    EOS semantics matching beam_search's `stop_tokens`."""
    if not prompts:
        return []
    V = vocab_size
    for p in prompts:
        _check_seed(p, steps, max_length)
    stop_tokens = set(stop_tokens)
    W = min(beam_width, V)
    n = len(prompts)
    out, T, _, Bb, cap = _batch_prime(net, prompts, V)
    # expand each prompt's primed state to its own W beam rows (+ pad
    # rows): flattened row layout is [prompt0 x Wb | prompt1 x Wb | ...]
    Wb = _width_bucket(W)
    expand = np.repeat(np.arange(Bb), Wb)      # [Bb*Wb]
    reorder_stream_state(net, expand)
    probs0 = _probs(out)                        # [Bb, V, T]
    out = np.repeat(probs0, Wb, axis=0)         # [Bb*Wb, V, T]

    beams = [[list(p) for _ in range(W)] for p in prompts]
    scores = np.zeros((n, W))
    alive = np.ones((n, W), bool)
    finished: List[List[Tuple[List[int], float]]] = [[] for _ in range(n)]
    searching = np.ones(n, bool)    # prompt-level: still extending
    first = True
    for i in range(steps):
        if max_length is not None and \
                all(len(beams[b][0]) >= max_length for b in range(n)):
            break
        probs = _probs(out)
        all_parents = np.zeros((n, W), np.int64)
        all_tokens = np.zeros((n, W), np.int64)
        for b in range(n):
            if not searching[b]:
                continue
            if max_length is not None and \
                    len(beams[b][0]) >= max_length:
                searching[b] = False
                continue
            logp = np.log(np.clip(
                probs[b * Wb:b * Wb + W, :, -1], 1e-12, None))  # [W,V]
            if first:
                top = np.argsort(logp[0])[::-1][:W]
                parents, tokens = np.zeros(W, np.int64), top
                scores[b] = logp[0][top]
                beams[b] = [beams[b][p] + [int(t)]
                            for p, t in zip(parents, tokens)]
                alive[b], stop_now = _beam_finish(
                    tokens, scores[b], alive[b], beams[b], stop_tokens,
                    finished[b], W)
            else:
                # the shared rule (_beam_update) per prompt — one copy
                # across beam_search / beam_search_batch / speculative
                parents, tokens, scores[b], alive[b], beams[b], \
                    stop_now = _beam_update(
                        logp, scores[b], alive[b], beams[b],
                        stop_tokens, finished[b], W, V)
            all_parents[b], all_tokens[b] = parents, tokens
            if stop_now:
                searching[b] = False
            # max_length reached AFTER this extension: stop eagerly so a
            # fully-capped batch skips the trailing decode dispatch
            if searching[b] and max_length is not None and \
                    len(beams[b][0]) >= max_length:
                searching[b] = False
        first = False
        if not searching.any():
            break
        if i + 1 < steps:
            if cap is not None and T + i + 1 > cap:
                break
            # flattened gather: prompt b's parents live at rows b*Wb+.
            pp = np.arange(Bb * Wb, dtype=np.int64)
            tok = np.zeros(Bb * Wb, np.int64)
            for b in range(n):
                pp[b * Wb:b * Wb + W] = b * Wb + all_parents[b]
                tok[b * Wb:b * Wb + W] = all_tokens[b]
            if not np.array_equal(pp, np.arange(Bb * Wb)):
                reorder_stream_state(net, pp)
            out = net.rnn_time_step(_one_hot(tok[:, None], V))
    results = []
    for b in range(n):
        live = [(beams[b][w], float(scores[b][w])) for w in range(W)
                if alive[b][w] and np.isfinite(scores[b][w])]
        pool = finished[b] if finished[b] else live
        if not pool:
            pool = [(beams[b][w], float(scores[b][w])) for w in range(W)]
        results.append(max(pool, key=lambda bs: bs[1]))
    return results


def beam_search(net, seed_ids, steps: int, vocab_size: int,
                beam_width: int = 4,
                max_length: Optional[int] = None,
                prime_chunk_max: Optional[int] = None,
                prime_padded: bool = False,
                stop_tokens=()
                ) -> Tuple[List[int], float]:
    """Highest-log-prob continuation of `seed_ids` by beam search.

    `net` needs rnn_time_step / rnn_clear_previous_state (MultiLayerNetwork
    or ComputationGraph, single one-hot [N,V,T] input). `max_length`
    bounds seed+generation (None = unbounded; required finite for models
    with positional tables or non-rolling caches). `prime_chunk_max`
    overrides the process default (set_prime_chunk_max) per call;
    `prime_padded=True` primes the whole prompt in ONE left-padded
    dispatch (see _prime_padded).

    `stop_tokens` enables standard beam EOS semantics: a hypothesis that
    extends with a stop token FINISHES (keeps the stop as its final id,
    stops extending, leaves its beam slot to live candidates); the
    search ends when every slot is finished, when no live hypothesis can
    still beat the best finished one (log-prob totals only decrease as
    hypotheses extend), or when the step budget runs out. The best
    finished hypothesis wins (falling back to the best live one if
    nothing finished)."""
    V = vocab_size
    _check_seed(seed_ids, steps, max_length)
    stop_tokens = set(stop_tokens)
    W = min(beam_width, V)     # top-k can't exceed the vocab
    Wb = _width_bucket(W)      # decode batch: per-bucket jit shape
    net.rnn_clear_previous_state()

    # prime ONCE at batch 1 (bucketed chunks), then broadcast the carried
    # state to the padded beam batch; pad rows never enter scoring (the
    # logp slice below keeps only the first W rows)
    out = (_prime_padded(net, seed_ids, V, prime_chunk_max)
           if prime_padded
           else _prime(net, seed_ids, V, prime_chunk_max))
    reorder_stream_state(net, np.zeros(Wb, np.int64))
    out = np.repeat(_probs(out)[:1], Wb, axis=0)
    beams = [list(seed_ids) for _ in range(W)]
    scores = np.zeros(W)
    alive = np.ones(W, bool)   # slots still extending (EOS finishes one)
    finished = []              # (sequence, score) hypotheses that hit EOS
    first = True
    for i in range(steps):
        if max_length is not None and len(beams[0]) >= max_length:
            break
        logp = np.log(np.clip(_probs(out)[:W, :, -1], 1e-12, None))  # [W,V]
        if first:
            # identical primed beams must diverge: top-W FIRST tokens of
            # beam 0, not W copies of the argmax
            top = np.argsort(logp[0])[::-1][:W]
            parents, tokens, scores = np.zeros(W, np.int64), top, \
                logp[0][top]
            first = False
            beams = [beams[p] + [int(t)] for p, t in zip(parents,
                                                         tokens)]
            alive, stop_now = _beam_finish(tokens, scores, alive, beams,
                                           stop_tokens, finished, W)
        else:
            parents, tokens, scores, alive, beams, stop_now = \
                _beam_update(logp, scores, alive, beams, stop_tokens,
                             finished, W, V)
        if stop_now:
            break
        more = i + 1 < steps and (max_length is None
                                  or len(beams[0]) < max_length)
        if more:
            # pad rows keep their own (discarded) state so the
            # identity-parents fast path still skips the cache gather
            pp = np.arange(Wb, dtype=np.int64)
            pp[:W] = parents
            if not np.array_equal(pp, np.arange(Wb)):
                reorder_stream_state(net, pp)   # inherit caches
            tok = np.zeros(Wb, np.int64)
            tok[:W] = tokens
            out = net.rnn_time_step(_one_hot(tok[:, None], V))
    live = [(beams[w], float(scores[w])) for w in range(W)
            if alive[w] and np.isfinite(scores[w])]
    pool = finished if finished else live
    if not pool:
        pool = [(beams[w], float(scores[w])) for w in range(W)]
    best_seq, best_score = max(pool, key=lambda bs: bs[1])
    return best_seq, best_score


def _beam_finish(tokens, scores, alive, beams, stop_set, finished, W):
    """The finishing/early-stop tail of one beam step (EOS hypotheses
    move to `finished`, their slots die; the search is decided when
    nothing live can beat the best finished). Shared by beam_search's
    both branches and speculative_beam_search so the rule has exactly
    one copy. Returns (alive, stop)."""
    stop = False
    if stop_set:
        alive = np.ones(W, bool)
        for w, t in enumerate(tokens):
            if int(t) in stop_set and np.isfinite(scores[w]):
                finished.append((beams[w], float(scores[w])))
                alive[w] = False
        if not alive.any():
            stop = True
        elif finished:
            best_fin = max(sc for _, sc in finished)
            if scores[alive].max() <= best_fin:
                stop = True
    return alive, stop


def _beam_update(logp, scores, alive, beams, stop_set, finished, W, V):
    """One beam-search scoring update (total/-inf masking, flat top-W,
    then _beam_finish) — the ONLY copy of the rule: beam_search's loop
    body and speculative_beam_search's host-side reconstruction both
    call it, so the speculative replay applies the same rule by
    construction. Returns (parents, tokens, scores, alive, beams, stop).
    Dtype note: `scores` stays the logp dtype (float32 from the net) —
    accumulation dtype is part of the parity contract."""
    total = scores[:, None] + logp
    total[~alive] = -np.inf             # finished slots never extend
    flat = np.argsort(total.ravel())[::-1][:W]
    parents, tokens = np.divmod(flat, V)
    scores = total.ravel()[flat]
    beams = [beams[p] + [int(t)] for p, t in zip(parents, tokens)]
    alive, stop = _beam_finish(tokens, scores, alive, beams, stop_set,
                               finished, W)
    return parents, tokens, scores, alive, beams, stop


def speculative_beam_search(net, draft, seed_ids, steps: int,
                            vocab_size: int,
                            beam_width: int = 4,
                            gamma: int = 4,
                            max_length: Optional[int] = None,
                            prime_chunk_max: Optional[int] = None,
                            stop_tokens=()
                            ) -> Tuple[List[int], float]:
    """Beam search accelerated by speculation — the last edge of the
    serving matrix (beam × speculative). Output EQUALS beam_search's
    (sequence, score) exactly (test-pinned); the target runs once per
    round instead of once per step.

    Structure: `draft` proposes a continuation for EVERY beam — either
    a host proposer callable `(ids, gamma) -> proposals` (e.g.
    prompt_lookup_proposer, zero extra dispatches) or a same-vocab
    streaming net (beam-synchronized greedy model draft: it streams the
    same W-row batch, mirroring every feed/rewind/reorder, and costs g
    draft dispatches per round — wins when the target's forward is much
    more expensive than the draft's); one batched target forward
    scores each beam's pending token plus all its proposals; the
    host-side walk then replays the exact beam-update rule
    (_beam_update) step by step from the verify logits. A drafted step
    is accepted while the true update extends each beam with its own
    proposal (identity parents, drafted tokens, nothing finishing) —
    the collective beam state advances exactly as drafted, so every
    row's cache is already correct. The first divergence applies the
    TRUE update from the same verify logits (no extra dispatch), the
    uniform over-consumed tail rewinds (scalar rewind_stream_state —
    composes with windowed rolling caches), and the corrected tokens
    ride the next round's verify chunk as the per-beam pending front.

    Acceptance is collective — beam reordering anywhere rejects the
    round's remainder — so speculation pays off on peaky/repetitive
    workloads where each beam confidently extends itself (extraction,
    quoting, memorized serving); elsewhere it degrades to plain beam's
    one-dispatch-per-step with identical output. Finished-slot rounds
    (EOS) also degrade gracefully: a dead slot makes identity parents
    impossible, so rounds commit one corrected step each, still never
    exceeding plain beam's dispatch count (+1 worst case).

    ref: the reference's beam decoding lives in its seq2seq examples;
    speculative verification is the Leviathan et al. 2023 scheme with
    the acceptance rule adapted from token-match to beam-state-match.
    """
    from deeplearning4j_tpu.nn.conf.layers import (check_rewindable,
                                                   rewind_stream_state)
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if not hasattr(draft, "rnn_time_step") and not callable(draft):
        raise TypeError(
            "draft must be a streaming net (beam-synchronized greedy "
            "model draft) or a host proposer callable "
            "(ids, gamma) -> proposals")
    V = vocab_size
    _check_seed(seed_ids, steps, max_length)
    check_rewindable(net, gamma)
    draft_is_fn = not hasattr(draft, "rnn_time_step")
    if not draft_is_fn:
        check_rewindable(draft, gamma)
    stop_set = set(stop_tokens)
    W = min(beam_width, V)
    Wb = _width_bucket(W)
    net.rnn_clear_previous_state()

    out = _prime(net, seed_ids, V, prime_chunk_max)
    reorder_stream_state(net, np.zeros(Wb, np.int64))
    logp0 = np.log(np.clip(_probs(out)[0, :, -1], 1e-12, None))
    if not draft_is_fn:
        # the draft streams the SAME beam batch, mirroring every feed,
        # rewind and reorder, so its caches always hold the committed
        # beam prefixes (the beam-synchronized draft stream)
        draft.rnn_clear_previous_state()
        _prime(draft, seed_ids, V, prime_chunk_max)
        reorder_stream_state(draft, np.zeros(Wb, np.int64))

    # first expansion: top-W first tokens of beam 0 (identical to
    # beam_search's `first` branch, incl. _beam_finish and the float32
    # score dtype — accumulation dtype is part of the parity contract);
    # the chosen tokens become the per-beam pending front of round 1
    top = np.argsort(logp0)[::-1][:W]
    beams = [list(seed_ids) + [int(t)] for t in top]
    scores = logp0[top]
    alive = np.ones(W, bool)
    finished = []
    pending = top.astype(np.int64)      # [W] committed, not yet consumed
    committed = 1
    want = steps
    if max_length is not None:
        want = min(want, max_length - len(seed_ids))
    alive, stop_now = _beam_finish(top, scores, alive, beams, stop_set,
                                   finished, W)
    decided = committed >= want or stop_now

    while not decided:
        # draft per live beam; collective acceptance needs a common
        # depth, so g is the shortest proposal list (0 => pure
        # correction round, one dispatch per token — plain beam's rate)
        g = min(gamma, want - committed - 1)
        proposals = None
        if g > 0 and alive.all():
            if draft_is_fn:
                plists = [[int(t) for t in draft(beams[w], g)][:g]
                          for w in range(W)]
                g = min(len(p) for p in plists)
                if g > 0:
                    proposals = np.asarray([p[:g] for p in plists],
                                           np.int64)      # [W, g]
            else:
                # greedy model draft: feed pending, then each argmax —
                # the draft consumes 1+g tokens exactly like the target
                # and rewinds/reorders with it below
                tok = np.zeros(Wb, np.int64)
                tok[:W] = pending
                out_d = draft.rnn_time_step(_one_hot(tok[:, None], V))
                props = []
                for _ in range(g):
                    nxt = _probs(out_d)[:W, :, -1].argmax(axis=1)
                    props.append(nxt.astype(np.int64))
                    tok = np.zeros(Wb, np.int64)
                    tok[:W] = nxt
                    out_d = draft.rnn_time_step(
                        _one_hot(tok[:, None], V))
                proposals = np.stack(props, axis=1)       # [W, g]
        if proposals is None:
            g = 0
            if not draft_is_fn:
                # correction-only round: the draft still consumes the
                # pending front to stay position-synchronized
                tok = np.zeros(Wb, np.int64)
                tok[:W] = pending
                draft.rnn_time_step(_one_hot(tok[:, None], V))

        chunk = np.zeros((Wb, 1 + g), np.int64)
        chunk[:W, 0] = pending
        if g:
            chunk[:W, 1:] = proposals
        out = net.rnn_time_step(_one_hot(chunk, V))
        tp = _probs(out)                                   # [Wb, V, 1+g]

        accepted = 0
        stop_now = False
        parents = tokens = None
        # invariant: committed + g + 1 <= want (g was clamped to
        # want - committed - 1 and only shrinks), so every walk step
        # below is within the budget
        for j in range(g + 1):
            logp = np.log(np.clip(tp[:W, :, j], 1e-12, None))
            parents, tokens, scores, alive, beams, stop_now = \
                _beam_update(logp, scores, alive, beams, stop_set,
                             finished, W, V)
            committed += 1
            if stop_now:
                break
            if (j < g
                    and np.array_equal(parents, np.arange(W))
                    and np.array_equal(tokens, proposals[:, j])
                    and alive.all()):
                accepted += 1
                parents = tokens = None   # state advanced as drafted
                continue
            break                         # divergence or bonus applied

        # drop the over-consumed drafted tail (uniform across rows: the
        # accepted prefix advanced every cache identically)
        over = g - accepted
        if over:
            rewind_stream_state(net, over)
            if not draft_is_fn:
                rewind_stream_state(draft, over)
        if committed >= want or stop_now:
            break
        # the walk always ends with a true update (the j == g bonus
        # step can't take the accept branch), so parents/tokens are set:
        # align caches to the new beam assignment; tokens become pending
        pp = np.arange(Wb, dtype=np.int64)
        pp[:W] = parents
        if not np.array_equal(pp, np.arange(Wb)):
            reorder_stream_state(net, pp)
            if not draft_is_fn:
                reorder_stream_state(draft, pp)
        pending = np.zeros(W, np.int64)
        pending[:] = tokens

    live = [(beams[w], float(scores[w])) for w in range(W)
            if alive[w] and np.isfinite(scores[w])]
    pool = finished if finished else live
    if not pool:
        pool = [(beams[w], float(scores[w])) for w in range(W)]
    best_seq, best_score = max(pool, key=lambda bs: bs[1])
    return best_seq, best_score
