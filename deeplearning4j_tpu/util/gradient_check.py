"""Numeric-vs-analytic gradient checking.

TPU-native equivalent of deeplearning4j-nn/.../gradientcheck/
GradientCheckUtil.java:57-454 (checkGradients MLN :112, CG :281): central
finite differences on every parameter vs the analytic gradient, with a
max-relative-error threshold. The reference calls this "the correctness
backbone" of its test suite (SURVEY §4); here the analytic side is jax.grad,
so this validates layer math + loss wiring end to end.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.util.jax_compat import enable_x64

log = logging.getLogger(__name__)

DEFAULT_EPS = 1e-5
DEFAULT_MAX_REL_ERROR = 1e-3
DEFAULT_MIN_ABS_ERROR = 1e-8


def check_gradients_fn(loss_fn, params, eps: float = DEFAULT_EPS,
                       max_rel_error: float = DEFAULT_MAX_REL_ERROR,
                       min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
                       max_per_param: int = 64, seed: int = 0,
                       print_failures: bool = True) -> bool:
    """Check d loss_fn / d params via central differences (float64 on CPU).

    loss_fn: params_pytree -> scalar. Checks up to `max_per_param` randomly
    chosen elements per parameter array (the reference checks every element;
    sampling keeps large nets tractable — pass max_per_param=0 for all).

    Runs under a local enable_x64 scope: central differences with eps=1e-5
    are meaningless in float32 (the reference runs on float64 ND4J arrays,
    GradientCheckUtil.java:112 requires DataBuffer.Type.DOUBLE).
    """
    with enable_x64(True):
        return _check_gradients_fn_x64(loss_fn, params, eps, max_rel_error,
                                       min_abs_error, max_per_param, seed,
                                       print_failures)


def _check_gradients_fn_x64(loss_fn, params, eps, max_rel_error,
                            min_abs_error, max_per_param, seed,
                            print_failures) -> bool:
    params = jax.tree_util.tree_map(lambda a: jnp.asarray(a, jnp.float64), params)
    analytic = jax.grad(loss_fn)(params)
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(analytic)
    # one compile, thousands of perturbed evaluations: the eager per-eval
    # dispatch dominates check time otherwise (2 * max_per_param * n_params
    # full forward passes)
    jitted_loss = jax.jit(lambda flat: loss_fn(
        jax.tree_util.tree_unflatten(treedef, flat)))
    rng = np.random.default_rng(seed)
    ok = True
    for pi, (p, g) in enumerate(zip(flat_p, flat_g)):
        p_np = np.asarray(p, np.float64)
        g_np = np.asarray(g, np.float64)
        n = p_np.size
        if max_per_param and n > max_per_param:
            idxs = rng.choice(n, size=max_per_param, replace=False)
        else:
            idxs = np.arange(n)
        for flat_idx in idxs:
            idx = np.unravel_index(flat_idx, p_np.shape)
            orig = p_np[idx]

            def eval_at(v):
                p_mod = p_np.copy()
                p_mod[idx] = v
                flat2 = list(flat_p)
                flat2[pi] = jnp.asarray(p_mod)
                return float(jitted_loss(flat2))

            plus = eval_at(orig + eps)
            minus = eval_at(orig - eps)
            numeric = (plus - minus) / (2 * eps)
            a = g_np[idx]
            abs_err = abs(numeric - a)
            denom = abs(numeric) + abs(a)
            rel_err = abs_err / denom if denom > 0 else 0.0
            if rel_err > max_rel_error and abs_err > min_abs_error:
                ok = False
                if print_failures:
                    log.warning(
                        "grad check FAIL param %d idx %s: numeric=%.8g analytic=%.8g "
                        "relErr=%.4g", pi, idx, numeric, a, rel_err)
    return ok


def check_gradients(net, ds, eps: float = DEFAULT_EPS,
                    max_rel_error: float = DEFAULT_MAX_REL_ERROR,
                    min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
                    max_per_param: int = 32, seed: int = 0) -> bool:
    """Gradient-check a MultiLayerNetwork or ComputationGraph on a DataSet
    (ref: GradientCheckUtil.checkGradients :112/:281). Dropout must be
    disabled (train=True forward but rng=None disables dropout here)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    if not net._initialized:
        net.init()
    with enable_x64(True):
        return _check_gradients_x64(net, ds, eps, max_rel_error,
                                    min_abs_error, max_per_param, seed)


def _check_gradients_x64(net, ds, eps, max_rel_error, min_abs_error,
                         max_per_param, seed) -> bool:
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    x = jnp.asarray(ds.features, jnp.float64)
    y = jnp.asarray(ds.labels, jnp.float64)
    fmask = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
    lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)

    if isinstance(net, MultiLayerNetwork):
        def loss_fn(p):
            loss, _ = net._loss(p, net.state, x, y, None, fmask, lmask, train=True)
            return loss
    else:
        inputs = net._as_input_dict(x)
        labels = {net.conf.network_outputs[0]: y}
        fmasks = None if fmask is None else {net.conf.network_inputs[0]: fmask}
        lmasks = None if lmask is None else {net.conf.network_outputs[0]: lmask}

        def loss_fn(p):
            loss, _ = net._loss(p, net.state, inputs, labels, None, fmasks,
                                lmasks, train=True)
            return loss

    return check_gradients_fn(loss_fn, net.params, eps=eps,
                              max_rel_error=max_rel_error,
                              min_abs_error=min_abs_error,
                              max_per_param=max_per_param, seed=seed)
