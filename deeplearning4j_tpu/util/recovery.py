"""Fault-tolerant training: checkpoint-based automatic restart + rollback.

SURVEY §5 ("Failure/elastic recovery"): the reference has essentially no
fault tolerance beyond Spark task retry; on TPU the idiomatic equivalent
is checkpoint-restart — preemption and crash recovery both reduce to
"resume from the latest checkpoint and keep going". This wrapper owns
that loop:

    trainer = FaultTolerantTrainer(net, checkpoint_dir,
                                   save_every_n_iterations=100)
    trainer.fit(iterator, epochs=10)        # resumes automatically

- On entry, if the checkpoint dir has saved steps, the newest one is
  restored (params, optimizer state, BN stats, iteration/epoch counters)
  and training continues from the NEXT epoch boundary.
- During fit a CheckpointListener persists periodically.
- `max_restarts` bounds in-process retries of transient failures
  (`retry_on` exception types), re-restoring from the latest checkpoint
  between attempts — the single-host analogue of an elastic scheduler
  relaunching a preempted worker.

Divergence handling (resilience/): with ``watch_divergence=True`` a
``DivergenceWatchdog`` listener rides along and raises
``DivergenceError`` when the non-finite sentinel reports K consecutive
bad steps or the loss blows past its trailing window. The restart path
then restores the newest checkpoint that PREDATES the divergence:
GOOD-tagged by the sentinel, and — for a finite loss blowup, which
every bad-step tag misses — with a recorded save-time score still under
the watchdog limit that fired; when nothing qualifies it falls back to
the newest save of any tag (a finite on-disk state beats the diverged
in-memory tree). It then optionally multiplies the learning rate by
``lr_backoff`` (< 1) before resuming — the classic "rewind and cool
down" divergence recovery — and clears the net's jit cache so the new
LR actually traces (the updater bakes its float into the compiled
step).

The exact resume==straight-run invariant (tests/test_recovery.py,
tests/test_durable.py) holds for epoch-boundary checkpoints always, and
for mid-epoch (iteration-cadence or preemption-emergency) checkpoints
whenever the data iterator supports the durable-cursor protocol
(state()/restore_state() — ArrayDataSetIterator and
DevicePrefetchIterator do): the checkpoint captures the RNG stream and
the dispatched-batch cursor, and resume fast-forwards the stream to the
exact next batch. Iterators without the protocol degrade to the classic
approximate continuation (the interrupted epoch's consumed batches
replay); a warning says so at restore time. Every recovery decision
re-VERIFIES checkpoint checksums first (resilience/durable.py format)
and skips torn/corrupt candidates with a warning + counter instead of
restoring garbage or raising mid-recovery.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple, Type

from deeplearning4j_tpu.monitoring.events import emit as emit_event
from deeplearning4j_tpu.monitoring.metrics import global_registry
from deeplearning4j_tpu.resilience.durable import declare_checkpoint_series
from deeplearning4j_tpu.resilience.watchdog import (
    DivergenceError, DivergenceWatchdog)
from deeplearning4j_tpu.util.checkpoint import (
    CheckpointListener, checkpoint_status, delete_checkpoint,
    list_checkpoints, list_good_checkpoints, restore_checkpoint,
)

RESTARTS = "dl4jtpu_training_restarts_total"

log = logging.getLogger(__name__)


class FaultTolerantTrainer:
    def __init__(self, net, checkpoint_dir: str,
                 save_every_n_iterations: Optional[int] = None,
                 save_every_epoch: bool = True, keep_last: int = 3,
                 max_restarts: int = 2,
                 retry_on: Tuple[Type[BaseException], ...] = (RuntimeError,),
                 watch_divergence: bool = False,
                 watchdog: Optional[DivergenceWatchdog] = None,
                 lr_backoff: Optional[float] = None,
                 async_save: bool = False):
        if lr_backoff is not None and not 0.0 < lr_backoff < 1.0:
            raise ValueError(f"lr_backoff must be in (0, 1), "
                             f"got {lr_backoff}")
        self.net = net
        self.dir = checkpoint_dir
        self.max_restarts = max_restarts
        self.retry_on = retry_on
        self.lr_backoff = lr_backoff
        self.watchdog = watchdog if watchdog is not None else (
            DivergenceWatchdog() if watch_divergence else None)
        self._listener = CheckpointListener(
            checkpoint_dir, save_every_n_iterations=save_every_n_iterations,
            save_every_epoch=save_every_epoch, keep_last=keep_last,
            async_save=async_save)
        if not save_every_epoch:
            log.warning(
                "iteration-only checkpoints: exact mid-epoch resume "
                "needs an iterator with the state()/restore_state() "
                "cursor protocol; others replay the interrupted epoch's "
                "consumed batches (approximate continuation)")

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Wait for pending async checkpoint writes to be durable —
        every recovery decision (resume, rollback, prune) flushes first
        so it reasons about on-disk state, not an in-flight save."""
        return self._listener.flush(timeout)

    def health(self) -> dict:
        """Ops surface: checkpoint-writer health (async failures land
        here instead of killing training) + restart posture."""
        return {"checkpoint_writer": self._listener.health(),
                "checkpoint_dir": self.dir,
                "max_restarts": self.max_restarts}

    # -- recovery ---------------------------------------------------------
    def _try_restore(self, step: int) -> bool:
        """Restore one candidate, treating corruption as a skip (warning
        + counter), never a raise mid-recovery. Tags are written BEFORE
        any later corruption can be known and disk bytes rot
        independently of them, so every restore checksum-verifies the
        bytes it loads — inside the one read, not as a separate
        full-file pre-verification pass."""
        from deeplearning4j_tpu.resilience.durable import \
            CorruptCheckpointError
        try:
            restore_checkpoint(self.net, self.dir, step=step)
            return True
        except CorruptCheckpointError as e:
            log.warning("checkpoint step %d failed integrity "
                        "verification (%s); skipping it for recovery",
                        step, e)
            declare_checkpoint_series()[4].inc()
            return False

    def resume_if_possible(self, only_good: bool = False) -> Optional[int]:
        """Restore the newest INTACT checkpoint (with ``only_good``, the
        newest one the sentinel tagged GOOD — verified, since a tag
        predates whatever corrupted the bytes); returns the restored
        step or None (fresh start). Verification happens inside the
        single restore read, so each candidate's bytes are read once,
        not twice."""
        self.flush()
        steps = (list_good_checkpoints(self.dir) if only_good
                 else list_checkpoints(self.dir))
        for step in reversed(steps):
            if not self._try_restore(step):
                continue
            log.info("resumed from checkpoint step %d (epoch %d)%s", step,
                     self.net.epoch_count,
                     " [last good]" if only_good else "")
            return step
        return None

    def _rollback_candidates(self, cause: BaseException) -> list:
        """Rollback priority order, newest-first within each tier:
        GOOD-tagged saves with a recorded score under the watchdog limit
        that fired (a FINITE blowup poisons saves every tag calls good),
        then any GOOD-tagged save, then any save at all (a finite
        on-disk state beats the diverged in-memory tree). Chosen from
        tags/scores alone — integrity is verified lazily by the restore
        attempt itself, so rollback reads each candidate at most once
        instead of pre-checksumming every checkpoint on disk."""
        good = list_good_checkpoints(self.dir)
        limit = getattr(cause, "limit", None)
        ordered: list = []
        if limit is not None:
            def saved_score(s):
                v = checkpoint_status(self.dir, s).get("score")
                # explicit None check: 0.0 is a real (and fine) score
                return -float("inf") if v is None else v

            ordered += [s for s in reversed(good) if saved_score(s) <= limit]
        ordered += [s for s in reversed(good) if s not in ordered]
        ordered += [s for s in reversed(list_checkpoints(self.dir))
                    if s not in ordered]
        return ordered

    def _pick_rollback_step(self, cause: BaseException) -> Optional[int]:
        """The tag/score policy's first choice (no integrity read — the
        restore attempt in _rollback verifies lazily)."""
        cands = self._rollback_candidates(cause)
        return cands[0] if cands else None

    def _rollback(self, cause: BaseException) -> Optional[int]:
        """Divergence recovery: restore the best intact pre-divergence
        state, cool the LR, reset the watchdog/sentinel windows so stale
        history can't immediately re-trigger."""
        self.flush()
        step = None
        for cand in self._rollback_candidates(cause):
            if self._try_restore(cand):
                step = cand
                break
        if step is not None:
            emit_event("resilience", "rollback", step=step,
                       cause=repr(cause))
            log.info("rolled back to checkpoint step %d (epoch %d)",
                     step, self.net.epoch_count)
            # drop the mid-divergence saves BEYOND the rewind point:
            # left on disk, a later transient restart would restore the
            # newest (diverged) one, and keep-last pruning — which keeps
            # the HIGHEST steps — would evict the fresh post-rollback
            # saves while preserving the poisoned ones
            for stale in list_checkpoints(self.dir):
                if stale > step:
                    delete_checkpoint(self.dir, stale)
                    log.info("pruned post-divergence checkpoint step %d",
                             stale)
        if self.lr_backoff is not None:
            upd = self.net.conf.updater
            upd.learning_rate *= self.lr_backoff
            # the compiled steps baked the old LR in as a constant
            self.net._jit_cache.clear()
            log.warning("divergence (%s): learning rate backed off to %g",
                        cause, upd.learning_rate)
        self._reset_windows()
        return step

    def _reset_windows(self) -> None:
        """Forget watchdog/sentinel history after ANY restore: the score
        window sampled the pre-restore trajectory, and a rewound (older,
        higher-loss) state compared against it would spuriously re-trip
        the blowup check on a healthy run."""
        acct = getattr(self.net, "_sentinel_accounting", None)
        if acct is not None:
            acct.reset_window()
        if self.watchdog is not None:
            self.watchdog.reset()

    # -- training ---------------------------------------------------------
    def fit(self, data, labels=None, epochs: int = 1, batch_size: int = 32):
        """Train to `epochs` TOTAL epochs (counting any epochs already in
        the restored state), restarting from the latest checkpoint on
        transient failures — or the latest GOOD checkpoint on divergence
        — up to `max_restarts` times."""
        listeners = getattr(self.net, "listeners", [])
        if self._listener not in listeners:
            self.net.add_listener(self._listener)
        if self.watchdog is not None and self.watchdog not in listeners:
            self.net.add_listener(self.watchdog)
        self.resume_if_possible()
        # divergence handling is this class's explicit contract — it must
        # work even when retry_on was narrowed to, say, (OSError,)
        catch = (DivergenceError,) + tuple(self.retry_on)
        attempts = 0
        while True:
            remaining = epochs - self.net.epoch_count
            if remaining <= 0:
                log.info("target of %d epochs already reached", epochs)
                return self.net
            try:
                self.net.fit(data, labels=labels, epochs=remaining,
                             batch_size=batch_size)
                # terminal checkpoint so a later run resumes cleanly
                # (skip when the epoch-end listener just wrote this step)
                self.flush()
                steps = list_checkpoints(self.dir)
                if not steps or steps[-1] != self.net.iteration_count:
                    self._listener._save(self.net,
                                         self.net.iteration_count)
                    # the terminal save must be DURABLE before fit
                    # returns: an async submit alone rides a daemon
                    # thread that dies with the process
                    self.flush()
                return self.net
            except catch as e:
                attempts += 1
                if attempts > self.max_restarts:
                    log.error("giving up after %d restarts", attempts - 1)
                    raise
                global_registry().counter(
                    RESTARTS, "In-process training restarts from checkpoint",
                    ("cause",)).inc(
                    cause="divergence" if isinstance(e, DivergenceError)
                    else "transient")
                emit_event(
                    "resilience", "restart", attempt=attempts,
                    cause=("divergence" if isinstance(e, DivergenceError)
                           else "transient"), error=repr(e))
                log.warning("training failed (%s); restart %d/%d from "
                            "latest checkpoint", e, attempts,
                            self.max_restarts)
                if isinstance(e, DivergenceError):
                    restored = self._rollback(e)
                    if restored is None and self.lr_backoff is None:
                        # nothing to rewind to and nothing changed:
                        # refitting the diverged in-memory state would
                        # burn every remaining restart on guaranteed
                        # re-divergence — fail now, actionably
                        log.error("divergence with no checkpoint to "
                                  "roll back to and no lr_backoff "
                                  "configured — not retrying")
                        raise
                else:
                    restored = self.resume_if_possible()
                    self._reset_windows()
                if restored is None:
                    log.warning("no checkpoint yet — restarting from "
                                "current in-memory state")
