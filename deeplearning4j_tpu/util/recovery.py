"""Fault-tolerant training: checkpoint-based automatic restart.

SURVEY §5 ("Failure/elastic recovery"): the reference has essentially no
fault tolerance beyond Spark task retry; on TPU the idiomatic equivalent
is checkpoint-restart — preemption and crash recovery both reduce to
"resume from the latest checkpoint and keep going". This wrapper owns
that loop:

    trainer = FaultTolerantTrainer(net, checkpoint_dir,
                                   save_every_n_iterations=100)
    trainer.fit(iterator, epochs=10)        # resumes automatically

- On entry, if the checkpoint dir has saved steps, the newest one is
  restored (params, optimizer state, BN stats, iteration/epoch counters)
  and training continues from the NEXT epoch boundary.
- During fit a CheckpointListener persists periodically.
- `max_restarts` bounds in-process retries of transient failures
  (`retry_on` exception types), re-restoring from the latest checkpoint
  between attempts — the single-host analogue of an elastic scheduler
  relaunching a preempted worker.

The exact resume==straight-run invariant holds for EPOCH-BOUNDARY
checkpoints (save_every_epoch=True, the default — the state tree incl.
the RNG stream restores exactly; tests/test_recovery.py). Iteration-based
checkpoints (save_every_n_iterations without epoch saves) give
approximate continuation: the interrupted epoch's already-consumed
batches are replayed on resume — standard practice, but not bit-equal to
an uninterrupted run; fit() logs a warning in that configuration.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple, Type

from deeplearning4j_tpu.util.checkpoint import (
    CheckpointListener, list_checkpoints, restore_checkpoint,
)

log = logging.getLogger(__name__)


class FaultTolerantTrainer:
    def __init__(self, net, checkpoint_dir: str,
                 save_every_n_iterations: Optional[int] = None,
                 save_every_epoch: bool = True, keep_last: int = 3,
                 max_restarts: int = 2,
                 retry_on: Tuple[Type[BaseException], ...] = (RuntimeError,)):
        self.net = net
        self.dir = checkpoint_dir
        self.max_restarts = max_restarts
        self.retry_on = retry_on
        self._listener = CheckpointListener(
            checkpoint_dir, save_every_n_iterations=save_every_n_iterations,
            save_every_epoch=save_every_epoch, keep_last=keep_last)
        if not save_every_epoch:
            log.warning(
                "iteration-only checkpoints: resume replays the "
                "interrupted epoch's consumed batches (approximate "
                "continuation, not bit-exact — see module docstring)")

    # -- recovery ---------------------------------------------------------
    def resume_if_possible(self) -> Optional[int]:
        """Restore the newest checkpoint if one exists; returns the
        restored step or None (fresh start)."""
        steps = list_checkpoints(self.dir)
        if not steps:
            return None
        step = steps[-1]
        restore_checkpoint(self.net, self.dir, step=step)
        log.info("resumed from checkpoint step %d (epoch %d)", step,
                 self.net.epoch_count)
        return step

    # -- training ---------------------------------------------------------
    def fit(self, data, labels=None, epochs: int = 1, batch_size: int = 32):
        """Train to `epochs` TOTAL epochs (counting any epochs already in
        the restored state), restarting from the latest checkpoint on
        transient failures up to `max_restarts` times."""
        if self._listener not in getattr(self.net, "listeners", []):
            self.net.add_listener(self._listener)
        self.resume_if_possible()
        attempts = 0
        while True:
            remaining = epochs - self.net.epoch_count
            if remaining <= 0:
                log.info("target of %d epochs already reached", epochs)
                return self.net
            try:
                self.net.fit(data, labels=labels, epochs=remaining,
                             batch_size=batch_size)
                # terminal checkpoint so a later run resumes cleanly
                # (skip when the epoch-end listener just wrote this step)
                steps = list_checkpoints(self.dir)
                if not steps or steps[-1] != self.net.iteration_count:
                    self._listener._save(self.net,
                                         self.net.iteration_count)
                return self.net
            except self.retry_on as e:
                attempts += 1
                if attempts > self.max_restarts:
                    log.error("giving up after %d restarts", attempts - 1)
                    raise
                log.warning("training failed (%s); restart %d/%d from "
                            "latest checkpoint", e, attempts,
                            self.max_restarts)
                if self.resume_if_possible() is None:
                    log.warning("no checkpoint yet — restarting from "
                                "current in-memory state")
