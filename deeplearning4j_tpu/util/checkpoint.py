"""Crash-consistent checkpointing + periodic checkpoint listener.

The DL4J-zip format (util/model_serializer.py) is the portability/parity
path (ref: util/ModelSerializer.java — configuration.json + coefficients.bin
+ updaterState.bin). This module is the TPU-native production path the
SURVEY §5 checkpoint/resume row calls for, rebuilt on the durable-state
layer (resilience/durable.py):

- **Crash-consistent format**: every checkpoint is a directory
  (data.npz + MANIFEST.json) assembled under a tmp name and atomically
  renamed into place; the manifest carries a format version and a
  per-leaf crc32 checksum. A ``kill -9`` at ANY point during a save
  leaves the previously committed checkpoints byte-identical, and
  ``restore_checkpoint`` VERIFIES integrity before applying — falling
  back to the newest intact checkpoint instead of crashing on (or
  silently loading) torn bytes.
- **Async saves**: ``CheckpointListener(async_save=True)`` blocks the
  fit loop only for the device→host snapshot; serialize+write+prune run
  on a bounded ``AsyncCheckpointWriter`` with backpressure, failure
  telemetry, and ``health()``.
- **Preemption-exact state**: a checkpoint captures, beyond
  params/opt-state/BN-stats/counters, the dropout RNG stream, the
  data-pipeline cursor (epoch index + batches dispatched + canonical
  pad width), the current learning rate, the sentinel accounting, and
  any listener durable state (divergence-watchdog window) — so a run
  killed at a dispatch boundary resumes bit-identical to an
  uninterrupted run (tests/test_durable.py pins this on all three fit
  loops, including the fused ``lax.scan`` path).
- **Distributed commit**: ``save_distributed_checkpoint`` writes one
  shard per process and publishes a COMMIT marker from rank 0 only
  after every shard verified; resume selects the highest fully
  committed step (a worker dying between shard write and commit can
  never surface a half-checkpoint).

``CheckpointListener`` saves at DISPATCH boundaries (the fit loops'
``resilience.durable.dispatch_boundary`` hook), not inside the
iteration_done listener loop: on the fused multi-step path
iteration_done fires per LOGICAL step while params already hold the
post-group state, so a mid-group save would stitch a torn snapshot.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.monitoring.events import emit as emit_event
from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.resilience.durable import (
    AsyncCheckpointWriter, CommitTimeoutError, CorruptCheckpointError,
    MANIFEST_NAME,
    atomic_write_json, declare_checkpoint_series, publish_commit,
    read_commit, read_state_dir, shard_dir_name, snapshot_tree,
    verify_state_dir, wait_commit, write_checkpoint_dir, write_shard)

log = logging.getLogger(__name__)

__all__ = [
    "CheckpointListener", "CommitTimeoutError", "checkpoint_status",
    "delete_checkpoint",
    "list_checkpoints", "list_good_checkpoints", "load_checkpoint",
    "restore_checkpoint", "restore_distributed_checkpoint",
    "save_checkpoint", "save_distributed_checkpoint", "verify_checkpoint",
]


def _net_state_tree(net) -> Dict[str, Any]:
    return {
        "params": net.params,
        "state": net.state,
        "updater_state": net.updater_state,
        "counters": {
            "iteration": np.int64(net.iteration_count),
            "epoch": np.int64(net.epoch_count),
        },
        # the dropout/noise RNG stream: without it, resume would replay
        # the interrupted epoch with different masks than a straight run
        "rng": np.asarray(net._rng) if getattr(net, "_rng", None)
        is not None else np.zeros(2, np.uint32),
    }


def _sentinel_status(net) -> Dict[str, Any]:
    """Health tag for a checkpoint: flush the net's non-finite sentinel
    accounting (resilience/sentinel.py) and report whether the state
    being saved is GOOD (no live run of bad steps), plus the score at
    save time — the divergence-rollback path uses it to rewind past
    saves taken after a FINITE loss blowup, which the bad-step flag
    alone cannot see. A save is itself a full host materialization, so
    the flush/score syncs are free here."""
    from deeplearning4j_tpu.resilience.sentinel import flush_accounting
    acct = flush_accounting(net)
    score = getattr(net, "score_value", None)
    try:
        score = None if score is None or score != score else float(score)
    except (TypeError, ValueError):
        score = None
    if acct is None:  # sentinel never ran: nothing says this is bad
        return {"good": True, "bad_steps": 0, "consecutive_bad": 0,
                "score": score}
    return {"good": acct.consecutive_bad == 0,
            "bad_steps": acct.bad_steps,
            "consecutive_bad": acct.consecutive_bad,
            "score": score}


def _manifest_extras(net, status: Dict[str, Any]) -> Dict[str, Any]:
    """The preemption-exactness sidecar state: everything a bit-identical
    resume needs beyond the array tree."""
    extras: Dict[str, Any] = {"model_class": type(net).__name__,
                              "resilience": status}
    # data-pipeline cursor: pass index + batches DISPATCHED this pass +
    # the canonical pad width locked at the pass's first batch (fit
    # loops maintain these; absent outside fit = epoch-boundary cursor).
    # The pass index is the fit loop's ``_cursor_pass`` — captured from
    # the iterator's OWN cursor at epoch start (its counter drives the
    # shuffle seed, and a user-provided iterator's passes need not track
    # the net's absolute epoch_count) and held fixed through the pass.
    # It must NOT be re-read from the live iterator at save time: the
    # trailing-group flush fires its dispatch boundary AFTER the
    # generator exhausted, when the iterator already reports the NEXT
    # pass — pairing that with the current pass's dispatch count would
    # make resume skip an entire epoch. ``{pass, dispatched=all}`` is
    # the consistent encoding of "epoch stream done": the resumed pass
    # yields nothing and rolls over naturally.
    cursor_pass = getattr(net, "_cursor_pass", None)
    epoch = int(net.epoch_count) if cursor_pass is None else int(cursor_pass)
    canon = getattr(net, "_canon_in_epoch", None)
    extras["pipeline"] = {
        "epoch": epoch,
        "pos": int(getattr(net, "_dispatched_in_epoch", 0) or 0),
        "canon": None if canon is None else int(canon),
    }
    upd = getattr(getattr(net, "conf", None), "updater", None)
    lr = getattr(upd, "learning_rate", None)
    if lr is not None:
        # survives lr_backoff across process death: a resumed run keeps
        # the cooled-down rate, not the conf's original
        extras["learning_rate"] = float(lr)
    acct = getattr(net, "_sentinel_accounting", None)
    if acct is not None:
        extras["sentinel"] = {
            "total_steps": int(acct.total_steps),
            "bad_steps": int(acct.bad_steps),
            "skipped_updates": int(acct.skipped_updates),
            "consecutive_bad": int(acct.consecutive_bad),
        }
    listeners = {}
    for lst in getattr(net, "listeners", ()):
        state_fn = getattr(lst, "durable_state", None)
        if state_fn is None:
            continue
        key = type(lst).__name__
        if key not in listeners:  # first listener of a class wins
            listeners[key] = state_fn()
    if listeners:
        extras["listeners"] = listeners
    return extras


def _step_dirname(step: Optional[int]) -> str:
    return "latest" if step is None else f"step_{int(step)}"


def save_checkpoint(net, path: str, step: Optional[int] = None,
                    writer: Optional[AsyncCheckpointWriter] = None) -> str:
    """Write a crash-consistent checkpoint of the network's full
    training state. Returns the checkpoint directory.

    The device→host snapshot happens HERE, synchronously (the one part
    the fit loop must block for); with ``writer`` the serialize + write
    + atomic rename run on the background writer thread, in submission
    order, with backpressure. Each step dir carries a
    ``resilience.json`` health tag (sentinel state at save time) so
    rollback (util/recovery.py) can target the last GOOD checkpoint
    instead of the newest — which may already be poisoned."""
    import time as _time
    path = os.path.abspath(path)
    step_dir = os.path.join(path, _step_dirname(step))
    t0 = _time.perf_counter()
    host_tree = snapshot_tree(_net_state_tree(net))
    status = _sentinel_status(net)
    extras = _manifest_extras(net, status)
    meta = {"model_class": type(net).__name__, "config": net.conf.to_json()}

    def _write():
        write_checkpoint_dir(step_dir, host_tree, extras=extras)
        if step is not None:
            # tag lives NEXT TO the step dir so status probes never open
            # the (large) manifest; the manifest carries it too, as the
            # fallback of record
            atomic_write_json(_tag_path(path, step), status)
        atomic_write_json(os.path.join(path, "config.json"), meta)
        emit_event("resilience", "checkpoint_save", step=step,
                   mode="async" if writer is not None else "sync")

    if writer is not None:
        writer.submit(_write, label=os.path.basename(step_dir))
    else:
        _write()
        declare_checkpoint_series()[0].observe(
            _time.perf_counter() - t0, mode="sync")
    return step_dir


def _apply_tree(net, restored: Dict[str, Any]) -> None:
    net.params = restored["params"]
    net.state = restored["state"]
    net.updater_state = restored["updater_state"]
    net.iteration_count = int(restored["counters"]["iteration"])
    net.epoch_count = int(restored["counters"]["epoch"])
    rng = restored.get("rng")
    if rng is not None and hasattr(net, "_rng"):
        import jax.numpy as jnp
        net._rng = jnp.asarray(rng)


def _apply_extras(net, extras: Dict[str, Any]) -> None:
    """Re-arm the exactness sidecar state on the restored net."""
    status = extras.get("resilience") or {}
    score = status.get("score")
    if score is not None:
        net.score_value = float(score)
    lr = extras.get("learning_rate")
    upd = getattr(getattr(net, "conf", None), "updater", None)
    if lr is not None and upd is not None and \
            getattr(upd, "learning_rate", None) is not None and \
            float(upd.learning_rate) != float(lr):
        upd.learning_rate = float(lr)
        # compiled steps baked the old LR in as a constant
        cache = getattr(net, "_jit_cache", None)
        if cache is not None:
            cache.clear()
    sent = extras.get("sentinel")
    if sent is not None:
        from deeplearning4j_tpu.resilience.sentinel import accounting_for
        acct = accounting_for(net)
        acct.reset_window()
        acct.total_steps = int(sent.get("total_steps", 0))
        acct.bad_steps = int(sent.get("bad_steps", 0))
        acct.skipped_updates = int(sent.get("skipped_updates", 0))
        acct.consecutive_bad = int(sent.get("consecutive_bad", 0))
    saved_listeners = extras.get("listeners") or {}
    for lst in getattr(net, "listeners", ()):
        restore_fn = getattr(lst, "restore_durable_state", None)
        if restore_fn is None:
            continue
        saved = saved_listeners.get(type(lst).__name__)
        if saved is not None:
            restore_fn(saved)
    # the fit loops consume this to fast-forward the data pipeline to
    # the batch AFTER the last dispatched one (see MultiLayerNetwork.fit)
    net._restored_pipeline_state = extras.get("pipeline")


def _corrupt_skip_counter():
    return declare_checkpoint_series()[4]


def restore_checkpoint(net, path: str, step: Optional[int] = None,
                       verify: bool = True):
    """Restore training state into an initialized network (in place),
    verifying every leaf checksum first.

    With an explicit ``step``, corruption raises
    ``CorruptCheckpointError`` (the caller asked for THOSE bytes). With
    ``step=None`` the newest checkpoint is used — and if its bytes are
    torn/corrupt, restore logs a warning, bumps
    ``dl4jtpu_checkpoint_corrupt_skipped_total``, and transparently
    falls back to the next-newest intact checkpoint."""
    path = os.path.abspath(path)
    if step is not None:
        step_dir = os.path.join(path, _step_dirname(step))
        if not os.path.isdir(step_dir):
            # absent is NOT corrupt: a caller (or operator) must be able
            # to tell "never existed / already pruned" from "torn bytes"
            raise FileNotFoundError(
                f"no checkpoint step {step} under {path}")
        candidates = [step_dir]
    else:
        candidates = []
        latest = os.path.join(path, "latest")
        if os.path.isdir(latest):
            candidates.append(latest)
        candidates += [os.path.join(path, _step_dirname(s))
                       for s in reversed(list_checkpoints(path))]
        if not candidates:
            raise FileNotFoundError(f"no checkpoints under {path}")
    last_err: Optional[CorruptCheckpointError] = None
    for i, step_dir in enumerate(candidates):
        try:
            restored, manifest = read_state_dir(step_dir, verify=verify)
        except CorruptCheckpointError as e:
            last_err = e
            if step is not None:
                raise
            log.warning("checkpoint %s failed integrity verification "
                        "(%s); falling back to the next-newest intact "
                        "checkpoint", step_dir, e)
            _corrupt_skip_counter().inc()
            continue
        if i > 0:
            log.warning("restored fallback checkpoint %s", step_dir)
        _apply_tree(net, restored)
        _apply_extras(net, manifest.get("extras") or {})
        return net
    raise CorruptCheckpointError(
        f"every checkpoint under {path} failed integrity verification "
        f"(last error: {last_err})")


def verify_checkpoint(path: str, step: Optional[int] = None) -> bool:
    """True iff the step's on-disk bytes pass manifest + checksum
    verification."""
    return verify_state_dir(os.path.join(os.path.abspath(path),
                                         _step_dirname(step)))


def load_checkpoint(path: str, step: Optional[int] = None):
    """Rebuild the network object from the stored config, then restore."""
    path = os.path.abspath(path)
    with open(os.path.join(path, "config.json")) as f:
        meta = json.load(f)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import (
        MultiLayerConfiguration, ComputationGraphConfiguration)
    if meta["model_class"] == "MultiLayerNetwork":
        net = MultiLayerNetwork(
            MultiLayerConfiguration.from_json(meta["config"]))
    else:
        net = ComputationGraph(
            ComputationGraphConfiguration.from_json(meta["config"]))
    net.init()
    return restore_checkpoint(net, path, step)


def list_checkpoints(path: str) -> List[int]:
    """Step numbers of COMMITTED checkpoints under a dir, ascending.
    A step dir only ever exists committed (tmp-assembled + renamed), so
    this is a directory listing filtered to manifest-bearing step dirs;
    integrity of the bytes is verified lazily at restore."""
    if not os.path.isdir(path):
        return []
    steps, legacy = [], []
    for name in os.listdir(path):
        if not name.startswith("step_") or name.endswith(".json"):
            continue
        try:
            s = int(name.split("_", 1)[1])
        except ValueError:
            continue
        if os.path.exists(os.path.join(path, name, MANIFEST_NAME)):
            steps.append(s)
        else:
            legacy.append(s)
    if legacy:
        # step dirs from the pre-manifest (orbax-era) format: ignoring
        # them SILENTLY would make an upgraded job restart from scratch
        # without a trace — say so, loudly
        log.warning("ignoring %d checkpoint dir(s) without a manifest "
                    "under %s (steps %s — pre-durable-format?); they "
                    "cannot be integrity-verified or restored by this "
                    "version, migrate or delete them",
                    len(legacy), path, sorted(legacy))
    return sorted(steps)


def _tag_path(path: str, step: int) -> str:
    """Canonical location of a step's resilience health tag."""
    return os.path.join(os.path.abspath(path),
                        f"step_{step}.resilience.json")


def delete_checkpoint(path: str, step: int) -> None:
    """Remove a step dir AND its health tag (the two must never drift
    apart — a stale tag would be read as the status of a future save
    reusing the step number). The ONE sanctioned eviction path: pruning
    that bypasses it orphans tags/manifests."""
    shutil.rmtree(os.path.join(os.path.abspath(path), f"step_{step}"),
                  ignore_errors=True)
    try:
        os.unlink(_tag_path(path, step))
    except OSError:
        pass


def checkpoint_status(path: str, step: int) -> Dict[str, Any]:
    """The resilience tag written beside a step dir; falls back to the
    manifest's copy (tag write is the last act of a save — a crash
    between dir commit and tag write must not lose the status), then to
    good (untagged pre-resilience checkpoints)."""
    try:
        with open(_tag_path(path, step)) as f:
            return json.load(f)
    except (OSError, ValueError):
        pass
    try:
        from deeplearning4j_tpu.resilience.durable import read_manifest
        m = read_manifest(os.path.join(os.path.abspath(path),
                                       _step_dirname(step)))
        status = (m.get("extras") or {}).get("resilience")
        if status:
            return status
    except CorruptCheckpointError:
        pass
    return {"good": True}


def list_good_checkpoints(path: str) -> List[int]:
    """Steps whose saved state the sentinel tagged GOOD, ascending."""
    return [s for s in list_checkpoints(path)
            if checkpoint_status(path, s).get("good", True)]


# ---------------------------------------------------------------------------
# distributed commit protocol (net-level wrappers)
# ---------------------------------------------------------------------------
def _dist_rank_world(rank: Optional[int], world: Optional[int]):
    if rank is None or world is None:
        import jax
        rank = jax.process_index() if rank is None else rank
        world = jax.process_count() if world is None else world
    return int(rank), int(world)


def save_distributed_checkpoint(net, path: str, step: int,
                                rank: Optional[int] = None,
                                world: Optional[int] = None,
                                timeout: float = 60.0,
                                wait: bool = True,
                                publish: bool = True) -> str:
    """Multi-process checkpoint: every worker writes its own shard dir
    (atomic + checksummed) under ``step_N/``; rank 0 then waits for all
    shards, verifies them, and atomically publishes the COMMIT marker.
    Non-zero ranks (with ``wait=True``) block until the marker appears,
    so a returning save means the step is globally durable.

    A worker dying between shard write and commit leaves the step
    UNCOMMITTED (rank 0 times out, raises, and writes no marker) —
    resume via ``restore_distributed_checkpoint`` only ever selects
    fully committed steps.

    ``publish=False`` (rank 0 only) writes the shard and config but
    leaves the marker to the caller (``resilience.durable
    .publish_commit``): the elastic trainer sequences a membership
    decision between shard arrival and the marker so every rank that
    passes the commit barrier is guaranteed to observe it."""
    rank, world = _dist_rank_world(rank, world)
    path = os.path.abspath(path)
    step_dir = os.path.join(path, f"step_{int(step)}")
    host_tree = snapshot_tree(_net_state_tree(net))
    extras = _manifest_extras(net, _sentinel_status(net))
    extras["rank"] = rank
    extras["world"] = world
    sdir = write_shard(step_dir, rank, host_tree, extras=extras)
    if rank == 0:
        meta = {"model_class": type(net).__name__,
                "config": net.conf.to_json()}
        atomic_write_json(os.path.join(path, "config.json"), meta)
        if publish:
            publish_commit(step_dir, step=int(step), world=world,
                           timeout=timeout)
    elif wait:
        wait_commit(step_dir, timeout=timeout, world=world)
    return sdir


def restore_distributed_checkpoint(net, path: str,
                                   rank: Optional[int] = None,
                                   world: Optional[int] = None,
                                   step: Optional[int] = None):
    """Restore this worker's shard from the highest fully COMMITTED
    step (or an explicit one). Uncommitted steps — a worker died before
    rank 0 could publish the marker — are invisible; corrupt committed
    shards fall back to the next-newest committed step. Returns the
    restored step (None = nothing committed, fresh start)."""
    from deeplearning4j_tpu.resilience.durable import list_committed_steps
    rank, world = _dist_rank_world(rank, world)
    path = os.path.abspath(path)
    if step is not None:
        steps = [int(step)]
        if read_commit(os.path.join(path, f"step_{int(step)}")) is None:
            raise CorruptCheckpointError(
                f"step {step} under {path} has no COMMIT marker")
    else:
        steps = list(reversed(list_committed_steps(path)))
        if not steps:
            return None
    last_err: Optional[CorruptCheckpointError] = None
    for s in steps:
        sdir = os.path.join(path, f"step_{s}", shard_dir_name(rank))
        try:
            restored, manifest = read_state_dir(sdir, verify=True)
        except CorruptCheckpointError as e:
            last_err = e
            if step is not None:
                raise
            log.warning("committed step %d shard %d failed verification "
                        "(%s); falling back", s, rank, e)
            _corrupt_skip_counter().inc()
            continue
        _apply_tree(net, restored)
        _apply_extras(net, manifest.get("extras") or {})
        return s
    raise CorruptCheckpointError(
        f"every committed step under {path} failed shard verification "
        f"for rank {rank} (last error: {last_err})")


# ---------------------------------------------------------------------------
# periodic checkpoint listener
# ---------------------------------------------------------------------------
class CheckpointListener(TrainingListener):
    """Periodic checkpointing during fit (save every N iterations or
    every epoch; keep the most recent K).

    Iteration-cadence saves happen at DISPATCH boundaries
    (``on_dispatch_boundary``, driven by the fit loops through
    ``resilience.durable.dispatch_boundary``): there — and only there —
    params, opt-state, counters, the RNG stream, and the data-pipeline
    cursor are mutually consistent, including on the fused K-step scan
    path (where iteration_done fires per logical step against
    post-group params). With a cadence of N and K-step dispatches, the
    save lands at the first boundary where ``iteration_count`` crossed
    the next multiple of N.

    ``async_save=True`` moves serialize+write+prune onto a bounded
    background writer: the fit loop blocks only for the device→host
    snapshot. Failures surface on ``health()`` / telemetry and NEVER
    delete the predecessor checkpoint (writes are tmp-assembled; pruning
    runs only after the new step committed).
    """

    def __init__(self, path: str, save_every_n_iterations: Optional[int] = None,
                 save_every_epoch: bool = False, keep_last: int = 3,
                 async_save: bool = False, max_pending: int = 2):
        if not save_every_n_iterations and not save_every_epoch:
            raise ValueError("set save_every_n_iterations and/or "
                             "save_every_epoch")
        self.path = path
        self.every_n = save_every_n_iterations
        self.every_epoch = save_every_epoch
        self.keep_last = max(1, keep_last)
        self.writer = AsyncCheckpointWriter(max_pending=max_pending) \
            if async_save else None
        self._last_saved_step: Optional[int] = None

    # -- cadence ---------------------------------------------------------
    def on_dispatch_boundary(self, model):
        if not self.every_n:
            return
        step = model.iteration_count
        if step <= 0 or step == self._last_saved_step:
            return
        last = self._last_saved_step or 0
        if step // self.every_n > last // self.every_n:
            self._save(model, step)

    def on_epoch_end(self, model, epoch: int):
        if self.every_epoch and \
                model.iteration_count != self._last_saved_step:
            self._save(model, model.iteration_count)

    # -- save + prune ----------------------------------------------------
    def _save(self, model, step: int):
        save_checkpoint(model, self.path, step=step, writer=self.writer)
        self._last_saved_step = step
        if self.writer is not None:
            # prune runs on the writer AFTER the save committed (FIFO),
            # so a failed save can never evict the predecessor it was
            # meant to replace
            self.writer.submit(self._prune, label=f"prune@{step}",
                               is_save=False)
        else:
            self._prune()
        log.info("checkpoint saved at step %d (%s)", step, self.path)

    def _prune(self):
        steps = list_checkpoints(self.path)
        for old in steps[:-self.keep_last]:
            # eviction goes through delete_checkpoint ONLY: dir + health
            # tag leave together, manifests can never orphan
            delete_checkpoint(self.path, old)

    # -- async plumbing --------------------------------------------------
    def flush(self, timeout: Optional[float] = None) -> bool:
        """Wait for queued async saves to be durable (True on drained)."""
        if self.writer is None:
            return True
        return self.writer.flush(timeout)

    def health(self) -> Dict[str, Any]:
        """Writer health for ops surfaces; sync listeners are trivially
        healthy (a sync save failure raises in the fit loop itself)."""
        if self.writer is None:
            return {"healthy": True, "pending": 0, "failures": 0,
                    "last_error": None}
        return self.writer.health()

    def close(self):
        """Drain pending async saves at the end of every fit (fit loops
        call close_listeners from their finally). The writer restarts
        lazily on the next save, so a FaultTolerantTrainer restart keeps
        checkpointing."""
        if self.writer is not None:
            self.writer.close()
