"""Sharded checkpointing (orbax) + periodic checkpoint listener.

The DL4J-zip format (util/model_serializer.py) is the portability/parity
path (ref: util/ModelSerializer.java — configuration.json + coefficients.bin
+ updaterState.bin). This module is the TPU-native production path the
SURVEY §5 checkpoint/resume row calls for: orbax sharded save/restore of
the full training state (params + layer state + updater state + counters),
usable under multi-host pjit where every host writes only its param shards.

Also provides CheckpointListener (ref: the reference's early-stopping
LocalFileModelSaver periodic-persistence idea generalized: save every N
iterations/epochs, keep last K).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener

log = logging.getLogger(__name__)

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except Exception:  # pragma: no cover - orbax is baked into this image
    ocp = None
    _HAVE_ORBAX = False


def _net_state_tree(net) -> Dict[str, Any]:
    return {
        "params": net.params,
        "state": net.state,
        "updater_state": net.updater_state,
        "counters": {
            "iteration": np.int64(net.iteration_count),
            "epoch": np.int64(net.epoch_count),
        },
        # the dropout/noise RNG stream: without it, resume would replay
        # the interrupted epoch with different masks than a straight run
        "rng": np.asarray(net._rng) if getattr(net, "_rng", None)
        is not None else np.zeros(2, np.uint32),
    }


def _sentinel_status(net) -> Dict[str, Any]:
    """Health tag for a checkpoint: flush the net's non-finite sentinel
    accounting (resilience/sentinel.py) and report whether the state
    being saved is GOOD (no live run of bad steps), plus the score at
    save time — the divergence-rollback path uses it to rewind past
    saves taken after a FINITE loss blowup, which the bad-step flag
    alone cannot see. A save is itself a full host materialization, so
    the flush/score syncs are free here."""
    from deeplearning4j_tpu.resilience.sentinel import flush_accounting
    acct = flush_accounting(net)
    score = getattr(net, "score_value", None)
    try:
        score = None if score is None or score != score else float(score)
    except (TypeError, ValueError):
        score = None
    if acct is None:  # sentinel never ran: nothing says this is bad
        return {"good": True, "bad_steps": 0, "consecutive_bad": 0,
                "score": score}
    return {"good": acct.consecutive_bad == 0,
            "bad_steps": acct.bad_steps,
            "consecutive_bad": acct.consecutive_bad,
            "score": score}


def save_checkpoint(net, path: str, step: Optional[int] = None) -> str:
    """Write a sharded checkpoint of the network's full training state.

    Returns the checkpoint directory. Config JSON is stored alongside so
    ``load_checkpoint`` can rebuild the network object. Each step dir
    carries a ``resilience.json`` health tag (sentinel state at save
    time) so rollback (util/recovery.py) can target the last GOOD
    checkpoint instead of the newest — which may already be poisoned.
    """
    if not _HAVE_ORBAX:
        raise RuntimeError("orbax is not available")
    path = os.path.abspath(path)
    step_dir = os.path.join(path, f"step_{step}" if step is not None
                            else "latest")
    if os.path.exists(step_dir):
        shutil.rmtree(step_dir)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(step_dir, _net_state_tree(net))
    if step is not None:
        # tag lives NEXT TO the step dir (orbax owns the dir's contents)
        with open(_tag_path(path, step), "w") as f:
            json.dump(_sentinel_status(net), f)
    meta = {"model_class": type(net).__name__,
            "config": net.conf.to_json()}
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(meta, f)
    return step_dir


def restore_checkpoint(net, path: str, step: Optional[int] = None):
    """Restore training state into an initialized network (in place).
    ``path`` is the directory given to save_checkpoint."""
    if not _HAVE_ORBAX:
        raise RuntimeError("orbax is not available")
    path = os.path.abspath(path)
    if step is None:
        # CheckpointListener writes only step_N dirs; fall back to the
        # newest one when no explicit "latest" dir exists
        latest = os.path.join(path, "latest")
        if os.path.exists(latest):
            step_dir = latest
        else:
            steps = list_checkpoints(path)
            if not steps:
                raise FileNotFoundError(f"no checkpoints under {path}")
            step_dir = os.path.join(path, f"step_{steps[-1]}")
    else:
        step_dir = os.path.join(path, f"step_{step}")
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(step_dir, _net_state_tree(net))
    net.params = restored["params"]
    net.state = restored["state"]
    net.updater_state = restored["updater_state"]
    net.iteration_count = int(restored["counters"]["iteration"])
    net.epoch_count = int(restored["counters"]["epoch"])
    rng = restored.get("rng")
    if rng is not None and hasattr(net, "_rng"):
        import jax.numpy as jnp
        net._rng = jnp.asarray(rng)
    return net


def load_checkpoint(path: str, step: Optional[int] = None):
    """Rebuild the network object from the stored config, then restore."""
    path = os.path.abspath(path)
    with open(os.path.join(path, "config.json")) as f:
        meta = json.load(f)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import (
        MultiLayerConfiguration, ComputationGraphConfiguration)
    if meta["model_class"] == "MultiLayerNetwork":
        net = MultiLayerNetwork(
            MultiLayerConfiguration.from_json(meta["config"]))
    else:
        net = ComputationGraph(
            ComputationGraphConfiguration.from_json(meta["config"]))
    net.init()
    return restore_checkpoint(net, path, step)


def list_checkpoints(path: str):
    """Step numbers present under a checkpoint dir, ascending."""
    if not os.path.isdir(path):
        return []
    steps = []
    for name in os.listdir(path):
        if name.startswith("step_") and not name.endswith(".json"):
            try:
                steps.append(int(name.split("_", 1)[1]))
            except ValueError:
                continue
    return sorted(steps)


def _tag_path(path: str, step: int) -> str:
    """Canonical location of a step's resilience health tag."""
    return os.path.join(os.path.abspath(path),
                        f"step_{step}.resilience.json")


def delete_checkpoint(path: str, step: int) -> None:
    """Remove a step dir AND its health tag (the two must never drift
    apart — a stale tag would be read as the status of a future save
    reusing the step number)."""
    shutil.rmtree(os.path.join(os.path.abspath(path), f"step_{step}"),
                  ignore_errors=True)
    try:
        os.unlink(_tag_path(path, step))
    except OSError:
        pass


def checkpoint_status(path: str, step: int) -> Dict[str, Any]:
    """The resilience tag written beside a step dir; untagged (pre-
    resilience) checkpoints count as good."""
    try:
        with open(_tag_path(path, step)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"good": True}


def list_good_checkpoints(path: str):
    """Steps whose saved state the sentinel tagged GOOD, ascending."""
    return [s for s in list_checkpoints(path)
            if checkpoint_status(path, s).get("good", True)]


class CheckpointListener(TrainingListener):
    """Periodic checkpointing during fit (save every N iterations or every
    epoch; keep the most recent K)."""

    def __init__(self, path: str, save_every_n_iterations: Optional[int] = None,
                 save_every_epoch: bool = False, keep_last: int = 3):
        if not save_every_n_iterations and not save_every_epoch:
            raise ValueError("set save_every_n_iterations and/or "
                             "save_every_epoch")
        self.path = path
        self.every_n = save_every_n_iterations
        self.every_epoch = save_every_epoch
        self.keep_last = max(1, keep_last)

    def iteration_done(self, model, iteration: int, score: float):
        if self.every_n and iteration > 0 and iteration % self.every_n == 0:
            self._save(model, iteration)

    def on_epoch_end(self, model, epoch: int):
        if self.every_epoch:
            self._save(model, model.iteration_count)

    def _save(self, model, step: int):
        save_checkpoint(model, self.path, step=step)
        steps = list_checkpoints(self.path)
        for old in steps[:-self.keep_last]:
            delete_checkpoint(self.path, old)
        log.info("checkpoint saved at step %d (%s)", step, self.path)
