"""Object-store dataset/model IO.

Equivalent of deeplearning4j-aws (SURVEY §2.5): s3/uploader/S3Uploader.java,
s3/reader/S3Downloader.java (dataset/checkpoint transfer) and — in role —
the EC2 ClusterSetup provisioning (which on TPU is the platform's job:
queued resources / GKE, not framework code; documented here, not mimicked).

URLs select the backend: ``file://`` (or a bare path) works everywhere;
``s3://`` needs boto3 and ``gs://`` needs google-cloud-storage — neither is
baked into this image, so those imports are gated with a clear error.
"""

from __future__ import annotations

import os
import shutil
from typing import List
from urllib.parse import urlparse


_BACKEND_CACHE = {}


def _backend(url: str):
    """Backend per scheme, cached — client construction (boto3/GCS auth)
    must not repeat per object."""
    scheme = urlparse(url).scheme
    if scheme in _BACKEND_CACHE:
        return _BACKEND_CACHE[scheme]
    if scheme in ("", "file"):
        b = _FileBackend()
    elif scheme == "s3":
        b = _S3Backend()
    elif scheme == "gs":
        b = _GSBackend()
    else:
        raise ValueError(f"unsupported storage scheme {scheme!r} in {url!r}")
    _BACKEND_CACHE[scheme] = b
    return b


class _FileBackend:
    @staticmethod
    def _path(url: str) -> str:
        p = urlparse(url)
        return p.path if p.scheme else url

    def upload(self, local: str, url: str):
        dst = self._path(url)
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        shutil.copyfile(local, dst)

    def download(self, url: str, local: str):
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        shutil.copyfile(self._path(url), local)

    def list(self, url: str) -> List[str]:
        base = self._path(url)
        if not os.path.isdir(base):
            return []
        return sorted(os.path.join(base, f) for f in os.listdir(base))


class _S3Backend:
    def __init__(self):
        try:
            import boto3  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "s3:// URLs need boto3, which is not installed in this "
                "image; use file:// paths or install boto3") from e
        import boto3
        self._s3 = boto3.client("s3")

    @staticmethod
    def _split(url: str):
        p = urlparse(url)
        return p.netloc, p.path.lstrip("/")

    def upload(self, local: str, url: str):
        bucket, key = self._split(url)
        self._s3.upload_file(local, bucket, key)

    def download(self, url: str, local: str):
        bucket, key = self._split(url)
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        self._s3.download_file(bucket, key, local)

    def list(self, url: str) -> List[str]:
        bucket, prefix = self._split(url)
        resp = self._s3.list_objects_v2(Bucket=bucket, Prefix=prefix)
        return [f"s3://{bucket}/{o['Key']}"
                for o in resp.get("Contents", [])]


class _GSBackend:
    def __init__(self):
        try:
            from google.cloud import storage  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "gs:// URLs need google-cloud-storage, which is not "
                "installed in this image; use file:// paths") from e
        from google.cloud import storage
        self._client = storage.Client()

    @staticmethod
    def _split(url: str):
        p = urlparse(url)
        return p.netloc, p.path.lstrip("/")

    def upload(self, local: str, url: str):
        bucket, key = self._split(url)
        self._client.bucket(bucket).blob(key).upload_from_filename(local)

    def download(self, url: str, local: str):
        bucket, key = self._split(url)
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        self._client.bucket(bucket).blob(key).download_to_filename(local)

    def list(self, url: str) -> List[str]:
        bucket, prefix = self._split(url)
        return [f"gs://{bucket}/{b.name}"
                for b in self._client.list_blobs(bucket, prefix=prefix)]


class Uploader:
    """ref: S3Uploader.java — push local files to object storage."""

    def upload(self, local_path: str, url: str) -> None:
        _backend(url).upload(local_path, url)

    def upload_directory(self, local_dir: str, url_prefix: str) -> int:
        n = 0
        for root, _dirs, files in os.walk(local_dir):
            for f in files:
                local = os.path.join(root, f)
                rel = os.path.relpath(local, local_dir)
                self.upload(local, url_prefix.rstrip("/") + "/" + rel)
                n += 1
        return n


class Downloader:
    """ref: S3Downloader.java — fetch remote objects to local paths."""

    def download(self, url: str, local_path: str) -> str:
        _backend(url).download(url, local_path)
        return local_path

    def list(self, url_prefix: str) -> List[str]:
        return _backend(url_prefix).list(url_prefix)
