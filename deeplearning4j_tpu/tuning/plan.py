"""Execution-plan resolution: the first slice of the step-compiler seam.

``net.fit(..., execution_plan="auto"|"fused"|"xla")`` is the user-facing
switch for the fused training kernels — what ``BENCH_FUSE`` used to gate
for the bench only, lifted behind the fit loops all seven step builders
share (MultiLayerNetwork, ComputationGraph, ParallelWrapper). Resolution
happens ONCE per fit() entry, host-side, from explicit inputs (never
from env vars inside a step builder — the retrace-on-flip class of bug
tpulint's recompile-hazard rule now flags):

- ``"xla"``   — the unfused graph (the measured-best static default,
  PERF.md round 3);
- ``"fused"`` — every eligible bottleneck chain runs the Pallas kernel
  cascade (nn/layers/bottleneck.py); the space-to-depth stem
  (nn/layers/stem.py) additionally engages iff the crossover store
  says it wins (its expected ceiling is ~2% — only a measurement may
  turn it on);
- ``"auto"``  — per shape from the measured crossover store
  (tuning/crossover.py): each candidate block (and the stem) runs the
  kernel only where a calibrated, platform-matching entry says the
  kernel wins. Uncalibrated (or mismatched) entries resolve to the XLA
  plan — "auto" on a fresh machine is exactly "xla" until a live
  window calibrates it.

``set_fusion`` applies the resolved plan with change detection, so
re-resolving the same plan on every fit() call never rebuilds jitted
steps: zero retraces after warmup holds with the plan layer on.
"""

from __future__ import annotations

import logging
from typing import Optional

from deeplearning4j_tpu.tuning.crossover import (
    KernelCrossoverStore, bottleneck_fingerprint, decode_fingerprint,
    default_store, quant_fingerprint, stem_fingerprint)

log = logging.getLogger(__name__)

EXECUTION_PLANS = ("auto", "fused", "xla")


def _net_dtype(net) -> str:
    return getattr(net.conf, "dtype", None) or "float32"


def _block_key(group: dict, dtype: str) -> str:
    return bottleneck_fingerprint(
        group["h"], group["w"], group["cin"], group["cmid"],
        group["cout"], group.get("stride", 1), "conv_skip" in group,
        dtype)


def _stem_key(group: dict, dtype: str) -> str:
    return stem_fingerprint(group["h"], group["w"], group["cin"],
                            group["cout"], dtype)


def apply_execution_plan(net, plan: Optional[str], *,
                         store: Optional[KernelCrossoverStore] = None
                         ) -> Optional[dict]:
    """Resolve ``plan`` onto ``net``'s step builders. Returns the
    resolution record ({plan, level, blocks, stem, keys}) for
    bench/test introspection, or None when plan is None (leave the
    net's current plan untouched — fit() without the kwarg must not
    reset an explicitly set_fusion'd net)."""
    if plan is None:
        return None
    if plan not in EXECUTION_PLANS:
        raise ValueError(
            f"execution_plan must be one of {EXECUTION_PLANS}, got "
            f"{plan!r}")
    if not hasattr(net, "set_fusion"):
        # sequential nets (MultiLayerNetwork): the plan seam exists —
        # the kwarg validates and resolves — but the fused chains are
        # residual-graph features, so every plan runs the XLA step.
        # Bit-exactness of "fused" vs "xla" here is definitional.
        if plan == "fused":
            log.debug("execution_plan='fused' on %s: no fusable graph "
                      "chains — running the XLA plan",
                      type(net).__name__)
        return {"plan": plan, "level": False, "blocks": 0,
                "stem": False, "keys": {}}
    if plan == "xla":
        net.set_fusion(False)
        return {"plan": plan, "level": False, "blocks": 0,
                "stem": False, "keys": {}}
    store = default_store() if store is None else store
    dtype = _net_dtype(net)
    bcands, scands = net.fusion_candidates()
    keys = {}
    if plan == "fused":
        chosen = set(bcands)
        only = None
    else:
        chosen = set()
        for name, grp in bcands.items():
            key = _block_key(grp, dtype)
            choice = store.choose(key, default="fallback")
            keys[name] = {"key": key, "choice": choice}
            if choice == "kernel":
                chosen.add(name)
        only = frozenset(chosen)
    # the stem is store-gated under BOTH fused and auto: its expected
    # win is ~2% of step time and the round-3 lesson (a pallas boundary
    # can cost more than it saves) applies — only a measured verdict
    # may engage it (PERF.md round 5)
    stem_on = False
    for name, grp in scands.items():
        key = _stem_key(grp, dtype)
        choice = store.choose(key, default="fallback")
        keys[name] = {"key": key, "choice": choice}
        stem_on = stem_on or choice == "kernel"
    if not chosen and not stem_on:
        net.set_fusion(False)
        return {"plan": plan, "level": False, "blocks": 0,
                "stem": False, "keys": keys}
    net.set_fusion("bottleneck", stem=stem_on, only=only)
    return {"plan": plan, "level": "bottleneck", "blocks": len(chosen),
            "stem": stem_on, "keys": keys}


def resolve_decode_impl(eligible: bool, key: str, *,
                        store: Optional[KernelCrossoverStore] = None
                        ) -> str:
    """The serving twin: ``decode_impl="auto"`` resolution for the
    paged-attention kernel. ``eligible`` is the STATIC gate the engine
    already computes (``paged_attention_supported`` shapes + a TPU
    backend) — eligibility says the kernel *can* run; the store says
    whether it *should*. Uncalibrated behavior is unchanged: eligible →
    the kernel (the PR 10 default), ineligible → the XLA fallback,
    regardless of what any store says."""
    if not eligible:
        return "xla"
    store = default_store() if store is None else store
    return ("xla" if (store.choose(key, default="kernel")
                      == "fallback") else "pallas")


def decode_key_for_engine(page_size: int, head_dim: int,
                          n_kv_heads: int, cache_length: int,
                          dtype) -> str:
    return decode_fingerprint(page_size, head_dim, n_kv_heads,
                              cache_length, dtype)


def resolve_kv_dtype(eligible: bool, key: str, *,
                     store: Optional[KernelCrossoverStore] = None
                     ) -> str:
    """``kv_dtype="auto"`` resolution for the int8 KV page pool.
    ``eligible`` is the engine's static gate (direct paged decode, no
    recurrent h/c state) — eligibility says int8 *can* serve this net;
    only a measurement says it *should*. Uncalibrated (or platform-
    mismatched — the store's lookup already refuses a CPU-calibrated
    entry on TPU) runs stay on bf16: quantization is an accuracy
    trade, so unlike the decode-impl default it must be OPTED INTO by
    a calibrated win ("kernel" = the int8 leg measured faster)."""
    if not eligible:
        return "bf16"
    store = default_store() if store is None else store
    return ("int8" if store.choose(key, default="fallback") == "kernel"
            else "bf16")


def quant_key_for_engine(page_size: int, head_dim: int,
                         n_kv_heads: int, cache_length: int,
                         dtype) -> str:
    return quant_fingerprint(page_size, head_dim, n_kv_heads,
                             cache_length, dtype)


# ---------------------------------------------------------------------------
# per-step HBM-traffic model (tokens of truth for the bench record)
# ---------------------------------------------------------------------------

#: tensor traversals per STAGE OUTPUT per train step, from the
#: bottleneck.py accounting: XLA plan — conv write, BN stats read,
#: normalize read+write, next-conv read fwd; stats/elementwise re-reads
#: in backward (~14 per bottleneck ≈ 4.7 per stage tensor); fused plan —
#: 1W+1R fwd, 3R+1W bwd per stage (~8 per bottleneck ≈ 2.7 per stage).
_XLA_TRAVERSALS = 14 / 3.0
_FUSED_TRAVERSALS = 8 / 3.0
#: stem: XLA — conv W, stats R, normalize R+W, pool R fwd + ~3 bwd
#: re-reads of the 112²×64 activation; fused — conv W + one fused
#: output-stage R fwd, recompute R + dy W/R bwd (stem.py docstring)
_XLA_STEM_TRAVERSALS = 8.0
_FUSED_STEM_TRAVERSALS = 4.0


def modeled_train_step_traffic(net, batch_size: int) -> dict:
    """Crude per-step HBM-traffic model over the net's fusable chains:
    bytes moved across the BN/elementwise tensors under the XLA vs the
    fused plan. Not a simulator — a consistent accounting that lets a
    bench record say how much traffic the plan REMOVES, priced against
    the measured img/s (PERF.md profile: the model is HBM-bound on
    exactly these tensors)."""
    bpe = 2 if _net_dtype(net) in ("bfloat16", "bf16") else 4
    if not hasattr(net, "fusion_candidates"):
        return {"xla_bytes": 0, "fused_bytes": 0, "blocks": 0,
                "stems": 0}
    bcands, scands = net.fusion_candidates()
    xla = fused = 0.0
    for grp in bcands.values():
        s = grp.get("stride", 1)
        ho, wo = grp["h"] // s, grp["w"] // s
        stage = batch_size * ho * wo * bpe
        tensors = stage * (grp["cmid"] * 2 + grp["cout"]
                           * (2 if "conv_skip" in grp else 1))
        xla += tensors * _XLA_TRAVERSALS
        fused += tensors * _FUSED_TRAVERSALS
    for grp in scands.values():
        ho, wo = (grp["h"] - 1) // 2 + 1, (grp["w"] - 1) // 2 + 1
        y = batch_size * ho * wo * grp["cout"] * bpe
        xla += y * _XLA_STEM_TRAVERSALS
        fused += y * _FUSED_STEM_TRAVERSALS
    return {"xla_bytes": int(xla), "fused_bytes": int(fused),
            "blocks": len(bcands), "stems": len(scands)}
