"""Per-shape kernel-crossover store: measured kernel-vs-fallback timings,
persisted like TPULINT_BASELINE.

Every hand kernel in this repo ships with an equal-semantics fallback
(the XLA graph), and the round-3 lesson (PERF.md) is that which side
wins is a property of the SHAPE and the HARDWARE, not of the kernel:
``pallas_call`` boundaries can cost more than the traffic they save.
The store turns that into data:

- an **entry** is one paired measurement: ``kernel_ms`` vs
  ``fallback_ms`` for a fingerprinted (domain, shape, dtype) point,
  stamped with the platform + device kind it was measured on and the
  implementation revision of the kernel it timed;
- ``choose(key)`` is the hot-path read: "auto" plan/impl resolution
  asks it which side to run. A missing, platform-mismatched, or
  stale-revision entry yields the caller's default (the current static
  behavior) — calibration can only ever *refine* the defaults, never
  silently change an uncalibrated run;
- ``record``/``calibrate`` ratchet measurements in (running mean over
  samples) and persist atomically, the baseline pattern: one live TPU
  window writes ``KERNEL_CROSSOVER.json`` and every later process —
  including ones with no TPU — resolves "auto" from it.

Telemetry: ``dl4jtpu_autotune_decisions_total{domain,choice}`` counts
every ``choose`` (choice = kernel | fallback | default) and
``dl4jtpu_autotune_calibrations_total{domain,choice}`` every recorded
measurement (choice = the measured winner), so a run's records show
which plans the store actually picked.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

log = logging.getLogger(__name__)

CROSSOVER_NAME = "KERNEL_CROSSOVER.json"
CROSSOVER_VERSION = 1

#: implementation revision per kernel domain. Bump when the kernel (or
#: its fallback) changes enough that old timings no longer describe it —
#: load() prunes entries recorded against another revision (the
#: stale-entry ratchet: a rewritten kernel re-earns its calibration).
IMPL_REVS: Dict[str, int] = {
    "train_bottleneck": 1,   # nn/layers/bottleneck.py fused chain
    "train_stem": 1,         # nn/layers/stem.py space-to-depth stem
    "paged_decode": 1,       # serving/paged_kernel.py vs XLA fallback
    "paged_decode_quant": 1,  # int8 KV pool (serving/quant.py) vs bf16
}

AUTOTUNE_DECISIONS = "dl4jtpu_autotune_decisions_total"
AUTOTUNE_CALIBRATIONS = "dl4jtpu_autotune_calibrations_total"


def _count(metric: str, domain: str, choice: str) -> None:
    """Best-effort telemetry — the decision beats the counter."""
    try:
        from deeplearning4j_tpu.monitoring.metrics import global_registry
        global_registry().counter(
            metric, "kernel-crossover autotune events",
            ("domain", "choice")).inc(domain=domain, choice=choice)
    except Exception:  # noqa: BLE001 — telemetry must not cost a decision
        pass


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_path() -> str:
    """cwd first (a run can carry a local store), then the repo root
    where the committed store lives — the TPULINT_BASELINE resolution
    order."""
    for cand in (os.path.join(os.getcwd(), CROSSOVER_NAME),
                 os.path.join(_repo_root(), CROSSOVER_NAME)):
        if os.path.exists(cand):
            return cand
    return os.path.join(_repo_root(), CROSSOVER_NAME)


def fingerprint(domain: str, dtype: Any = None, **dims: Any) -> str:
    """Stable human-readable entry key: ``domain|k=v,...|dtype``. Dims
    sort by name so call sites can't produce two spellings of one shape;
    the batch dimension is deliberately NOT part of the key (entries
    describe the per-shape crossover at the calibration batch — keys
    must survive the caller's batch choice, PERF.md round-3 A/Bs showed
    the verdict stable across B=64..256)."""
    dt = "any" if dtype is None else str(dtype)
    dt = {"bfloat16": "bf16", "float32": "f32", "float64": "f64"}.get(dt, dt)
    body = ",".join(f"{k}={dims[k]}" for k in sorted(dims))
    return f"{domain}|{body}|{dt}"


def bottleneck_fingerprint(h: int, w: int, c_in: int, c_mid: int,
                           c_out: int, stride: int, has_skip: bool,
                           dtype: Any) -> str:
    return fingerprint("train_bottleneck", dtype, h=int(h), w=int(w),
                       cin=int(c_in), cmid=int(c_mid), cout=int(c_out),
                       stride=int(stride), skip=int(bool(has_skip)))


def stem_fingerprint(h: int, w: int, c_in: int, c_out: int,
                     dtype: Any) -> str:
    return fingerprint("train_stem", dtype, h=int(h), w=int(w),
                       cin=int(c_in), cout=int(c_out))


def decode_fingerprint(page_size: int, head_dim: int, n_kv_heads: int,
                       cache_length: int, dtype: Any) -> str:
    return fingerprint("paged_decode", dtype, ps=int(page_size),
                       d=int(head_dim), hkv=int(n_kv_heads),
                       L=int(cache_length))


def quant_fingerprint(page_size: int, head_dim: int, n_kv_heads: int,
                      cache_length: int, dtype: Any) -> str:
    """int8-vs-bf16 KV-pool crossover key (``kv_dtype="auto"``):
    kernel_ms records the int8 leg's timing, fallback_ms the bf16
    leg's, so ``winner() == "kernel"`` means the quantized pool won on
    this shape/hardware. dtype is the NET's native dtype (the bf16
    side's storage — the int8 side is implied by the domain)."""
    return fingerprint("paged_decode_quant", dtype, ps=int(page_size),
                       d=int(head_dim), hkv=int(n_kv_heads),
                       L=int(cache_length))


def winner(entry: dict) -> str:
    """The ONE place the kernel-vs-fallback verdict rule lives:
    'kernel' iff the measured kernel time beats the fallback. choose(),
    record() telemetry, and every bench record derive from this."""
    return ("kernel" if entry.get("kernel_ms", float("inf"))
            < entry.get("fallback_ms", 0.0) else "fallback")


def _current_platform() -> str:
    try:
        import jax
        return jax.default_backend()
    except Exception:  # noqa: BLE001 — a store read must not need a backend
        return "unknown"


def _current_device_kind() -> str:
    try:
        import jax
        return getattr(jax.devices()[0], "device_kind", "unknown")
    except Exception:  # noqa: BLE001
        return "unknown"


class KernelCrossoverStore:
    """Load → consult → ratchet (the TPULINT_BASELINE lifecycle) for
    measured kernel-vs-fallback timings. Thread-safe: ``choose`` is on
    serving/fit resolution paths."""

    def __init__(self, path: Optional[str] = None,
                 entries: Optional[Dict[str, dict]] = None):
        self.path = path or default_path()
        self._entries: Dict[str, dict] = dict(entries or {})
        self._lock = threading.Lock()
        self._warned: set = set()

    # -- persistence ---------------------------------------------------
    @classmethod
    def load(cls, path: Optional[str] = None) -> "KernelCrossoverStore":
        path = path or default_path()
        entries: Dict[str, dict] = {}
        if os.path.exists(path):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    data = json.load(f)
                entries = dict(data.get("entries", {}))
            except (OSError, ValueError) as e:
                # a torn/garbled store must not take down a fit loop —
                # behave as uncalibrated and say why
                log.warning("kernel-crossover store %s unreadable (%s): "
                            "running uncalibrated", path, e)
                entries = {}
        store = cls(path=path, entries=entries)
        stale = store.prune_stale()
        if stale:
            log.info("kernel-crossover store: pruned %d stale entries "
                     "(impl revision changed): %s", len(stale),
                     ", ".join(sorted(stale)[:5]))
        return store

    def save(self, path: Optional[str] = None) -> str:
        """Atomic write (tmp + rename) — a crash mid-save must not leave
        future runs resolving from a torn store."""
        path = path or self.path
        with self._lock:
            payload = {"version": CROSSOVER_VERSION,
                       "tool": "kernel-crossover",
                       "entries": dict(sorted(self._entries.items()))}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    # -- accounting ----------------------------------------------------
    def __len__(self) -> int:
        return len(self._entries)

    def entries(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def prune_stale(self) -> list:
        """Drop entries whose recorded ``impl_rev`` no longer matches the
        current kernel revision for their domain (IMPL_REVS) — old
        timings describe a kernel that no longer exists."""
        dropped = []
        with self._lock:
            for key in list(self._entries):
                domain = key.split("|", 1)[0]
                rev = self._entries[key].get("impl_rev")
                if rev != IMPL_REVS.get(domain, rev):
                    dropped.append(key)
                    del self._entries[key]
        return dropped

    # -- consult -------------------------------------------------------
    def lookup(self, key: str) -> Optional[dict]:
        """The entry for ``key`` iff it was measured on THIS platform +
        device kind; a mismatched entry is ignored with a (once-per-key)
        warning — a CPU-calibrated store must never decide a TPU run,
        and v5e timings don't transfer to v4."""
        with self._lock:
            e = self._entries.get(key)
        if e is None:
            return None
        plat, kind = _current_platform(), _current_device_kind()
        if e.get("platform") != plat or (
                e.get("device_kind") not in (kind, "any")):
            if key not in self._warned:
                self._warned.add(key)
                log.warning(
                    "kernel-crossover entry %s was calibrated on %s/%s "
                    "but this run is %s/%s — ignoring it (recalibrate "
                    "on this hardware)", key, e.get("platform"),
                    e.get("device_kind"), plat, kind)
            return None
        return dict(e)

    def choose(self, key: str, default: Optional[str] = None
               ) -> Optional[str]:
        """'kernel' or 'fallback' from a usable calibrated entry, else
        ``default`` (the caller's static behavior — uncalibrated runs
        are unchanged by construction). Counts the decision."""
        domain = key.split("|", 1)[0]
        e = self.lookup(key)
        if e is None or not e.get("kernel_ms") or not e.get("fallback_ms"):
            _count(AUTOTUNE_DECISIONS, domain, "default")
            return default
        choice = winner(e)
        _count(AUTOTUNE_DECISIONS, domain, choice)
        return choice

    # -- ratchet -------------------------------------------------------
    def record(self, key: str, kernel_ms: float, fallback_ms: float, *,
               platform: Optional[str] = None,
               device_kind: Optional[str] = None,
               source: str = "record") -> dict:
        """Merge one paired measurement (running mean over samples —
        repeated calibrations ratchet toward the stable verdict instead
        of thrashing on run-to-run spread). Returns the merged entry."""
        kernel_ms = float(kernel_ms)
        fallback_ms = float(fallback_ms)
        if kernel_ms <= 0 or fallback_ms <= 0:
            raise ValueError(
                f"timings must be positive, got kernel={kernel_ms} "
                f"fallback={fallback_ms} for {key}")
        domain = key.split("|", 1)[0]
        plat = platform or _current_platform()
        kind = device_kind or _current_device_kind()
        with self._lock:
            e = self._entries.get(key)
            if (e is None or e.get("platform") != plat
                    or e.get("device_kind") != kind
                    or e.get("impl_rev") != IMPL_REVS.get(domain)):
                # fresh hardware or fresh kernel revision: start over
                e = {"kernel_ms": kernel_ms, "fallback_ms": fallback_ms,
                     "platform": plat, "device_kind": kind,
                     "impl_rev": IMPL_REVS.get(domain), "samples": 1,
                     "source": source}
            else:
                n = int(e.get("samples", 1))
                e = dict(e)
                e["kernel_ms"] = round(
                    (e["kernel_ms"] * n + kernel_ms) / (n + 1), 6)
                e["fallback_ms"] = round(
                    (e["fallback_ms"] * n + fallback_ms) / (n + 1), 6)
                e["samples"] = n + 1
                e["source"] = source
            self._entries[key] = e
        _count(AUTOTUNE_CALIBRATIONS, domain, winner(e))
        return dict(e)

    # -- measurement harness ------------------------------------------
    def calibrate(self, key: str, kernel_fn: Callable[[], Any],
                  fallback_fn: Callable[[], Any], *, warmup: int = 2,
                  iters: int = 5, persist: bool = False) -> dict:
        """Time the two thunks back to back (same-moment paired
        comparison — the only kind run-to-run spread permits, PERF.md)
        and record the result. Thunks must return their device output;
        the harness blocks on it so async dispatch can't flatter either
        side. ``persist=True`` saves the store after recording."""
        k_ms = _time_thunk(kernel_fn, warmup, iters)
        f_ms = _time_thunk(fallback_fn, warmup, iters)
        entry = self.record(key, k_ms, f_ms, source="calibrate")
        if persist:
            self.save()
        return entry


def _time_thunk(fn: Callable[[], Any], warmup: int, iters: int) -> float:
    """Mean ms per call, synced via block_until_ready on the thunk's
    output (tests monkeypatch this to decouple the harness from wall
    time)."""
    import jax
    out = None
    for _ in range(max(0, warmup)):
        out = fn()
    if out is not None:
        jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(max(1, iters)):
        out = fn()
    if out is not None:
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) * 1000.0 / max(1, iters)


_default_store: Optional[KernelCrossoverStore] = None
_default_lock = threading.Lock()


def default_store() -> KernelCrossoverStore:
    """Process-wide store singleton, loaded from the committed
    KERNEL_CROSSOVER.json on first use (resolution paths must not
    re-read the file per fit/engine construction)."""
    global _default_store
    with _default_lock:
        if _default_store is None:
            _default_store = KernelCrossoverStore.load()
        return _default_store


def reset_default_store(store: Optional[KernelCrossoverStore] = None
                        ) -> None:
    """Swap (or clear) the process singleton — tests and calibration
    runs point resolution at a scratch store."""
    global _default_store
    with _default_lock:
        _default_store = store
