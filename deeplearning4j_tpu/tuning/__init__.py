"""Measured kernel-crossover autotuning (ROADMAP item 1, "learned
crossover").

The round-3 lesson is the charter: a hand-fused Pallas kernel can LOSE
to XLA's own fusion because the ``pallas_call`` boundary costs more than
the saved traffic at some shapes (PERF.md round 3: the bn→act→conv plan
measured 20-25% slower; round 10: the paged-decode kernel's win depends
on context length). Static gates cannot know which side wins — only a
measurement on the target hardware can. This package makes that
measurement a persistent, consultable artifact:

- ``crossover.KernelCrossoverStore`` records paired kernel-vs-fallback
  timings keyed by a stable shape/dtype/impl fingerprint and persists
  them to a committed ``KERNEL_CROSSOVER.json`` (the TPULINT_BASELINE
  pattern: load → consult → ratchet), so ONE live TPU window calibrates
  every future run. Entries carry platform + device kind — a
  CPU-calibrated entry never decides a TPU run.
- ``plan`` resolves user-facing execution plans
  (``net.fit(..., execution_plan="auto"|"fused"|"xla")``) against the
  store: the first slice of the step-compiler seam (ROADMAP item 5) —
  kernels become a composable plan layer on the step builders instead
  of a bench-only env flag.
- ``calibrate`` is the explicit measurement harness that fills the
  store from a live window (per-shape paired timings of the fused
  training kernels and the paged-decode read path).
"""

from deeplearning4j_tpu.tuning.crossover import (  # noqa: F401
    CROSSOVER_NAME, IMPL_REVS, KernelCrossoverStore, decode_fingerprint,
    default_store, fingerprint, reset_default_store, stem_fingerprint,
    bottleneck_fingerprint, winner)
from deeplearning4j_tpu.tuning.plan import (  # noqa: F401
    EXECUTION_PLANS, apply_execution_plan, modeled_train_step_traffic,
    resolve_decode_impl)
from deeplearning4j_tpu.tuning.calibrate import (  # noqa: F401
    calibrate_training_kernels)

__all__ = [
    "CROSSOVER_NAME", "EXECUTION_PLANS", "IMPL_REVS",
    "KernelCrossoverStore", "apply_execution_plan",
    "bottleneck_fingerprint", "calibrate_training_kernels",
    "decode_fingerprint", "default_store", "fingerprint",
    "modeled_train_step_traffic", "reset_default_store",
    "resolve_decode_impl", "stem_fingerprint", "winner",
]
