"""The explicit calibration harness: fill the crossover store from a
live window.

``calibrate_training_kernels(net)`` walks the net's fusion candidates
(every distinct bottleneck-block shape + the stem), builds
representative tensors at each shape, and times the fused kernel chain
against its exact-semantics XLA fallback — fwd+bwd through jit, synced
— recording each paired measurement into the store. One call on a live
TPU window writes the entries every later ``execution_plan="auto"``
(and ``decode_impl="auto"``) resolution reads; PERF.md lists the exact
commands for the next window.

On a non-TPU backend the kernels run in interpret mode — the timings
are meaningless as TPU predictions, which is exactly why store entries
carry platform + device kind and a CPU-calibrated entry never decides
a TPU run. Calibrating on CPU is still useful in tests (it exercises
the full record/resolve loop) and harmless in production (the entries
only ever match an identical platform).
"""

from __future__ import annotations

import logging
from typing import Optional

from deeplearning4j_tpu.tuning.crossover import (
    KernelCrossoverStore, default_store)
from deeplearning4j_tpu.tuning.plan import (
    _block_key, _net_dtype, _stem_key)

log = logging.getLogger(__name__)


def _jdtype(dtype: str):
    import jax.numpy as jnp
    return jnp.bfloat16 if dtype in ("bfloat16", "bf16") else jnp.float32


def calibrate_training_kernels(
        net, *, batch_size: int = 8,
        store: Optional[KernelCrossoverStore] = None,
        warmup: int = 1, iters: int = 3, persist: bool = False,
        include_stem: bool = True) -> dict:
    """Measure kernel-vs-fallback for every distinct fusable shape on
    ``net`` and record the results. Returns {key: entry}."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.nn.layers.bottleneck import (
        BnParams, fused_bottleneck, reference_bottleneck)
    from deeplearning4j_tpu.nn.layers.stem import (
        fused_stem, reference_stem)

    # the store is this harness's OUTPUT sink (measurements are written
    # into it), not a knob baked into a cached trace: every timed jit
    # here is built fresh per call and discarded
    # tpulint: disable=jit-key-drift
    store = default_store() if store is None else store
    dtype = _net_dtype(net)
    jdt = _jdtype(dtype)
    interpret = jax.default_backend() != "tpu"
    if not hasattr(net, "fusion_candidates"):
        return {}
    bcands, scands = net.fusion_candidates()
    rng = np.random.default_rng(0)

    def arr(*shape, scale=1.0):
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32) * scale, jdt)

    def bn_of(c):
        return BnParams(gamma=jnp.ones((c,), jdt),
                        beta=jnp.zeros((c,), jdt),
                        running_mean=jnp.zeros((c,), jnp.float32),
                        running_var=jnp.ones((c,), jnp.float32))

    results = {}
    seen = set()
    for grp in bcands.values():
        key = _block_key(grp, dtype)
        if key in seen:
            continue
        seen.add(key)
        h, w, cin = grp["h"], grp["w"], grp["cin"]
        cmid, cout = grp["cmid"], grp["cout"]
        stride = grp.get("stride", 1)
        has_skip = "conv_skip" in grp
        x = arr(batch_size, h, w, cin)
        wa = arr(cin, cmid, scale=0.1)
        wb = arr(9, cmid, cmid, scale=0.05)
        wc = arr(cmid, cout, scale=0.1)
        ws = arr(cin, cout, scale=0.1) if has_skip else None
        bns = (bn_of(cmid), bn_of(cmid), bn_of(cout))
        bn_s = bn_of(cout) if has_skip else None

        def loss(fn, kw):
            def f(args):
                out, _ = fn(args[0], args[1], bns[0], args[2], bns[1],
                            args[3], bns[2], w_skip=args[4],
                            bn_skip=bn_s, stride=stride, train=True,
                            **kw)
                return jnp.sum(out.astype(jnp.float32))
            return jax.jit(jax.grad(f))

        gk = loss(fused_bottleneck, {"interpret": interpret})
        gf = loss(reference_bottleneck, {})
        args = (x, wa, wb, wc, ws)
        results[key] = store.calibrate(
            key, lambda: gk(args), lambda: gf(args),
            warmup=warmup, iters=iters)
        log.info("calibrated %s: kernel %.3fms vs fallback %.3fms",
                 key, results[key]["kernel_ms"],
                 results[key]["fallback_ms"])
    if include_stem:
        for grp in scands.values():
            key = _stem_key(grp, dtype)
            if key in seen:
                continue
            seen.add(key)
            x = arr(batch_size, grp["h"], grp["w"], grp["cin"])
            w7 = arr(grp["cout"], grp["cin"], 7, 7, scale=0.1)
            bnp = bn_of(grp["cout"])

            def sloss(fn, kw):
                def f(args):
                    out, _ = fn(args[0], args[1], bnp, train=True, **kw)
                    return jnp.sum(out.astype(jnp.float32))
                return jax.jit(jax.grad(f))

            gk = sloss(fused_stem, {"interpret": interpret})
            gf = sloss(reference_stem, {})
            args = (x, w7)
            results[key] = store.calibrate(
                key, lambda: gk(args), lambda: gf(args),
                warmup=warmup, iters=iters)
            log.info("calibrated %s: kernel %.3fms vs fallback %.3fms",
                     key, results[key]["kernel_ms"],
                     results[key]["fallback_ms"])
    if persist and results:
        try:
            store.save()
        except OSError as e:
            # a read-only install dir must not discard a completed
            # calibration run — the measurements are in the returned
            # (and in-memory) store either way
            log.warning("kernel-crossover store not persisted to %s "
                        "(%s); pass a writable path via "
                        "KernelCrossoverStore(path=...)", store.path, e)
    return results
