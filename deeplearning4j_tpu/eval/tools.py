"""EvaluationTools: self-contained HTML exports of evaluation results.

Equivalent of deeplearning4j-core evaluation/EvaluationTools.java:329
(exportRocChartsToHtmlFile, exportConfusionMatrixToHtmlFile) — renders
ROC curves and confusion matrices as standalone HTML (inline SVG, no
external assets; the reference embeds its ui-components JS the same way).
"""

from __future__ import annotations

import html
from typing import Optional, Sequence

import numpy as np

_STYLE = """
body{font-family:sans-serif;margin:24px;color:#222}
h1{font-size:20px} h2{font-size:16px;margin-top:28px}
table{border-collapse:collapse;font-size:13px;margin:10px 0}
td,th{border:1px solid #ccc;padding:4px 10px;text-align:right}
th{background:#f0f0f0}
td.diag{background:#e3f2e3;font-weight:bold}
.meta{color:#555;font-size:13px}
"""


def _svg_roc(points: Sequence[tuple], auc: float, title: str,
             size: int = 380) -> str:
    """Inline-SVG ROC curve from (fpr, tpr) points."""
    pad = 40
    w = h = size
    inner = size - 2 * pad

    def X(x):
        return pad + x * inner

    def Y(y):
        return h - pad - y * inner

    pts = sorted(points)
    path = " ".join(f"{'M' if i == 0 else 'L'}{X(p[0]):.1f},{Y(p[1]):.1f}"
                    for i, p in enumerate(pts))
    return f"""<svg width="{w}" height="{h}" viewBox="0 0 {w} {h}">
<rect x="{pad}" y="{pad}" width="{inner}" height="{inner}"
 fill="#fff" stroke="#999"/>
<line x1="{X(0)}" y1="{Y(0)}" x2="{X(1)}" y2="{Y(1)}"
 stroke="#bbb" stroke-dasharray="4"/>
<path d="{path}" fill="none" stroke="#1976d2" stroke-width="2"/>
<text x="{w/2}" y="16" text-anchor="middle" font-size="13">{html.escape(title)}
 (AUC={auc:.4f})</text>
<text x="{w/2}" y="{h-6}" text-anchor="middle" font-size="11">FPR</text>
<text x="12" y="{h/2}" font-size="11" transform="rotate(-90 12 {h/2})">TPR</text>
<text x="{pad}" y="{h-pad+14}" font-size="10">0</text>
<text x="{X(1)}" y="{h-pad+14}" font-size="10">1</text>
<text x="{pad-14}" y="{Y(1)+4}" font-size="10">1</text>
</svg>"""


def roc_chart_html(roc, title: str = "ROC") -> str:
    """HTML fragment for one fitted ROC object (eval/roc.py)."""
    _, fpr, tpr = roc.get_roc_curve()
    return _svg_roc(list(zip(fpr, tpr)), roc.calculate_auc(), title)


def confusion_matrix_html(evaluation, class_names: Optional[Sequence[str]]
                          = None) -> str:
    """HTML fragment: confusion matrix table + summary stats."""
    cm = evaluation.confusion.matrix
    n = cm.shape[0]
    names = class_names or [str(i) for i in range(n)]
    rows = ["<table><tr><th>actual \\ predicted</th>" +
            "".join(f"<th>{html.escape(str(names[j]))}</th>"
                    for j in range(n)) + "</tr>"]
    for i in range(n):
        cells = "".join(
            f'<td class="{"diag" if i == j else ""}">{int(cm[i, j])}</td>'
            for j in range(n))
        rows.append(f"<tr><th>{html.escape(str(names[i]))}</th>{cells}</tr>")
    rows.append("</table>")
    stats = (f'<p class="meta">accuracy {evaluation.accuracy():.4f} · '
             f'precision {evaluation.precision():.4f} · '
             f'recall {evaluation.recall():.4f} · '
             f'F1 {evaluation.f1():.4f}</p>')
    return "".join(rows) + stats


def export_roc_charts_to_html_file(path: str, rocs, titles=None) -> None:
    """ref: EvaluationTools.exportRocChartsToHtmlFile. ``rocs`` is one ROC
    or a list (e.g. ROCMultiClass per-class curves)."""
    if not isinstance(rocs, (list, tuple)):
        rocs = [rocs]
    titles = list(titles) if titles else []
    titles += [f"class {i}" for i in range(len(titles), len(rocs))]
    body = "".join(roc_chart_html(r, t) for r, t in zip(rocs, titles))
    _write(path, "ROC", body)


def export_evaluation_to_html_file(path: str, evaluation,
                                   class_names=None) -> None:
    """ref: EvaluationTools confusion-matrix export."""
    _write(path, "Evaluation", confusion_matrix_html(evaluation,
                                                     class_names))


def _write(path: str, title: str, body: str) -> None:
    with open(path, "w") as f:
        f.write(f"<!DOCTYPE html><html><head><title>{title}</title>"
                f"<style>{_STYLE}</style></head><body><h1>{title}</h1>"
                f"{body}</body></html>")


def evaluation_report_components(evaluation=None, rocs=None, roc_titles=None,
                                 scores=None, class_names=None):
    """Build a ui-components report for evaluation results (the DSL from
    ui/components.py — ref: the reference renders its eval exports through
    the ui-components chart classes). Returns a list of Components; pass
    to ui.components.render_page for a standalone HTML page.

    evaluation: eval/Evaluation -> confusion table + per-class F1 bars
    rocs: ROC or list of ROCs -> one scatter/line chart per curve
    scores: [(iteration, score)] -> training-score line chart
    """
    from deeplearning4j_tpu.ui.components import (
        ChartHorizontalBar, ChartLine, ComponentTable, ComponentText,
    )
    comps = []
    if scores:
        chart = ChartLine("Training score")
        chart.add_series("score", [s[0] for s in scores],
                         [s[1] for s in scores])
        comps.append(chart)
    if evaluation is not None:
        cm = evaluation.confusion.matrix
        n = cm.shape[0]
        names = [str(c) for c in (class_names or range(n))]
        comps.append(ComponentTable(
            header=["actual \\ predicted"] + names,
            rows=[[names[i]] + [int(cm[i, j]) for j in range(n)]
                  for i in range(n)],
            title="Confusion matrix"))
        bars = ChartHorizontalBar("Per-class F1")
        for i in range(n):
            bars.add_bar(names[i], float(evaluation.f1(i)))
        comps.append(bars)
        comps.append(ComponentText(
            f"accuracy {evaluation.accuracy():.4f}, "
            f"precision {evaluation.precision():.4f}, "
            f"recall {evaluation.recall():.4f}, "
            f"F1 {evaluation.f1():.4f}", title="Summary"))
    if rocs is not None:
        if not isinstance(rocs, (list, tuple)):
            rocs = [rocs]
        titles = list(roc_titles or [])
        titles += [f"class {i}" for i in range(len(titles), len(rocs))]
        for roc, title in zip(rocs, titles):
            _, fpr, tpr = roc.get_roc_curve()
            chart = ChartLine(f"ROC — {title} "
                              f"(AUC {roc.calculate_auc():.4f})")
            chart.add_series("roc", [float(v) for v in fpr],
                             [float(v) for v in tpr])
            chart.add_series("chance", [0.0, 1.0], [0.0, 1.0])
            comps.append(chart)
    return comps


def export_report_to_html_file(path: str, **kwargs) -> None:
    """One-call evaluation report through the ui-components DSL
    (kwargs = evaluation_report_components arguments)."""
    from deeplearning4j_tpu.ui.components import render_page
    with open(path, "w") as f:
        f.write(render_page(evaluation_report_components(**kwargs),
                            title="Evaluation report"))
