"""Evaluation JSON serialization.

Equivalent of deeplearning4j-nn eval/serde/ (ROCSerializer.java,
ROCArraySerializer.java, ConfusionMatrixSerializer.java,
ConfusionMatrixDeserializer.java) + the Jackson round-trip every eval class
supports via BaseEvaluation.toJson/fromJson. Envelope: a JSON object with an
"@class" discriminator (the reference uses Jackson @class type info the same
way), numbers stored as plain JSON (shortest-repr floats round-trip float64
exactly, so metric state survives bit for bit).

Unlike ROCSerializer.java — which drops the raw predictions in exact mode
and keeps only the AUC and curves — the repo's exact ROC stores its
label/score arrays, so a reloaded ROC can keep accumulating via eval();
cached auc/auprc are included for readers that only want the headline.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

import numpy as np

from deeplearning4j_tpu.eval.binary import EvaluationBinary
from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
from deeplearning4j_tpu.eval.evaluation import (
    ConfusionMatrix, Evaluation, RegressionEvaluation,
)
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass


def _opt_list(a) -> Optional[list]:
    return None if a is None else np.asarray(a).tolist()


# -- per-class encoders ------------------------------------------------------

def _cm_to(cm: ConfusionMatrix) -> Dict[str, Any]:
    # ref ConfusionMatrixSerializer.java stores {classes, matrix}; the dense
    # int matrix here carries the same counts without the Multiset encoding
    return {"@class": "ConfusionMatrix",
            "numClasses": cm.num_classes,
            "matrix": cm.matrix.tolist()}


def _cm_from(d: Dict[str, Any]) -> ConfusionMatrix:
    cm = ConfusionMatrix(int(d["numClasses"]))
    cm.matrix = np.asarray(d["matrix"], dtype=np.int64)
    return cm


def _eval_to(e: Evaluation) -> Dict[str, Any]:
    return {"@class": "Evaluation",
            "labelNames": e.label_names,
            "numClasses": e.num_classes,
            "topN": e.top_n,
            "topNCorrectCount": e.top_n_correct_count,
            "topNTotalCount": e.top_n_total_count,
            "confusion": None if e.confusion is None else _cm_to(e.confusion)}


def _eval_from(d: Dict[str, Any]) -> Evaluation:
    e = Evaluation(num_classes=d.get("numClasses"),
                   labels=d.get("labelNames"),
                   top_n=d.get("topN", 1))
    e.top_n_correct_count = int(d.get("topNCorrectCount", 0))
    e.top_n_total_count = int(d.get("topNTotalCount", 0))
    if d.get("confusion") is not None:
        e.confusion = _cm_from(d["confusion"])
        e.num_classes = e.confusion.num_classes
    return e


_REG_FIELDS = ("_sum_sq_err", "_sum_abs_err", "_sum_label", "_sum_label_sq",
               "_sum_pred", "_sum_pred_sq", "_sum_label_pred")


def _reg_to(r: RegressionEvaluation) -> Dict[str, Any]:
    return {"@class": "RegressionEvaluation",
            "numColumns": r.num_columns,
            "count": r._count,
            **{f.lstrip("_"): _opt_list(getattr(r, f))
               for f in _REG_FIELDS}}


def _reg_from(d: Dict[str, Any]) -> RegressionEvaluation:
    r = RegressionEvaluation(num_columns=d.get("numColumns"))
    r._count = int(d.get("count", 0))
    for f in _REG_FIELDS:
        v = d.get(f.lstrip("_"))
        if v is not None:
            setattr(r, f, np.asarray(v, dtype=np.float64))
    return r


def _roc_to(r: ROC) -> Dict[str, Any]:
    has_data = bool(r._labels) and any(len(l) for l in r._labels)
    return {"@class": "ROC",
            "thresholdSteps": r.threshold_steps,      # ref ROCSerializer
            "labels": _opt_list(np.concatenate(r._labels))
            if r._labels else [],
            "scores": _opt_list(np.concatenate(r._scores))
            if r._scores else [],
            # headline numbers up front, like ROCSerializer.java:
            "auc": r.calculate_auc() if has_data else None,
            "auprc": r.calculate_auprc() if has_data else None}


def _roc_from(d: Dict[str, Any]) -> ROC:
    r = ROC(threshold_steps=d.get("thresholdSteps", 0))
    labels = np.asarray(d.get("labels") or [], dtype=np.float64)
    scores = np.asarray(d.get("scores") or [], dtype=np.float64)
    if labels.size:
        r._labels.append(labels)
        r._scores.append(scores)
    return r


def _rocbin_to(r: ROCBinary) -> Dict[str, Any]:
    # ref ROCArraySerializer.java: an array of per-column ROC objects
    return {"@class": "ROCBinary",
            "rocs": None if r._rocs is None else [_roc_to(x)
                                                  for x in r._rocs]}


def _rocbin_from(d: Dict[str, Any]) -> ROCBinary:
    r = ROCBinary()
    if d.get("rocs") is not None:
        r._rocs = [_roc_from(x) for x in d["rocs"]]
    return r


def _rocmc_to(r: ROCMultiClass) -> Dict[str, Any]:
    return {"@class": "ROCMultiClass",
            "rocs": None if r._rocs is None else [_roc_to(x)
                                                  for x in r._rocs]}


def _rocmc_from(d: Dict[str, Any]) -> ROCMultiClass:
    r = ROCMultiClass()
    if d.get("rocs") is not None:
        r._rocs = [_roc_from(x) for x in d["rocs"]]
    return r


def _bin_to(e: EvaluationBinary) -> Dict[str, Any]:
    return {"@class": "EvaluationBinary",
            "threshold": e.threshold,
            "tp": _opt_list(e._tp), "fp": _opt_list(e._fp),
            "tn": _opt_list(e._tn), "fn": _opt_list(e._fn)}


def _bin_from(d: Dict[str, Any]) -> EvaluationBinary:
    e = EvaluationBinary(decision_threshold=d.get("threshold", 0.5))
    for f in ("tp", "fp", "tn", "fn"):
        v = d.get(f)
        if v is not None:
            setattr(e, "_" + f, np.asarray(v, dtype=np.int64))
    return e


def _cal_to(e: EvaluationCalibration) -> Dict[str, Any]:
    return {"@class": "EvaluationCalibration",
            "reliabilityBins": e.reliability_bins,
            "histogramBins": e.histogram_bins,
            "binCounts": _opt_list(e._bin_counts),
            "binPos": _opt_list(e._bin_pos),
            "binProbSum": _opt_list(e._bin_prob_sum)}


def _cal_from(d: Dict[str, Any]) -> EvaluationCalibration:
    e = EvaluationCalibration(reliability_bins=d.get("reliabilityBins", 10),
                              histogram_bins=d.get("histogramBins", 10))
    if d.get("binCounts") is not None:
        e._bin_counts = np.asarray(d["binCounts"], dtype=np.int64)
        e._bin_pos = np.asarray(d["binPos"], dtype=np.int64)
        e._bin_prob_sum = np.asarray(d["binProbSum"], dtype=np.float64)
    return e


_ENCODERS = {
    ConfusionMatrix: _cm_to, Evaluation: _eval_to,
    RegressionEvaluation: _reg_to, ROC: _roc_to, ROCBinary: _rocbin_to,
    ROCMultiClass: _rocmc_to, EvaluationBinary: _bin_to,
    EvaluationCalibration: _cal_to,
}
_DECODERS = {
    "ConfusionMatrix": _cm_from, "Evaluation": _eval_from,
    "RegressionEvaluation": _reg_from, "ROC": _roc_from,
    "ROCBinary": _rocbin_from, "ROCMultiClass": _rocmc_from,
    "EvaluationBinary": _bin_from, "EvaluationCalibration": _cal_from,
}


def to_dict(obj) -> Dict[str, Any]:
    enc = _ENCODERS.get(type(obj))
    if enc is None:   # subclasses serialize as their nearest base
        for klass, fn in _ENCODERS.items():
            if isinstance(obj, klass):
                enc = fn
                break
    if enc is None:
        raise TypeError(f"no eval serde for {type(obj).__name__}")
    return enc(obj)


def from_dict(d: Dict[str, Any]):
    kind = d.get("@class")
    dec = _DECODERS.get(kind)
    if dec is None:
        raise ValueError(f"unknown eval class {kind!r}")
    return dec(d)


def to_json(obj) -> str:
    """ref: BaseEvaluation.toJson."""
    return json.dumps(to_dict(obj))


def from_json(s: str):
    """ref: BaseEvaluation.fromJson."""
    return from_dict(json.loads(s))
