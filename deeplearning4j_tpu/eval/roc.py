"""ROC / AUC evaluation.

TPU-native equivalent of eval/ROC.java, ROCBinary.java, ROCMultiClass.java.
Uses exact (sorted-score) ROC computation rather than the reference's
fixed-threshold-step approximation — strictly more accurate, same API shape
(`thresholdSteps=0` in later DL4J means exact too).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.eval.base import EvalJsonMixin


def _auc_from_scores(labels: np.ndarray, scores: np.ndarray) -> float:
    """Exact ROC AUC via the rank statistic."""
    pos = scores[labels > 0.5]
    neg = scores[labels <= 0.5]
    if len(pos) == 0 or len(neg) == 0:
        return 0.0
    order = np.argsort(np.concatenate([pos, neg]), kind="mergesort")
    ranks = np.empty(len(order), dtype=np.float64)
    ranks[order] = np.arange(1, len(order) + 1)
    # average ranks for ties
    allscores = np.concatenate([pos, neg])
    sorted_scores = allscores[order]
    i = 0
    while i < len(sorted_scores):
        j = i
        while j + 1 < len(sorted_scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            avg = (i + j + 2) / 2.0
            for k in range(i, j + 1):
                ranks[order[k]] = avg
        i = j + 1
    r_pos = ranks[:len(pos)].sum()
    auc = (r_pos - len(pos) * (len(pos) + 1) / 2.0) / (len(pos) * len(neg))
    return float(auc)


class ROC(EvalJsonMixin):
    """Binary ROC: single-column probabilities or 2-column softmax
    (ref: eval/ROC.java)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self._labels: List[np.ndarray] = []
        self._scores: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 3:
            n, c, t = labels.shape
            labels = labels.transpose(0, 2, 1).reshape(n * t, c)
            predictions = predictions.transpose(0, 2, 1).reshape(n * t, c)
            if mask is not None:
                keep = np.asarray(mask).astype(bool).reshape(-1)
                labels, predictions = labels[keep], predictions[keep]
        if labels.ndim == 2 and labels.shape[1] == 2:
            lab = labels[:, 1]
            sc = predictions[:, 1]
        else:
            lab = labels.reshape(-1)
            sc = predictions.reshape(-1)
        self._labels.append(lab)
        self._scores.append(sc)

    def calculate_auc(self) -> float:
        labels = np.concatenate(self._labels)
        scores = np.concatenate(self._scores)
        return _auc_from_scores(labels, scores)

    def get_roc_curve(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Return (thresholds, fpr, tpr)."""
        labels = np.concatenate(self._labels)
        scores = np.concatenate(self._scores)
        order = np.argsort(-scores, kind="mergesort")
        labels = labels[order]
        scores = scores[order]
        tps = np.cumsum(labels > 0.5)
        fps = np.cumsum(labels <= 0.5)
        p = max(1, (labels > 0.5).sum())
        n = max(1, (labels <= 0.5).sum())
        return scores, fps / n, tps / p

    def calculate_auprc(self) -> float:
        labels = np.concatenate(self._labels)
        scores = np.concatenate(self._scores)
        order = np.argsort(-scores, kind="mergesort")
        labels = labels[order]
        tps = np.cumsum(labels > 0.5)
        denom = np.arange(1, len(labels) + 1)
        precision = tps / denom
        recall = tps / max(1, (labels > 0.5).sum())
        return float(np.trapezoid(precision, recall))


class ROCBinary(EvalJsonMixin):
    """Per-output-column binary ROC (ref: eval/ROCBinary.java)."""

    def __init__(self):
        self._rocs: Optional[List[ROC]] = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        n_cols = labels.shape[1] if labels.ndim >= 2 else 1
        if self._rocs is None:
            self._rocs = [ROC() for _ in range(n_cols)]
        for c in range(n_cols):
            self._rocs[c].eval(labels[:, c], predictions[:, c])

    def calculate_auc(self, col: int = 0) -> float:
        return self._rocs[col].calculate_auc()


class ROCMultiClass(EvalJsonMixin):
    """One-vs-all ROC per class (ref: eval/ROCMultiClass.java)."""

    def __init__(self):
        self._rocs: Optional[List[ROC]] = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            n, c, t = labels.shape
            labels = labels.transpose(0, 2, 1).reshape(n * t, c)
            predictions = predictions.transpose(0, 2, 1).reshape(n * t, c)
        n_cls = labels.shape[1]
        if self._rocs is None:
            self._rocs = [ROC() for _ in range(n_cls)]
        for c in range(n_cls):
            self._rocs[c].eval(labels[:, c], predictions[:, c])

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))
