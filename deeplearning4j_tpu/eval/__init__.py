from deeplearning4j_tpu.eval.evaluation import (  # noqa: F401
    Evaluation,
    RegressionEvaluation,
    ConfusionMatrix,
)
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass  # noqa: F401
from deeplearning4j_tpu.eval.binary import EvaluationBinary  # noqa: F401
from deeplearning4j_tpu.eval.calibration import EvaluationCalibration  # noqa: F401
from deeplearning4j_tpu.eval.serde import (  # noqa: F401
    from_dict as eval_from_dict,
    from_json as eval_from_json,
    to_dict as eval_to_dict,
    to_json as eval_to_json,
)
