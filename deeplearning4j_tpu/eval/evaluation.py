"""Classification + regression evaluation.

TPU-native equivalent of deeplearning4j-nn/.../eval/Evaluation.java (1627 LoC:
eval :285, stats :499, precision :664, recall :803, f1 :1031, accuracy :1138,
ConfusionMatrix) and RegressionEvaluation.java. Accumulation is host-side
numpy (cheap vs the device forward pass); metrics formulas match the
reference, including macro-averaging behavior.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.eval.base import EvalJsonMixin


class ConfusionMatrix(EvalJsonMixin):
    """Counts of (actual, predicted) pairs (ref: eval/ConfusionMatrix.java)."""

    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        self.matrix = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual: int, predicted: int, count: int = 1):
        self.matrix[actual, predicted] += count

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def actual_total(self, cls: int) -> int:
        return int(self.matrix[cls].sum())

    def predicted_total(self, cls: int) -> int:
        return int(self.matrix[:, cls].sum())

    def __str__(self):
        return str(self.matrix)


def _flatten_time(labels: np.ndarray, preds: np.ndarray, mask):
    """[N,C,T] -> [N*T, C] with mask [N,T] -> [N*T] (ref: Evaluation
    evalTimeSeries path)."""
    if labels.ndim == 3:
        n, c, t = labels.shape
        labels = labels.transpose(0, 2, 1).reshape(n * t, c)
        preds = preds.transpose(0, 2, 1).reshape(n * t, c)
        if mask is not None:
            mask = np.asarray(mask).reshape(n * t)
    return labels, preds, mask


class Evaluation(EvalJsonMixin):
    """Multiclass classification metrics (ref: eval/Evaluation.java)."""

    def __init__(self, num_classes: Optional[int] = None,
                 labels: Optional[List[str]] = None, top_n: int = 1):
        self.label_names = labels
        self.num_classes = num_classes or (len(labels) if labels else None)
        self.confusion: Optional[ConfusionMatrix] = None
        # top-N accuracy (ref: Evaluation(List, int) constructor :130-138;
        # an example counts correct when the true class probability is
        # among the N highest outputs, :440-450)
        self.top_n = max(1, int(top_n))
        self.top_n_correct_count = 0
        self.top_n_total_count = 0

    def _ensure(self, n):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = ConfusionMatrix(self.num_classes)

    def eval(self, labels, predictions, mask=None):
        """Accumulate a batch (ref: eval :285). labels/predictions are
        one-hot/probability arrays [N,C] or time series [N,C,T]."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        labels, predictions, mask = _flatten_time(labels, predictions, mask)
        self._ensure(labels.shape[-1])
        actual = labels.argmax(axis=-1)
        pred = predictions.argmax(axis=-1)
        if mask is not None:
            keep = np.asarray(mask).astype(bool).reshape(-1)
            actual, pred = actual[keep], pred[keep]
            predictions = predictions[keep]
        np.add.at(self.confusion.matrix, (actual, pred), 1)
        if self.top_n > 1:
            n = min(self.top_n, predictions.shape[-1])
            # true-class prob among the n highest (ref eval :440-450)
            topn = np.argpartition(-predictions, n - 1, axis=-1)[..., :n]
            self.top_n_correct_count += int(
                (topn == actual[..., None]).any(axis=-1).sum())
            self.top_n_total_count += int(actual.size)

    # ---- metrics ----
    def _tp(self, c):
        return self.confusion.get_count(c, c)

    def _fp(self, c):
        return self.confusion.predicted_total(c) - self._tp(c)

    def _fn(self, c):
        return self.confusion.actual_total(c) - self._tp(c)

    def accuracy(self) -> float:
        m = self.confusion.matrix
        total = m.sum()
        return float(np.trace(m)) / total if total else 0.0

    def top_n_accuracy(self) -> float:
        """Fraction of examples whose true class is among the top_n
        highest-probability outputs (ref: topNAccuracy :1156-1161;
        equals accuracy() when top_n == 1)."""
        if self.top_n <= 1:
            return self.accuracy()
        if not self.top_n_total_count:
            return 0.0
        return self.top_n_correct_count / self.top_n_total_count

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self._tp(cls) + self._fp(cls)
            return self._tp(cls) / denom if denom else 0.0
        vals = [self.precision(c) for c in range(self.num_classes)
                if self.confusion.actual_total(c) > 0 or self.confusion.predicted_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self._tp(cls) + self._fn(cls)
            return self._tp(cls) / denom if denom else 0.0
        vals = [self.recall(c) for c in range(self.num_classes)
                if self.confusion.actual_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def false_positive_rate(self, cls: int) -> float:
        tn = self.confusion.matrix.sum() - self._tp(cls) - self._fp(cls) - self._fn(cls)
        denom = self._fp(cls) + tn
        return self._fp(cls) / denom if denom else 0.0

    def matthews_correlation(self, cls: int) -> float:
        tp, fp, fn = self._tp(cls), self._fp(cls), self._fn(cls)
        tn = self.confusion.matrix.sum() - tp - fp - fn
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return float((tp * tn - fp * fn) / denom) if denom else 0.0

    def stats(self) -> str:
        """Human-readable report (ref: stats :499)."""
        name = lambda c: (self.label_names[c] if self.label_names else str(c))
        lines = ["", "========================Evaluation Metrics========================",
                 f" # of classes:    {self.num_classes}",
                 f" Accuracy:        {self.accuracy():.4f}"]
        if self.top_n > 1:  # ref stats :560-567
            lines.append(f" Top {self.top_n} Accuracy:  "
                         f"{self.top_n_accuracy():.4f}")
        lines += [f" Precision:       {self.precision():.4f}",
                  f" Recall:          {self.recall():.4f}",
                  f" F1 Score:        {self.f1():.4f}",
                  "", "=========================Confusion Matrix=========================="]
        lines.append(str(self.confusion))
        lines.append("==================================================================")
        return "\n".join(lines)


class RegressionEvaluation(EvalJsonMixin):
    """Per-column regression metrics (ref: eval/RegressionEvaluation.java):
    MSE, MAE, RMSE, RSE, correlation, R^2."""

    def __init__(self, num_columns: Optional[int] = None):
        self.num_columns = num_columns
        self._sum_sq_err = None
        self._sum_abs_err = None
        self._count = 0
        self._sum_label = None
        self._sum_label_sq = None
        self._sum_pred = None
        self._sum_pred_sq = None
        self._sum_label_pred = None

    def _ensure(self, n):
        if self._sum_sq_err is None:
            self.num_columns = self.num_columns or n
            z = np.zeros(self.num_columns)
            self._sum_sq_err = z.copy()
            self._sum_abs_err = z.copy()
            self._sum_label = z.copy()
            self._sum_label_sq = z.copy()
            self._sum_pred = z.copy()
            self._sum_pred_sq = z.copy()
            self._sum_label_pred = z.copy()

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        labels, predictions, mask = _flatten_time(labels, predictions, mask)
        self._ensure(labels.shape[-1])
        if mask is not None:
            keep = np.asarray(mask).astype(bool).reshape(-1)
            labels, predictions = labels[keep], predictions[keep]
        err = predictions - labels
        self._sum_sq_err += (err ** 2).sum(axis=0)
        self._sum_abs_err += np.abs(err).sum(axis=0)
        self._sum_label += labels.sum(axis=0)
        self._sum_label_sq += (labels ** 2).sum(axis=0)
        self._sum_pred += predictions.sum(axis=0)
        self._sum_pred_sq += (predictions ** 2).sum(axis=0)
        self._sum_label_pred += (labels * predictions).sum(axis=0)
        self._count += labels.shape[0]

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self._sum_sq_err[col] / self._count)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self._sum_abs_err[col] / self._count)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def correlation_r2(self, col: int = 0) -> float:
        n = self._count
        num = n * self._sum_label_pred[col] - self._sum_label[col] * self._sum_pred[col]
        den = np.sqrt(n * self._sum_label_sq[col] - self._sum_label[col] ** 2) * \
            np.sqrt(n * self._sum_pred_sq[col] - self._sum_pred[col] ** 2)
        r = num / den if den else 0.0
        return float(r)

    def r_squared(self, col: int = 0) -> float:
        mean_label = self._sum_label[col] / self._count
        ss_tot = self._sum_label_sq[col] - self._count * mean_label ** 2
        ss_res = self._sum_sq_err[col]
        return float(1.0 - ss_res / ss_tot) if ss_tot else 0.0

    def stats(self) -> str:
        lines = ["", "=================Regression Evaluation================="]
        for c in range(self.num_columns):
            lines.append(
                f" col {c}: MSE={self.mean_squared_error(c):.5f} "
                f"MAE={self.mean_absolute_error(c):.5f} "
                f"RMSE={self.root_mean_squared_error(c):.5f} "
                f"corr={self.correlation_r2(c):.4f} R2={self.r_squared(c):.4f}")
        return "\n".join(lines)
