"""Probability calibration evaluation.

TPU-native equivalent of eval/EvaluationCalibration.java: reliability diagram
bins + residual plot + probability histogram.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.eval.base import EvalJsonMixin


class EvaluationCalibration(EvalJsonMixin):
    def __init__(self, reliability_bins: int = 10, histogram_bins: int = 10):
        self.reliability_bins = reliability_bins
        self.histogram_bins = histogram_bins
        self._bin_counts = None
        self._bin_pos = None
        self._bin_prob_sum = None

    def _ensure(self, n_cls):
        if self._bin_counts is None:
            shape = (n_cls, self.reliability_bins)
            self._bin_counts = np.zeros(shape, dtype=np.int64)
            self._bin_pos = np.zeros(shape, dtype=np.int64)
            self._bin_prob_sum = np.zeros(shape)

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            n, c, t = labels.shape
            labels = labels.transpose(0, 2, 1).reshape(n * t, c)
            predictions = predictions.transpose(0, 2, 1).reshape(n * t, c)
        n_cls = labels.shape[1]
        self._ensure(n_cls)
        bins = np.clip((predictions * self.reliability_bins).astype(int), 0,
                       self.reliability_bins - 1)
        for c in range(n_cls):
            np.add.at(self._bin_counts[c], bins[:, c], 1)
            np.add.at(self._bin_pos[c], bins[:, c], (labels[:, c] > 0.5).astype(np.int64))
            np.add.at(self._bin_prob_sum[c], bins[:, c], predictions[:, c])

    def reliability_diagram(self, cls: int):
        """Return (mean_predicted_prob, fraction_positive) per bin."""
        counts = np.maximum(self._bin_counts[cls], 1)
        return (self._bin_prob_sum[cls] / counts, self._bin_pos[cls] / counts)

    def expected_calibration_error(self, cls: int = 0) -> float:
        counts = self._bin_counts[cls]
        total = max(1, counts.sum())
        mean_p, frac = self.reliability_diagram(cls)
        return float(np.sum(counts / total * np.abs(mean_p - frac)))
