"""Shared JSON serde surface for evaluation classes.

Equivalent of the reference's Jackson annotations on eval classes
(eval/serde/ROCSerializer.java, ConfusionMatrixSerializer.java,
ConfusionMatrixDeserializer.java): every evaluation object round-trips
through JSON so results can be persisted, shipped to the UI, and reloaded.
"""

from __future__ import annotations


class EvalJsonMixin:
    """to_json/from_json via the central eval/serde registry."""

    def to_json(self) -> str:
        from deeplearning4j_tpu.eval import serde
        return serde.to_json(self)

    def to_dict(self) -> dict:
        from deeplearning4j_tpu.eval import serde
        return serde.to_dict(self)

    @classmethod
    def from_json(cls, s: str):
        from deeplearning4j_tpu.eval import serde
        obj = serde.from_json(s)
        if not isinstance(obj, cls):
            raise TypeError(
                f"JSON encodes {type(obj).__name__}, not {cls.__name__}")
        return obj

    @classmethod
    def from_dict(cls, d: dict):
        from deeplearning4j_tpu.eval import serde
        obj = serde.from_dict(d)
        if not isinstance(obj, cls):
            raise TypeError(
                f"dict encodes {type(obj).__name__}, not {cls.__name__}")
        return obj
