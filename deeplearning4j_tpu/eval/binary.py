"""Per-output binary classification evaluation.

TPU-native equivalent of eval/EvaluationBinary.java: independent binary
metrics (accuracy/precision/recall/f1) for each output column at threshold 0.5.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.eval.base import EvalJsonMixin


class EvaluationBinary(EvalJsonMixin):
    def __init__(self, decision_threshold: float = 0.5):
        self.threshold = decision_threshold
        self._tp = None
        self._fp = None
        self._tn = None
        self._fn = None

    def _ensure(self, n):
        if self._tp is None:
            z = np.zeros(n, dtype=np.int64)
            self._tp, self._fp, self._tn, self._fn = z.copy(), z.copy(), z.copy(), z.copy()

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            n, c, t = labels.shape
            labels = labels.transpose(0, 2, 1).reshape(n * t, c)
            predictions = predictions.transpose(0, 2, 1).reshape(n * t, c)
            if mask is not None:
                keep = np.asarray(mask).astype(bool).reshape(-1)
                labels, predictions = labels[keep], predictions[keep]
        self._ensure(labels.shape[1])
        pred = predictions >= self.threshold
        actual = labels > 0.5
        self._tp += (pred & actual).sum(axis=0)
        self._fp += (pred & ~actual).sum(axis=0)
        self._tn += (~pred & ~actual).sum(axis=0)
        self._fn += (~pred & actual).sum(axis=0)

    def accuracy(self, col: int = 0) -> float:
        total = self._tp[col] + self._fp[col] + self._tn[col] + self._fn[col]
        return float(self._tp[col] + self._tn[col]) / total if total else 0.0

    def precision(self, col: int = 0) -> float:
        d = self._tp[col] + self._fp[col]
        return float(self._tp[col]) / d if d else 0.0

    def recall(self, col: int = 0) -> float:
        d = self._tp[col] + self._fn[col]
        return float(self._tp[col]) / d if d else 0.0

    def f1(self, col: int = 0) -> float:
        p, r = self.precision(col), self.recall(col)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def stats(self) -> str:
        n = len(self._tp)
        lines = ["EvaluationBinary:"]
        for c in range(n):
            lines.append(f"  col {c}: acc={self.accuracy(c):.4f} "
                         f"prec={self.precision(c):.4f} rec={self.recall(c):.4f} "
                         f"f1={self.f1(c):.4f}")
        return "\n".join(lines)
