"""Early stopping.

TPU-native equivalent of deeplearning4j-nn/.../earlystopping/*:
EarlyStoppingConfiguration, trainer/BaseEarlyStoppingTrainer.java:76-196
(epoch loop :100, saveBestModel :196), saver/ (LocalFile/InMemory),
scorecalc/ (DataSetLossCalculator), termination/ (MaxEpochs,
ScoreImprovementEpochs, MaxTime, MaxScore, InvalidScore).
"""

from __future__ import annotations

import copy
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

import numpy as np


# ---------------------------------------------------------------------------
# termination conditions (ref: earlystopping/termination/*)
# ---------------------------------------------------------------------------


class EpochTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, iteration: int, score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs without improvement (ref:
    ScoreImprovementEpochTerminationCondition.java)."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.max_no_improve = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self.best = None
        self.since = 0

    def initialize(self):
        self.best = None
        self.since = 0

    def terminate(self, epoch, score):
        if self.best is None or self.best - score > self.min_improvement:
            self.best = score
            self.since = 0
            return False
        self.since += 1
        return self.since > self.max_no_improve


class MaxTimeTerminationCondition(IterationTerminationCondition,
                                  EpochTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self.start = None

    def initialize(self):
        self.start = time.time()

    def terminate(self, _i, _s):
        return (time.time() - self.start) > self.max_seconds


class MaxScoreTerminationCondition(IterationTerminationCondition,
                                   EpochTerminationCondition):
    """Abort if score exceeds a bound (divergence guard)."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, _i, score):
        return score > self.max_score


class InvalidScoreTerminationCondition(IterationTerminationCondition,
                                       EpochTerminationCondition):
    def terminate(self, _i, score):
        return not np.isfinite(score)


# ---------------------------------------------------------------------------
# model savers (ref: earlystopping/saver/*)
# ---------------------------------------------------------------------------


class InMemoryModelSaver:
    def __init__(self):
        self.best = None
        self.latest = None

    def save_best(self, model, score):
        self.best = (copy_model(model), score)

    def save_latest(self, model, score):
        self.latest = (copy_model(model), score)

    def get_best(self):
        return self.best[0] if self.best else None

    def get_latest(self):
        return self.latest[0] if self.latest else None


class LocalFileModelSaver:
    """Persist best/latest checkpoints to a directory
    (ref: LocalFileModelSaver.java)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.directory, name)

    def save_best(self, model, score):
        from deeplearning4j_tpu.util.model_serializer import write_model
        write_model(model, self._path("bestModel.zip"))

    def save_latest(self, model, score):
        from deeplearning4j_tpu.util.model_serializer import write_model
        write_model(model, self._path("latestModel.zip"))

    def get_best(self):
        from deeplearning4j_tpu.util.model_serializer import restore_model
        p = self._path("bestModel.zip")
        return restore_model(p) if os.path.exists(p) else None

    def get_latest(self):
        from deeplearning4j_tpu.util.model_serializer import restore_model
        p = self._path("latestModel.zip")
        return restore_model(p) if os.path.exists(p) else None


def copy_model(model):
    """Deep-copy a network's learned arrays (host-side snapshot)."""
    import jax
    m2 = copy.copy(model)
    m2.params = jax.tree_util.tree_map(np.asarray, model.params)
    m2.state = jax.tree_util.tree_map(np.asarray, model.state)
    return m2


# ---------------------------------------------------------------------------
# score calculators (ref: earlystopping/scorecalc/*)
# ---------------------------------------------------------------------------


class DataSetLossCalculator:
    """Average loss over a validation iterator (ref: DataSetLossCalculator.java)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        for ds in self.iterator:
            total += model.score(ds) * ds.num_examples()
            n += ds.num_examples()
        return total / n if (self.average and n) else total


class ClassificationScoreCalculator:
    """1 - accuracy so that lower is better (ref: ClassificationScoreCalculator)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        e = model.evaluate(self.iterator)
        return 1.0 - e.accuracy()


# ---------------------------------------------------------------------------
# configuration + trainer (ref: EarlyStoppingConfiguration / BaseEarlyStoppingTrainer)
# ---------------------------------------------------------------------------


@dataclass
class EarlyStoppingConfiguration:
    epoch_termination_conditions: List[EpochTerminationCondition] = field(
        default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = field(
        default_factory=list)
    score_calculator: Any = None
    model_saver: Any = field(default_factory=InMemoryModelSaver)
    save_last_model: bool = False
    evaluate_every_n_epochs: int = 1


@dataclass
class EarlyStoppingResult:
    termination_reason: str
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    score_vs_epoch: dict
    best_model: Any


class EarlyStoppingTrainer:
    """Epoch loop with termination checks (ref: BaseEarlyStoppingTrainer.fit
    :100)."""

    def __init__(self, config: EarlyStoppingConfiguration, model, train_iterator):
        self.config = config
        self.model = model
        self.train_iterator = train_iterator

    def _fit_epoch(self):
        """Train one epoch with per-iteration termination checks. Returns
        (aborted, condition_name) — subclasses override just this
        (EarlyStoppingParallelTrainer trains across the mesh)."""
        for ds in self.train_iterator:
            self.model._fit_batch(ds) if hasattr(self.model, "_fit_batch") \
                else self.model.fit(ds)
            s = self.model.score_value
            for c in self.config.iteration_termination_conditions:
                if c.terminate(self.model.iteration_count, s):
                    return True, type(c).__name__
        return False, None

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        best_score, best_epoch = None, -1
        scores = {}
        epoch = 0
        reason, details = "MaxEpochs", ""
        while True:
            aborted, details_ = self._fit_epoch()
            if aborted:
                reason = "IterationTerminationCondition"
                details = details_
                break
            # score on validation
            if cfg.score_calculator is not None and \
                    epoch % cfg.evaluate_every_n_epochs == 0:
                score = cfg.score_calculator.calculate_score(self.model)
            else:
                score = self.model.score_value
            scores[epoch] = score
            if best_score is None or score < best_score:
                best_score, best_epoch = score, epoch
                cfg.model_saver.save_best(self.model, score)
            if cfg.save_last_model:
                cfg.model_saver.save_latest(self.model, score)
            term = False
            for c in cfg.epoch_termination_conditions:
                if c.terminate(epoch, score):
                    reason = "EpochTerminationCondition"
                    details = type(c).__name__
                    term = True
                    break
            if term:
                break
            epoch += 1
        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            total_epochs=epoch + 1,
            best_model_epoch=best_epoch,
            best_model_score=best_score if best_score is not None else float("nan"),
            score_vs_epoch=scores,
            best_model=cfg.model_saver.get_best(),
        )
