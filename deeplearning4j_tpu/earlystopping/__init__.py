from deeplearning4j_tpu.earlystopping.core import (  # noqa: F401
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
    EarlyStoppingTrainer,
    # termination conditions
    MaxEpochsTerminationCondition,
    MaxTimeTerminationCondition,
    MaxScoreTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    InvalidScoreTerminationCondition,
    # savers
    InMemoryModelSaver,
    LocalFileModelSaver,
    # score calculators
    DataSetLossCalculator,
    ClassificationScoreCalculator,
)
