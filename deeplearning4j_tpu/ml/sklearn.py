"""scikit-learn-protocol estimators over MultiLayerNetwork.

Mirrors dl4j-spark-ml's surface (SparkDl4jNetwork.scala train->model,
SparkDl4jModel.predict = argmax / output = raw vector;
AutoEncoderWrapper.scala compress/reconstruct) in the fit/predict/
predict_proba/transform/score protocol. `mesh=` trains data-parallel via
ParallelWrapper the way the reference's trainingMaster trains via Spark.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import one_hot
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


class _BaseNetworkEstimator:
    def __init__(self, conf, epochs: int = 1, batch_size: int = 32,
                 mesh=None, listeners: Sequence = ()):
        self.conf = conf
        self.epochs = epochs
        self.batch_size = batch_size
        self.mesh = mesh
        self.listeners = list(listeners)
        self.network_: Optional[MultiLayerNetwork] = None

    # sklearn protocol pieces --------------------------------------------
    def get_params(self, deep: bool = True) -> dict:
        return {"conf": self.conf, "epochs": self.epochs,
                "batch_size": self.batch_size, "mesh": self.mesh,
                "listeners": self.listeners}

    def set_params(self, **params) -> "_BaseNetworkEstimator":
        valid = set(self.get_params())
        for k, v in params.items():
            if k not in valid:
                raise ValueError(f"unknown parameter {k!r}; "
                                 f"valid: {sorted(valid)}")
            setattr(self, k, v)
        return self

    def _check_fitted(self):
        if self.network_ is None:
            raise RuntimeError("estimator is not fitted yet; call fit first")

    def _fit_arrays(self, x: np.ndarray, y: np.ndarray) -> None:
        net = MultiLayerNetwork(self.conf).init()
        for lst in self.listeners:
            net.add_listener(lst)
        if self.mesh is not None:
            from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
            pw = ParallelWrapper(net, mesh=self.mesh,
                                 training_mode="allreduce")
            pw.fit(x, y, epochs=self.epochs, batch_size=self.batch_size)
        else:
            net.fit(x, y, epochs=self.epochs, batch_size=self.batch_size)
        self.network_ = net


class NetworkClassifier(_BaseNetworkEstimator):
    """Classification estimator (ref: SparkDl4jNetwork + SparkDl4jModel —
    predict() argmax, output() raw network vector).

    fit accepts integer class labels [N] or one-hot [N, K].
    """

    def fit(self, x, y) -> "NetworkClassifier":
        x = np.asarray(x, np.float32)
        y = np.asarray(y)
        if y.ndim == 1:
            self.classes_ = np.unique(y)
            idx = np.searchsorted(self.classes_, y)
            y = one_hot(idx, len(self.classes_))
        else:
            self.classes_ = np.arange(y.shape[1])
        self._fit_arrays(x, y.astype(np.float32))
        return self

    def predict_proba(self, x) -> np.ndarray:
        self._check_fitted()
        return np.asarray(self.network_.output(np.asarray(x, np.float32)))

    def predict(self, x) -> np.ndarray:
        self._check_fitted()
        return self.classes_[self.predict_proba(x).argmax(axis=1)]

    def output(self, x) -> np.ndarray:
        """Raw network output vector (ref: SparkDl4jModel.output)."""
        return self.predict_proba(x)

    def score(self, x, y) -> float:
        self._check_fitted()
        y = np.asarray(y)
        if y.ndim == 2:
            y = self.classes_[y.argmax(axis=1)]
        return float(np.mean(self.predict(x) == y))


class NetworkRegressor(_BaseNetworkEstimator):
    """Regression estimator (the reference's predict() returns the
    continuous head output for regression nets)."""

    def fit(self, x, y) -> "NetworkRegressor":
        x = np.asarray(x, np.float32)
        y = np.asarray(y, np.float32)
        if y.ndim == 1:
            y = y[:, None]
        self._fit_arrays(x, y)
        return self

    def predict(self, x) -> np.ndarray:
        self._check_fitted()
        out = np.asarray(self.network_.output(np.asarray(x, np.float32)))
        return out[:, 0] if out.shape[1] == 1 else out

    def score(self, x, y) -> float:
        """R^2, the sklearn regressor convention."""
        y = np.asarray(y, np.float32)
        if y.ndim == 2 and y.shape[1] == 1:
            y = y[:, 0]
        pred = self.predict(x)
        ss_res = float(np.sum((y - pred) ** 2))
        ss_tot = float(np.sum((y - np.mean(y)) ** 2))
        if ss_tot == 0.0:  # constant targets: sklearn r2_score convention
            return 1.0 if ss_res == 0.0 else 0.0
        return 1.0 - ss_res / ss_tot


class AutoEncoderEstimator(_BaseNetworkEstimator):
    """Unsupervised autoencoder estimator (ref: AutoEncoder.scala /
    AutoEncoderWrapper — fit on features only, `compress` to the bottleneck
    activations, `reconstruct` back to input space).

    `compress_layer` selects the bottleneck: index into the network's
    layer activations (default = middle layer).
    """

    def __init__(self, conf, epochs: int = 1, batch_size: int = 32,
                 mesh=None, listeners: Sequence = (),
                 compress_layer: Optional[int] = None):
        super().__init__(conf, epochs, batch_size, mesh, listeners)
        self.compress_layer = compress_layer

    def get_params(self, deep: bool = True) -> dict:
        p = super().get_params(deep)
        p["compress_layer"] = self.compress_layer
        return p

    def fit(self, x, y=None) -> "AutoEncoderEstimator":
        x = np.asarray(x, np.float32)
        self._fit_arrays(x, x)  # reconstruction target = input
        return self

    def _bottleneck_index(self) -> int:
        if self.compress_layer is not None:
            return self.compress_layer
        return (len(self.network_.layers) - 1) // 2

    def compress(self, x) -> np.ndarray:
        """Bottleneck activations (ref: AutoEncoderWrapper.compress)."""
        self._check_fitted()
        acts = self.network_.feed_forward(np.asarray(x, np.float32))
        return np.asarray(acts[self._bottleneck_index()])

    transform = compress  # sklearn.Transformer spelling

    def reconstruct(self, x) -> np.ndarray:
        """Full forward pass back to input space
        (ref: AutoEncoderWrapper.reconstruct)."""
        self._check_fitted()
        return np.asarray(self.network_.output(np.asarray(x, np.float32)))

    def score(self, x, y=None) -> float:
        """Negative mean reconstruction MSE (higher is better, sklearn
        convention for unsupervised scores)."""
        x = np.asarray(x, np.float32)
        return -float(np.mean((self.reconstruct(x) - x) ** 2))
