"""ML-pipeline glue: scikit-learn-style estimator wrappers.

TPU-native equivalent of deeplearning4j-scaleout/spark/dl4j-spark-ml
(SparkDl4jNetwork.scala / SparkDl4jModel — Spark ML Estimator/Model pair
fitting a MultiLayerConfiguration on a DataFrame, argmax `predict`,
`output` vector; AutoEncoder.scala / AutoEncoderWrapper — unsupervised
estimator exposing `compress`/`reconstruct`). The idiomatic Python
pipeline framework is scikit-learn's fit/predict/transform protocol, so
these wrappers target it (duck-typed — sklearn itself is not required);
cluster training via Spark maps to mesh training via ParallelWrapper.
"""

from deeplearning4j_tpu.ml.sklearn import (
    NetworkClassifier,
    NetworkRegressor,
    AutoEncoderEstimator,
)

__all__ = ["NetworkClassifier", "NetworkRegressor", "AutoEncoderEstimator"]
