"""Server-agnostic UI component DSL rendered to standalone HTML/JS.

TPU-native equivalent of deeplearning4j-ui-components
(ui/components/{chart,component,decorator,table,text} + api/Component,
api/Style, standalone/StaticPageUtil): declarative chart/table/text
components that serialize to JSON and render to a self-contained HTML page
— no server required, no external assets (zero-egress friendly; the
reference renders through its bundled dl4j-ui.js, here a small inline
canvas renderer fills that role).

Components: ChartLine, ChartScatter, ChartHistogram, ChartHorizontalBar,
ChartStackedArea, ChartTimeline, ComponentTable, ComponentText,
ComponentDiv, DecoratorAccordion. Each takes an optional Style.
`render_page(components)` is StaticPageUtil.renderHTML's role;
EvaluationTools and the training-stats HTML exports build on it.
"""

from __future__ import annotations

import html as _html
import json
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

__all__ = [
    "Style", "Component", "ChartLine", "ChartScatter", "ChartHistogram",
    "ChartHorizontalBar", "ChartStackedArea", "ChartTimeline",
    "ComponentTable", "ComponentText", "ComponentDiv",
    "DecoratorAccordion", "render_page",
]


@dataclass
class Style:
    """Visual style (ref: api/Style.java + chart/style/StyleChart.java —
    width/height in px, margins, colors, stroke width)."""

    width: int = 700
    height: int = 300
    margin_top: int = 24
    margin_bottom: int = 32
    margin_left: int = 48
    margin_right: int = 16
    series_colors: Sequence[str] = ("#1976d2", "#e53935", "#43a047",
                                    "#fb8c00", "#8e24aa", "#00897b")
    stroke_width: float = 1.5
    background: str = "#ffffff"

    def to_dict(self) -> dict:
        return {"width": self.width, "height": self.height,
                "marginTop": self.margin_top,
                "marginBottom": self.margin_bottom,
                "marginLeft": self.margin_left,
                "marginRight": self.margin_right,
                "seriesColors": list(self.series_colors),
                "strokeWidth": self.stroke_width,
                "background": self.background}


class Component:
    """Base component (ref: api/Component.java — type tag + JSON)."""

    type_name = "Component"

    def __init__(self, style: Optional[Style] = None, title: str = ""):
        self.style = style or Style()
        self.title = title

    def to_dict(self) -> dict:
        return {"componentType": self.type_name, "title": self.title,
                "style": self.style.to_dict()}

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    # each component renders itself into an HTML fragment
    def render(self, cid: str) -> str:
        raise NotImplementedError

    def _render_canvas(self, cid: str, js_fn: str, payload: dict) -> str:
        st = self.style
        # escape '</' so data-driven strings can't terminate the <script>
        data = json.dumps(payload).replace("</", "<\\/")
        return f"""
<div class="dl4j-component">
  <h3>{_html.escape(self.title)}</h3>
  <canvas id="{cid}" width="{st.width}" height="{st.height}"
          style="background:{st.background};border:1px solid #ccc"></canvas>
  <script>{js_fn}(document.getElementById("{cid}"), {data});</script>
</div>"""


class _SeriesChart(Component):
    """Common base for x/y-series charts."""

    def __init__(self, title: str = "", style: Optional[Style] = None):
        super().__init__(style, title)
        self.series: List[Tuple[str, List[float], List[float]]] = []

    def add_series(self, name: str, x: Sequence[float],
                   y: Sequence[float]) -> "_SeriesChart":
        if len(x) != len(y):
            raise ValueError(f"series {name!r}: len(x) {len(x)} != "
                             f"len(y) {len(y)}")
        self.series.append((name, [float(v) for v in x],
                            [float(v) for v in y]))
        return self

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["series"] = [{"name": n, "x": x, "y": y}
                       for n, x, y in self.series]
        return d

    _MODE = "line"

    def render(self, cid: str) -> str:
        return self._render_canvas(cid, "dl4jChart", {
            "series": [{"name": n, "x": x, "y": y}
                       for n, x, y in self.series],
            "mode": self._MODE, "style": self.style.to_dict()})


class ChartLine(_SeriesChart):
    """ref: chart/ChartLine.java."""

    type_name = "ChartLine"
    _MODE = "line"


class ChartScatter(_SeriesChart):
    """ref: chart/ChartScatter.java."""

    type_name = "ChartScatter"
    _MODE = "scatter"


class ChartStackedArea(_SeriesChart):
    """ref: chart/ChartStackedArea.java (rendered as cumulative lines)."""

    type_name = "ChartStackedArea"
    _MODE = "stacked"


class ChartHistogram(Component):
    """ref: chart/ChartHistogram.java — explicit bin edges + counts."""

    type_name = "ChartHistogram"

    def __init__(self, title: str = "", style: Optional[Style] = None):
        super().__init__(style, title)
        self.bins: List[Tuple[float, float, float]] = []  # (low, high, y)

    def add_bin(self, low: float, high: float, y: float) -> "ChartHistogram":
        self.bins.append((float(low), float(high), float(y)))
        return self

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["bins"] = [{"low": lo, "high": hi, "y": y}
                     for lo, hi, y in self.bins]
        return d

    def render(self, cid: str) -> str:
        return self._render_canvas(cid, "dl4jHistogram",
                                   {"bins": [list(b) for b in self.bins],
                                    "style": self.style.to_dict()})


class ChartHorizontalBar(Component):
    """ref: chart/ChartHorizontalBar.java — named horizontal bars."""

    type_name = "ChartHorizontalBar"

    def __init__(self, title: str = "", style: Optional[Style] = None):
        super().__init__(style, title)
        self.bars: List[Tuple[str, float]] = []

    def add_bar(self, name: str, value: float) -> "ChartHorizontalBar":
        self.bars.append((name, float(value)))
        return self

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["bars"] = [{"name": n, "value": v} for n, v in self.bars]
        return d

    def render(self, cid: str) -> str:
        return self._render_canvas(cid, "dl4jHBar",
                                   {"bars": [list(b) for b in self.bars],
                                    "style": self.style.to_dict()})


class ChartTimeline(Component):
    """ref: chart/ChartTimeline.java — lanes of [start, end, label] spans
    (used by the Spark training-stats timeline export)."""

    type_name = "ChartTimeline"

    def __init__(self, title: str = "", style: Optional[Style] = None):
        super().__init__(style, title)
        self.lanes: List[Tuple[str, List[Tuple[float, float, str]]]] = []

    def add_lane(self, name: str,
                 spans: Sequence[Tuple[float, float, str]]) -> "ChartTimeline":
        self.lanes.append((name, [(float(a), float(b), str(lb))
                                  for a, b, lb in spans]))
        return self

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["lanes"] = [{"name": n,
                       "spans": [{"start": a, "end": b, "label": lb}
                                 for a, b, lb in spans]}
                      for n, spans in self.lanes]
        return d

    def render(self, cid: str) -> str:
        return self._render_canvas(
            cid, "dl4jTimeline",
            {"lanes": [[n, [list(s) for s in spans]]
                       for n, spans in self.lanes],
             "style": self.style.to_dict()})


class ComponentTable(Component):
    """ref: table/ComponentTable.java."""

    type_name = "ComponentTable"

    def __init__(self, header: Sequence[str] = (),
                 rows: Sequence[Sequence] = (), title: str = "",
                 style: Optional[Style] = None):
        super().__init__(style, title)
        self.header = [str(h) for h in header]
        self.rows = [[str(c) for c in r] for r in rows]

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["header"] = self.header
        d["rows"] = self.rows
        return d

    def render(self, cid: str) -> str:
        head = "".join(f"<th>{_html.escape(h)}</th>" for h in self.header)
        body = "".join(
            "<tr>" + "".join(f"<td>{_html.escape(c)}</td>" for c in r) +
            "</tr>" for r in self.rows)
        return f"""
<div class="dl4j-component">
  <h3>{_html.escape(self.title)}</h3>
  <table id="{cid}" class="dl4j-table">
    <thead><tr>{head}</tr></thead><tbody>{body}</tbody>
  </table>
</div>"""


class ComponentText(Component):
    """ref: text/ComponentText.java."""

    type_name = "ComponentText"

    def __init__(self, text: str = "", title: str = "",
                 style: Optional[Style] = None):
        super().__init__(style, title)
        self.text = text

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["text"] = self.text
        return d

    def render(self, cid: str) -> str:
        t = f"<h3>{_html.escape(self.title)}</h3>" if self.title else ""
        return (f'<div class="dl4j-component" id="{cid}">{t}'
                f"<p>{_html.escape(self.text)}</p></div>")


class ComponentDiv(Component):
    """ref: component/ComponentDiv.java — container of child components."""

    type_name = "ComponentDiv"

    def __init__(self, children: Sequence[Component] = (), title: str = "",
                 style: Optional[Style] = None):
        super().__init__(style, title)
        self.children = list(children)

    def add(self, c: Component) -> "ComponentDiv":
        self.children.append(c)
        return self

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["children"] = [c.to_dict() for c in self.children]
        return d

    def render(self, cid: str) -> str:
        inner = "".join(c.render(f"{cid}_{i}")
                        for i, c in enumerate(self.children))
        t = f"<h3>{_html.escape(self.title)}</h3>" if self.title else ""
        return f'<div class="dl4j-div" id="{cid}">{t}{inner}</div>'


class DecoratorAccordion(Component):
    """ref: decorator/DecoratorAccordion.java — collapsible section."""

    type_name = "DecoratorAccordion"

    def __init__(self, title: str = "", children: Sequence[Component] = (),
                 default_collapsed: bool = False,
                 style: Optional[Style] = None):
        super().__init__(style, title)
        self.children = list(children)
        self.default_collapsed = default_collapsed

    def add(self, c: Component) -> "DecoratorAccordion":
        self.children.append(c)
        return self

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["children"] = [c.to_dict() for c in self.children]
        d["defaultCollapsed"] = self.default_collapsed
        return d

    def render(self, cid: str) -> str:
        inner = "".join(c.render(f"{cid}_{i}")
                        for i, c in enumerate(self.children))
        open_attr = "" if self.default_collapsed else " open"
        return (f'<details class="dl4j-accordion" id="{cid}"{open_attr}>'
                f"<summary>{_html.escape(self.title)}</summary>"
                f"{inner}</details>")


_RENDER_JS = """
function dl4jAxes(ctx, st, xmin, xmax, ymin, ymax){
  const W=ctx.canvas.width, H=ctx.canvas.height;
  const L=st.marginLeft, R=W-st.marginRight, T=st.marginTop,
        B=H-st.marginBottom;
  ctx.strokeStyle='#999'; ctx.strokeRect(L, T, R-L, B-T);
  ctx.fillStyle='#333'; ctx.font='11px sans-serif';
  ctx.fillText(ymax.toPrecision(4), 2, T+5);
  ctx.fillText(ymin.toPrecision(4), 2, B);
  ctx.fillText(xmin.toPrecision(4), L, H-6);
  ctx.fillText(xmax.toPrecision(4), R-30, H-6);
  return [x=>L+(x-xmin)/((xmax-xmin)||1)*(R-L),
          y=>B-(y-ymin)/((ymax-ymin)||1)*(B-T)];
}
function dl4jChart(cv, d){
  const ctx=cv.getContext('2d'), st=d.style;
  let xs=[], ys=[];
  if(d.mode==='stacked'){
    const acc={};
    d.series.forEach(s=>{s.y=s.y.map((v,i)=>{
      const k=s.x[i]; acc[k]=(acc[k]||0)+v; return acc[k];});});
  }
  d.series.forEach(s=>{xs.push(...s.x); ys.push(...s.y);});
  if(!xs.length) return;
  const [X,Y]=dl4jAxes(ctx, st, Math.min(...xs), Math.max(...xs),
                       Math.min(0,...ys), Math.max(...ys));
  d.series.forEach((s,i)=>{
    const c=st.seriesColors[i%st.seriesColors.length];
    ctx.strokeStyle=c; ctx.fillStyle=c; ctx.lineWidth=st.strokeWidth;
    if(d.mode==='scatter'){
      s.x.forEach((x,j)=>{ctx.beginPath();
        ctx.arc(X(x),Y(s.y[j]),2.5,0,6.3); ctx.fill();});
    } else {
      ctx.beginPath();
      s.x.forEach((x,j)=>{j?ctx.lineTo(X(x),Y(s.y[j]))
                           :ctx.moveTo(X(x),Y(s.y[j]))});
      ctx.stroke();
    }
    ctx.fillText(s.name, st.marginLeft+8+i*120, 14);
  });
}
function dl4jHistogram(cv, d){
  const ctx=cv.getContext('2d'), st=d.style;
  if(!d.bins.length) return;
  const xmin=Math.min(...d.bins.map(b=>b[0]));
  const xmax=Math.max(...d.bins.map(b=>b[1]));
  const ymax=Math.max(...d.bins.map(b=>b[2]));
  const [X,Y]=dl4jAxes(ctx, st, xmin, xmax, 0, ymax);
  ctx.fillStyle=st.seriesColors[0];
  d.bins.forEach(b=>{
    ctx.fillRect(X(b[0]), Y(b[2]), Math.max(1,X(b[1])-X(b[0])-1),
                 Y(0)-Y(b[2]));});
}
function dl4jHBar(cv, d){
  const ctx=cv.getContext('2d'), st=d.style;
  if(!d.bars.length) return;
  const vmax=Math.max(...d.bars.map(b=>b[1]), 0);
  const H=cv.height, L=st.marginLeft+60, R=cv.width-st.marginRight;
  const bh=(H-st.marginTop-st.marginBottom)/d.bars.length;
  ctx.font='11px sans-serif';
  d.bars.forEach((b,i)=>{
    const y=st.marginTop+i*bh;
    ctx.fillStyle='#333'; ctx.fillText(b[0], 4, y+bh/2+4);
    ctx.fillStyle=st.seriesColors[i%st.seriesColors.length];
    ctx.fillRect(L, y+2, (R-L)*(b[1]/(vmax||1)), bh-4);
    ctx.fillStyle='#333';
    ctx.fillText(b[1].toPrecision(4), L+4, y+bh/2+4);});
}
function dl4jTimeline(cv, d){
  const ctx=cv.getContext('2d'), st=d.style;
  if(!d.lanes.length) return;
  let tmin=Infinity, tmax=-Infinity;
  d.lanes.forEach(l=>l[1].forEach(s=>{
    tmin=Math.min(tmin,s[0]); tmax=Math.max(tmax,s[1]);}));
  const L=st.marginLeft+60, R=cv.width-st.marginRight;
  const lh=(cv.height-st.marginTop-st.marginBottom)/d.lanes.length;
  const X=t=>L+(t-tmin)/((tmax-tmin)||1)*(R-L);
  ctx.font='11px sans-serif';
  d.lanes.forEach((l,i)=>{
    const y=st.marginTop+i*lh;
    ctx.fillStyle='#333'; ctx.fillText(l[0], 4, y+lh/2+4);
    l[1].forEach((s,j)=>{
      ctx.fillStyle=st.seriesColors[j%st.seriesColors.length];
      ctx.fillRect(X(s[0]), y+2, Math.max(1,X(s[1])-X(s[0])), lh-4);
      if(s[2]) {ctx.fillStyle='#fff'; ctx.fillText(s[2], X(s[0])+3, y+lh/2+4);}
    });});
}
"""


def render_page(components: Sequence[Component],
                title: str = "deeplearning4j_tpu report") -> str:
    """Standalone HTML page embedding every component
    (ref: standalone/StaticPageUtil.renderHTML)."""
    body = "".join(c.render(f"c{i}") for i, c in enumerate(components))
    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{_html.escape(title)}</title>
<style>
body{{font-family:sans-serif;margin:20px;background:#fafafa}}
h3{{font-size:15px;margin:16px 0 6px}}
.dl4j-table{{border-collapse:collapse;font-size:13px}}
.dl4j-table td,.dl4j-table th{{border:1px solid #ddd;padding:4px 8px}}
.dl4j-accordion{{margin:8px 0;border:1px solid #ddd;padding:6px;
background:#fff}}
</style>
<script>{_RENDER_JS}</script>
</head><body>
<h1 style="font-size:20px">{_html.escape(title)}</h1>
{body}
</body></html>"""
