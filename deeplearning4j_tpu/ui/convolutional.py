"""Convolutional activation visualization.

Equivalent of deeplearning4j-ui ConvolutionalIterationListener
(ui/weights/ConvolutionalIterationListener.java — SURVEY §2.11 "ui legacy
bits") and the ConvolutionalListenerModule tab: every N iterations, run the
first sample of the current batch through the network, tile each conv
layer's channel activations into one grayscale grid image, and write PNGs
(or hand them to the UI server for display).
"""

from __future__ import annotations

import logging
import math
import os
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener

log = logging.getLogger(__name__)


def tile_activations(act: np.ndarray, pad: int = 1,
                     max_channels: int = 64) -> np.ndarray:
    """[C, H, W] activations → one [rows*H, cols*W] uint8 grid, each
    channel min-max normalized (ref: ConvolutionalIterationListener
    rasterizeConvoLayers)."""
    act = np.asarray(act)
    if act.ndim != 3:
        raise ValueError(f"expected [C,H,W] activations, got {act.shape}")
    c = min(act.shape[0], max_channels)
    act = act[:c]
    cols = int(math.ceil(math.sqrt(c)))
    rows = int(math.ceil(c / cols))
    h, w = act.shape[1], act.shape[2]
    grid = np.zeros((rows * (h + pad) - pad, cols * (w + pad) - pad),
                    np.uint8)
    for i in range(c):
        a = act[i]
        lo, hi = float(a.min()), float(a.max())
        img = ((a - lo) / (hi - lo) * 255.0 if hi > lo
               else np.zeros_like(a)).astype(np.uint8)
        r, col = divmod(i, cols)
        grid[r * (h + pad): r * (h + pad) + h,
             col * (w + pad): col * (w + pad) + w] = img
    return grid


class ConvolutionalIterationListener(TrainingListener):
    """Write per-conv-layer activation grids every ``frequency`` iterations
    (PNG files under ``output_dir``, named it<iter>_layer<i>.png)."""

    # networks stash the current batch only when a listener asks for it
    needs_batch_features = True

    def __init__(self, output_dir: str, frequency: int = 10,
                 max_channels: int = 64):
        self.output_dir = output_dir
        self.frequency = max(1, frequency)
        self.max_channels = max_channels
        os.makedirs(output_dir, exist_ok=True)
        self._warned = False

    def iteration_done(self, model, iteration: int, score: float):
        if iteration % self.frequency != 0:
            return
        x = getattr(model, "_last_batch_features", None)
        if x is None:
            return
        try:
            from PIL import Image  # optional dep ([viz] extra)
            acts = self._conv_activations(model, np.asarray(x)[:1])
            for li, act in acts:
                grid = tile_activations(act, max_channels=self.max_channels)
                Image.fromarray(grid, mode="L").save(os.path.join(
                    self.output_dir, f"it{iteration}_layer{li}.png"))
        except Exception as e:  # noqa: BLE001 - visualization must not kill fit
            if not self._warned:  # surface the reason once, then go quiet
                log.warning("ConvolutionalIterationListener disabled: %s", e)
                self._warned = True
            else:
                log.debug("conv listener skipped: %s", e)

    @staticmethod
    def _conv_activations(model, x) -> List:
        """(layer index, [C,H,W]) for each 4-D activation."""
        acts = model.feed_forward(x, train=False)
        out = []
        for i, a in enumerate(acts):
            a = np.asarray(a)
            if a.ndim == 4:  # [1, C, H, W]
                out.append((i, a[0]))
        return out
