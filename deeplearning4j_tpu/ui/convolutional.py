"""Convolutional activation visualization.

Equivalent of deeplearning4j-ui ConvolutionalIterationListener
(ui/weights/ConvolutionalIterationListener.java — SURVEY §2.11 "ui legacy
bits") and the ConvolutionalListenerModule tab: every N iterations, run the
first sample of the current batch through the network, tile each conv
layer's channel activations into one grayscale grid image, and write PNGs
(or hand them to the UI server for display).
"""

from __future__ import annotations

import logging
import math
import os
import struct
import zlib
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener

log = logging.getLogger(__name__)


def encode_png_gray(arr: np.ndarray) -> bytes:
    """Minimal 8-bit grayscale PNG encoder (stdlib only — the HTTP
    activations tab must not depend on the optional [viz] PIL extra)."""
    a = np.asarray(arr, np.uint8)
    if a.ndim != 2:
        raise ValueError(f"expected [H,W] grayscale, got {a.shape}")
    h, w = a.shape
    # each scanline prefixed by filter byte 0 (None)
    raw = b"".join(b"\x00" + a[r].tobytes() for r in range(h))

    def chunk(tag: bytes, data: bytes) -> bytes:
        return (struct.pack(">I", len(data)) + tag + data
                + struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF))

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 0, 0, 0, 0)  # 8-bit gray
    return (b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr)
            + chunk(b"IDAT", zlib.compress(raw, 6)) + chunk(b"IEND", b""))


def tile_activations(act: np.ndarray, pad: int = 1,
                     max_channels: int = 64) -> np.ndarray:
    """[C, H, W] activations → one [rows*H, cols*W] uint8 grid, each
    channel min-max normalized (ref: ConvolutionalIterationListener
    rasterizeConvoLayers)."""
    act = np.asarray(act)
    if act.ndim != 3:
        raise ValueError(f"expected [C,H,W] activations, got {act.shape}")
    c = min(act.shape[0], max_channels)
    act = act[:c]
    cols = int(math.ceil(math.sqrt(c)))
    rows = int(math.ceil(c / cols))
    h, w = act.shape[1], act.shape[2]
    grid = np.zeros((rows * (h + pad) - pad, cols * (w + pad) - pad),
                    np.uint8)
    for i in range(c):
        a = act[i]
        lo, hi = float(a.min()), float(a.max())
        img = ((a - lo) / (hi - lo) * 255.0 if hi > lo
               else np.zeros_like(a)).astype(np.uint8)
        r, col = divmod(i, cols)
        grid[r * (h + pad): r * (h + pad) + h,
             col * (w + pad): col * (w + pad) + w] = img
    return grid


class ConvolutionalIterationListener(TrainingListener):
    """Publish per-conv-layer activation grids every ``frequency``
    iterations — as PNG files under ``output_dir`` and/or to a UIServer's
    /activations tab (ref: ConvolutionalIterationListener.java writes the
    image, ConvolutionalListenerModule.java:47 serves it)."""

    # networks stash the current batch only when a listener asks for it
    needs_batch_features = True

    def __init__(self, output_dir: Optional[str] = None, frequency: int = 10,
                 max_channels: int = 64, ui_server=None,
                 session_id: str = "conv-activations"):
        if output_dir is None and ui_server is None:
            raise ValueError("need output_dir and/or ui_server")
        self.output_dir = output_dir
        self.frequency = max(1, frequency)
        self.max_channels = max_channels
        self.ui_server = ui_server
        self.session_id = session_id
        if output_dir is not None:
            os.makedirs(output_dir, exist_ok=True)
        self._warned = False

    def iteration_done(self, model, iteration: int, score: float):
        if iteration % self.frequency != 0:
            return
        x = getattr(model, "_last_batch_features", None)
        if x is None:
            return
        try:
            acts = self._conv_activations(model, np.asarray(x)[:1])
            grids = [(li, tile_activations(a, max_channels=self.max_channels))
                     for li, a in acts]
            if self.output_dir is not None:
                for li, grid in grids:
                    with open(os.path.join(
                            self.output_dir,
                            f"it{iteration}_layer{li}.png"), "wb") as f:
                        f.write(encode_png_gray(grid))
            if self.ui_server is not None:
                self.ui_server.publish_activations(self.session_id,
                                                   iteration, grids)
        except Exception as e:  # noqa: BLE001 - visualization must not kill fit
            if not self._warned:  # surface the reason once, then go quiet
                log.warning("ConvolutionalIterationListener disabled: %s", e)
                self._warned = True
            else:
                log.debug("conv listener skipped: %s", e)

    @staticmethod
    def _conv_activations(model, x) -> List:
        """(layer index, [C,H,W]) for each 4-D activation."""
        acts = model.feed_forward(x, train=False)
        out = []
        for i, a in enumerate(acts):
            a = np.asarray(a)
            if a.ndim == 4:  # [1, C, H, W]
                out.append((i, a[0]))
        return out
