"""Web UI server + remote stats routing.

Equivalent of ui/play/PlayUIServer.java (RoutingDsl routes :112-155, port
:274), api/UIServer.java SPI, module/train/TrainModule.java (overview/model
pages), module/remote/RemoteReceiverModule.java, and core
api/storage/impl/RemoteUIStatsStorageRouter.java:1-355 (HTTP POST of stats
to a remote UI).

The Play framework is replaced by stdlib http.server on a daemon thread;
charts render client-side from the JSON endpoints with inline JS (no
external assets — zero-egress friendly).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from deeplearning4j_tpu.ui.stats import StatsReport
from deeplearning4j_tpu.ui.storage import StatsStorage

log = logging.getLogger(__name__)

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
h1{font-size:20px} h2{font-size:16px;margin-top:24px}
.chart{border:1px solid #ccc;background:#fff;margin:8px 0}
#meta{color:#555;font-size:13px}
table{border-collapse:collapse;font-size:13px}
td,th{border:1px solid #ddd;padding:4px 8px}
</style></head>
<body>
<h1>Training overview</h1>
<div id="meta"></div>
<h2>Score vs iteration</h2>
<canvas id="score" class="chart" width="900" height="260"></canvas>
<h2>Parameter mean magnitudes</h2>
<canvas id="pmm" class="chart" width="900" height="260"></canvas>
<h2>Performance</h2>
<table id="perf"></table>
<script>
function drawSeries(cv, series, labels){
  const ctx = cv.getContext('2d');
  ctx.clearRect(0,0,cv.width,cv.height);
  let xs=[], ys=[];
  series.forEach(s=>{s.pts.forEach(p=>{xs.push(p[0]); ys.push(p[1]);});});
  if(!xs.length) return;
  const xmin=Math.min(...xs), xmax=Math.max(...xs,xmin+1);
  const ymin=Math.min(...ys), ymax=Math.max(...ys,ymin+1e-9);
  const X=x=>40+(x-xmin)/(xmax-xmin)*(cv.width-60);
  const Y=y=>cv.height-25-(y-ymin)/(ymax-ymin)*(cv.height-45);
  ctx.strokeStyle='#999';ctx.strokeRect(40,20,cv.width-60,cv.height-45);
  ctx.fillStyle='#333';ctx.font='11px sans-serif';
  ctx.fillText(ymax.toPrecision(4),2,25);
  ctx.fillText(ymin.toPrecision(4),2,cv.height-25);
  ctx.fillText(String(xmax),cv.width-40,cv.height-8);
  const colors=['#1976d2','#e53935','#43a047','#fb8c00','#8e24aa','#00897b'];
  series.forEach((s,i)=>{
    ctx.strokeStyle=colors[i%colors.length];ctx.beginPath();
    s.pts.forEach((p,j)=>{j?ctx.lineTo(X(p[0]),Y(p[1])):ctx.moveTo(X(p[0]),Y(p[1]))});
    ctx.stroke();
    ctx.fillStyle=colors[i%colors.length];
    ctx.fillText(s.name,50+i*150,14);
  });
}
async function refresh(){
  const sessions = await (await fetch('/train/sessions')).json();
  if(!sessions.length) return;
  const sid = sessions[sessions.length-1];
  const ov = await (await fetch('/train/overview?sid='+
                    encodeURIComponent(sid))).json();
  document.getElementById('meta').textContent =
    'session '+sid+' — '+(ov.modelClass||'?')+', '+
    (ov.numParams||'?')+' params, '+ov.scores.length+' reports';
  drawSeries(document.getElementById('score'),
    [{name:'score',pts:ov.scores}]);
  const pseries = Object.entries(ov.paramMeanMagnitudes).slice(0,6)
    .map(([k,v])=>({name:k,pts:v}));
  drawSeries(document.getElementById('pmm'), pseries);
  const perf=document.getElementById('perf');
  perf.replaceChildren();
  const hdr=perf.insertRow(), row=perf.insertRow();
  [['last iteration',ov.lastIteration],
   ['iter time (ms)',ov.lastIterTimeMs],
   ['memory RSS (MB)',ov.memoryRssMb]].forEach(([h,v])=>{
    const th=document.createElement('th'); th.textContent=h;
    hdr.appendChild(th);
    row.insertCell().textContent=(v==null)?'-':String(v);
  });
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""

_TSNE_PAGE = """<!DOCTYPE html>
<html><head><title>t-SNE — deeplearning4j_tpu UI</title>
<style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
h1{font-size:20px} #meta{color:#555;font-size:13px}
canvas{border:1px solid #ccc;background:#fff}
</style></head>
<body>
<h1>t-SNE plot</h1>
<div id="meta"></div>
<canvas id="plot" width="800" height="800"></canvas>
<script>
async function refresh(){
  const sids = await (await fetch('/tsne/sessions')).json();
  if(!sids.length){document.getElementById('meta').textContent=
    'no t-SNE data uploaded (POST /tsne/upload)'; return;}
  const sid = sids[sids.length-1];
  const d = await (await fetch('/tsne/coords?sid='+
                   encodeURIComponent(sid))).json();
  document.getElementById('meta').textContent =
    'session '+sid+' — '+d.coords.length+' points';
  const cv=document.getElementById('plot'), ctx=cv.getContext('2d');
  ctx.clearRect(0,0,cv.width,cv.height);
  const xs=d.coords.map(p=>p[0]), ys=d.coords.map(p=>p[1]);
  const xmin=Math.min(...xs), xmax=Math.max(...xs,xmin+1e-9);
  const ymin=Math.min(...ys), ymax=Math.max(...ys,ymin+1e-9);
  const X=x=>20+(x-xmin)/(xmax-xmin)*(cv.width-40);
  const Y=y=>cv.height-20-(y-ymin)/(ymax-ymin)*(cv.height-40);
  ctx.font='10px sans-serif'; ctx.fillStyle='#1976d2';
  d.coords.forEach((p,i)=>{
    ctx.beginPath();ctx.arc(X(p[0]),Y(p[1]),2,0,6.3);ctx.fill();
    if(d.labels && d.labels[i]!=null)
      ctx.fillText(String(d.labels[i]),X(p[0])+3,Y(p[1])-3);
  });
}
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-ui/0.1"

    def log_message(self, fmt, *args):  # quiet
        log.debug("ui: " + fmt, *args)

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        storages: List[StatsStorage] = self.server.storages
        path, _, query = self.path.partition("?")
        params = {k: v[0] for k, v in
                  urllib.parse.parse_qs(query).items()}
        if path in ("/", "/train", "/train/overview.html"):
            body = _PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path == "/train/sessions":
            sids = sorted({s for st in storages for s in st.list_session_ids()})
            return self._json(sids)
        if path == "/train/overview":
            sid = params.get("sid")
            if sid is None:
                return self._json({"error": "sid required"}, 400)
            return self._json(self._overview(storages, sid))
        # t-SNE module (ref: ui/module/tsne/TsneModule.java — upload +
        # per-session coordinate plots)
        if path in ("/tsne", "/tsne/"):
            body = _TSNE_PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path == "/tsne/sessions":
            return self._json(list(self.server.tsne_sessions))
        if path == "/tsne/coords":
            sid = params.get("sid")
            data = self.server.tsne_sessions.get(sid)
            if data is None:
                return self._json({"error": f"unknown session {sid!r}"}, 404)
            return self._json(data)
        self._json({"error": "not found"}, 404)

    def do_POST(self):
        path = self.path.partition("?")[0].rstrip("/")
        # t-SNE upload (ref: TsneModule.java POST /tsne/upload/:sid)
        if path == "/tsne/upload":
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(payload, dict):
                    raise ValueError("body must be a JSON object")
                sid = str(payload.get("sessionId", "uploaded"))
                coords = [[float(a), float(b)]
                          for a, b in payload["coords"]]
                labels = payload.get("labels")
                if labels is not None:
                    labels = [str(l) for l in labels]
                    if len(labels) != len(coords):
                        raise ValueError("labels/coords length mismatch")
            except (KeyError, TypeError, ValueError) as e:
                return self._json({"error": f"malformed payload: {e}"}, 400)
            self.server.tsne_sessions[sid] = {"coords": coords,
                                              "labels": labels}
            return self._json({"status": "ok", "sessionId": sid})
        # remote stats receiver (ref: RemoteReceiverModule.java)
        if path != "/remoteReceive":
            return self._json({"error": "not found"}, 404)
        if not self.server.remote_enabled:
            return self._json({"error": "remote receiver disabled"}, 403)
        if not self.server.storages:
            return self._json({"error": "no storage attached"}, 503)
        storage = self.server.storages[0]
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
            kind = payload.get("type")
            if kind == "staticInfo":
                storage.put_static_info(str(payload["sessionId"]),
                                        dict(payload["data"]))
            elif kind == "update":
                storage.put_update(StatsReport.from_dict(payload["data"]))
            else:
                return self._json({"error": f"unknown type {kind!r}"}, 400)
        except (KeyError, TypeError, ValueError) as e:
            return self._json({"error": f"malformed payload: {e}"}, 400)
        self._json({"status": "ok"})

    @staticmethod
    def _overview(storages: List[StatsStorage], sid: str) -> dict:
        static = None
        updates: List[StatsReport] = []
        for st in storages:
            static = static or st.get_static_info(sid)
            updates.extend(st.get_all_updates(sid))
        updates.sort(key=lambda r: r.iteration)

        def num(v):  # reports may come from untrusted remote POSTs
            try:
                return None if v is None else float(v)
            except (TypeError, ValueError):
                return None

        pmm: dict = {}
        for r in updates:
            for k, v in r.param_mean_magnitudes.items():
                pmm.setdefault(str(k), []).append([int(r.iteration), num(v)])
        last = updates[-1] if updates else None
        return {
            "sessionId": sid,
            "modelClass": str((static or {}).get("modelClass") or "")[:200],
            "numParams": num((static or {}).get("numParams")),
            "scores": [[int(r.iteration), num(r.score)] for r in updates],
            "paramMeanMagnitudes": pmm,
            "lastIteration": int(last.iteration) if last else None,
            "lastIterTimeMs": num(last.iteration_time_ms) if last else None,
            "memoryRssMb": num(last.memory_rss_mb) if last else None,
        }


class UIServer:
    """Singleton UI server (ref: api/UIServer.java — getInstance(),
    attach(statsStorage), enableRemoteListener())."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.storages = []
        self._httpd.remote_enabled = False
        self._httpd.tsne_sessions = {}
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        log.info("UI server at http://127.0.0.1:%d/train", self.port)

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    def attach(self, storage: StatsStorage) -> None:
        if storage not in self._httpd.storages:
            self._httpd.storages.append(storage)

    def detach(self, storage: StatsStorage) -> None:
        if storage in self._httpd.storages:
            self._httpd.storages.remove(storage)

    def upload_tsne(self, coords, labels=None,
                    session_id: str = "uploaded") -> None:
        """Publish 2-D t-SNE coordinates to the /tsne tab (ref:
        TsneModule.uploadFile — here arrays instead of a coord file;
        pair with plot.tsne.Tsne/BarnesHutTsne.fit_transform)."""
        import numpy as _np
        c = _np.asarray(coords, float)
        if c.ndim != 2 or c.shape[1] < 2:
            raise ValueError("coords must be [N, 2+]")
        data = {"coords": c[:, :2].tolist(),
                "labels": None if labels is None
                else [str(l) for l in labels]}
        if data["labels"] is not None and len(data["labels"]) != len(c):
            raise ValueError("labels/coords length mismatch")
        self._httpd.tsne_sessions[session_id] = data

    def enable_remote_listener(self, storage: Optional[StatsStorage] = None):
        """ref: UIServer.enableRemoteListener — POSTs to /remoteReceive land
        in the first attached storage (or the one given here); with no
        storage at all an InMemoryStatsStorage is created, like the
        reference."""
        if storage is not None:
            # atomic list swap: handler threads index storages[0] and must
            # never observe a transiently-empty list
            self._httpd.storages = [storage] + [
                s for s in self._httpd.storages if s is not storage]
        elif not self._httpd.storages:
            from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
            self._httpd.storages.append(InMemoryStatsStorage())
        self._httpd.remote_enabled = True

    def stop(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        self._httpd.server_close()  # release the listening socket
        if UIServer._instance is self:
            UIServer._instance = None


class RemoteUIStatsStorageRouter(StatsStorage):
    """Client that routes stats to a remote UIServer over HTTP POST
    (ref: core api/storage/impl/RemoteUIStatsStorageRouter.java:1-355 —
    retry with backoff on failure; here: bounded retries, then drop+warn)."""

    def __init__(self, url: str, retries: int = 3, timeout: float = 5.0):
        self.url = url.rstrip("/") + "/remoteReceive"
        self.retries = retries
        self.timeout = timeout

    def _post(self, payload: dict) -> bool:
        data = json.dumps(payload).encode()
        for attempt in range(self.retries):
            try:
                req = urllib.request.Request(
                    self.url, data=data,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    return r.status == 200
            except Exception as e:  # noqa: BLE001
                if attempt == self.retries - 1:
                    log.warning("remote stats post failed: %s", e)
        return False

    def put_static_info(self, session_id, info):
        self._post({"type": "staticInfo", "sessionId": session_id,
                    "data": info})

    def put_update(self, report: StatsReport):
        self._post({"type": "update", "data": report.to_dict()})

    # remote router is write-only (ref: RemoteUIStatsStorageRouter is a
    # StatsStorageRouter, not a StatsStorage)
    def list_session_ids(self):
        return []

    def get_static_info(self, session_id):
        return None

    def get_all_updates(self, session_id):
        return []
