"""Web UI server + remote stats routing.

Equivalent of ui/play/PlayUIServer.java (RoutingDsl routes :112-155, port
:274), api/UIServer.java SPI, module/train/TrainModule.java (overview/model
pages), module/remote/RemoteReceiverModule.java, and core
api/storage/impl/RemoteUIStatsStorageRouter.java:1-355 (HTTP POST of stats
to a remote UI).

The Play framework is replaced by stdlib http.server on a daemon thread;
charts render client-side from the JSON endpoints with inline JS (no
external assets — zero-egress friendly).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from deeplearning4j_tpu.ui.stats import StatsReport
from deeplearning4j_tpu.ui.storage import StatsStorage

log = logging.getLogger(__name__)


def _num(v):
    """Lenient float coercion — reports/histograms may come from untrusted
    remote POSTs, and one malformed value must not kill a whole route."""
    try:
        return None if v is None else float(v)
    except (TypeError, ValueError):
        return None


def _int(v, default: int = 0) -> int:
    try:
        return int(v)
    except (TypeError, ValueError):
        return default


_CHART_JS = """
// shared canvas plotting for all tabs (served at /chart.js)
function drawSeries(cv, series){
  const ctx = cv.getContext('2d');
  ctx.clearRect(0,0,cv.width,cv.height);
  let xs=[], ys=[];
  series.forEach(s=>{s.pts.forEach(p=>{xs.push(p[0]); ys.push(p[1]);});});
  if(!xs.length) return;
  const xmin=Math.min(...xs), xmax=Math.max(...xs,xmin+1);
  const ymin=Math.min(...ys), ymax=Math.max(...ys,ymin+1e-12);
  const X=x=>40+(x-xmin)/(xmax-xmin)*(cv.width-60);
  const Y=y=>cv.height-25-(y-ymin)/(ymax-ymin)*(cv.height-45);
  ctx.strokeStyle='#999';ctx.strokeRect(40,20,cv.width-60,cv.height-45);
  ctx.fillStyle='#333';ctx.font='11px sans-serif';
  ctx.fillText(ymax.toPrecision(4),2,25);
  ctx.fillText(ymin.toPrecision(4),2,cv.height-25);
  ctx.fillText(String(xmax),cv.width-40,cv.height-8);
  const colors=['#1976d2','#e53935','#43a047','#fb8c00','#8e24aa','#00897b'];
  series.forEach((s,i)=>{
    ctx.strokeStyle=colors[i%colors.length];ctx.beginPath();
    s.pts.forEach((p,j)=>{j?ctx.lineTo(X(p[0]),Y(p[1])):ctx.moveTo(X(p[0]),Y(p[1]))});
    ctx.stroke();
    ctx.fillStyle=colors[i%colors.length];
    ctx.fillText(s.name,50+i*150,14);
  });
}
function drawHist(cv, bins, counts){
  const ctx=cv.getContext('2d');ctx.clearRect(0,0,cv.width,cv.height);
  if(!counts||!counts.length)return;
  const cmax=Math.max(...counts,1);
  const bw=(cv.width-60)/counts.length;
  ctx.fillStyle='#1976d2';
  counts.forEach((c,i)=>{
    const h=c/cmax*(cv.height-45);
    ctx.fillRect(40+i*bw,cv.height-25-h,bw-1,h);
  });
  ctx.fillStyle='#333';ctx.font='11px sans-serif';
  ctx.fillText(bins[0].toPrecision(3),40,cv.height-8);
  ctx.fillText(bins[bins.length-1].toPrecision(3),cv.width-60,cv.height-8);
}
"""

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
h1{font-size:20px} h2{font-size:16px;margin-top:24px}
.chart{border:1px solid #ccc;background:#fff;margin:8px 0}
#meta{color:#555;font-size:13px}
table{border-collapse:collapse;font-size:13px}
td,th{border:1px solid #ddd;padding:4px 8px}
</style></head>
<body>
<h1>Training overview</h1>
<div id="meta"></div>
<h2>Score vs iteration</h2>
<canvas id="score" class="chart" width="900" height="260"></canvas>
<h2>Parameter mean magnitudes</h2>
<canvas id="pmm" class="chart" width="900" height="260"></canvas>
<h2>Performance</h2>
<table id="perf"></table>
<script src="/chart.js"></script>
<script>
async function refresh(){
  const sessions = await (await fetch('/train/sessions')).json();
  if(!sessions.length) return;
  const sid = sessions[sessions.length-1];
  const ov = await (await fetch('/train/overview?sid='+
                    encodeURIComponent(sid))).json();
  document.getElementById('meta').textContent =
    'session '+sid+' — '+(ov.modelClass||'?')+', '+
    (ov.numParams||'?')+' params, '+ov.scores.length+' reports';
  drawSeries(document.getElementById('score'),
    [{name:'score',pts:ov.scores}]);
  const pseries = Object.entries(ov.paramMeanMagnitudes).slice(0,6)
    .map(([k,v])=>({name:k,pts:v}));
  drawSeries(document.getElementById('pmm'), pseries);
  const perf=document.getElementById('perf');
  perf.replaceChildren();
  const hdr=perf.insertRow(), row=perf.insertRow();
  [['last iteration',ov.lastIteration],
   ['iter time (ms)',ov.lastIterTimeMs],
   ['memory RSS (MB)',ov.memoryRssMb]].forEach(([h,v])=>{
    const th=document.createElement('th'); th.textContent=h;
    hdr.appendChild(th);
    row.insertCell().textContent=(v==null)?'-':String(v);
  });
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""

_MODEL_PAGE = """<!DOCTYPE html>
<html><head><title>model — deeplearning4j_tpu UI</title>
<style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
h1{font-size:20px} h2{font-size:16px;margin-top:24px}
.chart{border:1px solid #ccc;background:#fff;margin:8px 0}
#meta{color:#555;font-size:13px}
select{margin:8px 0}
</style></head>
<body>
<h1>Model — per-layer parameters</h1>
<div id="meta"></div>
<select id="layer"></select>
<h2>Mean magnitudes vs iteration</h2>
<canvas id="mm" class="chart" width="900" height="260"></canvas>
<h2>Parameter histogram (latest)</h2>
<canvas id="hist" class="chart" width="900" height="260"></canvas>
<script src="/chart.js"></script>
<script>
let currentLayer=null;
async function refresh(){
  const sessions=await (await fetch('/train/sessions')).json();
  if(!sessions.length)return;
  const sid=sessions[sessions.length-1];
  const layers=await (await fetch('/train/model/layers?sid='+
                      encodeURIComponent(sid))).json();
  const sel=document.getElementById('layer');
  if(sel.options.length!=layers.length){
    sel.replaceChildren();
    layers.forEach(l=>{const o=document.createElement('option');
      o.value=l;o.textContent=l;sel.appendChild(o);});
    sel.onchange=()=>{currentLayer=sel.value;refresh();};
  }
  const layer=currentLayer||layers[0];
  if(!layer)return;
  const d=await (await fetch('/train/model/data/'+
      encodeURIComponent(layer)+'?sid='+encodeURIComponent(sid))).json();
  document.getElementById('meta').textContent=
    'session '+sid+' — layer '+layer;
  drawSeries(document.getElementById('mm'),
    Object.entries(d.meanMagnitudes).map(([k,v])=>({name:k,pts:v})));
  const hk=Object.keys(d.histograms);
  if(hk.length){const h=d.histograms[hk[0]];
    drawHist(document.getElementById('hist'),h.bins,h.counts);}
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""

_SYSTEM_PAGE = """<!DOCTYPE html>
<html><head><title>system — deeplearning4j_tpu UI</title>
<style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
h1{font-size:20px} h2{font-size:16px;margin-top:24px}
.chart{border:1px solid #ccc;background:#fff;margin:8px 0}
table{border-collapse:collapse;font-size:13px}
td,th{border:1px solid #ddd;padding:4px 8px}
</style></head>
<body>
<h1>System</h1>
<h2>Memory RSS (MB) vs iteration</h2>
<canvas id="mem" class="chart" width="900" height="220"></canvas>
<h2>Iteration time (ms)</h2>
<canvas id="it" class="chart" width="900" height="220"></canvas>
<h2>Software / hardware</h2>
<table id="sw"></table>
<script src="/chart.js"></script>
<script>
async function refresh(){
  const sessions=await (await fetch('/train/sessions')).json();
  if(!sessions.length)return;
  const sid=sessions[sessions.length-1];
  const d=await (await fetch('/train/system/data?sid='+
                  encodeURIComponent(sid))).json();
  drawSeries(document.getElementById('mem'),
    [{name:'rss',pts:d.memory}]);
  drawSeries(document.getElementById('it'),
    [{name:'iter ms',pts:d.iterationTimesMs}]);
  const t=document.getElementById('sw');t.replaceChildren();
  Object.entries(d.software).forEach(([k,v])=>{
    const r=t.insertRow();
    const th=document.createElement('th');th.textContent=k;
    r.appendChild(th);r.insertCell().textContent=String(v);
  });
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""

_ACTIVATIONS_PAGE = """<!DOCTYPE html>
<html><head><title>activations — deeplearning4j_tpu UI</title>
<style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
h1{font-size:20px} #meta{color:#555;font-size:13px}
img{border:1px solid #ccc;background:#fff;margin:8px;image-rendering:
pixelated}
</style></head>
<body>
<h1>Convolutional activations</h1>
<div id="meta"></div>
<div id="grids"></div>
<script>
async function refresh(){
  const d=await (await fetch('/activations/data')).json();
  if(!d.sessions.length){document.getElementById('meta').textContent=
    'no activations published yet';return;}
  const sid=d.sessions[d.sessions.length-1];
  const info=d.info[sid];
  document.getElementById('meta').textContent=
    'session '+sid+' — iteration '+info.iteration;
  const g=document.getElementById('grids');g.replaceChildren();
  info.layers.forEach(l=>{
    const img=document.createElement('img');
    img.src='/activations/img?sid='+encodeURIComponent(sid)+
            '&layer='+l+'&it='+info.iteration;
    g.appendChild(img);
  });
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""

_TSNE_PAGE = """<!DOCTYPE html>
<html><head><title>t-SNE — deeplearning4j_tpu UI</title>
<style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
h1{font-size:20px} #meta{color:#555;font-size:13px}
canvas{border:1px solid #ccc;background:#fff}
</style></head>
<body>
<h1>t-SNE plot</h1>
<div id="meta"></div>
<canvas id="plot" width="800" height="800"></canvas>
<script>
async function refresh(){
  const sids = await (await fetch('/tsne/sessions')).json();
  if(!sids.length){document.getElementById('meta').textContent=
    'no t-SNE data uploaded (POST /tsne/upload)'; return;}
  const sid = sids[sids.length-1];
  const d = await (await fetch('/tsne/coords?sid='+
                   encodeURIComponent(sid))).json();
  document.getElementById('meta').textContent =
    'session '+sid+' — '+d.coords.length+' points';
  const cv=document.getElementById('plot'), ctx=cv.getContext('2d');
  ctx.clearRect(0,0,cv.width,cv.height);
  const xs=d.coords.map(p=>p[0]), ys=d.coords.map(p=>p[1]);
  const xmin=Math.min(...xs), xmax=Math.max(...xs,xmin+1e-9);
  const ymin=Math.min(...ys), ymax=Math.max(...ys,ymin+1e-9);
  const X=x=>20+(x-xmin)/(xmax-xmin)*(cv.width-40);
  const Y=y=>cv.height-20-(y-ymin)/(ymax-ymin)*(cv.height-40);
  ctx.font='10px sans-serif'; ctx.fillStyle='#1976d2';
  d.coords.forEach((p,i)=>{
    ctx.beginPath();ctx.arc(X(p[0]),Y(p[1]),2,0,6.3);ctx.fill();
    if(d.labels && d.labels[i]!=null)
      ctx.fillText(String(d.labels[i]),X(p[0])+3,Y(p[1])-3);
  });
}
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-ui/0.1"

    def log_message(self, fmt, *args):  # quiet
        log.debug("ui: " + fmt, *args)

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _html(self, page: str):
        body = page.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        storages: List[StatsStorage] = self.server.storages
        path, _, query = self.path.partition("?")
        params = {k: v[0] for k, v in
                  urllib.parse.parse_qs(query).items()}
        if path in ("/", "/train", "/train/overview.html"):
            return self._html(_PAGE)
        # Prometheus scrape endpoint: the global telemetry registry
        # (monitoring/) in text exposition format. Runtime gauges
        # (RSS/HBM) refresh per scrape but never initialize a backend —
        # same rule as the system tab below.
        if path == "/metrics":
            from deeplearning4j_tpu.monitoring import exporters
            body = exporters.render_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type", exporters.CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        # the structured ops timeline (monitoring/events.py), JSON:
        # ?n=<count> bounds the tail, ?category=<serving|fleet|...>
        # filters. The ring is snapshotted under its lock and serialized
        # OUTSIDE it — a slow client can never stall an emitter.
        if path == "/events":
            from deeplearning4j_tpu.monitoring import events as ev
            elog = ev.global_event_log()
            try:
                n = max(0, int(params.get("n", 200)))
            except ValueError:
                return self._json({"error": "n must be an integer"}, 400)
            tail = elog.tail(n, category=params.get("category"))
            return self._json({
                "depth": elog.depth(),
                "dropped": elog.dropped_total,
                "enabled": ev.events_enabled(),
                "events": [e.as_dict() for e in tail]})
        # liveness/health probe beside /metrics and /events: every
        # attached health probe (an engine's or fleet router's
        # ``health()`` callable) dumped as JSON, HTTP 200 only while
        # every component reports healthy (503 otherwise — so a load
        # balancer can act on the status code without parsing)
        if path == "/health":
            probes = getattr(self.server, "health_probes", {})
            components, ok = {}, True
            for name, probe in sorted(probes.items()):
                try:
                    payload = probe()
                except Exception as e:  # noqa: BLE001 — report, don't die
                    components[name] = {"error": repr(e)}
                    ok = False
                    continue
                components[name] = payload
                healthy = payload.get("healthy") \
                    if isinstance(payload, dict) else None
                if healthy is False:
                    ok = False
            return self._json(
                {"healthy": ok, "components": components},
                200 if ok else 503)
        if path == "/chart.js":
            body = _CHART_JS.encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/javascript")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path == "/train/sessions":
            sids = sorted({s for st in storages for s in st.list_session_ids()})
            return self._json(sids)
        if path == "/train/overview":
            sid = params.get("sid")
            if sid is None:
                return self._json({"error": "sid required"}, 400)
            return self._json(self._overview(storages, sid))
        # model tab (ref: TrainModule.java:98-104 — /train/model,
        # /train/model/data/:layerId, /train/model/graph)
        if path in ("/train/model", "/train/model/"):
            return self._html(_MODEL_PAGE)
        if path == "/train/model/layers":
            sid = params.get("sid")
            if sid is None:
                return self._json({"error": "sid required"}, 400)
            return self._json(self._layer_ids(storages, sid))
        if path.startswith("/train/model/data"):
            sid = params.get("sid")
            if sid is None:
                return self._json({"error": "sid required"}, 400)
            layer_id = urllib.parse.unquote(
                path[len("/train/model/data"):].lstrip("/"))
            layer_id = params.get("layerId", layer_id)
            return self._json(self._model_data(storages, sid, layer_id))
        # system tab (ref: TrainModule.java:105-116 — /train/system,
        # /train/system/data)
        if path in ("/train/system", "/train/system/"):
            return self._html(_SYSTEM_PAGE)
        if path == "/train/system/data":
            sid = params.get("sid")
            if sid is None:
                return self._json({"error": "sid required"}, 400)
            return self._json(self._system_data(storages, sid))
        # evaluation results stored via the router (eval/serde round-trip)
        if path == "/train/evaluations":
            sid = params.get("sid")
            if sid is None:
                return self._json({"error": "sid required"}, 400)
            out = []
            for st in storages:
                try:
                    out.extend(st.get_evaluations(sid))
                except NotImplementedError:
                    pass
            return self._json(out)
        # conv-activations tab (ref: ConvolutionalListenerModule.java:47 —
        # /activations serves the latest tiled grids)
        if path in ("/activations", "/activations/"):
            return self._html(_ACTIVATIONS_PAGE)
        if path == "/activations/data":
            # snapshot: the fit thread may insert sessions mid-iteration
            acts = dict(self.server.activation_sessions)
            return self._json({
                "sessions": sorted(acts),
                "info": {sid: {"iteration": a["iteration"],
                               "layers": sorted(a["pngs"])}
                         for sid, a in acts.items()}})
        if path == "/activations/img":
            sid = params.get("sid")
            a = self.server.activation_sessions.get(sid)
            try:
                layer = int(params.get("layer", -1))
            except ValueError:
                layer = -1
            png = (a or {}).get("pngs", {}).get(layer)
            if png is None:
                return self._json({"error": "no such activation"}, 404)
            self.send_response(200)
            self.send_header("Content-Type", "image/png")
            self.send_header("Content-Length", str(len(png)))
            self.end_headers()
            self.wfile.write(png)
            return
        # t-SNE module (ref: ui/module/tsne/TsneModule.java — upload +
        # per-session coordinate plots)
        if path in ("/tsne", "/tsne/"):
            body = _TSNE_PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path == "/tsne/sessions":
            return self._json(list(self.server.tsne_sessions))
        if path == "/tsne/coords":
            sid = params.get("sid")
            data = self.server.tsne_sessions.get(sid)
            if data is None:
                return self._json({"error": f"unknown session {sid!r}"}, 404)
            return self._json(data)
        self._json({"error": "not found"}, 404)

    def do_POST(self):
        path = self.path.partition("?")[0].rstrip("/")
        # t-SNE upload (ref: TsneModule.java POST /tsne/upload/:sid)
        if path == "/tsne/upload":
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(payload, dict):
                    raise ValueError("body must be a JSON object")
                sid = str(payload.get("sessionId", "uploaded"))
                coords = [[float(a), float(b)]
                          for a, b in payload["coords"]]
                labels = payload.get("labels")
                if labels is not None:
                    labels = [str(l) for l in labels]
                    if len(labels) != len(coords):
                        raise ValueError("labels/coords length mismatch")
            except (KeyError, TypeError, ValueError) as e:
                return self._json({"error": f"malformed payload: {e}"}, 400)
            self.server.tsne_sessions[sid] = {"coords": coords,
                                              "labels": labels}
            return self._json({"status": "ok", "sessionId": sid})
        # remote stats receiver (ref: RemoteReceiverModule.java)
        if path != "/remoteReceive":
            return self._json({"error": "not found"}, 404)
        if not self.server.remote_enabled:
            return self._json({"error": "remote receiver disabled"}, 403)
        if not self.server.storages:
            return self._json({"error": "no storage attached"}, 503)
        storage = self.server.storages[0]
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
            kind = payload.get("type")
            if kind == "staticInfo":
                storage.put_static_info(str(payload["sessionId"]),
                                        dict(payload["data"]))
            elif kind == "update":
                storage.put_update(StatsReport.from_dict(payload["data"]))
            elif kind == "evaluation":
                # eval/serde JSON rides the same remote route and is
                # reloadable via GET /train/evaluations + eval_from_dict
                storage.put_evaluation(str(payload["sessionId"]),
                                       dict(payload["data"]))
            else:
                return self._json({"error": f"unknown type {kind!r}"}, 400)
        except (KeyError, TypeError, ValueError) as e:
            return self._json({"error": f"malformed payload: {e}"}, 400)
        self._json({"status": "ok"})

    @staticmethod
    def _updates(storages: List[StatsStorage], sid: str) -> List[StatsReport]:
        updates: List[StatsReport] = []
        for st in storages:
            updates.extend(st.get_all_updates(sid))
        updates.sort(key=lambda r: r.iteration)
        return updates

    @classmethod
    def _layer_ids(cls, storages, sid) -> List[str]:
        """Top-level param-tree groups ("layer0", "layer1", ...) seen in any
        report — the :layerId values of the model tab."""
        layers = set()
        for r in cls._updates(storages, sid):
            for k in list(r.param_mean_magnitudes) + \
                    list(r.param_histograms):
                layers.add(str(k).split(".", 1)[0])
        return sorted(layers)

    @classmethod
    def _model_data(cls, storages, sid, layer_id: str) -> dict:
        """Per-layer time series + latest histograms (ref:
        TrainModule.getModelData :~400 — mean magnitude chart, activations,
        learning rates, param histograms per layer)."""
        def match(name: str) -> bool:
            return not layer_id or name == layer_id or \
                str(name).startswith(layer_id + ".")

        mm: dict = {}
        umm: dict = {}
        hists: dict = {}
        for r in cls._updates(storages, sid):
            for k, v in r.param_mean_magnitudes.items():
                if match(str(k)):
                    mm.setdefault(str(k), []).append(
                        [_int(r.iteration), _num(v)])
            for k, v in r.update_mean_magnitudes.items():
                if match(str(k)):
                    umm.setdefault(str(k), []).append(
                        [_int(r.iteration), _num(v)])
            for k, h in r.param_histograms.items():
                if match(str(k)) and isinstance(h, dict):
                    hists[str(k)] = {          # latest wins
                        "iteration": _int(r.iteration),
                        "bins": [_num(b) for b in h.get("bins", [])],
                        "counts": [_int(c) for c in h.get("counts", [])]}
        return {"sessionId": sid, "layerId": layer_id,
                "meanMagnitudes": mm, "updateMeanMagnitudes": umm,
                "histograms": hists}

    @classmethod
    def _system_data(cls, storages, sid) -> dict:
        """Memory/timing series + software info (ref: TrainModule
        /train/system/data — JVM memory, hardware, software tables)."""
        mem, itms, sps = [], [], []
        for r in cls._updates(storages, sid):
            it = _int(r.iteration)
            if r.memory_rss_mb is not None:
                mem.append([it, _num(r.memory_rss_mb)])
            if r.iteration_time_ms is not None:
                itms.append([it, _num(r.iteration_time_ms)])
            if r.samples_per_sec is not None:
                sps.append([it, _num(r.samples_per_sec)])
        import platform as _platform

        import jax as _jax
        import numpy as _np
        software = {"python": _platform.python_version(),
                    "jax": _jax.__version__,
                    "numpy": _np.__version__,
                    "platform": _platform.platform()}
        try:
            # device info only if a backend is ALREADY initialized —
            # default_backend() would otherwise block initializing one
            # (hangs when the TPU tunnel is down), and a UI route must
            # never be the thing that first touches the accelerator
            from jax._src import xla_bridge as _xb
            if getattr(_xb, "_backends", None):
                software["backend"] = _jax.default_backend()
                software["deviceCount"] = _jax.device_count()
        except Exception:  # noqa: BLE001 — info row is best-effort
            pass
        return {"sessionId": sid, "memory": mem,
                "iterationTimesMs": itms, "samplesPerSec": sps,
                "software": software}

    @staticmethod
    def _overview(storages: List[StatsStorage], sid: str) -> dict:
        static = None
        updates: List[StatsReport] = []
        for st in storages:
            static = static or st.get_static_info(sid)
            updates.extend(st.get_all_updates(sid))
        updates.sort(key=lambda r: r.iteration)

        pmm: dict = {}
        for r in updates:
            for k, v in r.param_mean_magnitudes.items():
                pmm.setdefault(str(k), []).append([_int(r.iteration), _num(v)])
        last = updates[-1] if updates else None
        return {
            "sessionId": sid,
            "modelClass": str((static or {}).get("modelClass") or "")[:200],
            "numParams": _num((static or {}).get("numParams")),
            "scores": [[_int(r.iteration), _num(r.score)] for r in updates],
            "paramMeanMagnitudes": pmm,
            "lastIteration": _int(last.iteration) if last else None,
            "lastIterTimeMs": _num(last.iteration_time_ms) if last else None,
            "memoryRssMb": _num(last.memory_rss_mb) if last else None,
        }


class UIServer:
    """Singleton UI server (ref: api/UIServer.java — getInstance(),
    attach(statsStorage), enableRemoteListener())."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.storages = []
        self._httpd.remote_enabled = False
        self._httpd.tsne_sessions = {}
        self._httpd.activation_sessions = {}
        self._httpd.health_probes = {}
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        log.info("UI server at http://127.0.0.1:%d/train", self.port)

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    def attach(self, storage: StatsStorage) -> None:
        if storage not in self._httpd.storages:
            self._httpd.storages.append(storage)

    def detach(self, storage: StatsStorage) -> None:
        if storage in self._httpd.storages:
            self._httpd.storages.remove(storage)

    def attach_health(self, name: str, probe) -> None:
        """Register a component under the ``/health`` endpoint:
        `probe` is a zero-arg callable returning a JSON-able dict (an
        engine's or fleet router's ``health()``). A dict carrying
        ``healthy: False`` — or a probe that raises — turns the
        endpoint's status into 503."""
        self._httpd.health_probes[name] = probe

    def detach_health(self, name: str) -> None:
        self._httpd.health_probes.pop(name, None)

    def upload_tsne(self, coords, labels=None,
                    session_id: str = "uploaded") -> None:
        """Publish 2-D t-SNE coordinates to the /tsne tab (ref:
        TsneModule.uploadFile — here arrays instead of a coord file;
        pair with plot.tsne.Tsne/BarnesHutTsne.fit_transform)."""
        import numpy as _np
        c = _np.asarray(coords, float)
        if c.ndim != 2 or c.shape[1] < 2:
            raise ValueError("coords must be [N, 2+]")
        data = {"coords": c[:, :2].tolist(),
                "labels": None if labels is None
                else [str(l) for l in labels]}
        if data["labels"] is not None and len(data["labels"]) != len(c):
            raise ValueError("labels/coords length mismatch")
        self._httpd.tsne_sessions[session_id] = data

    def publish_activations(self, session_id: str, iteration: int,
                            grids) -> None:
        """Publish conv activation grids to the /activations tab (ref:
        ConvolutionalListenerModule.java:47). `grids` is a list of
        (layer_index, [H,W] uint8 array); the latest iteration replaces the
        previous one, like the reference's single-image tab."""
        from deeplearning4j_tpu.ui.convolutional import encode_png_gray
        pngs = {int(li): encode_png_gray(g) for li, g in grids}
        self._httpd.activation_sessions[session_id] = {
            "iteration": int(iteration), "pngs": pngs}

    def enable_remote_listener(self, storage: Optional[StatsStorage] = None):
        """ref: UIServer.enableRemoteListener — POSTs to /remoteReceive land
        in the first attached storage (or the one given here); with no
        storage at all an InMemoryStatsStorage is created, like the
        reference."""
        if storage is not None:
            # atomic list swap: handler threads index storages[0] and must
            # never observe a transiently-empty list
            self._httpd.storages = [storage] + [
                s for s in self._httpd.storages if s is not storage]
        elif not self._httpd.storages:
            from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
            self._httpd.storages.append(InMemoryStatsStorage())
        self._httpd.remote_enabled = True

    def stop(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        self._httpd.server_close()  # release the listening socket
        if UIServer._instance is self:
            UIServer._instance = None


class RemoteUIStatsStorageRouter(StatsStorage):
    """Client that routes stats to a remote UIServer over HTTP POST
    (ref: core api/storage/impl/RemoteUIStatsStorageRouter.java:1-355 —
    retry with backoff on failure; here: bounded retries, then drop+warn)."""

    def __init__(self, url: str, retries: int = 3, timeout: float = 5.0):
        self.url = url.rstrip("/") + "/remoteReceive"
        self.retries = retries
        self.timeout = timeout

    def _post(self, payload: dict) -> bool:
        data = json.dumps(payload).encode()
        for attempt in range(self.retries):
            try:
                req = urllib.request.Request(
                    self.url, data=data,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    return r.status == 200
            except Exception as e:  # noqa: BLE001
                if attempt == self.retries - 1:
                    log.warning("remote stats post failed: %s", e)
        return False

    def put_static_info(self, session_id, info):
        self._post({"type": "staticInfo", "sessionId": session_id,
                    "data": info})

    def put_update(self, report: StatsReport):
        self._post({"type": "update", "data": report.to_dict()})

    def put_evaluation(self, session_id, eval_dict):
        """POST an eval/serde dict to the remote UI; reload it with
        GET /train/evaluations + eval_from_dict."""
        self._post({"type": "evaluation", "sessionId": session_id,
                    "data": eval_dict})

    # remote router is write-only (ref: RemoteUIStatsStorageRouter is a
    # StatsStorageRouter, not a StatsStorage)
    def list_session_ids(self):
        return []

    def get_static_info(self, session_id):
        return None

    def get_all_updates(self, session_id):
        return []

    def get_evaluations(self, session_id):
        return []
