"""Stats collection listener.

Equivalent of ui-model ui/stats/BaseStatsListener.java (:233 onForwardPass,
:291 onBackwardPass, :296 iterationDone — score, param/gradient/update
histograms and mean magnitudes, memory, timings) + SbeStatsReport.

The SBE binary wire format is replaced by plain dict records (JSON-ready);
the storage layer handles persistence. Histograms are computed on host from
the (already device-resident) param pytree — one bulk transfer per report,
throttled by ``frequency`` exactly like the reference's listenerFrequency.
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener

try:
    import resource
except ImportError:  # non-posix
    resource = None


@dataclass
class StatsReport:
    """One iteration's stats record (ref: impl/SbeStatsReport.java)."""
    session_id: str
    worker_id: str
    iteration: int
    timestamp: float
    score: float
    # mean magnitude per param tensor name
    param_mean_magnitudes: Dict[str, float] = field(default_factory=dict)
    update_mean_magnitudes: Dict[str, float] = field(default_factory=dict)
    # histograms: name -> (bin_edges list, counts list)
    param_histograms: Dict[str, Any] = field(default_factory=dict)
    memory_rss_mb: Optional[float] = None
    iteration_time_ms: Optional[float] = None
    samples_per_sec: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "sessionId": self.session_id, "workerId": self.worker_id,
            "iteration": self.iteration, "timestamp": self.timestamp,
            "score": self.score,
            "paramMeanMagnitudes": self.param_mean_magnitudes,
            "updateMeanMagnitudes": self.update_mean_magnitudes,
            "paramHistograms": self.param_histograms,
            "memoryRssMb": self.memory_rss_mb,
            "iterationTimeMs": self.iteration_time_ms,
            "samplesPerSec": self.samples_per_sec,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StatsReport":
        return cls(session_id=d["sessionId"], worker_id=d["workerId"],
                   iteration=d["iteration"], timestamp=d["timestamp"],
                   score=d["score"],
                   param_mean_magnitudes=d.get("paramMeanMagnitudes", {}),
                   update_mean_magnitudes=d.get("updateMeanMagnitudes", {}),
                   param_histograms=d.get("paramHistograms", {}),
                   memory_rss_mb=d.get("memoryRssMb"),
                   iteration_time_ms=d.get("iterationTimeMs"),
                   samples_per_sec=d.get("samplesPerSec"))


def _current_rss_mb() -> Optional[float]:
    """Current (not peak) resident set size from /proc/self/status VmRSS."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0  # kB -> MB
    except OSError:
        pass
    return None


def _flatten_params(params, prefix="") -> Dict[str, np.ndarray]:
    out = {}
    if isinstance(params, dict):
        for k, v in params.items():
            out.update(_flatten_params(v, f"{prefix}{k}."))
    else:
        out[prefix.rstrip(".")] = np.asarray(params)
    return out


class StatsListener(TrainingListener):
    """Collects per-iteration stats into a StatsStorage
    (ref: BaseStatsListener.java; listenerFrequency semantics)."""

    def __init__(self, storage, frequency: int = 1,
                 session_id: Optional[str] = None, worker_id: str = "worker-0",
                 collect_histograms: bool = True, histogram_bins: int = 20,
                 collect_mean_magnitudes: bool = True):
        self.storage = storage
        self.frequency = max(1, frequency)
        self.session_id = session_id or f"session-{int(time.time() * 1000)}"
        self.worker_id = worker_id
        self.collect_histograms = collect_histograms
        self.histogram_bins = histogram_bins
        self.collect_mean_magnitudes = collect_mean_magnitudes
        self._last_iter_time: Optional[float] = None
        self._init_posted = False

    def iteration_done(self, model, iteration: int, score: float):
        now = time.time()
        it_ms = None
        if self._last_iter_time is not None:
            it_ms = (now - self._last_iter_time) * 1000.0
        self._last_iter_time = now
        if iteration % self.frequency != 0:
            return
        if not self._init_posted:
            self.storage.put_static_info(self.session_id, {
                "sessionId": self.session_id,
                "workerId": self.worker_id,
                "startTime": now,
                "modelClass": type(model).__name__,
                "numParams": getattr(model, "num_params", lambda: None)(),
                "configJson": self._config_json(model),
            })
            self._init_posted = True

        report = StatsReport(self.session_id, self.worker_id, iteration,
                             now, float(score), iteration_time_ms=it_ms)
        params = getattr(model, "params", None)
        if params:
            flat = _flatten_params(params)
            if self.collect_mean_magnitudes:
                report.param_mean_magnitudes = {
                    k: float(np.mean(np.abs(v))) for k, v in flat.items()}
            if self.collect_histograms:
                for k, v in flat.items():
                    counts, edges = np.histogram(v, bins=self.histogram_bins)
                    report.param_histograms[k] = {
                        "bins": [float(e) for e in edges],
                        "counts": [int(c) for c in counts]}
        rss_mb = _current_rss_mb()
        if rss_mb is None and resource is not None:
            # fallback: peak RSS (never decreases) when /proc is unavailable
            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # linux reports KiB, darwin reports bytes
            divisor = 1024.0 * 1024.0 if sys.platform == "darwin" else 1024.0
            rss_mb = rss / divisor
        if rss_mb is not None:
            report.memory_rss_mb = rss_mb
        self.storage.put_update(report)

    @staticmethod
    def _config_json(model) -> Optional[str]:
        conf = getattr(model, "conf", None)
        to_json = getattr(conf, "to_json", None)
        if callable(to_json):
            try:
                return to_json()
            except Exception:
                return None
        return None
