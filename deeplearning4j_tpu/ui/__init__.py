"""Training observability: stats collection, storage, web UI.

TPU-native equivalent of deeplearning4j-ui-parent (SURVEY §2.11):
StatsListener (ui/stats/BaseStatsListener.java), StatsStorage impls
(ui/storage/ InMemory/File/SQLite), PlayUIServer + train modules, and
RemoteUIStatsStorageRouter / RemoteReceiverModule.
"""

from deeplearning4j_tpu.ui.stats import StatsListener, StatsReport  # noqa: F401
from deeplearning4j_tpu.ui.storage import (  # noqa: F401
    StatsStorage, InMemoryStatsStorage, FileStatsStorage,
)
from deeplearning4j_tpu.ui.server import UIServer, RemoteUIStatsStorageRouter  # noqa: F401
from deeplearning4j_tpu.ui.components import (  # noqa: F401
    ChartHistogram, ChartHorizontalBar, ChartLine, ChartScatter,
    ChartStackedArea, ChartTimeline, Component, ComponentDiv,
    ComponentTable, ComponentText, DecoratorAccordion, Style, render_page,
)
