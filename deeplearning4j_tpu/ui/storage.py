"""Stats storage backends.

Equivalent of the StatsStorage API (core api/storage/StatsStorage.java:222,
StatsStorageRouter) and its impls (ui/storage/InMemoryStatsStorage,
FileStatsStorage (MapDB), sqlite/J7FileStatsStorage). FileStatsStorage here
uses stdlib sqlite3 — the idiomatic equivalent of linking MapDB/SQLite.
"""

from __future__ import annotations

import json
import sqlite3
import threading
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

from deeplearning4j_tpu.ui.stats import StatsReport


class StatsStorage:
    """Persistence-agnostic stats routing API
    (ref: api/storage/StatsStorage.java). Also the router: listeners call
    ``put_update``/``put_static_info`` directly."""

    def put_static_info(self, session_id: str, info: Dict[str, Any]) -> None:
        raise NotImplementedError

    def put_update(self, report: StatsReport) -> None:
        raise NotImplementedError

    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def get_static_info(self, session_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def get_all_updates(self, session_id: str) -> List[StatsReport]:
        raise NotImplementedError

    def get_latest_update(self, session_id: str) -> Optional[StatsReport]:
        ups = self.get_all_updates(session_id)
        return ups[-1] if ups else None

    # evaluation results ride the same storage/router chain (ref: the
    # reference persists eval JSON via eval/serde + stats storage)
    def put_evaluation(self, session_id: str,
                       eval_dict: Dict[str, Any]) -> None:
        raise NotImplementedError

    def get_evaluations(self, session_id: str) -> List[Dict[str, Any]]:
        raise NotImplementedError

    # listener registration (ref: StatsStorage.registerStatsStorageListener)
    def register_listener(self, cb: Callable[[str], None]) -> None:
        if not hasattr(self, "_listeners"):
            self._listeners = []
        self._listeners.append(cb)

    def _notify(self, session_id: str) -> None:
        for cb in getattr(self, "_listeners", []):
            cb(session_id)

    def close(self) -> None:
        pass


class InMemoryStatsStorage(StatsStorage):
    """ref: ui/storage/InMemoryStatsStorage.java."""

    def __init__(self):
        self._static: Dict[str, Dict[str, Any]] = {}
        self._updates: Dict[str, List[StatsReport]] = defaultdict(list)
        self._evals: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
        self._lock = threading.Lock()

    def put_static_info(self, session_id, info):
        with self._lock:
            self._static[session_id] = dict(info)
        self._notify(session_id)

    def put_update(self, report):
        with self._lock:
            self._updates[report.session_id].append(report)
        self._notify(report.session_id)

    def list_session_ids(self):
        with self._lock:
            keys = set(self._static) | set(self._updates)
        return sorted(keys)

    def get_static_info(self, session_id):
        with self._lock:
            return self._static.get(session_id)

    def get_all_updates(self, session_id):
        with self._lock:
            return list(self._updates.get(session_id, []))

    def put_evaluation(self, session_id, eval_dict):
        with self._lock:
            self._evals[session_id].append(dict(eval_dict))
        self._notify(session_id)

    def get_evaluations(self, session_id):
        with self._lock:
            return list(self._evals.get(session_id, []))


class FileStatsStorage(StatsStorage):
    """SQLite-backed storage (ref: ui/storage/FileStatsStorage.java /
    sqlite J7FileStatsStorage). One file, survives restarts, readable by a
    UIServer attached later."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS static_info "
                "(session_id TEXT PRIMARY KEY, json TEXT)")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS updates "
                "(session_id TEXT, iteration INTEGER, json TEXT)")
            self._conn.execute(
                "CREATE INDEX IF NOT EXISTS idx_updates ON updates "
                "(session_id, iteration)")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS evaluations "
                "(session_id TEXT, seq INTEGER, json TEXT)")
            self._conn.commit()

    def put_static_info(self, session_id, info):
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO static_info VALUES (?, ?)",
                (session_id, json.dumps(info)))
            self._conn.commit()
        self._notify(session_id)

    def put_update(self, report):
        with self._lock:
            self._conn.execute(
                "INSERT INTO updates VALUES (?, ?, ?)",
                (report.session_id, report.iteration,
                 json.dumps(report.to_dict())))
            self._conn.commit()
        self._notify(report.session_id)

    def list_session_ids(self):
        with self._lock:
            rows = self._conn.execute(
                "SELECT session_id FROM static_info UNION "
                "SELECT DISTINCT session_id FROM updates").fetchall()
        return sorted(r[0] for r in rows)

    def get_static_info(self, session_id):
        with self._lock:
            row = self._conn.execute(
                "SELECT json FROM static_info WHERE session_id=?",
                (session_id,)).fetchone()
        return json.loads(row[0]) if row else None

    def get_all_updates(self, session_id):
        with self._lock:
            rows = self._conn.execute(
                "SELECT json FROM updates WHERE session_id=? "
                "ORDER BY iteration", (session_id,)).fetchall()
        return [StatsReport.from_dict(json.loads(r[0])) for r in rows]

    def put_evaluation(self, session_id, eval_dict):
        with self._lock:
            (n,) = self._conn.execute(
                "SELECT COUNT(*) FROM evaluations WHERE session_id=?",
                (session_id,)).fetchone()
            self._conn.execute(
                "INSERT INTO evaluations VALUES (?, ?, ?)",
                (session_id, n, json.dumps(eval_dict)))
            self._conn.commit()
        self._notify(session_id)

    def get_evaluations(self, session_id):
        with self._lock:
            rows = self._conn.execute(
                "SELECT json FROM evaluations WHERE session_id=? "
                "ORDER BY seq", (session_id,)).fetchall()
        return [json.loads(r[0]) for r in rows]

    def close(self):
        with self._lock:
            self._conn.close()
