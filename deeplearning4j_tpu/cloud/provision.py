"""TPU-VM cluster provisioning glue.

TPU-native equivalent of the reference's EC2 cluster tooling
(deeplearning4j-aws/.../ec2/provision/ClusterSetup.java — create boxes,
provision via SSH/SCP (HostProvisioner.java), launch the distributed job;
Ec2BoxCreator for instance creation). The 2024-era counterpart of "spin up
an EC2 cluster for DL4J" is "create a TPU pod slice and start one
jax.distributed process per worker", and the vendor-blessed interface for
that is the gcloud CLI — so this module builds exact gcloud/scp command
PLANS and executes them through a pluggable runner:

- plans are inspectable and testable without any cloud credentials or
  network egress (the zero-egress CI runs assert the command lines);
- `exec()` runs the plan with subprocess when gcloud exists, raising a
  clear error when it does not (like the reference raising without AWS
  credentials).

The per-worker environment wiring is the part with real content: worker i
of an N-worker slice gets JAX_COORDINATOR_ADDRESS=<worker0>:<port>,
JAX_NUM_PROCESSES=N, JAX_PROCESS_ID=i — exactly what
parallel/distributed.initialize() consumes on the other end (the same
pairing as ClusterSetup's master/worker setup scripts + Spark master URL).
"""

from __future__ import annotations

import logging
import shutil
import subprocess
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

log = logging.getLogger(__name__)

#: workers (hosts) per accelerator type — chips/hosts follows the TPU
#: generation layout (v5e: 8 chips/host; v4: 4 chips/host pods)
_WORKERS_BY_TYPE = {
    "v5litepod-1": 1, "v5litepod-4": 1, "v5litepod-8": 1,
    "v5litepod-16": 2, "v5litepod-32": 4, "v5litepod-64": 8,
    "v5litepod-128": 16, "v5litepod-256": 32,
    "v4-8": 1, "v4-16": 2, "v4-32": 4, "v4-64": 8,
}


def workers_for(accelerator_type: str) -> int:
    """Host count of a slice (ref analogue: ClusterSetup numWorkers)."""
    if accelerator_type in _WORKERS_BY_TYPE:
        return _WORKERS_BY_TYPE[accelerator_type]
    raise ValueError(
        f"unknown accelerator type {accelerator_type!r}; known: "
        f"{sorted(_WORKERS_BY_TYPE)}")


@dataclass
class TpuClusterSpec:
    """What to create (ref: Ec2BoxCreator ami/size/securityGroup ->
    TPU-VM name/zone/type/version)."""

    name: str
    zone: str = "us-central1-a"
    accelerator_type: str = "v5litepod-8"
    runtime_version: str = "tpu-ubuntu2204-base"
    preemptible: bool = False
    network: Optional[str] = None

    @property
    def num_workers(self) -> int:
        return workers_for(self.accelerator_type)


Runner = Callable[[List[str]], "subprocess.CompletedProcess"]


def _default_runner(cmd: List[str]) -> "subprocess.CompletedProcess":
    if shutil.which(cmd[0]) is None:
        raise RuntimeError(
            f"{cmd[0]!r} not found on PATH — install the Google Cloud SDK "
            "or pass a custom runner (plans can also be used directly via "
            "the *_commands() methods)")
    log.info("exec: %s", " ".join(cmd))
    return subprocess.run(cmd, check=True, capture_output=True, text=True)


class ClusterSetup:
    """Create + provision + launch on a TPU pod slice
    (ref: ClusterSetup.java exec() — create boxes, provision master/
    workers, run the distributed job)."""

    def __init__(self, spec: TpuClusterSpec,
                 runner: Optional[Runner] = None):
        self.spec = spec
        self._run = runner or _default_runner

    # ---- plan builders (inspectable without credentials) -------------
    def create_commands(self) -> List[List[str]]:
        s = self.spec
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "create", s.name,
               f"--zone={s.zone}",
               f"--accelerator-type={s.accelerator_type}",
               f"--version={s.runtime_version}"]
        if s.preemptible:
            cmd.append("--preemptible")
        if s.network:
            cmd.append(f"--network={s.network}")
        return [cmd]

    def provision_commands(self, package_path: str,
                           remote_dir: str = "~/job") -> List[List[str]]:
        """SCP the training package to every worker (ref:
        HostProvisioner.uploadAndRun / ClusterSetup provisionMaster+
        provisionWorkers)."""
        s = self.spec
        return [["gcloud", "compute", "tpus", "tpu-vm", "scp",
                 "--recurse", package_path,
                 f"{s.name}:{remote_dir}", f"--zone={s.zone}",
                 f"--worker={w}"]
                for w in range(s.num_workers)]

    def setup_commands(self, setup_script: str) -> List[List[str]]:
        """Run a dependency-setup script on all workers at once (ref:
        ClusterSetup -wscript/-mscript customization hooks)."""
        s = self.spec
        return [["gcloud", "compute", "tpus", "tpu-vm", "ssh", s.name,
                 f"--zone={s.zone}", "--worker=all",
                 f"--command={setup_script}"]]

    def worker_env(self, worker: int, coordinator_host: str,
                   port: int = 8476) -> Dict[str, str]:
        """The jax.distributed environment for worker i — what
        parallel/distributed.initialize() consumes (the Spark-master-URL
        analogue)."""
        n = self.spec.num_workers
        if not 0 <= worker < n:
            raise ValueError(f"worker {worker} out of range 0..{n - 1}")
        return {"JAX_COORDINATOR_ADDRESS": f"{coordinator_host}:{port}",
                "JAX_NUM_PROCESSES": str(n),
                "JAX_PROCESS_ID": str(worker)}

    def run_commands(self, train_command: str,
                     coordinator_host: Optional[str] = None,
                     port: int = 8476,
                     auto_init: bool = False) -> List[List[str]]:
        """Per-worker launch commands for the distributed training job
        (ref: DistributedDeepLearningTrainer). Each worker runs the SAME
        train command (SPMD) with its process id in the env.

        `coordinator_host` must be worker 0's address as seen by every
        worker — a literal IP/hostname, NOT a shell substitution (a
        default like `$(hostname -i)` would expand to each worker's OWN
        address and only worker 0 would find the coordinator). On a
        TPU-VM slice you can instead pass `auto_init=True`: no JAX_*
        env is emitted and jax.distributed.initialize() discovers the
        coordinator from the slice metadata (the path
        parallel/distributed.initialize takes when TPU env markers are
        present)."""
        s = self.spec
        if auto_init:
            if coordinator_host is not None:
                raise ValueError("pass either coordinator_host or "
                                 "auto_init=True, not both")
        elif coordinator_host is None:
            raise ValueError(
                "coordinator_host is required (worker 0's address as "
                "seen by ALL workers), or pass auto_init=True to rely "
                "on TPU-VM metadata discovery")
        out = []
        for w in range(s.num_workers):
            if auto_init:
                launch = train_command
            else:
                env = self.worker_env(w, coordinator_host, port)
                env_str = " ".join(f"{k}={v}" for k, v in env.items())
                launch = f"{env_str} {train_command}"
            out.append(["gcloud", "compute", "tpus", "tpu-vm", "ssh",
                        s.name, f"--zone={s.zone}", f"--worker={w}",
                        f"--command={launch}"])
        return out

    def delete_commands(self) -> List[List[str]]:
        s = self.spec
        return [["gcloud", "compute", "tpus", "tpu-vm", "delete", s.name,
                 f"--zone={s.zone}", "--quiet"]]

    # ---- execution ---------------------------------------------------
    def exec(self, package_path: Optional[str] = None,
             setup_script: Optional[str] = None,
             train_command: Optional[str] = None,
             coordinator_host: Optional[str] = None,
             auto_init: bool = True) -> None:
        """ref: ClusterSetup.exec() — create, provision, run. The launch
        step defaults to TPU-VM metadata auto-discovery (auto_init);
        pass an explicit coordinator_host (with auto_init=False) to pin
        the jax.distributed env instead."""
        plan: List[List[str]] = list(self.create_commands())
        if package_path:
            plan += self.provision_commands(package_path)
        if setup_script:
            plan += self.setup_commands(setup_script)
        if train_command:
            plan += self.run_commands(train_command,
                                      coordinator_host=coordinator_host,
                                      auto_init=auto_init)
        for cmd in plan:
            self._run(cmd)

    def teardown(self) -> None:
        for cmd in self.delete_commands():
            self._run(cmd)


class GcsTransfer:
    """Dataset/checkpoint transfer to object storage (ref: S3Uploader /
    S3Downloader under aws/s3/). Command plans over `gcloud storage`."""

    def __init__(self, runner: Optional[Runner] = None):
        self._run = runner or _default_runner

    def upload_commands(self, local: str, bucket_url: str) -> List[List[str]]:
        if not bucket_url.startswith("gs://"):
            raise ValueError(f"bucket url must start with gs://, got "
                             f"{bucket_url!r}")
        return [["gcloud", "storage", "cp", "--recursive", local,
                 bucket_url]]

    def download_commands(self, bucket_url: str, local: str) -> List[List[str]]:
        if not bucket_url.startswith("gs://"):
            raise ValueError(f"bucket url must start with gs://, got "
                             f"{bucket_url!r}")
        return [["gcloud", "storage", "cp", "--recursive", bucket_url,
                 local]]

    def upload(self, local: str, bucket_url: str) -> None:
        for cmd in self.upload_commands(local, bucket_url):
            self._run(cmd)

    def download(self, bucket_url: str, local: str) -> None:
        for cmd in self.download_commands(bucket_url, local):
            self._run(cmd)
