"""Cloud provisioning glue (ref: deeplearning4j-aws — EC2 ClusterSetup,
HostProvisioner, S3 uploader/downloader — as TPU-VM/gcloud equivalents)."""

from deeplearning4j_tpu.cloud.provision import (  # noqa: F401
    ClusterSetup, GcsTransfer, TpuClusterSpec, workers_for,
)
