"""Sentence → CNN tensor iterator.

Equivalent of deeplearning4j-nlp iterator/CnnSentenceDataSetIterator.java:516
— embeds each token with a word-vector model and stacks into
[mb, 1, max_len, vector_size] image-like tensors (sentences along height,
the reference default) with a per-timestep feature mask, one-hot labels.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory,
)


class CnnSentenceDataSetIterator:
    def __init__(self, word_vectors: SequenceVectors,
                 sentences: Sequence[Tuple[str, str]],
                 labels: Sequence[str],
                 batch_size: int = 32,
                 max_sentence_length: int = 64,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 sentences_along_height: bool = True):
        """sentences: (text, label) pairs; labels: ordered label set."""
        self.wv = word_vectors
        self.sentences = list(sentences)
        self.labels = list(labels)
        self.batch_size = batch_size
        self.max_len = max_sentence_length
        self.tf = tokenizer_factory or DefaultTokenizerFactory()
        self.along_height = sentences_along_height
        self._pos = 0

    @property
    def vector_size(self) -> int:
        return self.wv.layer_size

    def reset(self) -> None:
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self.sentences)

    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        batch = self.sentences[self._pos:self._pos + self.batch_size]
        self._pos += len(batch)
        D, L = self.vector_size, self.max_len
        mb = len(batch)
        feats = np.zeros((mb, 1, L, D), np.float32)
        fmask = np.zeros((mb, L), np.float32)
        labels = np.zeros((mb, len(self.labels)), np.float32)
        for bi, (text, label) in enumerate(batch):
            toks = [t for t in self.tf.create(text)
                    if self.wv.vocab.contains_word(t)][:L]
            for ti, tok in enumerate(toks):
                feats[bi, 0, ti] = self.wv.get_word_vector(tok)
                fmask[bi, ti] = 1.0
            labels[bi, self.labels.index(label)] = 1.0
        if not self.along_height:  # [mb,1,D,L]
            feats = feats.transpose(0, 1, 3, 2)
        return DataSet(feats, labels, features_mask=fmask)
