"""Constituency trees + vectorization for recursive models.

TPU-framework equivalent of the reference's tree-parser corpus tooling
(deeplearning4j-nlp-uima text/corpora/treeparser/, SURVEY §2.6):

- Tree                    ← nn/layers/feedforward/autoencoder/recursive/Tree.java
                            (label/value/children/tokens/vector/goldLabel/error)
- ChunkTreeParser         ← TreeParser.java (the reference drives external
                            OpenNLP/cogcomp parser models; here a POS-driven
                            chunk parser builds S → NP/VP/PP → POS → token)
- BinarizeTreeTransformer ← transformer/BinarizeTreeTransformer.java
- CollapseUnaries         ← CollapseUnaries.java
- HeadWordFinder          ← HeadWordFinder.java (same PTB head-rule tables)
- TreeVectorizer          ← TreeVectorizer.java (parse → binarize → collapse
                            unaries → attach labels/word vectors)
- TreeIterator            ← TreeIterator.java (batched tree stream)

Trees come out CNF-shaped (≤2 children after binarization) with word
vectors attached at the leaves — ready for a scan-based recursive net.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.annotation import AnalysisEngine

# ---------------------------------------------------------------------------
# Tree
# ---------------------------------------------------------------------------


class Tree:
    """Labelled ordered tree (ref Tree.java:32-409)."""

    def __init__(self, label: str = "", children: Optional[List["Tree"]] = None,
                 value: Optional[str] = None, begin: int = 0, end: int = 0):
        self.label = label            # syntactic category (getType/label)
        self.value = value            # surface word at leaves (value())
        self.children: List[Tree] = children or []
        self.begin, self.end = begin, end
        self.gold_label: Optional[int] = None
        self.prediction: Optional[np.ndarray] = None
        self.vector: Optional[np.ndarray] = None
        self.error: float = 0.0
        self.tokens: List[str] = []

    # --- structure queries (Tree.java:147-177,300-323) ---
    def is_leaf(self) -> bool:
        return not self.children

    def is_preterminal(self) -> bool:
        return len(self.children) == 1 and self.children[0].is_leaf()

    def first_child(self) -> Optional["Tree"]:
        return self.children[0] if self.children else None

    def last_child(self) -> Optional["Tree"]:
        return self.children[-1] if self.children else None

    def leaves(self) -> List["Tree"]:
        if self.is_leaf():
            return [self]
        out: List[Tree] = []
        for c in self.children:
            out.extend(c.leaves())
        return out

    def yield_words(self) -> List[str]:
        """Surface string of the subtree (ref Tree.yield)."""
        return [leaf.value or "" for leaf in self.leaves()]

    def depth(self) -> int:
        if self.is_leaf():
            return 0
        return 1 + max(c.depth() for c in self.children)

    def parent(self, root: "Tree") -> Optional["Tree"]:
        """Parent of this node under `root` (ref Tree.parent(root))."""
        return root.parent_of(self)

    def parent_of(self, node: "Tree") -> Optional["Tree"]:
        for c in self.children:
            if c is node:
                return self
            p = c.parent_of(node)
            if p is not None:
                return p
        return None

    def error_sum(self) -> float:
        """Total error over the subtree (ref Tree.errorSum:278)."""
        return self.error + sum(c.error_sum() for c in self.children)

    def clone(self) -> "Tree":
        t = Tree(self.label, [c.clone() for c in self.children], self.value,
                 self.begin, self.end)
        t.gold_label, t.error = self.gold_label, self.error
        t.tokens = list(self.tokens)
        if self.vector is not None:
            t.vector = np.array(self.vector)
        return t

    def __repr__(self) -> str:  # PTB-style bracketing
        if self.is_leaf():
            return self.value or ""
        kids = " ".join(repr(c) for c in self.children)
        return f"({self.label} {kids})"


# ---------------------------------------------------------------------------
# Parser: POS-driven chunking into a shallow constituency tree
# ---------------------------------------------------------------------------

#: chunk → POS-tag membership, tried in order within a sentence sweep
_CHUNK_RULES = (
    ("NP", {"DT", "PRP$", "JJ", "JJR", "JJS", "NN", "NNS", "NNP", "NNPS",
            "PRP", "CD", "EX", "WP", "WDT"}),
    ("VP", {"MD", "VB", "VBD", "VBG", "VBN", "VBP", "VBZ", "TO", "RB"}),
    ("PP", {"IN"}),
    ("ADJP", {"JJ", "JJR", "JJS"}),
    ("ADVP", {"RB", "RBR", "RBS", "WRB"}),
)


class ChunkTreeParser:
    """Sentence → constituency tree via POS chunking (ref TreeParser.java
    builds trees from an external parser's output; the chunk grammar here
    produces the same Tree shape for downstream vectorization)."""

    def __init__(self, engine: Optional[AnalysisEngine] = None):
        self.engine = engine or AnalysisEngine.pos_tagger()

    def _chunk_label(self, tag: str) -> str:
        for label, members in _CHUNK_RULES:
            if tag in members:
                return label
        return "X"

    def parse_sentence(self, tagged: Sequence[tuple]) -> Tree:
        """tagged: [(word, pos, begin, end), ...] → S tree."""
        chunks: List[Tree] = []
        current: Optional[Tree] = None
        for word, tag, b, e in tagged:
            leaf = Tree(value=word, begin=b, end=e)
            pre = Tree(tag, [leaf], begin=b, end=e)
            label = self._chunk_label(tag)
            if current is not None and current.label == label:
                current.children.append(pre)
                current.end = e
            else:
                current = Tree(label, [pre], begin=b, end=e)
                chunks.append(current)
        root_b = chunks[0].begin if chunks else 0
        root_e = chunks[-1].end if chunks else 0
        root = Tree("S", chunks, begin=root_b, end=root_e)
        root.tokens = [w for w, _, _, _ in tagged]
        return root

    def get_trees(self, text: str) -> List[Tree]:
        """All sentence trees in `text` (ref TreeParser.getTrees)."""
        doc = self.engine.process(text)
        out = []
        for s in doc.select("sentence"):
            tagged = [(doc.covered_text(t), t.features.get("pos", "NN"),
                       t.begin, t.end) for t in doc.covered(s, "token")]
            if tagged:
                out.append(self.parse_sentence(tagged))
        return out


# ---------------------------------------------------------------------------
# Transformers
# ---------------------------------------------------------------------------


class TreeTransformer:
    """ref transformer/TreeTransformer.java."""

    def transform(self, tree: Tree) -> Tree:
        raise NotImplementedError


class BinarizeTreeTransformer(TreeTransformer):
    """Left-factored binarization: n>2 children become a right-leaning
    spine of @Label intermediates (ref BinarizeTreeTransformer.java)."""

    def transform(self, tree: Tree) -> Tree:
        children = [self.transform(c) for c in tree.children]
        while len(children) > 2:
            right = Tree(f"@{tree.label}", children[-2:],
                         begin=children[-2].begin, end=children[-1].end)
            children = children[:-2] + [right]
        out = Tree(tree.label, children, tree.value, tree.begin, tree.end)
        out.gold_label, out.tokens = tree.gold_label, list(tree.tokens)
        return out


class CollapseUnaries(TreeTransformer):
    """Collapse unary chains X→Y→... to the bottom non-unary node,
    keeping the top label (ref CollapseUnaries.java; preterminals stay)."""

    def transform(self, tree: Tree) -> Tree:
        if tree.is_leaf() or tree.is_preterminal():
            return tree
        node = tree
        while len(node.children) == 1 and not node.is_preterminal():
            node = node.children[0]
        children = [self.transform(c) for c in node.children]
        out = Tree(tree.label, children, node.value, tree.begin, tree.end)
        out.gold_label, out.tokens = tree.gold_label, list(tree.tokens)
        return out


class HeadWordFinder:
    """Per-constituent head word via PTB head-percolation rules
    (ref HeadWordFinder.java:30-48 — same parent/child priority tables)."""

    HEAD1 = {"ADJP JJ", "ADJP JJR", "ADJP JJS", "ADVP RB", "ADVP RBB",
             "LST LS", "NAC NNS", "NAC NN", "NAC PRP", "NAC NNPS", "NAC NNP",
             "NX NNS", "NX NN", "NX PRP", "NX NNPS", "NX NNP", "NP NNS",
             "NP NN", "NP PRP", "NP NNPS", "NP NNP", "NP POS", "NP $",
             "PP IN", "PP TO", "PP RP", "PRT RP", "S VP", "S1 S", "SBAR IN",
             "SBAR WHNP", "SBARQ SQ", "SBARQ VP", "SINV VP", "SQ MD",
             "SQ AUX", "VP VB", "VP VBZ", "VP VBP", "VP VBG", "VP VBN",
             "VP VBD", "VP AUX", "VP AUXG", "VP TO", "VP MD", "WHADJP WRB",
             "WHADVP WRB", "WHNP WP", "WHNP WDT", "WHNP WP$", "WHPP IN",
             "WHPP TO"}
    HEAD2 = {"ADJP VBN", "ADJP RB", "NAC NP", "NAC CD", "NAC FW", "NAC ADJP",
             "NAC JJ", "NX NP", "NX CD", "NX FW", "NX ADJP", "NX JJ",
             "NP CD", "NP ADJP", "NP JJ", "S SINV", "S SBARQ", "S X",
             "PRT RB", "PRT IN", "SBAR WHADJP", "SBAR WHADVP", "SBAR WHPP",
             "SBARQ S", "SBARQ SINV", "SBARQ X", "SINV SBAR", "SQ VP"}

    def find_head(self, tree: Tree) -> Optional[Tree]:
        """Head LEAF of the constituent (ref findHeadWord)."""
        node = tree
        while not node.is_leaf():
            node = self._head_child(node)
        return node

    def _head_child(self, tree: Tree) -> Tree:
        if tree.is_preterminal():
            return tree.children[0]
        for rules in (self.HEAD1, self.HEAD2):
            for c in tree.children:
                if f"{tree.label} {self._cat(c)}" in rules:
                    return c
        # fallback: rightmost child (PTB convention for head-final misses)
        return tree.children[-1]

    @staticmethod
    def _cat(t: Tree) -> str:
        return t.label if t.label else (t.value or "")


# ---------------------------------------------------------------------------
# Vectorization
# ---------------------------------------------------------------------------


class TreeVectorizer:
    """Parse → binarize → collapse-unaries → attach labels + word vectors
    (ref TreeVectorizer.java:33-86: BinarizeTreeTransformer then
    CollapseUnaries over TreeParser output, goldLabel from the sentence
    label)."""

    def __init__(self, parser: Optional[ChunkTreeParser] = None,
                 lookup: Optional[Dict[str, np.ndarray]] = None):
        self.parser = parser or ChunkTreeParser()
        self.binarizer = BinarizeTreeTransformer()
        self.collapser = CollapseUnaries()
        self.lookup = lookup or {}

    def _finalize(self, tree: Tree) -> Tree:
        tree = self.collapser.transform(self.binarizer.transform(tree))
        if self.lookup:
            dim = len(next(iter(self.lookup.values())))
            for leaf in tree.leaves():
                vec = self.lookup.get((leaf.value or "").lower())
                leaf.vector = (np.asarray(vec, np.float32)
                               if vec is not None
                               else np.zeros((dim,), np.float32))
        return tree

    def get_trees(self, text: str) -> List[Tree]:
        return [self._finalize(t) for t in self.parser.get_trees(text)]

    def get_trees_with_labels(self, text: str, label: str,
                              labels: Sequence[str]) -> List[Tree]:
        """Trees with goldLabel = index of `label` in `labels` (ref
        getTreesWithLabels: label index propagated to every node)."""
        idx = list(labels).index(label)
        trees = self.get_trees(text)
        for t in trees:
            stack = [t]
            while stack:
                node = stack.pop()
                node.gold_label = idx
                stack.extend(node.children)
        return trees


class TreeIterator:
    """Batched tree stream over labelled documents (ref TreeIterator.java:
    next(num) pulls sentences, vectorizes, returns tree batches)."""

    def __init__(self, documents: Iterable[tuple], labels: Sequence[str],
                 vectorizer: Optional[TreeVectorizer] = None,
                 batch_size: int = 32):
        self._docs = list(documents)  # (text, label) pairs
        self.labels = list(labels)
        self.vectorizer = vectorizer or TreeVectorizer()
        self.batch_size = batch_size

    def __iter__(self) -> Iterator[List[Tree]]:
        batch: List[Tree] = []
        for text, label in self._docs:
            for t in self.vectorizer.get_trees_with_labels(
                    text, label, self.labels):
                batch.append(t)
                if len(batch) >= self.batch_size:
                    yield batch
                    batch = []
        if batch:
            yield batch
