"""Text annotation pipeline (the deeplearning4j-nlp-uima module's role).

TPU-framework equivalent of the reference's UIMA glue (SURVEY §2.6,
deeplearning4j-nlp-parent/deeplearning4j-nlp-uima): a CAS-like annotated
document, a pipeline of annotators (sentence segmentation, tokenization,
stemming, part-of-speech tagging), sentence iterators and tokenizer
factories driven by the pipeline, and the SentiWordNet scorer.

Reference mapping (file → here):
- text/uima/UimaResource.java            → AnalysisEngine (owns the pipeline)
- text/annotator/SentenceAnnotator.java  → SentenceAnnotator
- text/annotator/TokenizerAnnotator.java → TokenizerAnnotator
- text/annotator/StemmerAnnotator.java   → StemmerAnnotator (Porter)
- text/annotator/PoStagger.java          → PosAnnotator
- text/sentenceiterator/UimaSentenceIterator.java → AnnotationSentenceIterator
- text/tokenization/tokenizerfactory/UimaTokenizerFactory.java
                                         → AnnotationTokenizerFactory
- text/tokenization/tokenizer/PosUimaTokenizer.java → PosFilterTokenizer
  ("any not valid part of speech tags become NONE"; optional stripNones)
- text/tokenization/tokenizer/preprocessor/StemmingPreprocessor.java
                                         → StemmingPreprocessor
- text/corpora/sentiwordnet/SWN3.java    → SWN3

The reference reaches these capabilities through Apache UIMA + OpenNLP
maxent models + the Snowball stemmer; here the pipeline machinery and data
model are first-class, the stemmer is a full Porter implementation, and the
POS tagger is a lexicon+suffix tagger (no bundled maxent model — zero
egress). Tag inventory is Penn Treebank, same as the reference's models.
"""

from __future__ import annotations

import bisect
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Sequence

from deeplearning4j_tpu.nlp.sentence import SentenceIterator
from deeplearning4j_tpu.nlp.tokenization import Tokenizer, TokenizerFactory

# ---------------------------------------------------------------------------
# Data model (CAS equivalent)
# ---------------------------------------------------------------------------


@dataclass
class Annotation:
    """A typed text span with features (UIMA AnnotationFS equivalent).

    `type` is "sentence" or "token"; tokens may carry `pos`, `stem`,
    `lemma` features (ref Token type has getPos/getStem/getLemma —
    PosUimaTokenizer.java:75-81)."""

    begin: int
    end: int
    type: str
    features: Dict[str, str] = field(default_factory=dict)

    def covered_text(self, text: str) -> str:
        return text[self.begin:self.end]


class AnnotatedDocument:
    """Document text + annotation index (UIMA CAS equivalent).

    select/covered mirror JCasUtil.select / JCasUtil.selectCovered, the two
    access patterns every reference consumer uses (SWN3.java:203-204,
    PosUimaTokenizer.java:72-73)."""

    def __init__(self, text: str):
        self.text = text
        self.annotations: List[Annotation] = []
        self._by_type: Dict[str, List[Annotation]] = {}
        self._sorted: Dict[str, bool] = {}

    def add(self, ann: Annotation) -> Annotation:
        self.annotations.append(ann)
        bucket = self._by_type.setdefault(ann.type, [])
        # annotators emit in document order; only mark dirty when not
        if bucket and (bucket[-1].begin, bucket[-1].end) > (ann.begin,
                                                            ann.end):
            self._sorted[ann.type] = False
        bucket.append(ann)
        return ann

    def select(self, type: str) -> List[Annotation]:
        """All annotations of a type, in document order."""
        bucket = self._by_type.get(type, [])
        if not self._sorted.get(type, True):
            bucket.sort(key=lambda a: (a.begin, a.end))
            self._sorted[type] = True
        return list(bucket)

    def covered(self, cover: Annotation, type: str) -> List[Annotation]:
        """Annotations of `type` fully inside `cover` (selectCovered)."""
        bucket = self.select(type)
        lo = bisect.bisect_left(bucket, (cover.begin,),
                                key=lambda a: (a.begin,))
        out = []
        for a in bucket[lo:]:
            if a.begin > cover.end:
                break
            if a.end <= cover.end:
                out.append(a)
        return out

    def covered_text(self, ann: Annotation) -> str:
        return ann.covered_text(self.text)


# ---------------------------------------------------------------------------
# Annotators
# ---------------------------------------------------------------------------


class Annotator:
    """One analysis step over a document (UIMA AnalysisComponent role)."""

    def process(self, doc: AnnotatedDocument) -> None:
        raise NotImplementedError


# candidate boundary: terminator (+ closing quotes) then whitespace then a
# sentence-start character
_SENT_BOUNDARY = re.compile(r"[.!?…][\"')\]]*\s+(?=[\"'(\[]?[A-Z0-9])")
_ABBREVIATIONS = frozenset({"mr", "ms", "mrs", "dr", "st", "vs", "etc", "jr",
                            "sr", "inc", "co", "no", "prof", "gen", "rep",
                            "sen", "e.g", "i.e", "al"})


class SentenceAnnotator(Annotator):
    """Sentence segmentation (ref SentenceAnnotator.java wraps OpenNLP's
    SentenceDetector; here rule-based boundary detection that keeps
    abbreviations and single-letter initials intact)."""

    @staticmethod
    def _is_boundary(text: str, dot: int) -> bool:
        if text[dot] != ".":
            return True  # !, ?, … always end a sentence
        word = re.search(r"[\w.]*$", text[:dot]).group(0).lower()
        if word in _ABBREVIATIONS:
            return False
        if len(word) == 1 and word.isalpha():  # initial: "J. Smith"
            return False
        return True

    def process(self, doc: AnnotatedDocument) -> None:
        text = doc.text
        start = 0
        ends = [m for m in _SENT_BOUNDARY.finditer(text)
                if self._is_boundary(text, m.start())]
        for m in ends + [None]:
            seg = text[start:(m.end() if m else len(text))]
            stripped = seg.strip()
            if stripped:
                b = start + seg.index(stripped[0])
                doc.add(Annotation(b, b + len(stripped), "sentence"))
            start = m.end() if m else len(text)


_TOKEN_RE = re.compile(
    r"<\/?[A-Z]+>"            # markup tokens (PosUimaTokenizer strips these)
    r"|[A-Za-z]+(?:'[A-Za-z]+)?"  # words incl. contractions
    r"|\d+(?:[.,]\d+)*"       # numbers
    r"|[^\sA-Za-z\d]")        # single punctuation


class TokenizerAnnotator(Annotator):
    """Token spans inside each sentence (ref TokenizerAnnotator.java wraps
    the ClearTK/OpenNLP tokenizer)."""

    def process(self, doc: AnnotatedDocument) -> None:
        sentences = doc.select("sentence") or [
            Annotation(0, len(doc.text), "sentence")]
        for s in sentences:
            for m in _TOKEN_RE.finditer(doc.text[s.begin:s.end]):
                doc.add(Annotation(s.begin + m.start(), s.begin + m.end(),
                                   "token"))


class StemmerAnnotator(Annotator):
    """Stores a Porter stem on each token's `stem` feature (ref
    StemmerAnnotator.java wraps the Snowball English stemmer)."""

    def process(self, doc: AnnotatedDocument) -> None:
        for t in doc.select("token"):
            t.features["stem"] = porter_stem(doc.covered_text(t).lower())


class PosAnnotator(Annotator):
    """Penn-Treebank POS tags on each token's `pos` feature.

    Ref PoStagger.java loads an OpenNLP maxent model; this tagger combines
    a closed-class lexicon with suffix/shape rules — the standard baseline
    tagger shape. Swap in a custom `lexicon` for domain text."""

    #: closed-class + frequent-word lexicon (Penn tags)
    LEXICON: Dict[str, str] = {
        "the": "DT", "a": "DT", "an": "DT", "this": "DT", "that": "DT",
        "these": "DT", "those": "DT", "some": "DT", "any": "DT", "no": "DT",
        "each": "DT", "every": "DT",
        "i": "PRP", "you": "PRP", "he": "PRP", "she": "PRP", "it": "PRP",
        "we": "PRP", "they": "PRP", "me": "PRP", "him": "PRP", "her": "PRP",
        "us": "PRP", "them": "PRP",
        "my": "PRP$", "your": "PRP$", "his": "PRP$", "its": "PRP$",
        "our": "PRP$", "their": "PRP$",
        "and": "CC", "or": "CC", "but": "CC", "nor": "CC", "yet": "CC",
        "in": "IN", "on": "IN", "at": "IN", "by": "IN", "with": "IN",
        "from": "IN", "of": "IN", "for": "IN", "as": "IN", "into": "IN",
        "over": "IN", "under": "IN", "after": "IN", "before": "IN",
        "if": "IN", "because": "IN", "while": "IN", "than": "IN",
        "to": "TO",
        "is": "VBZ", "are": "VBP", "was": "VBD", "were": "VBD", "be": "VB",
        "been": "VBN", "being": "VBG", "am": "VBP",
        "has": "VBZ", "have": "VBP", "had": "VBD", "having": "VBG",
        "do": "VBP", "does": "VBZ", "did": "VBD", "done": "VBN",
        "will": "MD", "would": "MD", "can": "MD", "could": "MD",
        "shall": "MD", "should": "MD", "may": "MD", "might": "MD",
        "must": "MD",
        "not": "RB", "n't": "RB", "very": "RB", "too": "RB", "also": "RB",
        "never": "RB", "always": "RB", "often": "RB", "here": "RB",
        "there": "EX", "when": "WRB", "where": "WRB", "why": "WRB",
        "how": "WRB", "who": "WP", "whom": "WP", "what": "WP",
        "which": "WDT", "whose": "WP$",
        "good": "JJ", "new": "JJ", "old": "JJ", "big": "JJ", "small": "JJ",
        "many": "JJ", "much": "JJ", "other": "JJ", "such": "JJ",
        # frequent irregular past forms (no -ed suffix to key on)
        "sat": "VBD", "ran": "VBD", "went": "VBD", "saw": "VBD",
        "said": "VBD", "made": "VBD", "took": "VBD", "got": "VBD",
        "came": "VBD", "gave": "VBD", "found": "VBD", "told": "VBD",
        "left": "VBD", "put": "VBD", "kept": "VBD", "began": "VBD",
        "wrote": "VBD", "stood": "VBD", "heard": "VBD", "let": "VBD",
        "meant": "VBD", "set": "VBD", "met": "VBD", "paid": "VBD",
        "held": "VBD", "knew": "VBD", "thought": "VBD", "felt": "VBD",
        "brought": "VBD", "bought": "VBD", "caught": "VBD",
    }

    def __init__(self, lexicon: Optional[Dict[str, str]] = None):
        self.lexicon = dict(self.LEXICON)
        if lexicon:
            self.lexicon.update(lexicon)

    _PUNCT = {".": ".", ",": ",", ":": ":", ";": ":", "?": ".", "!": ".",
              "(": "-LRB-", ")": "-RRB-", "``": "``", "''": "''",
              '"': "''", "'": "POS", "$": "$", "#": "#"}

    def _tag(self, word: str, prev_tag: Optional[str]) -> str:
        if word in self._PUNCT:
            return self._PUNCT[word]
        low = word.lower()
        if low in self.lexicon:
            return self.lexicon[low]
        if re.fullmatch(r"\d+(?:[.,]\d+)*", word):
            return "CD"
        # suffix/shape rules (ordered)
        if word[0].isupper() and prev_tag not in (None, ".",):
            return "NNPS" if low.endswith("s") else "NNP"
        if low.endswith("ing"):
            return "VBG"
        if low.endswith("ed"):
            return "VBN" if prev_tag in ("VBZ", "VBP", "VBD") else "VBD"
        if low.endswith("ly"):
            return "RB"
        if low.endswith(("ous", "ful", "ible", "able", "al", "ive", "ic")):
            return "JJ"
        if low.endswith("est"):
            return "JJS"
        if low.endswith("er") and prev_tag == "DT":
            return "NN"
        if low.endswith("s") and not low.endswith(("ss", "us", "is")):
            # after a modal/to it's a verb; default plural noun
            return "VBZ" if prev_tag in ("PRP", "NNP", "WDT") else "NNS"
        if prev_tag in ("TO", "MD"):
            return "VB"
        return "NN"

    def process(self, doc: AnnotatedDocument) -> None:
        for s in doc.select("sentence") or [Annotation(0, len(doc.text),
                                                       "sentence")]:
            prev = None
            for t in doc.covered(s, "token"):
                tag = self._tag(doc.covered_text(t), prev)
                t.features["pos"] = tag
                prev = tag


class TrainedPosAnnotator(Annotator):
    """Penn tags from the in-repo trained perceptron (pos_tagger.py) —
    the equivalent of PoStagger.java's trained OpenNLP maxent model,
    measured ~+10 points token accuracy over the PosAnnotator
    lexicon+suffix baseline on the held-out fixture sentences
    (tests/test_pos_tagger.py). Tags whole sentences at once (the model
    uses two-token context each side plus predicted tag history)."""

    def __init__(self, tagger=None):
        if tagger is None:
            from deeplearning4j_tpu.nlp.pos_tagger import default_tagger
            tagger = default_tagger()
        self.tagger = tagger

    def process(self, doc: AnnotatedDocument) -> None:
        for s in doc.select("sentence") or [Annotation(0, len(doc.text),
                                                       "sentence")]:
            tokens = doc.covered(s, "token")
            if not tokens:
                continue
            words = [doc.covered_text(t) for t in tokens]
            for t, tag in zip(tokens, self.tagger.tag(words)):
                t.features["pos"] = tag


class AnalysisEngine:
    """Ordered annotator pipeline over raw text (UimaResource.java role:
    owns the engine, `process(text)` returns a populated document).

    Factory methods mirror the reference's canned pipelines:
    - UimaSentenceIterator.segmenter() → AnalysisEngine.segmenter()
    - UimaTokenizerFactory default engine (tokenizer+stemmer)
      → AnalysisEngine.tokenizer()
    - PosUimaTokenizerFactory engine (sentence+token+pos)
      → AnalysisEngine.pos_tagger()
    """

    def __init__(self, annotators: Sequence[Annotator]):
        self.annotators = list(annotators)

    def process(self, text: str) -> AnnotatedDocument:
        doc = AnnotatedDocument(text)
        for a in self.annotators:
            a.process(doc)
        return doc

    @classmethod
    def segmenter(cls) -> "AnalysisEngine":
        return cls([SentenceAnnotator()])

    @classmethod
    def tokenizer(cls, stem: bool = True) -> "AnalysisEngine":
        anns: List[Annotator] = [SentenceAnnotator(), TokenizerAnnotator()]
        if stem:
            anns.append(StemmerAnnotator())
        return cls(anns)

    @classmethod
    def pos_tagger(cls, trained: bool = True) -> "AnalysisEngine":
        """trained=True (default) uses the in-repo perceptron model —
        the analogue of the reference's trained OpenNLP tagger;
        trained=False keeps the rule/lexicon baseline."""
        pos = TrainedPosAnnotator() if trained else PosAnnotator()
        return cls([SentenceAnnotator(), TokenizerAnnotator(),
                    StemmerAnnotator(), pos])


# ---------------------------------------------------------------------------
# Iterator / tokenizer-factory adapters (the reference module's public face)
# ---------------------------------------------------------------------------


class AnnotationSentenceIterator(SentenceIterator):
    """Sentence stream produced by the segmentation pipeline over documents
    (ref UimaSentenceIterator.java: segments blobs of text into sentences)."""

    def __init__(self, documents: Iterable[str],
                 engine: Optional[AnalysisEngine] = None,
                 preprocessor: Optional[Callable[[str], str]] = None):
        super().__init__(preprocessor)
        self._documents = list(documents)
        self._engine = engine or AnalysisEngine.segmenter()

    def _raw(self) -> Iterator[str]:
        for text in self._documents:
            doc = self._engine.process(text)
            for s in doc.select("sentence"):
                yield doc.covered_text(s)


class AnnotationTokenizerFactory(TokenizerFactory):
    """Tokenizers driven by the annotation pipeline; emits stems when the
    engine ran a StemmerAnnotator (ref UimaTokenizerFactory.java +
    UimaTokenizer.java: checkForLabel + lemma/stem preference)."""

    def __init__(self, engine: Optional[AnalysisEngine] = None,
                 preprocessor: Optional[Callable[[str], str]] = None,
                 use_stems: bool = True):
        super().__init__(preprocessor)
        self.engine = engine or AnalysisEngine.tokenizer()
        self.use_stems = use_stems

    def _words(self, text: str) -> List[str]:
        doc = self.engine.process(text)
        out = []
        for t in doc.select("token"):
            word = doc.covered_text(t)
            if re.fullmatch(r"</?[A-Z]+>", word):  # markup label guard
                continue
            if self.use_stems and "stem" in t.features:
                word = t.features["stem"]
            out.append(word)
        return out

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(self._words(text), self._pre)


class PosFilterTokenizer(Tokenizer):
    """Tokens whose POS is not in `allowed_pos_tags` become "NONE"
    (ref PosUimaTokenizer.java:44-84: invalid → "NONE"; strip_nones drops
    them instead)."""

    def __init__(self, text: str, engine: AnalysisEngine,
                 allowed_pos_tags: Sequence[str],
                 strip_nones: bool = False,
                 preprocessor: Optional[Callable[[str], str]] = None):
        allowed = set(allowed_pos_tags)
        doc = engine.process(text)
        tokens = []
        for t in doc.select("token"):
            word = doc.covered_text(t)
            valid = (not re.fullmatch(r"</?[A-Z]+>", word)
                     and t.features.get("pos") in allowed)
            if valid:
                tokens.append(t.features.get("lemma")
                              or t.features.get("stem") or word)
            elif not strip_nones:
                tokens.append("NONE")
        super().__init__(tokens, preprocessor)


class PosFilterTokenizerFactory(TokenizerFactory):
    """ref PosUimaTokenizerFactory.java."""

    def __init__(self, allowed_pos_tags: Sequence[str],
                 engine: Optional[AnalysisEngine] = None,
                 strip_nones: bool = False,
                 preprocessor: Optional[Callable[[str], str]] = None):
        super().__init__(preprocessor)
        self.engine = engine or AnalysisEngine.pos_tagger()
        self.allowed_pos_tags = list(allowed_pos_tags)
        self.strip_nones = strip_nones

    def create(self, text: str) -> Tokenizer:
        return PosFilterTokenizer(text, self.engine, self.allowed_pos_tags,
                                  self.strip_nones, self._pre)


class StemmingPreprocessor:
    """Token preprocessor applying the Porter stemmer (ref
    StemmingPreprocessor.java chains CommonPreprocessor → SnowballProgram;
    compose with CommonPreprocessor the same way)."""

    def pre_process(self, token: str) -> str:
        return porter_stem(token.lower())

    __call__ = pre_process


# ---------------------------------------------------------------------------
# Porter stemmer (standard algorithm; used by StemmerAnnotator)
# ---------------------------------------------------------------------------

_VOWELS = set("aeiou")


def _is_cons(word: str, i: int) -> bool:
    c = word[i]
    if c in _VOWELS:
        return False
    if c == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """Number of VC sequences ([C](VC)^m[V])."""
    m, i, n = 0, 0, len(stem)
    while i < n and _is_cons(stem, i):
        i += 1
    while i < n:
        while i < n and not _is_cons(stem, i):
            i += 1
        if i >= n:
            break
        m += 1
        while i < n and _is_cons(stem, i):
            i += 1
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(word: str) -> bool:
    return (len(word) >= 2 and word[-1] == word[-2]
            and _is_cons(word, len(word) - 1))


def _ends_cvc(word: str) -> bool:
    if len(word) < 3:
        return False
    return (_is_cons(word, len(word) - 3)
            and not _is_cons(word, len(word) - 2)
            and _is_cons(word, len(word) - 1)
            and word[-1] not in "wxy")


def porter_stem(word: str) -> str:
    """Porter (1980) stemming algorithm, steps 1a-5b."""
    if len(word) <= 2:
        return word
    w = word

    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif not w.endswith("ss") and w.endswith("s"):
        w = w[:-1]

    # step 1b
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    else:
        flag = False
        if w.endswith("ed") and _has_vowel(w[:-2]):
            w, flag = w[:-2], True
        elif w.endswith("ing") and _has_vowel(w[:-3]):
            w, flag = w[:-3], True
        if flag:
            if w.endswith(("at", "bl", "iz")):
                w += "e"
            elif _ends_double_cons(w) and w[-1] not in "lsz":
                w = w[:-1]
            elif _measure(w) == 1 and _ends_cvc(w):
                w += "e"

    # step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"

    # step 2
    for suf, rep in (("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
                     ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
                     ("alli", "al"), ("entli", "ent"), ("eli", "e"),
                     ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
                     ("ator", "ate"), ("alism", "al"), ("iveness", "ive"),
                     ("fulness", "ful"), ("ousness", "ous"), ("aliti", "al"),
                     ("iviti", "ive"), ("biliti", "ble")):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break

    # step 3
    for suf, rep in (("icate", "ic"), ("ative", ""), ("alize", "al"),
                     ("iciti", "ic"), ("ical", "ic"), ("ful", ""),
                     ("ness", "")):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break

    # step 4
    for suf in ("al", "ance", "ence", "er", "ic", "able", "ible", "ant",
                "ement", "ment", "ent", "ou", "ism", "ate", "iti", "ous",
                "ive", "ize"):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 1:
                w = w[:-len(suf)]
            break
    else:
        if w.endswith("ion") and len(w) > 3 and w[-4] in "st":
            if _measure(w[:-3]) > 1:
                w = w[:-3]

    # step 5a
    if w.endswith("e"):
        stem = w[:-1]
        if _measure(stem) > 1 or (_measure(stem) == 1
                                  and not _ends_cvc(stem)):
            w = stem
    # step 5b
    if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]

    return w


# ---------------------------------------------------------------------------
# SentiWordNet scorer
# ---------------------------------------------------------------------------


class SWN3:
    """SentiWordNet 3 polarity scorer (ref SWN3.java).

    Loads the standard SentiWordNet TSV format
    (``pos\tid\tPosScore\tNegScore\tterm#rank [term#rank...]\tgloss``),
    collapsing each word#pos's per-sense scores with the reference's
    harmonic rank weighting (SWN3.java:104-117:
    score = Σ score_i/(i+1) / Σ 1/i). Sentence scoring sums token scores
    and flips the sign when any negation word appears
    (SWN3.java:180-197)."""

    #: bare negators; contractions ("isn't", "don't") are caught by the
    #: n't-suffix check in score_tokens (the tokenizer keeps them whole)
    NEGATION_WORDS = frozenset({
        "not", "no", "never", "cannot", "cant", "wont", "neither",
        "nor", "nothing", "nobody", "none", "without",
    })

    @classmethod
    def _is_negation(cls, token: str) -> bool:
        t = token.lower()
        return t in cls.NEGATION_WORDS or t.endswith("n't")

    #: classForScore thresholds (SWN3.java:156-171). The reference's literal
    #: if-chain leaves (0, 0.25) and (-0.75, -0.5) unreachable/neutral and
    #: routes (0.5, 0.75) to weak_positive; here the same band edges form a
    #: monotone chain instead.
    _CLASSES = (
        (0.75, "strong_positive"), (0.25, "positive"), (0.0, "weak_positive"),
        (-0.25, "weak_negative"), (-0.75, "negative"),
    )

    def __init__(self, path: Optional[str] = None,
                 engine: Optional[AnalysisEngine] = None):
        self._dict: Dict[str, float] = {}
        self._by_term: Dict[str, float] = {}  # term -> sum over POS entries
        self.engine = engine or AnalysisEngine.tokenizer(stem=False)
        if path is not None:
            self.load(path)

    def load(self, path: str) -> None:
        temp: Dict[str, Dict[int, float]] = {}
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or line.startswith("#"):
                    continue
                data = line.split("\t")
                if len(data) < 5 or not data[2] or not data[3]:
                    continue
                score = float(data[2]) - float(data[3])
                for w in data[4].split(" "):
                    if not w or "#" not in w:
                        continue
                    term, rank = w.rsplit("#", 1)
                    key = f"{term}#{data[0]}"
                    temp.setdefault(key, {})[int(rank) - 1] = score
        for key, senses in temp.items():
            num = sum(s / (i + 1) for i, s in senses.items())
            den = sum(1.0 / i for i in range(1, max(senses) + 2))
            score = num / den if den else 0.0
            self._dict[key] = score
            term = key.rsplit("#", 1)[0]
            self._by_term[term] = self._by_term.get(term, 0.0) + score

    def extract(self, word: str) -> float:
        """Sum of the word's scores across POS entries (SWN3.extract)."""
        return self._by_term.get(word, 0.0)

    def score_tokens(self, tokens: Sequence[str]) -> float:
        total = sum(self.extract(t.lower()) for t in tokens)
        if any(self._is_negation(t) for t in tokens):
            total *= -1.0  # negation context flip (SWN3.java:190-194)
        return total

    def score(self, text: str) -> float:
        doc = self.engine.process(text)
        total = 0.0
        for s in doc.select("sentence"):
            total += self.score_tokens(
                [doc.covered_text(t) for t in doc.covered(s, "token")])
        return total

    def class_for_score(self, score: float) -> str:
        if score == 0.0:
            return "neutral"
        for bound, name in self._CLASSES:
            if score > bound:
                return name
        return "strong_negative"

    def classify(self, text: str) -> str:
        return self.class_for_score(self.score(text))
