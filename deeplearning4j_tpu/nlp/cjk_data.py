"""Compact built-in CJK dictionaries for the lattice tokenizers.

The reference's CJK analyzers ship multi-megabyte system dictionaries
(deeplearning4j-nlp-japanese bundles the kuromoji/IPADIC data,
deeplearning4j-nlp-chinese the ansj/jieba tables) — most of their 19.6k
LoC + resources is dictionary data. This module is the zero-egress
counterpart: a hand-curated core-vocabulary dictionary (~1475 Chinese
words with relative frequencies, ~4254 Japanese entries with POS — the
round-3..5b expansions generate frequency-weighted conjugated surfaces
for curated verb, i/na-adjective, suru-noun, counter and keigo lists:
core + extended paradigms (progressive, potential, passive, causative,
volitional, conditionals, imperative), the stand-in for IPADIC's
per-surface costs) that
makes `ChineseTokenizerFactory(dictionary="builtin")` /
`JapaneseTokenizerFactory(dictionary="builtin")` segment everyday text
sensibly out of the box. It is deliberately small: domain text should
add `load_user_dictionary` entries on top (jieba-style lines), exactly
as the reference's user-dictionary mechanism works.

Frequencies are rank-bucketed relative weights (only ratios matter —
dict_from_frequencies converts to -log(p) costs), ordered by the
well-known frequency structure of modern Chinese/Japanese corpora.
"""

# --- Chinese: word -> relative frequency -------------------------------

_ZH_BUCKETS = (
    # function words / pronouns (highest band)
    (50000, "的 是 不 了 在 有 我 他 这 个 们 中 来 上 大 为 和 国 地 到"),
    (30000, "你 她 它 我们 他们 你们 就 说 要 也 都 而 去 能 会 着 没有 看 好 自己"),
    (20000, "这个 那个 什么 一个 没 很 再 可以 因为 所以 但是 如果 虽然 还是 或者 而且 然后 现在 已经 还"),
    # common verbs
    (12000, "知道 觉得 认为 希望 喜欢 开始 成为 进行 出现 发现 使用 需要 应该 可能 表示 通过 作为 得到 发展 工作"),
    (9000, "学习 生活 研究 生产 管理 服务 建设 活动 经济 问题 时候 时间 地方 今天 明天 昨天 每天 以后 以前 之间"),
    # common nouns
    (7000, "中国 北京 上海 美国 日本 世界 国家 人民 政府 社会 历史 文化 教育 科学 技术 信息 系统 公司 市场 银行"),
    (5000, "大学 学校 学生 老师 先生 朋友 孩子 父母 家庭 城市 农村 电话 电脑 网络 汽车 火车 飞机 医院 医生 音乐"),
    (4000, "东西 事情 方面 方法 结果 原因 情况 条件 关系 内容 标准 水平 能力 机会 力量 影响 作用 意义 目的 过程"),
    # segmentation classics + frequent bigrams
    (3000, "研究生 生命 起源 天安门 长城 电影 电视 新闻 报纸 杂志 小说 故事 节目 比赛 运动 足球 篮球 游戏 旅游 天气"),
    (2500, "春天 夏天 秋天 冬天 早上 上午 中午 下午 晚上 星期 月份 年代 世纪 小时 分钟 左右 前面 后面 里面 外面"),
    (2000, "非常 特别 十分 比较 更加 越来越 几乎 差不多 大概 也许 当然 一定 必须 只有 只要 无论 即使 尽管 不过 否则"),
    (1500, "高兴 快乐 幸福 难过 生气 担心 害怕 奇怪 重要 容易 困难 简单 复杂 漂亮 美丽 干净 安静 热闹 方便 舒服"),
    (1200, "吃饭 喝水 睡觉 起床 上班 下班 上课 下课 回家 出门 买东西 做饭 洗澡 跑步 走路 说话 唱歌 跳舞 画画 写字"),
    (1000, "经过 根据 关于 对于 由于 为了 按照 随着 除了 以及 并且 甚至 尤其 例如 比如 总之 另外 同时 首先 最后"),
    (800, "增加 减少 提高 降低 改变 改革 开放 发达 先进 落后 成功 失败 胜利 解决 决定 选择 准备 参加 组织 举行"),
    (600, "数学 物理 化学 生物 语文 英语 汉语 外语 历史课 地理 体育 艺术 哲学 法律 政治 军事 宗教 环境 资源 能源"),
    (500, "苹果 香蕉 西瓜 牛奶 面包 米饭 面条 饺子 茶叶 咖啡 啤酒 蔬菜 水果 鸡蛋 牛肉 羊肉 鱼肉 糖果 蛋糕 早饭"),
    # numbers / measure words / ordinals
    (15000, "一 二 三 四 五 六 七 八 九 十 百 千 万 亿 零 两 第一 第二 第三 几个"),
    (8000, "一些 一样 一起 一直 一切 一般 一点 一下 不如 一方面 有些 有的 有人 有点 许多 多少 不少 大家 大多 各种"),
    (6000, "块 条 张 只 件 位 名 本 辆 台 层 间 套 双 对 群 批 份 页 篇"),
    # verbs round 2
    (9000, "打开 关闭 打电话 发送 接受 接收 收到 回答 回复 离开 到达 经历 继续 停止 完成 实现 保持 保护 支持 反对"),
    (7000, "帮助 介绍 解释 讨论 交流 合作 竞争 顺便 检查 测试 训练 练习 记住 忘记 想起 相信 怀疑 同意 拒绝 邀请"),
    (5000, "安排 计划 设计 建立 创造 创新 改善 扩大 缩小 加强 减轻 推动 促进 引起 导致 造成 形成 产生 消失 存在"),
    # nouns round 2: society / economy / daily life
    (6000, "价格 价值 质量 数量 收入 支出 利润 成本 投资 贸易 工业 农业 商业 企业 产品 项目 方案 合同 会议 报告"),
    (5000, "政策 法规 制度 机构 部门 单位 职业 工资 经验 知识 理论 实践 观点 态度 思想 精神 传统 习惯 风俗 礼物"),
    (4000, "房子 房间 厨房 卧室 客厅 桌子 椅子 窗户 门口 钥匙 衣服 裤子 鞋子 帽子 眼镜 手机 手表 钱包 行李 箱子"),
    (3500, "身体 头发 眼睛 耳朵 鼻子 嘴巴 手指 肚子 心脏 健康 疾病 感冒 发烧 药品 治疗 调查 锻炼 营养 休息 睡眠"),
    (3000, "道路 街道 桥梁 公园 广场 商店 超市 商场 邮局 图书馆 博物馆 餐厅 厕所 车站 机场 码头 宾馆 教室 办公室 工厂"),
    # adjectives / adverbs round 2
    (4000, "新鲜 成熟 年轻 年老 聪明 愚蠢 勇敢 胆小 诚实 虚假 认真 马虎 积极 消极 主动 被动 正式 随便 严格 宽松"),
    (3000, "突然 立刻 马上 渐渐 慢慢 终于 果然 居然 竟然 似乎 好像 仿佛 确实 的确 明显 显然 毕竟 究竟 到底 反而"),
    # geography / nature / science
    (2500, "地球 月亮 太阳 星星 宇宙 空气 温度 气候 森林 沙漠 草原 湖泊 河流 海洋 岛屿 大陆 山脉 平原 土地 石头"),
    (2000, "植物 动物 鸟类 昆虫 老虎 狮子 大象 猴子 熊猫 兔子 鸡 鸭 猪 马 牛 羊 狗 猫 鱼 虫"),
    (1800, "电力 石油 煤炭 钢铁 机器 设备 工具 材料 零件 发动机 程序 软件 硬件 数据 文件 密码 账号 邮件 网站 屏幕"),
    # idioms / fixed expressions (lattice stress cases)
    (1200, "实事求是 乱七八糟 马马虎虎 认认真真 自言自语 无所谓 不好意思 没关系 对不起 谢谢 再见 欢迎 请问 麻烦 打扰 辛苦 恭喜 加油 小心 注意"),
    (1000, "越来越多 越来越好 不得不 忍不住 来不及 算了 受不了 了不起 差一点 好不容易 说不定 怪不得 恨不得 巴不得 大不了 看不起 想不到 舍不得 用不着 免不了"),
    # round-3b expansion: modern/tech + media vocabulary
    (2200, "视频 照片 图片 文章 媒体 评论 点赞 分享 关注 粉丝 直播 主播 平台 应用 下载 上传 安装 更新 升级"),
    (2000, "人工智能 机器学习 大数据 云计算 算法 模型 芯片 机器人 自动化 数字化 智能化 虚拟 现实 科技 创业 互联网 电商 物流 快递"),
    (1800, "支付 转账 红包 打折 优惠 免费 会员 订单 退货 客服 质保 品牌 广告 营销 推广 流量 用户 客户 消费 购物"),
    # verbs round 3
    (4500, "打算 决心 坚持 放弃 尝试 努力 争取 避免 防止 禁止 允许 批准 申请 报名 注册 登录 退出 取消 确认 提交"),
    (3500, "感觉 感到 感谢 感动 激动 兴奋 紧张 放松 享受 欣赏 佩服 羡慕 嫉妒 抱怨 批评 表扬 鼓励 安慰 提醒 警告"),
    (2800, "搬家 装修 打扫 整理 收拾 修理 保养 种植 浇水 喂养 照顾 陪伴 接送 迎接 送别 拜访 看望 聚会 庆祝 祝贺"),
    # places / countries / travel
    (2200, "英国 法国 德国 俄罗斯 韩国 印度 泰国 新加坡 澳大利亚 加拿大 欧洲 亚洲 非洲 南美 广州 深圳 香港 澳门 台湾 西安"),
    (1800, "护照 签证 机票 车票 行程 导游 景点 风景 古迹 寺庙 教堂 城堡 海滩 温泉 滑雪 爬山 露营 拍照 纪念品 特产"),
    # time / quantity refinements
    (3200, "正在 刚才 刚刚 从前 将来 未来 目前 如今 当时 近年来 本来 原来 后来 然而 此外 于是 因此 不仅 不但 既然 哪怕"),
    # round-3c expansion: family / people
    (4200, "爸爸 妈妈 哥哥 姐姐 弟弟 妹妹 爷爷 奶奶 外公 外婆 叔叔 阿姨 丈夫 妻子 儿子 女儿 亲戚 邻居 同学 同事"),
    # colors / shapes / senses
    (2400, "红色 黄色 蓝色 绿色 白色 黑色 灰色 紫色 粉色 颜色 圆形 方形 形状 大小 长短 高矮 声音 味道 气味 光线"),
    # professions
    (2000, "工人 农民 司机 警察 军人 律师 记者 演员 歌手 画家 作家 科学家 工程师 教授 经理 秘书 售货员 服务员 厨师 翻译"),
    # cooking / restaurant
    (1800, "炒菜 烤肉 火锅 烧烤 调料 酱油 点菜 菜单 筷子 勺子 碗 盘子 杯子 锅 刀叉 食堂 外卖 请客 买单"),
    # written / formal function words (news register)
    (2600, "即 与 及 将 被 使 令 据 且 则 亦 均 尚 仍 曾 未 须 应 宜"),
    # education / exams
    (2200, "考试 成绩 分数 及格 毕业 入学 作业 课程 专业 学位 硕士 博士 论文 讲座 实验 实习 奖学金 辅导 复习 预习"),
    # feelings / evaluation round 2
    (2000, "满意 失望 后悔 骄傲 自豪 惭愧 感激 同情 信任 尊重 热情 冷淡 温柔 严肃 幽默 可爱 可怕 可惜 危险 安全"),
    # internet / daily modern life
    (1600, "微信 短信 邮箱 搜索 浏览 充电 信号 蓝牙 耳机 键盘 鼠标 打印 复印 扫描 截图 保存 删除 备份 恢复 设置"),
    # round-4 expansion: verb bands (motion / transfer / perception)
    (4800, "拿 放 给 送 带 搬 推 拉 抬 扔 捡 抱 背 提 挂 摆 递 装 卸 藏"),
    (3800, "看见 听见 看到 听到 见到 遇到 碰到 找到 拿到 学到 想到 感到 受到 达到 做到 办到 赶到 轮到 提到 谈到"),
    (3200, "出去 进来 出来 进去 回来 回去 上来 上去 下来 下去 过来 过去 起来 醒来 站起来 坐下 躺下 留下 剩下 落下"),
    (2600, "打破 打断 打败 打碎 切断 折断 撕开 拆开 打包 包装 挖 埋 铺 砌 钉 锯 磨 擦 抹 刷"),
    # verb-complement / resultative bands (segmentation stress cases)
    (2400, "看完 吃完 做完 写完 说完 用完 听懂 看懂 读懂 学会 抓紧 抓住 停住 站住 愣住 吃饱 喝醉 睡着 累坏 吓坏"),
    # psychological / communication verbs
    (2800, "商量 考虑 分析 打听 询问 回忆 反思 反省 思考 琢磨 估计 预测 推测 假设 证明 否认 承认 强调 声明 宣布"),
    # round-5 expansion: measure words / classifiers
    (5500, "座 棵 朵 头 艘 架 部 所 款 项 笔 幅 盏 扇 枚 粒 滴 串 束 堆"),
    (4500, "排 队 顿 场 阵 圈 趟 遍 声 句 段 节 道 副 把 根 支 枝 瓶 叠"),
    (4000, "袋 盒 包 桶 篮 筐 罐 壶 锅 炉 床 幢 栋 捆 亩 吨 克 千克 公斤 公里"),
    # number+measure fused surfaces (jieba-style lexicalized compounds)
    (3500, "一次 两次 三次 一遍 一场 一段 一句 一声 一道 一笔 一项 一部 一家 一座 一根 一把 一瓶 一杯 一碗 一顿"),
    (2800, "两个 三个 四个 五个 几次 几天 几年 一会儿 一阵子 一辈子 半天 半年 多年 多次 每次 每年 每月 每周 每个 整个"),
    # round-5 chengyu (classic 4-char idioms, lattice stress cases)
    (900, "一心一意 三心二意 四面八方 五颜六色 七上八下 十全十美 百发百中 千方百计 万无一失 半途而废 画蛇添足 守株待兔 井底之蛙 亡羊补牢 对牛弹琴 狐假虎威 掩耳盗铃 杯弓蛇影 刻舟求剑 自相矛盾"),
    (800, "理所当然 迫不及待 情不自禁 恍然大悟 全力以赴 聚精会神 专心致志 一丝不苟 精益求精 持之以恒 再接再厉 勇往直前 坚持不懈 脚踏实地 实话实说 将心比心 设身处地 风和日丽 阳光明媚 春暖花开"),
    # round-5b breadth: everyday vocabulary tier 2
    (2600, "早晨 夜晚 半夜 凌晨 周末 假期 节日 生日 纪念日 日子 年底 月底 季节 日期 钟头 刹那 瞬间 片刻 从此 至今"),
    (2400, "客人 主人 大人 小孩 青年 老人 男人 女人 男孩 女孩 婴儿 夫妻 情侣 伙伴 队友 对手 陌生人 熟人 本人 人们"),
    (2200, "墙壁 地板 天花板 阳台 车库 地下室 院子 栅栏 家具 沙发 地毯 窗帘 镜子 抽屉 柜子 架子 灯泡 插座 开关 水管"),
    (2000, "毛巾 牙刷 牙膏 肥皂 洗发水 梳子 剪刀 针线 锤子 钉子 螺丝 胶水 绳子 袋子 瓶子 罐子 盖子 把手 轮子 电池"),
    (2000, "驾驶证 驾照 车牌 地铁 公交车 出租车 自行车 摩托车 卡车 船只 地图 路口 红绿灯 人行道 高速公路 隧道 加油站 车祸 堵车 车速"),
    (1800, "胳膊 手臂 手腕 脚趾 膝盖 肩膀 脖子 腰部 皮肤 骨头 肌肉 血液 大脑 神经 嗓子 牙齿 舌头 眉毛 胡子 指甲"),
    (1800, "雷雨 闪电 彩虹 雾气 霜冻 冰雹 微风 大风 暴雨 晴天 阴天 雨天 雪花 气温 湿度 预报 降温 升温 干旱 洪水"),
    (1600, "钢琴 吉他 小提琴 鼓 笛子 乐器 画笔 颜料 相机 镜头 棋盘 扑克 玩具 拼图 风筝 气球 礼品 奖品 奖杯 证书"),
    (1600, "感冒药 退烧药 创可贴 绷带 体温计 血压 脉搏 症状 过敏 咳嗽 头疼 牙疼 肚子疼 发炎 受伤 骨折 康复 预防 疫苗 体检"),
)

ZH_FREQ = {}
for _f, _words in _ZH_BUCKETS:
    for _w in _words.split():
        ZH_FREQ.setdefault(_w, _f)

# --- Japanese: word -> (relative frequency, POS) -----------------------

_JA_BUCKETS = (
    # particles (highest band — the backbone of the lattice)
    (50000, "助詞", "の は が を に で と も へ や か ね よ から まで など しか だけ ほど より って"),
    # copula / auxiliaries / frequent verb endings
    (30000, "助動詞", "です ます でした ました ません でしょう だ である だった ない なかった たい たく れる られる せる させる"),
    # frequent verbs (dictionary + common conjugated surfaces)
    (15000, "動詞",
     "する した して します しました いる いた いて います ある あった あり なる なった なって なります"),
    (10000, "動詞",
     "行く 行った 行きます 来る 来た 来ます 見る 見た 見ます 言う 言った 思う 思った 思います 分かる 分かった 知る 知って 食べる 食べた 飲む 読む 書く 聞く 話す 使う 作る 買う 持つ 待つ 会う 帰る 出る 入る 住む 働く 学ぶ 遊ぶ 泳ぐ 歩く 走る 休む 始まる 終わる できる"),
    # pronouns / demonstratives / adverbs
    (12000, "代名詞", "これ それ あれ どれ ここ そこ あそこ どこ この その あの どの 私 僕 君 彼 彼女 誰 何"),
    (8000, "副詞", "とても もっと すこし 少し たくさん よく もう まだ また すぐ いつも 今日 明日 昨日 今 毎日 時々 全然 多分 本当に 一緒に"),
    # common nouns
    (7000, "名詞",
     "日本 東京 大阪 京都 中国 アメリカ 世界 国 人 方 時 年 月 日 時間 今年 去年 来年 午前 午後"),
    (5000, "名詞",
     "学生 先生 学校 大学 会社 仕事 電車 駅 車 家 部屋 店 料理 水 お金 映画 音楽 写真 電話 手紙"),
    (4000, "名詞",
     "友達 家族 父 母 子供 男 女 犬 猫 山 川 海 空 雨 雪 風 花 木 本 言葉"),
    (3000, "名詞",
     "問題 質問 答え 意味 名前 気持ち 天気 気温 朝ご飯 昼ご飯 晩ご飯 朝 昼 夜 週末 旅行 買い物 勉強 練習 試験"),
    # i-adjectives / na-adjectives
    (4000, "形容詞",
     "いい 良い 悪い 大きい 小さい 高い 安い 新しい 古い 長い 短い 早い 遅い 近い 遠い 暑い 寒い 楽しい 面白い 難しい 易しい 美味しい 忙しい 嬉しい 悲しい"),
    (3000, "形容動詞", "元気 静か 有名 便利 大変 大切 簡単 綺麗 親切 丁寧 好き 嫌い 上手 下手 必要"),
    # katakana loanwords
    (3000, "名詞",
     "コーヒー テレビ パソコン スマホ インターネット ニュース ホテル レストラン バス タクシー カメラ ゲーム スポーツ サッカー テニス"),
)

_JA_EXTRA_BUCKETS = (
    # counters / numbers
    (12000, "名詞", "一 二 三 四 五 六 七 八 九 十 百 千 万 一つ 二つ 三つ 一人 二人 三人 一番"),
    (5000, "名詞", "一日 二日 今週 来週 先週 今月 来月 先月 半分 全部 最初 最後 次 前 後 上 下 中 外 間"),
    # nouns round 2
    (4000, "名詞",
     "病院 銀行 郵便局 図書館 公園 空港 道 橋 町 村 市 県 国際 社会 経済 政治 文化 歴史 科学 技術"),
    (3500, "名詞",
     "日本語 英語 中国語 韓国語 情報 番組 新聞 雑誌 辞書 教科書 宿題 授業 教室 黒板 机 椅子 鞄 傘 眼鏡 靴 服 帽子 切符 荷物"),
    (3000, "名詞",
     "体 頭 顔 目 耳 口 手 足 声 心 病気 薬 熱 風邪 医者 看護師 運動 散歩 休み 夢"),
    (2500, "名詞",
     "果物 野菜 魚 肉 卵 パン 米 酒 茶 塩 砂糖 味 朝食 昼食 夕食 弁当 箸 皿 台所 冷蔵庫"),
    # adverbs / conjunctions round 2
    (6000, "副詞", "そして しかし でも だから それで それから つまり 例えば もし たとえ きっと 必ず 絶対 やっと ついに ほとんど かなり ずっと やはり やっぱり"),
    (4000, "副詞", "ゆっくり はっきり しっかり ちょっと ちゃんと なかなか そろそろ だんだん どんどん いろいろ 特に 実は 最近 先に 後で 初めて 久しぶり 突然 急に 自然に"),
)

JA_ENTRIES = {}
for _f, _pos, _words in _JA_BUCKETS + _JA_EXTRA_BUCKETS:
    for _w in _words.split():
        JA_ENTRIES.setdefault(_w, (_f, _pos))


# --- Japanese verb conjugation surfaces (frequency-weighted) -----------
#
# The kuromoji/IPADIC system dictionary lists every conjugated surface of
# every verb with per-surface costs; the zero-egress counterpart GENERATES
# the common surfaces for a curated verb list. Frequencies decay per form
# (dictionary form > polite > past > te-form > negative > volitional...),
# mirroring the corpus frequency ordering the IPADIC costs encode.

#: (dictionary form, relative frequency, stem kind): "godan" consonant
#: stem verbs keyed by final kana row, "ichidan" vowel-stem verbs
_JA_VERBS = (
    ("行く", 10000, "godan"), ("書く", 5000, "godan"), ("聞く", 5000, "godan"),
    ("歩く", 3000, "godan"), ("働く", 3500, "godan"), ("泳ぐ", 1500, "godan"),
    ("話す", 5000, "godan"), ("出す", 4000, "godan"), ("貸す", 1500, "godan"),
    ("待つ", 3500, "godan"), ("持つ", 4500, "godan"), ("立つ", 2500, "godan"),
    ("死ぬ", 1200, "godan"),
    ("遊ぶ", 2000, "godan"), ("呼ぶ", 2000, "godan"), ("飛ぶ", 1500, "godan"),
    ("読む", 4000, "godan"), ("飲む", 4000, "godan"), ("住む", 3000, "godan"),
    ("休む", 2500, "godan"),
    ("買う", 4500, "godan"), ("会う", 4000, "godan"), ("使う", 4000, "godan"),
    ("思う", 8000, "godan"), ("言う", 8000, "godan"), ("習う", 1500, "godan"),
    ("帰る", 3500, "godan"), ("入る", 3500, "godan"), ("分かる", 6000, "godan"),
    ("作る", 4000, "godan"), ("送る", 2500, "godan"), ("乗る", 2500, "godan"),
    ("座る", 1500, "godan"), ("走る", 2000, "godan"), ("知る", 5000, "godan"),
    ("食べる", 5000, "ichidan"), ("見る", 6000, "ichidan"),
    ("寝る", 3000, "ichidan"), ("起きる", 3000, "ichidan"),
    ("出る", 4000, "ichidan"), ("着る", 2000, "ichidan"),
    ("教える", 3000, "ichidan"), ("覚える", 2500, "ichidan"),
    ("忘れる", 2500, "ichidan"), ("借りる", 1500, "ichidan"),
    ("開ける", 2000, "ichidan"), ("閉める", 1500, "ichidan"),
    ("始める", 2500, "ichidan"), ("続ける", 2000, "ichidan"),
    # round-3c expansion
    ("急ぐ", 1200, "godan"), ("洗う", 1500, "godan"),
    ("歌う", 1500, "godan"), ("払う", 1500, "godan"),
    ("笑う", 2000, "godan"), ("泣く", 1200, "godan"),
    ("置く", 2000, "godan"), ("着く", 2000, "godan"),
    ("動く", 1800, "godan"), ("引く", 1500, "godan"),
    ("押す", 1500, "godan"), ("消す", 1200, "godan"),
    ("直す", 1200, "godan"), ("返す", 1500, "godan"),
    ("渡す", 1500, "godan"), ("勝つ", 1500, "godan"),
    ("選ぶ", 1500, "godan"), ("運ぶ", 1200, "godan"),
    ("並ぶ", 1200, "godan"), ("進む", 1500, "godan"),
    ("頼む", 1500, "godan"), ("切る", 1800, "godan"),
    ("売る", 1800, "godan"), ("降る", 1800, "godan"),
    ("困る", 1500, "godan"), ("止まる", 1500, "godan"),
    ("始まる", 2500, "godan"), ("終わる", 2500, "godan"),
    ("変わる", 2000, "godan"), ("かかる", 2500, "godan"),
    ("もらう", 2500, "godan"), ("違う", 2500, "godan"),
    ("見せる", 1800, "ichidan"), ("見える", 2000, "ichidan"),
    ("聞こえる", 1500, "ichidan"), ("考える", 3000, "ichidan"),
    ("答える", 1800, "ichidan"), ("捨てる", 1200, "ichidan"),
    ("集める", 1500, "ichidan"), ("決める", 1800, "ichidan"),
    ("届ける", 1200, "ichidan"), ("調べる", 1800, "ichidan"),
    ("比べる", 1500, "ichidan"), ("並べる", 1200, "ichidan"),
    ("入れる", 2200, "ichidan"), ("生まれる", 1800, "ichidan"),
    ("別れる", 1200, "ichidan"), ("疲れる", 1800, "ichidan"),
    ("慣れる", 1500, "ichidan"), ("遅れる", 1500, "ichidan"),
)

#: godan final-kana -> (masu-stem kana, te/ta sound change, negative kana)
_GODAN_ROWS = {
    "く": ("き", ("いて", "いた"), "か"), "ぐ": ("ぎ", ("いで", "いだ"), "が"),
    "す": ("し", ("して", "した"), "さ"), "つ": ("ち", ("って", "った"), "た"),
    "ぬ": ("に", ("んで", "んだ"), "な"), "ぶ": ("び", ("んで", "んだ"), "ば"),
    "む": ("み", ("んで", "んだ"), "ま"), "う": ("い", ("って", "った"), "わ"),
    "る": ("り", ("って", "った"), "ら"),
}

#: per-form frequency multipliers (×1000): dictionary form dominates,
#: polite/past next, rarer moods tail off
_FORM_WEIGHTS = {
    "dict": 1.0, "masu": 0.6, "mashita": 0.45, "te": 0.55, "ta": 0.5,
    "nai": 0.4, "nakatta": 0.2, "masen": 0.25, "tai": 0.3,
}


def _conjugate(dict_form: str, kind: str):
    """Common conjugated surfaces of one verb -> {surface: form_key}."""
    out = {dict_form: "dict"}
    if kind == "ichidan":
        stem = dict_form[:-1]                      # drop る
        out[stem + "ます"] = "masu"
        out[stem + "ました"] = "mashita"
        out[stem + "ません"] = "masen"
        out[stem + "て"] = "te"
        out[stem + "た"] = "ta"
        out[stem + "ない"] = "nai"
        out[stem + "なかった"] = "nakatta"
        out[stem + "たい"] = "tai"
        return out
    base, last = dict_form[:-1], dict_form[-1]
    masu_k, (te, ta), neg_k = _GODAN_ROWS[last]
    # 行く is the te/ta irregular: 行って/行った
    if dict_form == "行く":
        te, ta = "って", "った"
    out[base + masu_k + "ます"] = "masu"
    out[base + masu_k + "ました"] = "mashita"
    out[base + masu_k + "ません"] = "masen"
    out[base + te] = "te"
    out[base + ta] = "ta"
    out[base + neg_k + "ない"] = "nai"
    out[base + neg_k + "なかった"] = "nakatta"
    out[base + masu_k + "たい"] = "tai"
    return out


for _dict_form, _freq, _kind in _JA_VERBS:
    for _surface, _form in _conjugate(_dict_form, _kind).items():
        _f = max(100, int(_freq * _FORM_WEIGHTS[_form]))
        if _surface not in JA_ENTRIES or JA_ENTRIES[_surface][0] < _f:
            JA_ENTRIES[_surface] = (_f, "動詞")


# --- Japanese suru-verb compounds (round-3b expansion) -----------------
#
# IPADIC lists サ変 nouns plus every する surface; the generator covers
# the productive noun+する pattern the same way: the bare noun enters as
# 名詞 (it also appears standalone), and the する compound surfaces are
# emitted with the shared per-form decay weights, damped a further ×0.5
# (the fused surface is rarer than the noun alone). する itself is already
# a high-band entry, so the lattice can also split 勉強+する — the fused
# surfaces just price the common analysis correctly.

_JA_SURU_NOUNS = (
    ("勉強", 4500), ("練習", 3000), ("運動", 2500), ("散歩", 2000),
    ("旅行", 3000), ("買い物", 2500), ("電話", 3000), ("結婚", 2500),
    ("研究", 3000), ("説明", 3000), ("紹介", 2500), ("質問", 2500),
    ("連絡", 2500), ("予約", 2000), ("準備", 2500), ("掃除", 2000),
    ("洗濯", 1800), ("料理", 2500), ("運転", 2200), ("卒業", 1800),
    ("入学", 1500), ("出発", 2000), ("到着", 1800), ("心配", 2500),
    ("安心", 2000), ("成功", 1800), ("失敗", 1800), ("参加", 2500),
    ("利用", 2500), ("使用", 2200), ("発表", 2000), ("相談", 2200),
    ("約束", 2000), ("翻訳", 1200), ("注文", 1800), ("案内", 1800),
)

_SURU_FORMS = {
    "する": "dict", "します": "masu", "しました": "mashita",
    "しません": "masen", "して": "te", "した": "ta", "しない": "nai",
    "しなかった": "nakatta", "したい": "tai",
}

# round-3c: more suru-nouns (business / school / communication register)
_JA_SURU_NOUNS_3C = (
    ("会話", 2000), ("挨拶", 1800), ("遠慮", 1500), ("招待", 1500),
    ("返事", 1800), ("出張", 1500), ("残業", 1500), ("報告", 2000),
    ("計算", 1800), ("録音", 1000), ("撮影", 1200), ("放送", 1500),
    ("輸入", 1200), ("輸出", 1200), ("販売", 1500), ("生産", 1500),
    ("建設", 1200), ("開発", 1800), ("経営", 1500), ("管理", 1800),
    ("教育", 2000), ("訓練", 1200), ("実験", 1500), ("観察", 1000),
    ("想像", 1500), ("記憶", 1200), ("理解", 2000), ("判断", 1500),
    ("決定", 1500), ("選択", 1500), ("注意", 2200), ("用意", 2000),
    ("我慢", 1500), ("感動", 1500), ("感謝", 1800), ("協力", 1800),
)

for _noun, _freq in _JA_SURU_NOUNS + _JA_SURU_NOUNS_3C:
    if _noun not in JA_ENTRIES or JA_ENTRIES[_noun][0] < _freq:
        JA_ENTRIES[_noun] = (_freq, "名詞")
    for _suffix, _form in _SURU_FORMS.items():
        _f = max(100, int(_freq * 0.5 * _FORM_WEIGHTS[_form]))
        _surface = _noun + _suffix
        if _surface not in JA_ENTRIES or JA_ENTRIES[_surface][0] < _f:
            JA_ENTRIES[_surface] = (_f, "動詞")


# --- Japanese i-adjective conjugation surfaces (round-3c expansion) ----
#
# IPADIC enumerates adjective conjugation surfaces the same way it does
# verbs; the generator covers the productive -i paradigm for a curated
# list: 高い -> 高く / 高くて / 高かった / 高くない / 高くなかった.
# いい conjugates on the よ stem (よく / よかった / よくない).

_JA_I_ADJECTIVES = (
    ("高い", 4000), ("安い", 2500), ("大きい", 3500), ("小さい", 3000),
    ("新しい", 3000), ("古い", 2000), ("長い", 2500), ("短い", 1500),
    ("早い", 2500), ("遅い", 1800), ("近い", 2000), ("遠い", 1500),
    ("暑い", 1800), ("寒い", 1800), ("熱い", 1500), ("冷たい", 1500),
    ("楽しい", 2500), ("面白い", 2500), ("難しい", 2500),
    ("易しい", 1000), ("美味しい", 2500), ("忙しい", 2200),
    ("嬉しい", 2000), ("悲しい", 1500), ("強い", 2000), ("弱い", 1200),
    ("重い", 1500), ("軽い", 1200), ("広い", 1500), ("狭い", 1000),
    ("明るい", 1500), ("暗い", 1200), ("若い", 1800), ("多い", 3000),
    ("少ない", 2000), ("良い", 3000), ("悪い", 2500), ("いい", 5000),
)

_ADJ_FORM_WEIGHTS = {
    "dict": 1.0, "ku": 0.5, "kute": 0.4, "katta": 0.45,
    "kunai": 0.35, "kunakatta": 0.15,
}


def _conjugate_i_adj(dict_form: str):
    """Common surfaces of one i-adjective -> {surface: form_key}."""
    stem = "よ" if dict_form == "いい" else dict_form[:-1]
    out = {dict_form: "dict"}
    out[stem + "く"] = "ku"
    out[stem + "くて"] = "kute"
    out[stem + "かった"] = "katta"
    out[stem + "くない"] = "kunai"
    out[stem + "くなかった"] = "kunakatta"
    return out


for _dict_form, _freq in _JA_I_ADJECTIVES:
    for _surface, _form in _conjugate_i_adj(_dict_form).items():
        _f = max(100, int(_freq * _ADJ_FORM_WEIGHTS[_form]))
        if _surface not in JA_ENTRIES or JA_ENTRIES[_surface][0] < _f:
            JA_ENTRIES[_surface] = (_f, "形容詞")


# --- Japanese na-adjective surfaces (round-4 expansion) ----------------
#
# IPADIC lists 形容動詞 stems plus their copula-fused surfaces; the
# generator emits the productive paradigm for the curated stems already
# in the 形容動詞 band plus a round-4 extension list: 元気な / 元気に /
# 元気だ / 元気だった / 元気じゃない / 元気です / 元気でした.

_JA_NA_ADJECTIVES = (
    ("元気", 3000), ("静か", 2500), ("有名", 2500), ("便利", 2500),
    ("大変", 3000), ("大切", 2500), ("簡単", 2500), ("綺麗", 2500),
    ("親切", 2000), ("丁寧", 1800), ("好き", 4000), ("嫌い", 2000),
    ("上手", 2200), ("下手", 1500), ("必要", 2800),
    # round-4 extension stems
    ("大丈夫", 3000), ("無理", 2200), ("自由", 2000), ("特別", 2000),
    ("普通", 2200), ("安全", 1800), ("危険", 1500), ("健康", 1800),
    ("幸せ", 2000), ("残念", 1800), ("失礼", 1800), ("真面目", 1500),
    ("熱心", 1200), ("複雑", 1500), ("十分", 1800), ("不便", 1200),
    ("暇", 1500), ("楽", 2000), ("確か", 2000), ("変", 1800),
)

_NA_FORMS = {
    "な": 0.8, "に": 0.6, "だ": 0.5, "だった": 0.35, "では": 0.2,
    "じゃない": 0.3, "じゃなかった": 0.12, "です": 0.55, "でした": 0.3,
}

for _stem, _freq in _JA_NA_ADJECTIVES:
    if _stem not in JA_ENTRIES or JA_ENTRIES[_stem][0] < _freq:
        JA_ENTRIES[_stem] = (_freq, "形容動詞")
    for _suffix, _w in _NA_FORMS.items():
        _f = max(100, int(_freq * _w))
        _surface = _stem + _suffix
        if _surface not in JA_ENTRIES or JA_ENTRIES[_surface][0] < _f:
            JA_ENTRIES[_surface] = (_f, "形容動詞")


# --- Japanese counter surfaces (round-4 expansion) ---------------------
#
# IPADIC enumerates number+counter compounds as 名詞(数); the generator
# crosses the numerals 1-10 (+ 何 "how many") with the everyday counter
# suffixes. Frequencies decay with the numeral (1-3 dominate corpora)
# and by counter band. Readings/sound changes (一本=いっぽん) are a
# pronunciation concern; segmentation needs only the surfaces.

_JA_COUNTER_NUMS = (
    ("一", 1.0), ("二", 0.8), ("三", 0.7), ("四", 0.5), ("五", 0.5),
    ("六", 0.35), ("七", 0.35), ("八", 0.35), ("九", 0.3), ("十", 0.45),
    ("何", 0.6),
)

_JA_COUNTERS = (
    ("人", 4000), ("つ", 3500), ("年", 3500), ("月", 3000), ("日", 3000),
    ("時", 3000), ("分", 2800), ("円", 3000), ("個", 2500), ("本", 2500),
    ("枚", 2200), ("冊", 1800), ("台", 2000), ("匹", 1800), ("回", 2800),
    ("階", 2000), ("歳", 2200), ("番", 2200), ("杯", 1800), ("度", 2000),
    ("秒", 1500), ("週間", 2000), ("ヶ月", 2000), ("時間", 2800),
)

for _num, _nw in _JA_COUNTER_NUMS:
    for _ctr, _cf in _JA_COUNTERS:
        _surface = _num + _ctr
        _f = max(100, int(_cf * _nw))
        if _surface not in JA_ENTRIES or JA_ENTRIES[_surface][0] < _f:
            JA_ENTRIES[_surface] = (_f, "名詞")


def _ja_upsert(surface, freq, pos):
    """Insert/raise a JA_ENTRIES row (max frequency wins, POS follows
    the winning entry) — the ONE copy of the merge rule for all the
    round-5 sections below."""
    if surface not in JA_ENTRIES or JA_ENTRIES[surface][0] < freq:
        JA_ENTRIES[surface] = (freq, pos)


# --- Japanese extended verb paradigms (round-5 expansion) --------------
#
# IPADIC prices every inflected surface; the round-3 generator covered
# the plain/polite/te/ta/negative core. This pass adds the remaining
# everyday paradigm: progressive ている (+polite/past + spoken てる
# contraction), potential, passive, causative, volitional, the two
# conditionals (-ば / -たら) and the plain imperative, derived from the
# same godan rows (plus their e/o-row kana below).

_GODAN_EO = {  # final kana -> (e-row kana, o-row kana)
    "く": ("け", "こ"), "ぐ": ("げ", "ご"), "す": ("せ", "そ"),
    "つ": ("て", "と"), "ぬ": ("ね", "の"), "ぶ": ("べ", "ぼ"),
    "む": ("め", "も"), "う": ("え", "お"), "る": ("れ", "ろ"),
}

_EXT_FORM_WEIGHTS = {
    "teiru": 0.5, "teimasu": 0.3, "teita": 0.25, "teru": 0.2,
    "potential": 0.25, "passive": 0.2, "causative": 0.1,
    "volitional": 0.15, "ba": 0.15, "tara": 0.2, "imperative": 0.07,
}


def _conjugate_ext(dict_form: str, kind: str):
    """Extended-paradigm surfaces of one verb -> {surface: form_key}."""
    out = {}
    if kind == "ichidan":
        stem = dict_form[:-1]
        te = stem + "て"
        out[stem + "られる"] = "potential"     # doubles as the passive
        out[stem + "させる"] = "causative"
        out[stem + "よう"] = "volitional"
        out[stem + "れば"] = "ba"
        out[stem + "たら"] = "tara"
        out[stem + "ろ"] = "imperative"
    else:
        base, last = dict_form[:-1], dict_form[-1]
        _, (te_s, ta_s), neg_k = _GODAN_ROWS[last]
        if dict_form == "行く":
            te_s, ta_s = "って", "った"
        e_k, o_k = _GODAN_EO[last]
        te = base + te_s
        out[base + e_k + "る"] = "potential"
        out[base + neg_k + "れる"] = "passive"
        out[base + neg_k + "せる"] = "causative"
        out[base + o_k + "う"] = "volitional"
        out[base + e_k + "ば"] = "ba"
        out[base + ta_s + "ら"] = "tara"
        out[base + e_k] = "imperative"
    out[te + "いる"] = "teiru"
    out[te + "います"] = "teimasu"
    out[te + "いた"] = "teita"
    out[te + "る"] = "teru"                    # spoken contraction
    return out


for _dict_form, _freq, _kind in _JA_VERBS:
    for _surface, _form in _conjugate_ext(_dict_form, _kind).items():
        _ja_upsert(_surface,
                   max(100, int(_freq * _EXT_FORM_WEIGHTS[_form])),
                   "動詞")


# --- Japanese keigo (round-5 expansion) --------------------------------
#
# The honorific/humble lexicon the reference's IPADIC carries as regular
# entries: the irregular -aru keigo verbs (いらっしゃる etc. take the
# い masu-stem), the suppletive humble/honorific verbs, the fixed polite
# formulas, and the productive お/ご noun prefixes.

_JA_KEIGO_ARU5 = (  # -aru row keigo: masu-stem い, って/った te/ta
    ("いらっしゃる", 2500), ("おっしゃる", 2000), ("なさる", 1800),
    ("くださる", 2200),
)

_JA_KEIGO_VERBS = (  # suppletive keigo that conjugates regularly
    ("召し上がる", 1200, "godan"), ("伺う", 1500, "godan"),
    ("参る", 1500, "godan"), ("申す", 1500, "godan"),
    ("申し上げる", 1200, "ichidan"), ("いただく", 3000, "godan"),
    ("さしあげる", 1000, "ichidan"), ("おる", 1800, "godan"),
)

_JA_KEIGO_FIXED = (
    ("ございます", 2500), ("でございます", 1500), ("おります", 1500),
    ("ご覧になる", 1000), ("ご覧ください", 800), ("拝見する", 800),
    ("拝見しました", 600), ("お願いします", 3000),
    ("お願いいたします", 1500), ("いただきます", 2500),
    ("いたします", 2000), ("いたしました", 1200),
    ("恐れ入ります", 800), ("お疲れ様です", 1500),
    ("お疲れ様でした", 1200), ("かしこまりました", 1000),
    ("承知しました", 1000), ("承知いたしました", 700),
    ("お世話になります", 1200), ("お世話になりました", 1000),
    ("よろしくお願いします", 2000), ("お待たせしました", 1000),
    ("お待ちください", 1200), ("ご遠慮ください", 700),
)

_JA_HONORIFIC_NOUNS = (  # お/ご prefixed everyday nouns
    ("お茶", 2500), ("お金", 3000), ("お水", 1800), ("お店", 2200),
    ("お仕事", 1800), ("お名前", 2000), ("お時間", 1500),
    ("お電話", 1500), ("お話", 1800), ("お部屋", 1500),
    ("お客様", 2500), ("お子さん", 1500), ("お宅", 1000),
    ("お土産", 1500), ("お弁当", 1800), ("お風呂", 1800),
    ("お祭り", 1200), ("お正月", 1200), ("ご飯", 3000),
    ("ご家族", 1200), ("ご連絡", 1800), ("ご案内", 1500),
    ("ご質問", 1500), ("ご利用", 1800), ("ご注意", 1500),
    ("ご意見", 1500), ("ご協力", 1200), ("ご確認", 1500),
    ("ご予約", 1200), ("ご紹介", 1200),
)

for _surface, _freq in _JA_KEIGO_ARU5:
    _base = _surface[:-1]
    for _sfx, _w in (("", 1.0), ("います", 0.8), ("いました", 0.5),
                     ("いませ", 0.3), ("って", 0.5), ("った", 0.4),
                     ("らない", 0.15)):
        _s = _surface if _sfx == "" else _base + _sfx
        _ja_upsert(_s, max(100, int(_freq * _w)), "動詞")

for _dict_form, _freq, _kind in _JA_KEIGO_VERBS:
    for _surface, _form in _conjugate(_dict_form, _kind).items():
        _f = max(100, int(_freq * _FORM_WEIGHTS[_form]))
        if _surface not in JA_ENTRIES or JA_ENTRIES[_surface][0] < _f:
            JA_ENTRIES[_surface] = (_f, "動詞")

for _surface, _freq in _JA_KEIGO_FIXED:
    _ja_upsert(_surface, _freq, "感動詞")

for _surface, _freq in _JA_HONORIFIC_NOUNS:
    _ja_upsert(_surface, _freq, "名詞")


# --- Japanese grammar formulae (round-5) -------------------------------
# Lexicalized multi-morpheme patterns IPADIC carries as entries; without
# them the lattice falls back to kana singletons (ように -> よ/う/に).

_JA_GRAMMAR = (
    ("ように", 3000), ("ような", 2500), ("ようです", 1500),
    ("ようになる", 1200), ("ようにする", 1000), ("そうです", 2000),
    ("そうだ", 1500), ("かもしれない", 1800), ("かもしれません", 1500),
    ("でしょう", 2500), ("だろう", 2200), ("はずです", 1200),
    ("はずだ", 1000), ("つもりです", 1200), ("つもりだ", 1000),
    ("ことができる", 1500), ("ことができます", 1200),
    ("なければならない", 1200), ("なければなりません", 1000),
    ("たほうがいい", 1000), ("ながら", 1800), ("について", 2200),
    ("によって", 2000), ("にとって", 1800), ("に対して", 1500),
    ("として", 2200), ("とともに", 1200), ("のために", 1800),
    ("のように", 1500), ("ばかり", 1500), ("だけでなく", 1000),
)

for _surface, _freq in _JA_GRAMMAR:
    _ja_upsert(_surface, _freq, "助詞")


# --- Breadth expansion (round-5b): everyday vocabulary -----------------
# The largest remaining gap vs IPADIC/ansj is plain vocabulary breadth;
# these bands extend nouns/adverbs/adjectives with the next tier of
# everyday words (same rank-bucketed weighting as the core bands).

_JA_EXTRA_NOUNS = (
    (3000, "今日 明日 昨日 今年 去年 来年 今月 先月 来月 今週 先週 来週"),
    (2800, "朝 昼 夜 夕方 午前 午後 週末 平日 休日 祝日 誕生日 記念日"),
    (2500, "家 部屋 台所 風呂 庭 玄関 窓 壁 床 屋根 階段 廊下"),
    (2500, "駅 空港 港 道 橋 信号 交差点 駐車場 停留所 地下鉄 新幹線 切符"),
    (2200, "会社 工場 事務所 会議 仕事 給料 残業 出張 休憩 退職 面接 名刺"),
    (2200, "学校 大学 教室 授業 宿題 試験 成績 先生 学生 生徒 卒業式 入学式"),
    (2000, "朝ご飯 昼ご飯 晩ご飯 野菜 果物 肉 魚 卵 米 パン 麺 スープ"),
    (2000, "水 湯 茶 牛乳 ジュース ビール 酒 砂糖 塩 醤油 味噌 油"),
    (1800, "頭 顔 目 耳 鼻 口 手 足 腕 指 背中 お腹"),
    (1800, "天気 雨 雪 風 雲 空 太陽 月 星 気温 台風 地震"),
    (1600, "音楽 映画 写真 絵 歌 踊り 本 新聞 雑誌 手紙 葉書 切手"),
    (1600, "病気 風邪 熱 薬 病院 医者 看護師 注射 手術 検査 保険 健康"),
    (1500, "服 シャツ ズボン スカート 靴 靴下 帽子 眼鏡 時計 鞄 財布 傘"),
    (1500, "犬 猫 鳥 魚類 馬 牛 豚 羊 兎 象 虎 猿"),
)

for _freq, _words in _JA_EXTRA_NOUNS:
    for _w in _words.split():
        _ja_upsert(_w, _freq, "名詞")

_JA_EXTRA_ADVERBS = (
    (4000, "とても もっと たくさん 少し ちょっと すぐ まだ もう"),
    (3000, "やっと きっと たぶん 全然 必ず 多分 本当に 特に"),
    (2500, "いつも ときどき たまに よく あまり ほとんど そろそろ なかなか"),
    (2000, "ゆっくり はっきり しっかり ちゃんと だんだん どんどん わざと うっかり"),
)

for _freq, _words in _JA_EXTRA_ADVERBS:
    for _w in _words.split():
        _ja_upsert(_w, _freq, "副詞")

# extra i-adjectives through the same conjugation generator
_JA_EXTRA_I_ADJ = (  # additions ONLY — the core _JA_I_ADJECTIVES list
    # stays the single source of truth for its own words
    ("寂しい", 1500), ("眠い", 1500), ("痛い", 1800), ("怖い", 1800),
    ("恥ずかしい", 1200), ("珍しい", 1200), ("素晴らしい", 1500),
    ("不味い", 800), ("甘い", 1500), ("辛い", 1500), ("苦い", 1000),
    ("深い", 1200), ("浅い", 800), ("固い", 1000), ("柔らかい", 1000),
    ("細い", 1000), ("太い", 1000), ("眩しい", 600), ("優しい", 1800),
    ("厳しい", 1500), ("激しい", 1200), ("詳しい", 1200),
    ("正しい", 1500), ("等しい", 600),
)

for _dict_form, _freq in _JA_EXTRA_I_ADJ:
    for _surface, _form in _conjugate_i_adj(_dict_form).items():
        _ja_upsert(_surface,
                   max(100, int(_freq * _ADJ_FORM_WEIGHTS[_form])),
                   "形容詞")
