"""Compact built-in CJK dictionaries for the lattice tokenizers.

The reference's CJK analyzers ship multi-megabyte system dictionaries
(deeplearning4j-nlp-japanese bundles the kuromoji/IPADIC data,
deeplearning4j-nlp-chinese the ansj/jieba tables) — most of their 19.6k
LoC + resources is dictionary data. This module is the zero-egress
counterpart: a hand-curated core-vocabulary dictionary (~700 Chinese
words with relative frequencies, ~350 Japanese entries with POS) that
makes `ChineseTokenizerFactory(dictionary="builtin")` /
`JapaneseTokenizerFactory(dictionary="builtin")` segment everyday text
sensibly out of the box. It is deliberately small: domain text should
add `load_user_dictionary` entries on top (jieba-style lines), exactly
as the reference's user-dictionary mechanism works.

Frequencies are rank-bucketed relative weights (only ratios matter —
dict_from_frequencies converts to -log(p) costs), ordered by the
well-known frequency structure of modern Chinese/Japanese corpora.
"""

# --- Chinese: word -> relative frequency -------------------------------

_ZH_BUCKETS = (
    # function words / pronouns (highest band)
    (50000, "的 是 不 了 在 有 我 他 这 个 们 中 来 上 大 为 和 国 地 到"),
    (30000, "你 她 它 我们 他们 你们 就 说 要 也 都 而 去 能 会 着 没有 看 好 自己"),
    (20000, "这个 那个 什么 一个 没 很 再 可以 因为 所以 但是 如果 虽然 还是 或者 而且 然后 现在 已经 还"),
    # common verbs
    (12000, "知道 觉得 认为 希望 喜欢 开始 成为 进行 出现 发现 使用 需要 应该 可能 表示 通过 作为 得到 发展 工作"),
    (9000, "学习 生活 研究 生产 管理 服务 建设 活动 经济 问题 时候 时间 地方 今天 明天 昨天 每天 以后 以前 之间"),
    # common nouns
    (7000, "中国 北京 上海 美国 日本 世界 国家 人民 政府 社会 历史 文化 教育 科学 技术 信息 系统 公司 市场 银行"),
    (5000, "大学 学校 学生 老师 先生 朋友 孩子 父母 家庭 城市 农村 电话 电脑 网络 汽车 火车 飞机 医院 医生 音乐"),
    (4000, "东西 事情 方面 方法 结果 原因 情况 条件 关系 内容 标准 水平 能力 机会 力量 影响 作用 意义 目的 过程"),
    # segmentation classics + frequent bigrams
    (3000, "研究生 生命 起源 天安门 长城 电影 电视 新闻 报纸 杂志 小说 故事 节目 比赛 运动 足球 篮球 游戏 旅游 天气"),
    (2500, "春天 夏天 秋天 冬天 早上 上午 中午 下午 晚上 星期 月份 年代 世纪 小时 分钟 左右 前面 后面 里面 外面"),
    (2000, "非常 特别 十分 比较 更加 越来越 几乎 差不多 大概 也许 当然 一定 必须 只有 只要 无论 即使 尽管 不过 否则"),
    (1500, "高兴 快乐 幸福 难过 生气 担心 害怕 奇怪 重要 容易 困难 简单 复杂 漂亮 美丽 干净 安静 热闹 方便 舒服"),
    (1200, "吃饭 喝水 睡觉 起床 上班 下班 上课 下课 回家 出门 买东西 做饭 洗澡 跑步 走路 说话 唱歌 跳舞 画画 写字"),
    (1000, "经过 根据 关于 对于 由于 为了 按照 随着 除了 以及 并且 甚至 尤其 例如 比如 总之 另外 同时 首先 最后"),
    (800, "增加 减少 提高 降低 改变 改革 开放 发达 先进 落后 成功 失败 胜利 解决 决定 选择 准备 参加 组织 举行"),
    (600, "数学 物理 化学 生物 语文 英语 汉语 外语 历史课 地理 体育 艺术 哲学 法律 政治 军事 宗教 环境 资源 能源"),
    (500, "苹果 香蕉 西瓜 牛奶 面包 米饭 面条 饺子 茶叶 咖啡 啤酒 蔬菜 水果 鸡蛋 牛肉 羊肉 鱼肉 糖果 蛋糕 早饭"),
)

ZH_FREQ = {}
for _f, _words in _ZH_BUCKETS:
    for _w in _words.split():
        ZH_FREQ.setdefault(_w, _f)

# --- Japanese: word -> (relative frequency, POS) -----------------------

_JA_BUCKETS = (
    # particles (highest band — the backbone of the lattice)
    (50000, "助詞", "の は が を に で と も へ や か ね よ から まで など しか だけ ほど より って"),
    # copula / auxiliaries / frequent verb endings
    (30000, "助動詞", "です ます でした ました ません でしょう だ である だった ない なかった たい たく れる られる せる させる"),
    # frequent verbs (dictionary + common conjugated surfaces)
    (15000, "動詞",
     "する した して します しました いる いた いて います ある あった あり なる なった なって なります"),
    (10000, "動詞",
     "行く 行った 行きます 来る 来た 来ます 見る 見た 見ます 言う 言った 思う 思った 思います 分かる 分かった 知る 知って 食べる 食べた 飲む 読む 書く 聞く 話す 使う 作る 買う 持つ 待つ 会う 帰る 出る 入る 住む 働く 学ぶ 遊ぶ 泳ぐ 歩く 走る 休む 始まる 終わる できる"),
    # pronouns / demonstratives / adverbs
    (12000, "代名詞", "これ それ あれ どれ ここ そこ あそこ どこ この その あの どの 私 僕 君 彼 彼女 誰 何"),
    (8000, "副詞", "とても もっと すこし 少し たくさん よく もう まだ また すぐ いつも 今日 明日 昨日 今 毎日 時々 全然 多分 本当に 一緒に"),
    # common nouns
    (7000, "名詞",
     "日本 東京 大阪 京都 中国 アメリカ 世界 国 人 方 時 年 月 日 時間 今年 去年 来年 午前 午後"),
    (5000, "名詞",
     "学生 先生 学校 大学 会社 仕事 電車 駅 車 家 部屋 店 料理 水 お金 映画 音楽 写真 電話 手紙"),
    (4000, "名詞",
     "友達 家族 父 母 子供 男 女 犬 猫 山 川 海 空 雨 雪 風 花 木 本 言葉"),
    (3000, "名詞",
     "問題 質問 答え 意味 名前 気持ち 天気 気温 朝ご飯 昼ご飯 晩ご飯 朝 昼 夜 週末 旅行 買い物 勉強 練習 試験"),
    # i-adjectives / na-adjectives
    (4000, "形容詞",
     "いい 良い 悪い 大きい 小さい 高い 安い 新しい 古い 長い 短い 早い 遅い 近い 遠い 暑い 寒い 楽しい 面白い 難しい 易しい 美味しい 忙しい 嬉しい 悲しい"),
    (3000, "形容動詞", "元気 静か 有名 便利 大変 大切 簡単 綺麗 親切 丁寧 好き 嫌い 上手 下手 必要"),
    # katakana loanwords
    (3000, "名詞",
     "コーヒー テレビ パソコン スマホ インターネット ニュース ホテル レストラン バス タクシー カメラ ゲーム スポーツ サッカー テニス"),
)

JA_ENTRIES = {}
for _f, _pos, _words in _JA_BUCKETS:
    for _w in _words.split():
        JA_ENTRIES.setdefault(_w, (_f, _pos))
