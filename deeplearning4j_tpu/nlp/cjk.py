"""CJK tokenizers: Chinese, Japanese, Korean.

Equivalent of the reference's language-specific tokenizer modules (SURVEY
§2.6: deeplearning4j-nlp-chinese 9.5k (ansj), -japanese 6.8k (kuromoji
fork), -korean 141 LoC). Those wrap large dictionary-driven morphological
analyzers; this module provides dependency-free segmenters with the same
TokenizerFactory SPI so CJK corpora flow through Word2Vec/ParagraphVectors:

- Chinese: with a dictionary, minimum-cost Viterbi over the word lattice
  (ansj/jieba's algorithm — `nlp.lattice`); frequencies weight the path
  like jieba's max-probability DAG. Greedy forward-maximum-match stays
  available as ``engine="fmm"``. Without a dictionary: character (or
  character-bigram) segmentation, the standard dictionary-free baseline.
- Japanese: with a dictionary, the same lattice engine with kuromoji-style
  unknown-word grouping by character class; without one, character-class
  run segmentation (kanji / hiragana / katakana / latin / digits).
- Korean: whitespace segmentation with optional particle (josa) stripping,
  mirroring the reference's Korean module (which is itself 141 lines of
  twitter-text wrapping).

What is NOT shipped is the reference's multi-megabyte system dictionaries
(ipadic / ansj library data) — load your own via
``load_user_dictionary(path)`` (jieba-style ``word [freq] [pos]`` lines).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Set

from deeplearning4j_tpu.nlp.lattice import (
    Entry, ViterbiLattice, dict_from_frequencies,
)
from deeplearning4j_tpu.nlp.tokenization import Tokenizer, TokenizerFactory


def load_user_dictionary(path: str):
    """Parse a jieba/mecab-style user dictionary: one entry per line,
    ``word [freq] [pos]`` (freq defaults to 1). Returns {word: (freq, pos)}."""
    out = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.strip().split()
            if not parts or parts[0].startswith("#"):
                continue
            word = parts[0]
            freq = 1.0
            pos = ""
            if len(parts) > 1:
                try:
                    freq = float(parts[1])
                    pos = parts[2] if len(parts) > 2 else ""
                except ValueError:
                    pos = parts[1]
            out[word] = (freq, pos)
    return out


def _is_cjk(ch: str) -> bool:
    return "一" <= ch <= "鿿" or "㐀" <= ch <= "䶿"


def _is_hiragana(ch: str) -> bool:
    return "぀" <= ch <= "ゟ"


def _is_katakana(ch: str) -> bool:
    return "゠" <= ch <= "ヿ" or ch == "ー"


def _char_class(ch: str) -> str:
    if _is_cjk(ch):
        return "kanji"
    if _is_hiragana(ch):
        return "hiragana"
    if _is_katakana(ch):
        return "katakana"
    if ch.isdigit():
        return "digit"
    if ch.isalpha():
        return "latin"
    if ch.isspace():
        return "space"
    return "other"


class ChineseTokenizerFactory(TokenizerFactory):
    """ref: deeplearning4j-nlp-chinese ChineseTokenizerFactory (ansj).

    With a dictionary: minimum-cost lattice segmentation (ansj/jieba
    algorithm); pass ``frequencies={word: count}`` to weight the path by
    corpus statistics, or a plain word iterable for uniform costs.
    ``dictionary="builtin"`` loads the embedded core-vocabulary
    frequency dictionary (nlp/cjk_data.py — the small-footprint stand-in
    for the reference's bundled ansj tables). ``engine="fmm"`` selects
    greedy forward maximum match instead.
    Without a dictionary: single characters (``bigrams=True`` adds
    overlapping bigrams, a strong baseline for embedding training).
    """

    def __init__(self, dictionary: Optional[Iterable[str]] = None, *,
                 bigrams: bool = False, preprocessor=None,
                 frequencies: Optional[dict] = None,
                 engine: str = "viterbi"):
        # everything after `dictionary` is keyword-only: the parameter set
        # grew this round, and positional binding against the old order
        # would silently misassign
        super().__init__(preprocessor)
        if isinstance(dictionary, str):
            if dictionary != "builtin":
                raise ValueError(
                    f"unknown dictionary {dictionary!r} (only the "
                    "\"builtin\" sentinel is accepted as a string; for a "
                    "dictionary file use load_user_dictionary)")
            from deeplearning4j_tpu.nlp.cjk_data import ZH_FREQ
            dictionary = None
            frequencies = {**ZH_FREQ, **(frequencies or {})}
        if frequencies:
            freqs = {w: (f[0] if isinstance(f, tuple) else f)
                     for w, f in frequencies.items()}
            for w in dictionary or ():  # plain words join at count 1
                freqs.setdefault(w, 1.0)
            self.dictionary: Set[str] = set(freqs)
            entries = dict_from_frequencies(freqs)
        else:
            self.dictionary = set(dictionary or ())
            entries = {w: Entry(cost=4.0) for w in self.dictionary}
        self.max_word = max((len(w) for w in self.dictionary), default=1)
        self.bigrams = bigrams
        if engine not in ("viterbi", "fmm"):
            raise ValueError(f"unknown engine {engine!r}")
        self.engine = engine
        self._lattice = ViterbiLattice(entries) if entries else None

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for run, cls in _runs(text):
            if cls != "han":
                tokens.extend(run.split())
                continue
            if self.dictionary:
                if self.engine == "viterbi":
                    tokens.extend(s for s, _ in self._lattice.segment(run))
                else:
                    tokens.extend(self._max_match(run))
            else:
                tokens.extend(run)
                if self.bigrams:
                    tokens.extend(run[i:i + 2]
                                  for i in range(len(run) - 1))
        return Tokenizer(tokens, self._pre)

    def _max_match(self, run: str) -> List[str]:
        out, i = [], 0
        while i < len(run):
            for ln in range(min(self.max_word, len(run) - i), 1, -1):
                if run[i:i + ln] in self.dictionary:
                    out.append(run[i:i + ln])
                    i += ln
                    break
            else:
                out.append(run[i])
                i += 1
        return out


def _runs(text: str):
    """Split text into (run, 'han'|'other') spans."""
    out = []
    cur, cur_han = "", None
    for ch in text:
        han = _is_cjk(ch)
        if cur_han is None or han == cur_han:
            cur += ch
        else:
            out.append((cur, "han" if cur_han else "other"))
            cur = ch
        cur_han = han
    if cur:
        out.append((cur, "han" if cur_han else "other"))
    return out


class JapaneseTokenizerFactory(TokenizerFactory):
    """ref: deeplearning4j-nlp-japanese (kuromoji fork). With a
    dictionary ({word: cost | (freq, pos)} or word iterable): kuromoji's
    lattice algorithm — dictionary edges + unknown edges grouped by
    character class, minimum-cost Viterbi path.
    ``dictionary="builtin"`` loads the embedded core vocabulary
    (nlp/cjk_data.py, (freq, POS) entries — the small-footprint stand-in
    for the bundled IPADIC data). Without one: segmentation at
    character-class boundaries (kanji / hiragana / katakana / latin /
    digit runs)."""

    def __init__(self, preprocessor=None, split_kanji_chars: bool = False,
                 dictionary=None, user_entries: Optional[dict] = None):
        super().__init__(preprocessor)
        self.split_kanji_chars = split_kanji_chars
        self._lattice = None
        if isinstance(dictionary, str):
            if dictionary != "builtin":
                raise ValueError(
                    f"unknown dictionary {dictionary!r} (only the "
                    "\"builtin\" sentinel is accepted as a string; for a "
                    "dictionary file use load_user_dictionary)")
            from deeplearning4j_tpu.nlp.cjk_data import JA_ENTRIES
            dictionary = dict(JA_ENTRIES)
        if user_entries:  # domain terms layered over the dictionary
            if dictionary and not isinstance(dictionary, dict):
                dictionary = {w: 4.0 for w in dictionary}
            dictionary = {**(dictionary or {}), **user_entries}
        if dictionary:
            if isinstance(dictionary, dict):
                tuples = {w: v for w, v in dictionary.items()
                          if isinstance(v, tuple)}
                entries = {w: Entry(cost=float(v))
                           for w, v in dictionary.items()
                           if not isinstance(v, tuple)}
                if tuples:  # (freq, pos) entries -> -log(p) like Chinese
                    costs = dict_from_frequencies(
                        {w: v[0] for w, v in tuples.items()})
                    for w, e in costs.items():
                        entries[w] = Entry(cost=e.cost, pos=tuples[w][1])
            else:
                entries = {w: Entry(cost=4.0) for w in dictionary}
            self._lattice = ViterbiLattice(
                entries, unknown_cost=9.0, char_class=_char_class,
                group_unknown=True)

    def create(self, text: str) -> Tokenizer:
        if self._lattice is not None:
            tokens = []
            for chunk in text.split():
                for surf, pos in self._lattice.segment(chunk):
                    if self.split_kanji_chars and pos == "UNK" and \
                            all(map(_is_cjk, surf)):
                        tokens.extend(surf)
                    else:
                        tokens.append(surf)
            return Tokenizer(tokens, self._pre)
        return self._runs_create(text)

    def _runs_create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        cur, cur_cls = "", None
        for ch in text:
            cls = _char_class(ch)
            if cls == "space" or cls == "other":
                if cur:
                    tokens.append(cur)
                    cur, cur_cls = "", None
                continue
            if cur_cls is None or cls == cur_cls:
                cur += ch
                cur_cls = cls
            else:
                tokens.append(cur)
                cur, cur_cls = ch, cls
        if cur:
            tokens.append(cur)
        if self.split_kanji_chars:
            tokens = [c for t in tokens
                      for c in (t if all(map(_is_cjk, t)) else [t])]
        return Tokenizer(tokens, self._pre)


# common single-syllable josa (particles) stripped from token ends
_KOREAN_JOSA = ("은", "는", "이", "가", "을", "를", "에", "의", "로", "와",
                "과", "도", "만", "에서", "으로", "까지", "부터", "하고")


class KoreanTokenizerFactory(TokenizerFactory):
    """ref: deeplearning4j-nlp-korean KoreanTokenizerFactory. Whitespace
    tokens with optional trailing-particle stripping."""

    def __init__(self, strip_josa: bool = True, preprocessor=None):
        super().__init__(preprocessor)
        self.strip_josa = strip_josa

    def create(self, text: str) -> Tokenizer:
        tokens = []
        for tok in text.split():
            tok = tok.strip("。，.,!?“”\"'()[]")
            if not tok:
                continue
            if self.strip_josa and len(tok) > 1:
                for josa in sorted(_KOREAN_JOSA, key=len, reverse=True):
                    if tok.endswith(josa) and len(tok) > len(josa):
                        tok = tok[:-len(josa)]
                        break
            tokens.append(tok)
        return Tokenizer(tokens, self._pre)
