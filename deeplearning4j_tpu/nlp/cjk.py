"""CJK tokenizers: Chinese, Japanese, Korean.

Equivalent of the reference's language-specific tokenizer modules (SURVEY
§2.6: deeplearning4j-nlp-chinese 9.5k (ansj), -japanese 6.8k (kuromoji
fork), -korean 141 LoC). Those wrap large dictionary-driven morphological
analyzers; this module provides dependency-free segmenters with the same
TokenizerFactory SPI so CJK corpora flow through Word2Vec/ParagraphVectors:

- Chinese: forward-maximum-match over a user dictionary when given one,
  character (or character-bigram) segmentation otherwise — the standard
  dictionary-free baseline for embeddings.
- Japanese: character-class run segmentation (kanji / hiragana / katakana /
  latin / digits), splitting at script boundaries — kuromoji-lite.
- Korean: whitespace segmentation with optional particle (josa) stripping,
  mirroring the reference's Korean module (which is itself 141 lines of
  twitter-text wrapping).

A real morphological analyzer (e.g. a mecab/kuromoji port) can be slotted
in by subclassing TokenizerFactory — the SPI is the integration point.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Set

from deeplearning4j_tpu.nlp.tokenization import Tokenizer, TokenizerFactory


def _is_cjk(ch: str) -> bool:
    return "一" <= ch <= "鿿" or "㐀" <= ch <= "䶿"


def _is_hiragana(ch: str) -> bool:
    return "぀" <= ch <= "ゟ"


def _is_katakana(ch: str) -> bool:
    return "゠" <= ch <= "ヿ" or ch == "ー"


def _char_class(ch: str) -> str:
    if _is_cjk(ch):
        return "kanji"
    if _is_hiragana(ch):
        return "hiragana"
    if _is_katakana(ch):
        return "katakana"
    if ch.isdigit():
        return "digit"
    if ch.isalpha():
        return "latin"
    if ch.isspace():
        return "space"
    return "other"


class ChineseTokenizerFactory(TokenizerFactory):
    """ref: deeplearning4j-nlp-chinese ChineseTokenizerFactory (ansj).

    With a dictionary: greedy forward maximum match. Without: single
    characters (``bigrams=True`` adds overlapping bigrams, a strong
    baseline for embedding training).
    """

    def __init__(self, dictionary: Optional[Iterable[str]] = None,
                 bigrams: bool = False, preprocessor=None):
        super().__init__(preprocessor)
        self.dictionary: Set[str] = set(dictionary or ())
        self.max_word = max((len(w) for w in self.dictionary), default=1)
        self.bigrams = bigrams

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for run, cls in _runs(text):
            if cls != "han":
                tokens.extend(run.split())
                continue
            if self.dictionary:
                tokens.extend(self._max_match(run))
            else:
                tokens.extend(run)
                if self.bigrams:
                    tokens.extend(run[i:i + 2]
                                  for i in range(len(run) - 1))
        return Tokenizer(tokens, self._pre)

    def _max_match(self, run: str) -> List[str]:
        out, i = [], 0
        while i < len(run):
            for ln in range(min(self.max_word, len(run) - i), 1, -1):
                if run[i:i + ln] in self.dictionary:
                    out.append(run[i:i + ln])
                    i += ln
                    break
            else:
                out.append(run[i])
                i += 1
        return out


def _runs(text: str):
    """Split text into (run, 'han'|'other') spans."""
    out = []
    cur, cur_han = "", None
    for ch in text:
        han = _is_cjk(ch)
        if cur_han is None or han == cur_han:
            cur += ch
        else:
            out.append((cur, "han" if cur_han else "other"))
            cur = ch
        cur_han = han
    if cur:
        out.append((cur, "han" if cur_han else "other"))
    return out


class JapaneseTokenizerFactory(TokenizerFactory):
    """ref: deeplearning4j-nlp-japanese (kuromoji fork). Segments at
    character-class boundaries: kanji runs, hiragana runs, katakana runs,
    latin words, digit runs."""

    def __init__(self, preprocessor=None, split_kanji_chars: bool = False):
        super().__init__(preprocessor)
        self.split_kanji_chars = split_kanji_chars

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        cur, cur_cls = "", None
        for ch in text:
            cls = _char_class(ch)
            if cls == "space" or cls == "other":
                if cur:
                    tokens.append(cur)
                    cur, cur_cls = "", None
                continue
            if cur_cls is None or cls == cur_cls:
                cur += ch
                cur_cls = cls
            else:
                tokens.append(cur)
                cur, cur_cls = ch, cls
        if cur:
            tokens.append(cur)
        if self.split_kanji_chars:
            tokens = [c for t in tokens
                      for c in (t if all(map(_is_cjk, t)) else [t])]
        return Tokenizer(tokens, self._pre)


# common single-syllable josa (particles) stripped from token ends
_KOREAN_JOSA = ("은", "는", "이", "가", "을", "를", "에", "의", "로", "와",
                "과", "도", "만", "에서", "으로", "까지", "부터", "하고")


class KoreanTokenizerFactory(TokenizerFactory):
    """ref: deeplearning4j-nlp-korean KoreanTokenizerFactory. Whitespace
    tokens with optional trailing-particle stripping."""

    def __init__(self, strip_josa: bool = True, preprocessor=None):
        super().__init__(preprocessor)
        self.strip_josa = strip_josa

    def create(self, text: str) -> Tokenizer:
        tokens = []
        for tok in text.split():
            tok = tok.strip("。，.,!?“”\"'()[]")
            if not tok:
                continue
            if self.strip_josa and len(tok) > 1:
                for josa in sorted(_KOREAN_JOSA, key=len, reverse=True):
                    if tok.endswith(josa) and len(tok) > len(josa):
                        tok = tok[:-len(josa)]
                        break
            tokens.append(tok)
        return Tokenizer(tokens, self._pre)
