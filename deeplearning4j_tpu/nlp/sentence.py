"""Sentence / document iterators.

Equivalent of deeplearning4j-nlp text/sentenceiterator/ and
text/documentiterator/ (SURVEY §2.6): streams of sentences (strings) for
Word2Vec, and label-aware document streams for ParagraphVectors.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator, List, Optional


class SentenceIterator:
    """ref: SentenceIterator.java (nextSentence/hasNext/reset +
    SentencePreProcessor)."""

    def __init__(self, preprocessor: Optional[Callable[[str], str]] = None):
        self.preprocessor = preprocessor

    def _raw(self) -> Iterator[str]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[str]:
        for s in self._raw():
            yield self.preprocessor(s) if self.preprocessor else s

    def reset(self) -> None:  # iterators here are restartable generators
        pass


class CollectionSentenceIterator(SentenceIterator):
    """ref: CollectionSentenceIterator.java."""

    def __init__(self, sentences: Iterable[str],
                 preprocessor: Optional[Callable[[str], str]] = None):
        super().__init__(preprocessor)
        self._sentences = list(sentences)

    def _raw(self) -> Iterator[str]:
        return iter(self._sentences)


class BasicLineIterator(SentenceIterator):
    """One sentence per line of a file (ref: BasicLineIterator.java)."""

    def __init__(self, path: str,
                 preprocessor: Optional[Callable[[str], str]] = None):
        super().__init__(preprocessor)
        self.path = path

    def _raw(self) -> Iterator[str]:
        with open(self.path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class FileSentenceIterator(SentenceIterator):
    """Every line of every file under a directory
    (ref: FileSentenceIterator.java)."""

    def __init__(self, root: str,
                 preprocessor: Optional[Callable[[str], str]] = None):
        super().__init__(preprocessor)
        self.root = root

    def _raw(self) -> Iterator[str]:
        for dirpath, _, files in sorted(os.walk(self.root)):
            for name in sorted(files):
                with open(os.path.join(dirpath, name), "r",
                          encoding="utf-8", errors="replace") as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            yield line


@dataclass
class LabelledDocument:
    """ref: documentiterator/LabelledDocument.java."""
    content: str
    labels: List[str] = field(default_factory=list)

    @property
    def label(self) -> Optional[str]:
        return self.labels[0] if self.labels else None


class LabelAwareIterator:
    """ref: documentiterator/LabelAwareIterator.java."""

    def __iter__(self) -> Iterator[LabelledDocument]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class SimpleLabelAwareIterator(LabelAwareIterator):
    """In-memory labelled docs (ref: SimpleLabelAwareIterator.java)."""

    def __init__(self, documents: Iterable[LabelledDocument]):
        self._docs = list(documents)

    def __iter__(self) -> Iterator[LabelledDocument]:
        return iter(self._docs)


class FileLabelAwareIterator(LabelAwareIterator):
    """Directory-per-label corpus: root/<label>/<doc>.txt
    (ref: FileLabelAwareIterator.java)."""

    def __init__(self, root: str):
        self.root = root

    def __iter__(self) -> Iterator[LabelledDocument]:
        for label in sorted(os.listdir(self.root)):
            d = os.path.join(self.root, label)
            if not os.path.isdir(d):
                continue
            for name in sorted(os.listdir(d)):
                with open(os.path.join(d, name), "r", encoding="utf-8",
                          errors="replace") as f:
                    yield LabelledDocument(f.read(), [label])
