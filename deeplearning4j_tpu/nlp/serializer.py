"""Word-vector model serialization.

Equivalent of deeplearning4j-nlp models/embeddings/loader/
WordVectorSerializer.java:2824 — text format ("word v1 v2 ...", one per
line, optional header) and the Google word2vec binary format
(header "V D\\n", then per word: name, space, D little-endian float32).
"""

from __future__ import annotations

import base64
import json
import struct
import zipfile
from typing import Dict, List, Optional, Tuple, Type

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord


def write_word_vectors(vectors: SequenceVectors, path: str,
                       write_header: bool = True) -> None:
    """ref: WordVectorSerializer.writeWordVectors (text)."""
    syn0 = np.asarray(vectors.syn0)
    words = vectors.vocab.vocab_words()
    with open(path, "w", encoding="utf-8") as f:
        if write_header:
            f.write(f"{len(words)} {syn0.shape[1]}\n")
        for w in words:
            vec = " ".join(f"{v:.6f}" for v in syn0[w.index])
            f.write(f"{w.word} {vec}\n")


def read_word_vectors(path: str) -> SequenceVectors:
    """ref: WordVectorSerializer.readWord2VecModel / loadTxtVectors."""
    words, rows = [], []
    with open(path, "r", encoding="utf-8") as f:
        first = f.readline().rstrip("\n")
        parts = first.split(" ")
        header = len(parts) == 2 and all(p.isdigit() for p in parts)
        if not header and parts:
            words.append(parts[0])
            rows.append([float(x) for x in parts[1:]])
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if len(parts) < 2:
                continue
            words.append(parts[0])
            rows.append([float(x) for x in parts[1:]])
    return _from_arrays(words, np.asarray(rows, np.float32))


def write_word2vec_binary(vectors: SequenceVectors, path: str) -> None:
    """Google word2vec .bin format (ref: WordVectorSerializer.writeWord2Vec
    binary branch)."""
    syn0 = np.asarray(vectors.syn0, np.float32)
    words = vectors.vocab.vocab_words()
    with open(path, "wb") as f:
        f.write(f"{len(words)} {syn0.shape[1]}\n".encode())
        for w in words:
            f.write(w.word.encode("utf-8") + b" ")
            f.write(syn0[w.index].astype("<f4").tobytes())
            f.write(b"\n")


def read_word2vec_binary(path: str) -> SequenceVectors:
    """ref: WordVectorSerializer.readBinaryModel."""
    with open(path, "rb") as f:
        header = f.readline().decode().split()
        V, D = int(header[0]), int(header[1])
        words, rows = [], np.empty((V, D), np.float32)
        for i in range(V):
            name = bytearray()
            while True:
                c = f.read(1)
                if c in (b" ", b""):
                    break
                if c != b"\n":
                    name.extend(c)
            words.append(name.decode("utf-8"))
            rows[i] = np.frombuffer(f.read(4 * D), "<f4")
            nl = f.read(1)
            if nl not in (b"\n", b""):
                f.seek(-1, 1)
    return _from_arrays(words, rows)


def _from_arrays(words, syn0: np.ndarray) -> SequenceVectors:
    sv = SequenceVectors(layer_size=syn0.shape[1])
    cache = VocabCache()
    for w in words:
        cache.add_token(VocabWord(w))
    cache.build_index(order_by_frequency=False)
    sv.vocab = cache
    sv.syn0 = jnp.asarray(syn0)
    return sv


# ---------------------------------------------------------------------------
# Full-model zip — the reference's writeWord2VecModel / writeParagraphVectors
# layout (WordVectorSerializer.java:472-677 write, :811-950 read): entries
# syn0.txt ("V D numDocs" header, then "B64:word v0 v1 ..."), syn1.txt,
# syn1Neg.txt, codes.txt, huffman.txt, frequencies.txt, labels.txt (paravec),
# config.json (VectorsConfiguration field names). One extra entry of ours,
# trainer_state.json, carries the rng stream + schedule position so a
# mid-fit save resumes bit-exactly; reference-written zips simply lack it
# (the model still loads for inference).
# ---------------------------------------------------------------------------

def encode_b64(word: str) -> str:
    """ref: WordVectorSerializer.encodeB64 — "B64:" + base64(utf8)."""
    return "B64:" + base64.b64encode(word.encode("utf-8")).decode("ascii")


def decode_b64(word: str) -> str:
    """ref: WordVectorSerializer.decodeB64 — passthrough when unprefixed."""
    if word.startswith("B64:"):
        return base64.b64decode(word[4:]).decode("utf-8")
    return word


def _fmt(v) -> str:
    # shortest float64 repr round-trips exactly; float32 values are exact
    # in float64, so text storage loses no bits
    return repr(float(v))


def _rows_txt(arr) -> str:
    a = np.asarray(arr, np.float32)
    return "\n".join(" ".join(_fmt(v) for v in row) for row in a)


def _config_json(sv: SequenceVectors) -> str:
    """VectorsConfiguration-shaped JSON (ref VectorsConfiguration.java:26-70
    field names) so the reference can parse our config and vice versa."""
    cfg = {
        "minWordFrequency": sv.min_word_frequency,
        "learningRate": sv.learning_rate,
        "minLearningRate": sv.min_learning_rate,
        "layersSize": sv.layer_size,
        "batchSize": sv.batch_size,
        "iterations": sv.iterations,
        "epochs": sv.epochs,
        "window": sv.window,
        "seed": sv.seed,
        "negative": float(sv.negative),
        "useHierarchicSoftmax": bool(sv.use_hs),
        "sampling": sv.sampling,
        "elementsLearningAlgorithm": sv.algo,
        "vocabSize": sv.vocab.num_words() if sv.vocab is not None else 0,
    }
    seq_algo = getattr(sv, "seq_algo", None)
    if seq_algo is not None:
        cfg["sequenceLearningAlgorithm"] = seq_algo
    return json.dumps(cfg, indent=1)


def _trainer_state_json(sv: SequenceVectors) -> str:
    state = {
        "class": type(sv).__name__,
        "rng_state": sv._rng.bit_generator.state,
        "devneg_ctr": int(getattr(sv, "_devneg_ctr", 0)),
        "epochs_trained": int(getattr(sv, "epochs_trained", 0)),
        "total_word_count": float(sv.vocab.total_word_count),
        "device_negatives": bool(sv.device_negatives),
    }
    if getattr(sv, "seq_algo", None) is not None:   # ParagraphVectors
        state["train_words"] = bool(getattr(sv, "train_words", False))
    if hasattr(sv, "x_max"):                        # Glove
        state["x_max"] = float(sv.x_max)
        state["alpha"] = float(sv.alpha)
        state["symmetric"] = bool(sv.symmetric)
        state["shuffle"] = bool(sv.shuffle)
        state["loss_history"] = [float(x) for x in sv.loss_history]
    return json.dumps(state)


def write_full_model(sv: SequenceVectors, path: str) -> None:
    """Save the COMPLETE model (ref writeWord2VecModel /
    writeParagraphVectors — WordVectorSerializer.java:493-677, :698-809)."""
    if sv.vocab is None or sv.syn0 is None:
        raise RuntimeError("model has no vocab/weights to save")
    words = sv.vocab.vocab_words()
    syn0 = np.asarray(sv.syn0, np.float32)
    labels = [w.word for w in words if w.is_label]
    lines = [f"{len(words)} {syn0.shape[1]} {len(labels)}"]
    for w in words:
        lines.append(encode_b64(w.word) + " "
                     + " ".join(_fmt(v) for v in syn0[w.index]))
    # atomic: zip assembled at a tmp path, renamed onto `path` on success
    # — a crash mid-save can't destroy an existing model archive
    from deeplearning4j_tpu.resilience.durable import atomic_replace_path
    with atomic_replace_path(path) as _tmp, \
            zipfile.ZipFile(_tmp, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("syn0.txt", "\n".join(lines))
        zf.writestr("syn1.txt",
                    _rows_txt(sv.syn1) if sv.syn1 is not None else "")
        zf.writestr("syn1Neg.txt",
                    _rows_txt(sv.syn1neg) if sv.syn1neg is not None else "")
        zf.writestr("codes.txt", "\n".join(
            encode_b64(w.word) + ((" " + " ".join(str(c) for c in w.codes))
                                  if w.codes else "")
            for w in words))
        zf.writestr("huffman.txt", "\n".join(
            encode_b64(w.word) + ((" " + " ".join(str(p) for p in w.points))
                                  if w.points else "")
            for w in words))
        zf.writestr("frequencies.txt", "\n".join(
            f"{encode_b64(w.word)} {_fmt(w.frequency)} 0" for w in words))
        zf.writestr("config.json", _config_json(sv))
        if labels:
            zf.writestr("labels.txt",
                        "\n".join(encode_b64(l) for l in labels))
        zf.writestr("trainer_state.json", _trainer_state_json(sv))
        if hasattr(sv, "x_max"):   # Glove: bias + AdaGrad accumulators
            import io as _io
            buf = _io.BytesIO()
            arrs = {}
            if sv.bias is not None:
                arrs["bias"] = np.asarray(sv.bias, np.float32)
            if getattr(sv, "_hist_w", None) is not None:
                arrs["hist_w"] = np.asarray(sv._hist_w, np.float32)
                arrs["hist_b"] = np.asarray(sv._hist_b, np.float32)
            np.savez(buf, **arrs)
            zf.writestr("glove_state.npz", buf.getvalue())


def _parse_cfg(cfg: Dict) -> Dict:
    """Map VectorsConfiguration JSON → our constructor kwargs."""
    kw = {}
    m = {"minWordFrequency": ("min_word_frequency", int),
         "learningRate": ("learning_rate", float),
         "minLearningRate": ("min_learning_rate", float),
         "layersSize": ("layer_size", int),
         "batchSize": ("batch_size", int),
         "iterations": ("iterations", int),
         "epochs": ("epochs", int),
         "window": ("window", int),
         "seed": ("seed", int),
         "negative": ("negative", lambda v: int(float(v))),
         "useHierarchicSoftmax": ("use_hierarchic_softmax", bool),
         "sampling": ("sampling", float)}
    for src, (dst, conv) in m.items():
        if src in cfg and cfg[src] is not None:
            kw[dst] = conv(cfg[src])
    algo = (cfg.get("elementsLearningAlgorithm") or "").lower()
    if "cbow" in algo:
        kw["elements_learning_algorithm"] = "cbow"
    elif "skipgram" in algo:
        kw["elements_learning_algorithm"] = "skipgram"
    return kw


def read_full_model(path: str, cls: Optional[Type[SequenceVectors]] = None
                    ) -> SequenceVectors:
    """Restore a full-model zip — ours or the reference's
    (ref readWord2Vec :864-950 / readParagraphVectors :811-852)."""
    with zipfile.ZipFile(path, "r") as zf:
        names = set(zf.namelist())

        def read_txt(name: str) -> str:
            return zf.read(name).decode("utf-8") if name in names else ""

        cfg = json.loads(read_txt("config.json") or "{}")
        state = json.loads(read_txt("trainer_state.json") or "{}")
        # -- class resolution ---------------------------------------------
        if cls is None or cls is SequenceVectors:
            hint = state.get("class")
            seq_algo = (cfg.get("sequenceLearningAlgorithm") or "")
            if cls is None:
                cls = SequenceVectors
            if hint or seq_algo or "labels.txt" in names:
                from deeplearning4j_tpu.nlp.glove import Glove
                from deeplearning4j_tpu.nlp.paragraph_vectors import (
                    ParagraphVectors,
                )
                from deeplearning4j_tpu.nlp.word2vec import Word2Vec
                by_name = {"Word2Vec": Word2Vec, "Glove": Glove,
                           "ParagraphVectors": ParagraphVectors,
                           "SequenceVectors": SequenceVectors}
                if hint in by_name:
                    cls = by_name[hint]
                elif seq_algo or "labels.txt" in names:
                    cls = ParagraphVectors
        kw = _parse_cfg(cfg)
        from deeplearning4j_tpu.nlp.glove import Glove
        from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
        if issubclass(cls, ParagraphVectors):
            # java stores the learning-algo CLASS name (…impl.sequence.DM)
            seq_algo = (cfg.get("sequenceLearningAlgorithm") or "dbow")
            kw["sequence_learning_algorithm"] = \
                "dm" if seq_algo.lower().split(".")[-1] == "dm" else "dbow"
            kw["train_words"] = bool(state.get("train_words", False))
            # keep elements_learning_algorithm if present: the constructor's
            # setdefault only fills it when the save predates the field
        if issubclass(cls, Glove):
            for k in ("x_max", "alpha", "symmetric", "shuffle"):
                if k in state:
                    kw[k] = state[k]
            for k in ("negative", "use_hierarchic_softmax", "sampling",
                      "iterations"):
                kw.pop(k, None)
        model = cls(**kw)

        # -- vocab + syn0 ---------------------------------------------------
        syn0_lines = read_txt("syn0.txt").splitlines()
        header = syn0_lines[0].split() if syn0_lines else ["0", "0"]
        V, D = int(header[0]), int(header[1])
        cache = VocabCache()
        syn0 = np.zeros((V, D), np.float32)
        order: List[VocabWord] = []
        for i, line in enumerate(syn0_lines[1:V + 1]):
            parts = line.rstrip("\n").split(" ")
            w = VocabWord(decode_b64(parts[0]))
            cache.add_token(w)
            order.append(w)
            syn0[i] = np.asarray([float(x) for x in parts[1:D + 1]],
                                 np.float32)
        for i, w in enumerate(order):
            w.index = i
        cache._index = order
        for line in read_txt("frequencies.txt").splitlines():
            parts = line.split(" ")
            vw = cache.word_for(decode_b64(parts[0]))
            if vw is not None and len(parts) > 1:
                vw.frequency = float(parts[1])
        for name, attr, conv in (("codes.txt", "codes", int),
                                 ("huffman.txt", "points", int)):
            for line in read_txt(name).splitlines():
                parts = line.split(" ")
                vw = cache.word_for(decode_b64(parts[0]))
                if vw is not None:
                    setattr(vw, attr, [conv(x) for x in parts[1:] if x])
        for line in read_txt("labels.txt").splitlines():
            vw = cache.word_for(decode_b64(line.strip()))
            if vw is not None:
                vw.is_label = True
        cache.total_word_count = float(
            state.get("total_word_count",
                      sum(w.frequency for w in order)))
        model.vocab = cache
        model.syn0 = jnp.asarray(syn0)

        # -- output tables --------------------------------------------------
        syn1_txt = read_txt("syn1.txt").strip()
        if syn1_txt:
            model.syn1 = jnp.asarray(
                [[float(x) for x in ln.split()]
                 for ln in syn1_txt.splitlines()], jnp.float32)
        elif model.use_hs:
            model.syn1 = jnp.zeros((max(V - 1, 1), D), jnp.float32)
        syn1neg_txt = read_txt("syn1Neg.txt").strip()
        if syn1neg_txt:
            model.syn1neg = jnp.asarray(
                [[float(x) for x in ln.split()]
                 for ln in syn1neg_txt.splitlines()], jnp.float32)
        elif model.negative > 0:
            model.syn1neg = jnp.zeros((V, D), jnp.float32)
        model._init_tables()

        # -- trainer state (exact resume) ----------------------------------
        if "rng_state" in state:
            rng = np.random.default_rng()
            rng.bit_generator.state = state["rng_state"]
            model._rng = rng
        if model.negative > 0 and "devneg_ctr" in state:
            model._devneg_ctr = int(state["devneg_ctr"])
        model.epochs_trained = int(state.get("epochs_trained", 0))
        if "device_negatives" in state:
            model.device_negatives = bool(state["device_negatives"])
        if "loss_history" in state:
            model.loss_history = list(state["loss_history"])
        if "glove_state.npz" in names:
            import io as _io
            npz = np.load(_io.BytesIO(zf.read("glove_state.npz")))
            if "bias" in npz:
                model.bias = jnp.asarray(npz["bias"])
            if "hist_w" in npz:
                model._hist_w = jnp.asarray(npz["hist_w"])
                model._hist_b = jnp.asarray(npz["hist_b"])
    return model


# reference-named conveniences (WordVectorSerializer method names)
def write_word2vec_model(vectors, path: str) -> None:
    """ref: WordVectorSerializer.writeWord2VecModel :493."""
    write_full_model(vectors, path)


def read_word2vec_model_full(path: str):
    """ref: WordVectorSerializer.readWord2Vec :864 (full model)."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec
    return read_full_model(path, cls=Word2Vec)


def write_paragraph_vectors(vectors, path: str) -> None:
    """ref: WordVectorSerializer.writeParagraphVectors :675."""
    write_full_model(vectors, path)


def read_paragraph_vectors(path: str):
    """ref: WordVectorSerializer.readParagraphVectors :811."""
    from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
    return read_full_model(path, cls=ParagraphVectors)


def write_sequence_vectors(vectors, path: str) -> None:
    """ref: WordVectorSerializer.writeSequenceVectors."""
    write_full_model(vectors, path)


def read_sequence_vectors(path: str):
    """ref: WordVectorSerializer.readSequenceVectors."""
    return read_full_model(path, cls=None)
