"""Word-vector model serialization.

Equivalent of deeplearning4j-nlp models/embeddings/loader/
WordVectorSerializer.java:2824 — text format ("word v1 v2 ...", one per
line, optional header) and the Google word2vec binary format
(header "V D\\n", then per word: name, space, D little-endian float32).
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord


def write_word_vectors(vectors: SequenceVectors, path: str,
                       write_header: bool = True) -> None:
    """ref: WordVectorSerializer.writeWordVectors (text)."""
    syn0 = np.asarray(vectors.syn0)
    words = vectors.vocab.vocab_words()
    with open(path, "w", encoding="utf-8") as f:
        if write_header:
            f.write(f"{len(words)} {syn0.shape[1]}\n")
        for w in words:
            vec = " ".join(f"{v:.6f}" for v in syn0[w.index])
            f.write(f"{w.word} {vec}\n")


def read_word_vectors(path: str) -> SequenceVectors:
    """ref: WordVectorSerializer.readWord2VecModel / loadTxtVectors."""
    words, rows = [], []
    with open(path, "r", encoding="utf-8") as f:
        first = f.readline().rstrip("\n")
        parts = first.split(" ")
        header = len(parts) == 2 and all(p.isdigit() for p in parts)
        if not header and parts:
            words.append(parts[0])
            rows.append([float(x) for x in parts[1:]])
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if len(parts) < 2:
                continue
            words.append(parts[0])
            rows.append([float(x) for x in parts[1:]])
    return _from_arrays(words, np.asarray(rows, np.float32))


def write_word2vec_binary(vectors: SequenceVectors, path: str) -> None:
    """Google word2vec .bin format (ref: WordVectorSerializer.writeWord2Vec
    binary branch)."""
    syn0 = np.asarray(vectors.syn0, np.float32)
    words = vectors.vocab.vocab_words()
    with open(path, "wb") as f:
        f.write(f"{len(words)} {syn0.shape[1]}\n".encode())
        for w in words:
            f.write(w.word.encode("utf-8") + b" ")
            f.write(syn0[w.index].astype("<f4").tobytes())
            f.write(b"\n")


def read_word2vec_binary(path: str) -> SequenceVectors:
    """ref: WordVectorSerializer.readBinaryModel."""
    with open(path, "rb") as f:
        header = f.readline().decode().split()
        V, D = int(header[0]), int(header[1])
        words, rows = [], np.empty((V, D), np.float32)
        for i in range(V):
            name = bytearray()
            while True:
                c = f.read(1)
                if c in (b" ", b""):
                    break
                if c != b"\n":
                    name.extend(c)
            words.append(name.decode("utf-8"))
            rows[i] = np.frombuffer(f.read(4 * D), "<f4")
            nl = f.read(1)
            if nl not in (b"\n", b""):
                f.seek(-1, 1)
    return _from_arrays(words, rows)


def _from_arrays(words, syn0: np.ndarray) -> SequenceVectors:
    sv = SequenceVectors(layer_size=syn0.shape[1])
    cache = VocabCache()
    for w in words:
        cache.add_token(VocabWord(w))
    cache.build_index(order_by_frequency=False)
    sv.vocab = cache
    sv.syn0 = jnp.asarray(syn0)
    return sv
