"""SequenceVectors: generic embedding trainer over sequences of elements.

Equivalent of deeplearning4j-nlp SequenceVectors.java:1244 (buildVocab :108,
fit :192, pluggable learning algos :56) + the SkipGram/CBOW elements learning
algorithms and InMemoryLookupTable syn0/syn1/syn1Neg storage.

TPU-first design: the reference trains via hogwild threads issuing native
AggregateSkipGram ops one pair at a time (SkipGram.java); here the host packs
(input, label) pairs + presampled negatives into fixed-shape int32 batches and
ONE jitted step does the whole batch on device — gathers, a [B,K+1,D]·[B,D]
batched dot (MXU), and scatter-adds back into the tables. In-batch index
collisions sum their updates (vs. sequential overwrite in hogwild) — same
stochastic objective.
"""

from __future__ import annotations

import itertools
import logging
from functools import partial
from typing import Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import (
    VocabCache, VocabConstructor, VocabWord, codes_points_arrays,
    make_unigram_table,
)

log = logging.getLogger(__name__)


# --------------------------------------------------------------------------
# Device kernels
# --------------------------------------------------------------------------

def _ns_update(syn0, syn1neg, inputs, targets, labels, valid, lr):
    """Negative-sampling update for a batch of pairs.

    inputs [B] int32 — rows of syn0 (context words / doc vectors)
    targets [B,K1] int32 — col 0 = positive word, cols 1.. = negatives
    labels [B,K1] float32 — 1 for positive, 0 for negatives
    valid [B] float32 — 0 for trailing pad rows (their update is zeroed)
    lr [B] float32 — per-pair learning rate (pairs from different points of
    the corpus share one device batch but keep their own decayed alpha).
    """
    l1 = syn0[inputs]                      # [B,D]
    w = syn1neg[targets]                   # [B,K1,D]
    f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", l1, w))
    g = (labels - f) * (lr * valid)[:, None]  # [B,K1]
    grad_l1 = jnp.einsum("bk,bkd->bd", g, w)
    grad_w = g[..., None] * l1[:, None, :]  # [B,K1,D]
    syn0 = syn0.at[inputs].add(grad_l1)
    syn1neg = syn1neg.at[targets.reshape(-1)].add(
        grad_w.reshape(-1, grad_w.shape[-1]))
    return syn0, syn1neg


_ns_step = jax.jit(_ns_update)


def _hs_update(syn0, syn1, inputs, points, codes, mask, lr):
    """Hierarchical-softmax update for a batch of pairs.

    points [B,L] int32 — inner-node rows along the label word's huffman path
    codes [B,L] float32 — path bits; mask [B,L] zeroes padded path slots.
    lr [B] float32 — per-pair learning rate.
    """
    l1 = syn0[inputs]                      # [B,D]
    w = syn1[points]                       # [B,L,D]
    f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", l1, w))
    g = (1.0 - codes - f) * lr[:, None] * mask  # [B,L]
    grad_l1 = jnp.einsum("bl,bld->bd", g, w)
    grad_w = g[..., None] * l1[:, None, :]
    syn0 = syn0.at[inputs].add(grad_l1)
    syn1 = syn1.at[points.reshape(-1)].add(grad_w.reshape(-1, w.shape[-1]))
    return syn0, syn1


_hs_step = jax.jit(_hs_update)


@partial(jax.jit, static_argnames=("negative", "use_hs"))
def _sg_scan(syn0, syn1, syn1neg, inputs, targets, labels, points, codes,
             pmask, valid, lr, *, negative: bool, use_hs: bool):
    """Many skip-gram batches in ONE dispatch: lax.scan over the leading
    batch axis (inputs [Nb,B], targets [Nb,B,K1], ...). Math and batch
    order identical to Nb sequential _ns_step/_hs_step dispatches — the
    device-side loop exists purely to cut host->device dispatch count
    (the measured Word2Vec bottleneck through the tunneled platform,
    PERF.md). Unused table/xs slots are passed as dummies and returned
    untouched when the corresponding variant is off."""
    def body(carry, xs):
        s0, s1, s1n = carry
        i, t, l, p, c, m, v, a = xs
        if negative:
            s0, s1n = _ns_update(s0, s1n, i, t, l, v, a)
        if use_hs:
            s0, s1 = _hs_update(s0, s1, i, p, c, m, a)
        return (s0, s1, s1n), None
    (syn0, syn1, syn1neg), _ = jax.lax.scan(
        body, (syn0, syn1, syn1neg),
        (inputs, targets, labels, points, codes, pmask, valid, lr))
    return syn0, syn1, syn1neg


@partial(jax.jit, static_argnames=("negative", "use_hs"))
def _sg_scan_devneg(syn0, syn1, syn1neg, table, key, inputs, outs, points,
                    codes, pmask, valid, lr, *, negative: int, use_hs: bool):
    """_sg_scan with the unigram-table negatives drawn ON DEVICE: the
    host ships only the pair streams (inputs/outs [Nb,B]) instead of the
    [Nb,B,K+1] targets + labels arrays — ~5x less host->device transfer
    per dispatch, which is the measured Word2Vec ceiling through the
    tunneled platform (PERF.md). Same stochastic objective as the host
    sampler (uniform draws into the same freq^0.75 table, no positive
    dedup — matching _sample_negatives); different rng stream, so the
    bit-exact scan==per-batch equivalence holds only for
    device_negatives=False."""
    B = inputs.shape[1]
    labels = jnp.zeros((B, negative + 1), jnp.float32).at[:, 0].set(1.0)

    def body(carry, xs):
        s0, s1, s1n, k = carry
        i, o, p, c, m, v, a = xs
        k, sub = jax.random.split(k)
        negs = table[jax.random.randint(sub, (B, negative), 0,
                                        table.shape[0])]
        t = jnp.concatenate([o[:, None], negs], axis=1)
        s0, s1n = _ns_update(s0, s1n, i, t, labels, v, a)
        if use_hs:
            s0, s1 = _hs_update(s0, s1, i, p, c, m, a)
        return (s0, s1, s1n, k), None

    (syn0, syn1, syn1neg, _), _ = jax.lax.scan(
        body, (syn0, syn1, syn1neg, key),
        (inputs, outs, points, codes, pmask, valid, lr))
    return syn0, syn1, syn1neg


@partial(jax.jit, static_argnames=("negative", "use_hs"))
def _cbow_scan_devneg(syn0, syn1, syn1neg, table, key, ctx, cmask, centers,
                      points, codes, pmask, valid, lr, *, negative: int,
                      use_hs: bool):
    """CBOW twin of _sg_scan_devneg (centers are the positive targets)."""
    B = centers.shape[1]
    labels = jnp.zeros((B, negative + 1), jnp.float32).at[:, 0].set(1.0)

    def body(carry, xs):
        s0, s1, s1n, k = carry
        cx, cm, o, p, c, m, v, a = xs
        k, sub = jax.random.split(k)
        negs = table[jax.random.randint(sub, (B, negative), 0,
                                        table.shape[0])]
        t = jnp.concatenate([o[:, None], negs], axis=1)
        s0, s1n = _cbow_ns_update(s0, s1n, cx, cm, t, labels, v, a)
        if use_hs:
            s0, s1 = _cbow_hs_update(s0, s1, cx, cm, p, c, m, a)
        return (s0, s1, s1n, k), None

    (syn0, syn1, syn1neg, _), _ = jax.lax.scan(
        body, (syn0, syn1, syn1neg, key),
        (ctx, cmask, centers, points, codes, pmask, valid, lr))
    return syn0, syn1, syn1neg


def _cbow_ns_update(syn0, syn1neg, ctx, ctx_mask, targets, labels, valid,
                    lr):
    """CBOW with negative sampling: input = mean of context rows
    (ref: CBOW.java — sums context + optional label vectors)."""
    denom = jnp.maximum(ctx_mask.sum(-1, keepdims=True), 1.0)  # [B,1]
    vecs = syn0[ctx] * ctx_mask[..., None]  # [B,C,D]
    l1 = vecs.sum(1) / denom                # [B,D]
    w = syn1neg[targets]                    # [B,K1,D]
    f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", l1, w))
    g = (labels - f) * (lr * valid)[:, None]
    grad_l1 = jnp.einsum("bk,bkd->bd", g, w) / denom   # distribute mean grad
    grad_w = g[..., None] * l1[:, None, :]
    grad_ctx = grad_l1[:, None, :] * ctx_mask[..., None]  # [B,C,D]
    syn0 = syn0.at[ctx.reshape(-1)].add(
        grad_ctx.reshape(-1, grad_ctx.shape[-1]))
    syn1neg = syn1neg.at[targets.reshape(-1)].add(
        grad_w.reshape(-1, grad_w.shape[-1]))
    return syn0, syn1neg


_cbow_ns_step = jax.jit(_cbow_ns_update)


def _cbow_hs_update(syn0, syn1, ctx, ctx_mask, points, codes, mask, lr):
    denom = jnp.maximum(ctx_mask.sum(-1, keepdims=True), 1.0)
    vecs = syn0[ctx] * ctx_mask[..., None]
    l1 = vecs.sum(1) / denom
    w = syn1[points]
    f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", l1, w))
    g = (1.0 - codes - f) * lr[:, None] * mask
    grad_l1 = jnp.einsum("bl,bld->bd", g, w) / denom
    grad_w = g[..., None] * l1[:, None, :]
    grad_ctx = grad_l1[:, None, :] * ctx_mask[..., None]
    syn0 = syn0.at[ctx.reshape(-1)].add(
        grad_ctx.reshape(-1, grad_ctx.shape[-1]))
    syn1 = syn1.at[points.reshape(-1)].add(grad_w.reshape(-1, w.shape[-1]))
    return syn0, syn1


_cbow_hs_step = jax.jit(_cbow_hs_update)


@partial(jax.jit, static_argnames=("negative", "use_hs"))
def _cbow_scan(syn0, syn1, syn1neg, ctx, cmask, targets, labels, points,
               codes, pmask, valid, lr, *, negative: bool, use_hs: bool):
    """Many CBOW batches in ONE dispatch (see _sg_scan)."""
    def body(carry, xs):
        s0, s1, s1n = carry
        cx, cm, t, l, p, c, m, v, a = xs
        if negative:
            s0, s1n = _cbow_ns_update(s0, s1n, cx, cm, t, l, v, a)
        if use_hs:
            s0, s1 = _cbow_hs_update(s0, s1, cx, cm, p, c, m, a)
        return (s0, s1, s1n), None
    (syn0, syn1, syn1neg), _ = jax.lax.scan(
        body, (syn0, syn1, syn1neg),
        (ctx, cmask, targets, labels, points, codes, pmask, valid, lr))
    return syn0, syn1, syn1neg


# --------------------------------------------------------------------------
# Host-side batch accumulation
# --------------------------------------------------------------------------

class _BatchBuffer:
    """Accumulates (pair, alpha) examples across many sequences into
    fixed-shape device batches, so the device sees one large jit dispatch
    per `batch_size` examples instead of one tiny dispatch per sentence
    (the reference amortizes per-pair cost with a hogwild worker pool,
    SequenceVectors.java:192; on TPU batching is the equivalent lever)."""

    def __init__(self):
        self._sg = []        # list of (ins [n], outs [n], lr [n])
        self._n_sg = 0
        self._cb = []        # list of (ctxs [n,C], cmask [n,C], centers [n], lr [n])
        self._n_cb = 0

    # -- skip-gram ---------------------------------------------------------
    def add_sg(self, ins: np.ndarray, outs: np.ndarray,
               alpha: float) -> None:
        n = len(ins)
        if n == 0:
            return
        self._sg.append((ins.astype(np.int32), outs.astype(np.int32),
                         np.full(n, alpha, np.float32)))
        self._n_sg += n

    def drain_sg(self, batch_size: int, final: bool = False):
        """Yield (ins, outs, lr) chunks of exactly `batch_size` rows; with
        final=True also yield the trailing partial chunk. Rows that don't
        fill a batch stay buffered for the next call."""
        if self._n_sg == 0 or (self._n_sg < batch_size and not final):
            return
        ins = np.concatenate([t[0] for t in self._sg])
        outs = np.concatenate([t[1] for t in self._sg])
        lr = np.concatenate([t[2] for t in self._sg])
        self._sg, self._n_sg = [], 0
        stop = len(ins) if final else len(ins) // batch_size * batch_size
        for s in range(0, stop, batch_size):
            yield ins[s:s + batch_size], outs[s:s + batch_size], \
                lr[s:s + batch_size]
        if stop < len(ins):  # keep the remainder buffered
            self._sg.append((ins[stop:], outs[stop:], lr[stop:]))
            self._n_sg = len(ins) - stop

    # -- CBOW --------------------------------------------------------------
    def add_cbow(self, ctxs: np.ndarray, cmask: np.ndarray,
                 centers: np.ndarray, alpha: float) -> None:
        n = len(centers)
        if n == 0:
            return
        self._cb.append((ctxs.astype(np.int32), cmask.astype(np.float32),
                         centers.astype(np.int32),
                         np.full(n, alpha, np.float32)))
        self._n_cb += n

    def drain_cbow(self, batch_size: int, final: bool = False):
        if self._n_cb == 0 or (self._n_cb < batch_size and not final):
            return
        # context width can differ when some sequences carry doc labels
        # (DM) and others don't — pad every chunk to the buffered max so
        # one concatenated array feeds fixed-shape kernels
        C = max(t[0].shape[1] for t in self._cb)

        def widen(a, fill=0):
            if a.shape[1] == C:
                return a
            return np.pad(a, ((0, 0), (0, C - a.shape[1])),
                          constant_values=fill)

        ctxs = np.concatenate([widen(t[0]) for t in self._cb])
        cmask = np.concatenate([widen(t[1]) for t in self._cb])
        centers = np.concatenate([t[2] for t in self._cb])
        lr = np.concatenate([t[3] for t in self._cb])
        self._cb, self._n_cb = [], 0
        stop = len(centers) if final \
            else len(centers) // batch_size * batch_size
        for s in range(0, stop, batch_size):
            yield ctxs[s:s + batch_size], cmask[s:s + batch_size], \
                centers[s:s + batch_size], lr[s:s + batch_size]
        if stop < len(centers):
            self._cb.append((ctxs[stop:], cmask[stop:], centers[stop:],
                             lr[stop:]))
            self._n_cb = len(centers) - stop


# --------------------------------------------------------------------------
# Engine
# --------------------------------------------------------------------------

class SequenceVectors:
    """Trains element embeddings over sequences (ref: SequenceVectors.java
    Builder defaults :375-386 — lr .025, minLr 1e-4, layerSize 100,
    window 5, negative 0 → hierarchical softmax on by default)."""

    def __init__(self, layer_size: int = 100, window: int = 5,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 negative: int = 0, sampling: float = 0.0,
                 min_word_frequency: int = 1, epochs: int = 1,
                 iterations: int = 1, batch_size: int = 4096,
                 elements_learning_algorithm: str = "skipgram",
                 use_hierarchic_softmax: Optional[bool] = None,
                 seed: int = 42, stop_words: Sequence[str] = (),
                 vocab_limit: int = 0, device_negatives: bool = True):
        self.layer_size = layer_size
        self.window = window
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = int(negative)
        self.sampling = sampling
        self.min_word_frequency = min_word_frequency
        self.epochs = epochs
        self.iterations = iterations
        self.batch_size = batch_size
        self._eff_batch = batch_size  # collision-bounded in _reset_weights
        algo = elements_learning_algorithm.lower()
        if algo not in ("skipgram", "cbow"):
            raise ValueError(f"unknown elements learning algorithm {algo!r}")
        self.algo = algo
        # ref semantics: negative>0 switches to NS unless HS explicitly kept
        self.use_hs = (self.negative == 0) if use_hierarchic_softmax is None \
            else use_hierarchic_softmax
        self.seed = seed
        self.stop_words = stop_words
        self.vocab_limit = vocab_limit
        #: sample NS negatives on device inside the scan dispatch (~5x
        #: less host->device traffic); False restores the host rng stream
        #: (bit-exact scan == per-batch equivalence)
        self.device_negatives = device_negatives

        self.vocab: Optional[VocabCache] = None
        self.syn0 = None            # [V,D] jnp
        self.syn1 = None            # HS inner nodes
        self.syn1neg = None         # NS output table
        self._codes = self._points = self._path_mask = None
        self._table: Optional[np.ndarray] = None
        self._rng = np.random.default_rng(seed)
        #: epochs completed so far (advanced by fit; persisted by save so a
        #: reloaded model resumes its learning-rate schedule mid-run)
        self.epochs_trained = 0

    # -- vocab + weights ---------------------------------------------------
    def build_vocab(self, sequences: Iterable[Sequence[str]],
                    extra_labels: Sequence[str] = ()) -> None:
        """ref: SequenceVectors.buildVocab :108 via VocabConstructor."""
        # type-check the FIRST element only, preserving streaming for
        # generator corpora (VocabConstructor.build is single-pass)
        if isinstance(sequences, (list, tuple)):
            first = sequences[0] if sequences else None
        else:
            it = iter(sequences)
            first = next(it, None)
            sequences = itertools.chain([first], it) if first is not None \
                else []
        if isinstance(first, str):
            raise TypeError(
                "build_vocab expects sequences of tokens (List[List[str]]);"
                " got strings — tokenize first, or use Word2Vec with a "
                "sentence_iterator/tokenizer_factory")
        ctor = VocabConstructor(self.min_word_frequency,
                                stop_words=self.stop_words,
                                build_huffman_tree=True,
                                vocab_limit=self.vocab_limit)
        self.vocab = ctor.build(sequences)
        for lb in extra_labels:
            if not self.vocab.contains_word(lb):
                vw = VocabWord(lb, frequency=1.0, is_label=True)
                self.vocab.add_token(vw)
        if extra_labels:
            self.vocab.build_index(order_by_frequency=False)
            from deeplearning4j_tpu.nlp.vocab import build_huffman
            build_huffman(self.vocab)
        self._reset_weights()

    def _reset_weights(self) -> None:
        """ref: InMemoryLookupTable.resetWeights — syn0 ~ U(-.5,.5)/D,
        syn1/syn1Neg zero."""
        V, D = self.vocab.num_words(), self.layer_size
        rnd = np.random.default_rng(self.seed)
        self.syn0 = jnp.asarray(
            (rnd.random((V, D), np.float32) - 0.5) / D)
        if self.use_hs:
            self.syn1 = jnp.zeros((max(V - 1, 1), D), jnp.float32)
        if self.negative > 0:
            self.syn1neg = jnp.zeros((V, D), jnp.float32)
        self._init_tables()

    def _init_tables(self) -> None:
        """(Re)build everything derived from the vocab but not trained:
        huffman path arrays, the NS unigram table, the device-negatives rng
        stream, and the collision-bounded dispatch batch. Called by
        _reset_weights on a fresh model and by the serializer after
        restoring trained syn0/syn1/syn1neg (nlp/serializer.py)."""
        V = self.vocab.num_words()
        if self.use_hs:
            c, p, m = codes_points_arrays(self.vocab)
            self._codes, self._points, self._path_mask = c, p, m
        if self.negative > 0:
            self._table = make_unigram_table(self.vocab)
            self._table_dev = None          # uploaded lazily per fit
            self._devneg_key = jax.random.PRNGKey(self.seed)
            self._devneg_ctr = 0
        # In-batch index collisions SUM their updates (hogwild would
        # interleave them); on a tiny vocab a big batch revisits each row
        # so often that summed stale gradients overshoot and collapse the
        # embedding. Bound expected collisions per table row: each batch
        # row touches `traffic` table entries (CBOW context width /
        # negatives+positive / huffman path), spread over the non-label
        # vocab. (DBOW label rows DO self-collide — every pair of a doc
        # shares its label input — but those collisions are bounded by the
        # doc's length, not the batch size, and match the reference's
        # per-sequence AggregateSkipGram batching, so they're excluded
        # here.) Real vocabs (>=10k) keep the full configured batch.
        v_words = sum(1 for vw in self.vocab.vocab_words()
                      if not vw.is_label) or V
        in_traffic = 2 * self.window if self.algo == "cbow" else 1
        out_traffic = 1
        if self.negative > 0:
            out_traffic = max(out_traffic, self.negative + 1)
        if self.use_hs:  # worst-case huffman path length actually built
            out_traffic = max(out_traffic, int(self._codes.shape[1]))
        traffic = max(in_traffic, out_traffic)
        self._eff_batch = min(self.batch_size,
                              max(64, (8 * v_words) // traffic))
        if self._eff_batch < self.batch_size:
            log.info(
                "dispatch batch clamped %d -> %d (vocab %d words, "
                "traffic %d/row) to bound in-batch update collisions",
                self.batch_size, self._eff_batch, v_words, traffic)

    # -- training ----------------------------------------------------------
    def fit(self, sequences: Iterable[Sequence[str]],
            labels_per_sequence: Optional[List[Sequence[str]]] = None,
            train_words: bool = True, train_labels: bool = False,
            start_epoch: Optional[int] = None,
            stop_epoch: Optional[int] = None,
            resume: bool = False) -> None:
        """ref: SequenceVectors.fit :192. `labels_per_sequence` attaches doc
        labels (ParagraphVectors DBOW/DM use them as extra input rows).

        The reference dispatches one native op per (pair, thread) from a
        worker pool (SequenceVectors.java:192 fit); here pairs ACCUMULATE
        across sequences into fixed-shape device batches and one jit step
        consumes each full batch — the device sees a few large dispatches
        per epoch instead of one tiny dispatch per sentence.

        start_epoch/stop_epoch run a slice of the epoch schedule (defaults
        0..self.epochs): the learning-rate decay and the rng streams are
        positioned exactly as the uninterrupted run would have them, so
        fit(stop_epoch=k); save; load; fit(start_epoch=k) equals one
        uninterrupted fit bit for bit (save persists the rng state —
        nlp/serializer.py trainer_state). resume=True is shorthand for
        start_epoch=self.epochs_trained (continue a checkpointed fit);
        a plain fit() always runs the full schedule from epoch 0."""
        if self.vocab is None:
            raise RuntimeError("call build_vocab first")
        if start_epoch is None:
            e0 = self.epochs_trained if resume else 0
        else:
            e0 = int(start_epoch)
        e1 = self.epochs if stop_epoch is None else int(stop_epoch)
        seqs = sequences if isinstance(sequences, list) else list(sequences)
        if seqs and isinstance(seqs[0], str):
            # a raw string would be iterated character-by-character and
            # silently train a character vocab — Word2Vec tokenizes
            # sentence strings; SequenceVectors wants token sequences
            raise TypeError(
                "SequenceVectors.fit expects sequences of tokens "
                "(List[List[str]]); got strings — tokenize first, or use "
                "Word2Vec with a sentence_iterator/tokenizer_factory")
        if (train_words and not train_labels
                and labels_per_sequence is None
                and self._fit_native(seqs, e0, e1)):
            self.epochs_trained = e1
            return
        total_words = sum(len(s) for s in seqs) * max(1, self.epochs)
        words_seen = sum(len(s) for s in seqs) * e0
        sg = self.algo == "skipgram"
        buf = _BatchBuffer()
        for epoch in range(e0, e1):
            for si, seq in enumerate(seqs):
                idxs = self._to_indices(seq)
                words_seen += len(seq)
                if len(idxs) == 0:
                    continue
                alpha = self._alpha(words_seen, total_words)
                lbl = None
                if labels_per_sequence is not None:
                    lbl = [self.vocab.index_of(l)
                           for l in labels_per_sequence[si]
                           if self.vocab.index_of(l) >= 0]
                for _ in range(self.iterations):
                    if sg:
                        if train_words:
                            ins, outs = self._pairs(idxs)
                            buf.add_sg(ins, outs, alpha)
                        if train_labels and lbl:
                            li, lo = self._label_pairs(idxs, lbl)
                            buf.add_sg(li, lo, alpha)
                    else:
                        ctxs, cmask, centers = self._cbow_contexts(idxs, lbl)
                        buf.add_cbow(ctxs, cmask, centers, alpha)
                # dispatch every full batch currently buffered.
                # (the per-batch H2D inside _dispatch_* is the native
                # word2vec path's jit boundary: pairs are BUILT on host
                # each batch — there is no device-resident iterator for
                # a prefetch stage to overlap, PR 2's documented
                # host-numpy exemption)
                if sg:
                    for bi, bo, ba in buf.drain_sg(self._eff_batch):
                        # tpulint: disable=device-transfer-in-hot-loop
                        self._dispatch_sg(bi, bo, ba)
                else:
                    for bx, bm, bc, ba in buf.drain_cbow(self._eff_batch):
                        # tpulint: disable=device-transfer-in-hot-loop
                        self._dispatch_cbow(bx, bm, bc, ba)
            # trailing partial batch — flushed per EPOCH (not per fit) so
            # the batch composition is identical whether the epoch range
            # runs in one call or is split for mid-fit checkpointing
            if sg:
                for bi, bo, ba in buf.drain_sg(self._eff_batch, final=True):
                    # tpulint: disable=device-transfer-in-hot-loop
                    self._dispatch_sg(bi, bo, ba)
            else:
                for bx, bm, bc, ba in buf.drain_cbow(self._eff_batch,
                                                     final=True):
                    # tpulint: disable=device-transfer-in-hot-loop
                    self._dispatch_cbow(bx, bm, bc, ba)
        self.epochs_trained = e1

    def _keep_probs(self) -> Optional[np.ndarray]:
        """Per-vocab-index keep probability for word2vec subsampling
        (None = no subsampling) — the vectorized form of _to_indices'
        per-token keep computation."""
        if self.sampling <= 0:
            return None
        t = self.sampling
        total = max(1.0, self.vocab.total_word_count)
        keep = np.ones(self.vocab.num_words(), np.float32)
        for i in range(self.vocab.num_words()):
            vw = self.vocab.element_at_index(i)
            f = (vw.frequency if vw is not None else 0.0) / total
            if f > 0:
                keep[i] = min(1.0, (np.sqrt(f / t) + 1) * (t / f))
        return keep

    def _fit_native(self, seqs, e0: int = 0, e1: Optional[int] = None) -> bool:
        """Epoch-at-a-time pair generation in the C++ runtime
        (native/src/word2vec.cpp; ref: the SequenceVectors.java:192
        multithreaded fit). Vocab lookup happens ONCE for the whole fit;
        each epoch×iteration generates all pairs across threads and
        dispatches the existing batched device steps. Returns False (use
        the numpy path) when the native lib is unavailable."""
        from deeplearning4j_tpu.native import word2vec as nw
        if not nw.native_available():
            return False
        # corpus as indices, once (OOV = -1, skipped natively but still
        # counted in the learning-rate schedule like the numpy path).
        # Vectorized: one numpy searchsorted over the flattened corpus
        # instead of 400k Python index_of calls (measured ~0.44s/400k
        # words — a material slice of the fit at device speeds)
        lens = np.asarray([len(s) for s in seqs], np.int64)
        offsets = np.zeros(len(seqs) + 1, np.int64)
        np.cumsum(lens, out=offsets[1:])
        toks = np.asarray([t for s in seqs for t in s], dtype=np.str_)
        index_of = self.vocab.index_of
        names = [vw.word for vw in self.vocab.vocab_words()]
        # host python list of vocab words, not a device value
        # tpulint: disable=host-sync-in-hot-loop
        name_arr = np.asarray(names, dtype=np.str_)
        vidx = np.asarray([index_of(w) for w in names], np.int32)
        order = np.argsort(name_arr)
        sorted_names, sorted_idx = name_arr[order], vidx[order]
        if len(toks) and len(sorted_names):
            pos = np.searchsorted(sorted_names, toks)
            pc = pos.clip(0, len(sorted_names) - 1)
            corpus = np.where(sorted_names[pc] == toks, sorted_idx[pc],
                              -1).astype(np.int32)
        else:           # empty vocab: every token is OOV (silent no-op fit)
            corpus = np.full(len(toks), -1, np.int32)
        keep = self._keep_probs()
        # per-sequence alpha: the numpy path's words_seen schedule.
        # `lens`/`self._rng` here are HOST numpy state (native word2vec
        # path, no device values) — the int() casts below cannot sync.
        # tpulint: disable=host-sync-in-hot-loop
        total_words = int(lens.sum()) * max(1, self.epochs)
        sg = self.algo == "skipgram"
        # bound host memory: generate per SHARD of sequences (~1M corpus
        # words => tens of MB of pairs), not per whole epoch — big
        # corpora keep the numpy path's bounded-memory property
        shard_words = 1 << 20
        shards = [0]
        acc = 0
        for si in range(len(seqs)):
            acc += int(lens[si])  # tpulint: disable=host-sync-in-hot-loop
            if acc >= shard_words:
                shards.append(si + 1)
                acc = 0
        if shards[-1] != len(seqs):
            shards.append(len(seqs))
        if e1 is None:
            e1 = self.epochs
        for epoch in range(e0, e1):
            # host numpy schedule arithmetic, not a device sync
            # tpulint: disable=host-sync-in-hot-loop
            seen = int(lens.sum()) * epoch + np.cumsum(lens)
            seq_alpha = np.maximum(
                self.min_learning_rate,
                self.learning_rate
                * (1.0 - np.minimum(1.0, seen / max(1, total_words)))
            ).astype(np.float32)
            for _ in range(self.iterations):
                # host np.random draw, not a device sync
                # tpulint: disable=host-sync-in-hot-loop
                seed = int(self._rng.integers(2 ** 63))
                for s0, s1 in zip(shards[:-1], shards[1:]):
                    sub_off = offsets[s0:s1 + 1] - offsets[s0]
                    sub_corpus = corpus[offsets[s0]:offsets[s1]]
                    if sg:
                        ins, outs, pair_seq = nw.sg_pairs(
                            sub_corpus, sub_off, self.window, keep,
                            seed + s0)
                        alphas = seq_alpha[pair_seq + s0]
                        # native-built host rows: the H2D inside the
                        # scan dispatch is this path's jit boundary
                        # (see the fit-loop exemption above)
                        # tpulint: disable=device-transfer-in-hot-loop
                        self._dispatch_sg_many(ins, outs, alphas)
                    else:
                        ctxs, cmask, centers, row_seq = nw.cbow_rows(
                            sub_corpus, sub_off, self.window, keep,
                            seed + s0, row_width=2 * self.window)
                        alphas = seq_alpha[row_seq + s0]
                        # tpulint: disable=device-transfer-in-hot-loop
                        self._dispatch_cbow_many(ctxs, cmask, centers,
                                                 alphas)
        return True

    def _alpha(self, seen: int, total: int) -> float:
        frac = min(1.0, seen / max(1, total))
        return max(self.min_learning_rate,
                   self.learning_rate * (1.0 - frac))

    def _to_indices(self, seq: Sequence[str]) -> np.ndarray:
        out = []
        t = self.sampling
        total = max(1.0, self.vocab.total_word_count)
        for tok in seq:
            i = self.vocab.index_of(tok)
            if i < 0:
                continue
            if t > 0:  # word2vec subsampling (ref SkipGram.applySubsampling)
                f = self.vocab.word_frequency(tok) / total
                keep = (np.sqrt(f / t) + 1) * (t / f) if f > 0 else 1.0
                if keep < self._rng.random():
                    continue
            out.append(i)
        # host-built index list -> host array: no device value involved
        # tpulint: disable=host-sync-in-hot-loop
        return np.asarray(out, np.int32)

    def _pairs(self, idxs: np.ndarray):
        """(input=context row, predict=center word) window pairs, mirroring
        word2vec C / SkipGram.java windowing with random window shrink
        b ∈ [0, window): offsets b-window .. window-b inclusive, skip 0.
        Vectorized: one [n, 2w] mask instead of a per-position Python loop."""
        n = len(idxs)
        w = self.window
        if n == 0:
            return (np.empty(0, np.int32),) * 2
        b = self._rng.integers(0, w, n)                      # [n]
        offs = np.concatenate([np.arange(-w, 0), np.arange(1, w + 1)])  # [2w]
        pos = np.arange(n)[:, None]                          # [n,1]
        c = pos + offs[None, :]                              # [n,2w]
        valid = (np.abs(offs)[None, :] <= (w - b)[:, None]) & \
            (c >= 0) & (c < n)
        ins = idxs[c.clip(0, n - 1)][valid]
        outs = np.broadcast_to(idxs[:, None], c.shape)[valid]
        return ins.astype(np.int32), outs.astype(np.int32)

    def _cbow_contexts(self, idxs: np.ndarray, label_rows=None):
        """Per-center context rows + mask, vectorized like _pairs.
        Returns (ctxs [n,C], cmask [n,C], centers [n])."""
        n = len(idxs)
        w = self.window
        n_lbl = len(label_rows) if label_rows else 0
        C = 2 * w + n_lbl
        b = self._rng.integers(0, w, n)
        offs = np.concatenate([np.arange(-w, 0), np.arange(1, w + 1)])
        pos = np.arange(n)[:, None]
        c = pos + offs[None, :]
        valid = (np.abs(offs)[None, :] <= (w - b)[:, None]) & \
            (c >= 0) & (c < n)
        ctxs = np.zeros((n, C), np.int32)
        cmask = np.zeros((n, C), np.float32)
        ctxs[:, :2 * w] = idxs[c.clip(0, n - 1)] * valid
        cmask[:, :2 * w] = valid
        if n_lbl:  # DM: doc vector(s) join the context average
            # host label-row list -> host array: no device value involved
            # tpulint: disable=host-sync-in-hot-loop
            ctxs[:, 2 * w:] = np.asarray(label_rows, np.int32)[None, :]
            cmask[:, 2 * w:] = 1.0
        return ctxs, cmask, idxs.astype(np.int32)

    def _dispatch_sg(self, bi, bo, alphas):
        """One device step on a full/padded skip-gram batch."""
        bi, bo, alphas, pad = self._pad(bi, bo, alphas)
        lr = jnp.asarray(alphas)
        if self.negative > 0:
            targets, labels = self._sample_negatives(bo)
            self.syn0, self.syn1neg = _ns_step(
                self.syn0, self.syn1neg, jnp.asarray(bi),
                jnp.asarray(targets), jnp.asarray(labels),
                jnp.asarray(1.0 - pad), lr)
        if self.use_hs:
            pts = self._points[bo]
            cds = self._codes[bo]
            msk = self._path_mask[bo] * (1.0 - pad[:, None])
            self.syn0, self.syn1 = _hs_step(
                self.syn0, self.syn1, jnp.asarray(bi), jnp.asarray(pts),
                jnp.asarray(cds), jnp.asarray(msk), lr)

    #: batches per _sg_scan dispatch: bounds the per-dispatch host->device
    #: transfer (~scan_chunk * B * (K+2+L) * 4 bytes) while still cutting
    #: dispatch count by the same factor
    scan_chunk = 64

    @staticmethod
    def _pad_rows(a, rows_to):
        """Zero-pad array `a` along axis 0 to `rows_to` rows."""
        if len(a) == rows_to:
            return a
        widths = [(0, rows_to - len(a))] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths)

    #: prepare+upload the NEXT scan group on a worker thread while the
    #: current group's scan runs on device (the measured Word2Vec ceiling
    #: was upload serialization between groups — PERF.md; the single
    #: worker preserves the host rng draw order, so exactness holds)
    upload_prefetch = True

    def _run_scan_dispatch(self, rows, alphas, lead_fn, scan_fn,
                           devneg_fn):
        """Shared scaffolding for the scan-batched dispatchers: group
        scan_chunk full batches per device dispatch, threading the table
        carries across groups. The remainder runs as ONE more scan group
        padded to a power-of-two batch count (pad rows carry lr=0 and
        valid=0, so their update is exactly zero — and at most
        log2(scan_chunk) extra compiled group sizes exist), instead of
        up to scan_chunk-1 individual per-batch dispatches.

        `rows` [n] are the output-table rows (sg labels / cbow centers)
        that negatives + huffman paths are drawn from — in batch order,
        so with device_negatives=False the rng stream matches the
        per-batch path and the result is numerically equivalent to
        per-batch dispatching (pinned to 1e-6 by the equivalence tests;
        XLA may reorder float ops inside the scan body). With
        device_negatives (default) the NS negatives are drawn on device
        by `devneg_fn` and only the pair streams ship. `lead_fn(a, b,
        nb)` supplies the variant-specific leading xs for rows [a:b)
        zero-padded to nb full batches (sg: inputs; cbow: ctx + mask).

        Payload prep + host->device upload of group i+1 runs on a
        single-slot worker thread while group i's scan executes
        (`upload_prefetch`; the groups' rng draws happen in prep order
        on ONE worker, so the stream is identical to serial prep)."""
        B = self._eff_batch
        nb = self.scan_chunk
        n = len(rows)
        ns, hs = self.negative > 0, self.use_hs
        devneg = ns and self.device_negatives
        D = self.syn0.shape[1]
        dummy1 = self.syn1 if hs else jnp.zeros((1, D), jnp.float32)
        dummy1n = self.syn1neg if ns else jnp.zeros((1, D), jnp.float32)
        if devneg and n and self._table_dev is None:
            self._table_dev = jnp.asarray(self._table)
        # group schedule: full scan_chunk groups, then one padded
        # power-of-two group for the remainder
        n_scan = ((n // B) // nb) * nb
        groups = [(g0 * B, (g0 + nb) * B, nb)
                  for g0 in range(0, n_scan, nb)]
        if n_scan * B < n:
            rem_b = -(-(n - n_scan * B) // B)       # ceil batches
            gb = 1
            while gb < rem_b:
                gb *= 2
            # the group constants are allocated [nb, ...]: a
            # non-power-of-two scan_chunk must not round past it
            # (rem_b <= nb always holds)
            gb = min(gb, nb)
            groups.append((n_scan * B, n, gb))
        # constant across groups: upload once, reuse every dispatch
        # (full groups slice nothing; the padded group slices [:g])
        ones = jnp.ones((nb, B), jnp.float32)
        if not ns:
            targets0 = jnp.zeros((nb, B, 1), jnp.int32)
            labels0 = jnp.zeros((nb, B, 1), jnp.float32)
        elif not devneg:
            # NS labels are the constant [1, 0, ...] pattern — never
            # re-ship them per group (they were ~40% of the payload)
            lab = np.zeros((nb, B, self.negative + 1), np.float32)
            lab[:, :, 0] = 1.0
            labels0 = jnp.asarray(lab)
        if not hs:
            pts0 = jnp.zeros((nb, B, 1), jnp.int32)
            cds0 = jnp.zeros((nb, B, 1), jnp.float32)
            msk0 = jnp.zeros((nb, B, 1), jnp.float32)
        def prep(a, b, g):
            """Build + upload one group's payload (rng draws happen
            here, in prep order). Returns the dispatch closure inputs."""
            k = b - a                                # real rows
            full = k == g * B
            ro = self._pad_rows(
                np.ascontiguousarray(rows[a:b]), g * B).reshape(g, B)
            lr = self._pad_rows(alphas[a:b].astype(np.float32),
                                g * B).reshape(g, B)
            if full:
                valid = ones if g == nb else ones[:g]
                vnp = None
            else:
                vnp = self._pad_rows(np.ones(k, np.float32),
                                     g * B).reshape(g, B)
                valid = jax.device_put(vnp)
            if hs:
                m = self._path_mask[ro]
                if vnp is not None:
                    m = m * vnp[..., None]
                pts = jax.device_put(self._points[ro])
                cds = jax.device_put(self._codes[ro])
                msk = jax.device_put(m)
            else:
                pts, cds, msk = pts0[:g], cds0[:g], msk0[:g]
            lead = tuple(jax.device_put(np.asarray(x)) if not isinstance(
                x, jax.Array) else x for x in lead_fn(a, b, g))
            if devneg:
                key = jax.random.fold_in(self._devneg_key,
                                         self._devneg_ctr)
                self._devneg_ctr += 1
                targets = None
            else:
                key = None
                if ns:
                    # sample only batches with >=1 real row: the padded
                    # group may round up to a power of two with fully-pad
                    # batches the per-batch path never sampled — drawing
                    # for them would advance _rng and break the bit-exact
                    # cross-call equivalence with per-batch dispatching
                    real_b = -(-k // B)
                    t_np = np.zeros((g, B, self.negative + 1), np.int32)
                    for j in range(real_b):
                        t_np[j] = self._sample_negatives(ro[j])[0]
                    targets = jax.device_put(t_np)
                else:
                    targets = targets0[:g]
            return (g, lead, jax.device_put(ro), pts, cds, msk, valid,
                    jax.device_put(lr), key, targets)

        def dispatch(payload):
            nonlocal dummy1, dummy1n
            g, lead, ro, pts, cds, msk, valid, lr, key, targets = payload
            if devneg:
                self.syn0, s1, s1n = devneg_fn(
                    self.syn0, dummy1, dummy1n, self._table_dev, key,
                    *lead, ro, pts, cds, msk, valid, lr,
                    negative=self.negative, use_hs=hs)
            else:
                self.syn0, s1, s1n = scan_fn(
                    self.syn0, dummy1, dummy1n, *lead, targets,
                    labels0[:g], pts, cds, msk, valid, lr,
                    negative=ns, use_hs=hs)
            if hs:
                self.syn1 = dummy1 = s1
            if ns:
                self.syn1neg = dummy1n = s1n

        if self.upload_prefetch and len(groups) > 1:
            import concurrent.futures as _cf
            if getattr(self, "_uploader", None) is None:
                self._uploader = _cf.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="w2v-upload")
            # 1-deep pipeline: while group i's scan runs, the worker
            # preps + uploads group i+1
            fut = self._uploader.submit(prep, *groups[0])
            for grp in groups[1:]:
                payload = fut.result()
                # snapshot BEFORE submitting the next prep (no concurrent
                # mutation): if dispatch fails, the already-prepped-but-
                # never-dispatched group's rng/counter draws are undone,
                # keeping the save/resume stream contract intact
                snap = (self._rng.bit_generator.state,
                        getattr(self, "_devneg_ctr", None))
                fut = self._uploader.submit(prep, *grp)
                try:
                    dispatch(payload)
                except BaseException:
                    try:
                        fut.result()          # worker must finish first
                    except Exception:         # noqa: BLE001
                        pass
                    self._rng.bit_generator.state = snap[0]
                    if snap[1] is not None:
                        self._devneg_ctr = snap[1]
                    raise
            dispatch(fut.result())
        else:
            for grp in groups:
                dispatch(prep(*grp))

    def _dispatch_sg_many(self, ins, outs, alphas):
        """Shard-sized skip-gram training through _run_scan_dispatch."""
        B = self._eff_batch

        def lead(a, b, g):
            return (jnp.asarray(self._pad_rows(
                np.ascontiguousarray(ins[a:b]), g * B).reshape(g, B)),)

        self._run_scan_dispatch(outs, alphas, lead, _sg_scan,
                                _sg_scan_devneg)

    def _dispatch_cbow_many(self, ctxs, cmask, centers, alphas):
        """CBOW twin of _dispatch_sg_many (same scaffolding)."""
        B = self._eff_batch
        C = ctxs.shape[1]

        def lead(a, b, g):
            return (jnp.asarray(self._pad_rows(
                        np.ascontiguousarray(ctxs[a:b]),
                        g * B).reshape(g, B, C)),
                    jnp.asarray(self._pad_rows(
                        np.ascontiguousarray(cmask[a:b]).astype(
                            np.float32), g * B).reshape(g, B, C)))

        self._run_scan_dispatch(centers, alphas, lead, _cbow_scan,
                                _cbow_scan_devneg)

    def _dispatch_cbow(self, bx, bm, bc, alphas):
        B = self._eff_batch
        pad = np.zeros(B, np.float32)
        k = len(bc)
        if k < B:
            pad[k:] = 1.0
            bc = np.pad(bc, (0, B - k))
            bx = np.pad(bx, ((0, B - k), (0, 0)))
            bm = np.pad(bm, ((0, B - k), (0, 0)))
            alphas = np.pad(alphas, (0, B - k))
        lr = jnp.asarray(alphas.astype(np.float32))
        if self.negative > 0:
            targets, labels = self._sample_negatives(bc)
            self.syn0, self.syn1neg = _cbow_ns_step(
                self.syn0, self.syn1neg, jnp.asarray(bx), jnp.asarray(bm),
                jnp.asarray(targets), jnp.asarray(labels),
                jnp.asarray(1.0 - pad), lr)
        if self.use_hs:
            pts, cds = self._points[bc], self._codes[bc]
            msk = self._path_mask[bc] * (1.0 - pad[:, None])
            self.syn0, self.syn1 = _cbow_hs_step(
                self.syn0, self.syn1, jnp.asarray(bx), jnp.asarray(bm),
                jnp.asarray(pts), jnp.asarray(cds), jnp.asarray(msk), lr)

    @staticmethod
    def _label_pairs(idxs: np.ndarray, label_rows: List[int]):
        """DBOW: each label row predicts every word of the sequence."""
        ins, outs = [], []
        for lr_ in label_rows:
            for w in idxs:
                ins.append(lr_)
                outs.append(w)
        # host-built pair lists -> host arrays: no device value involved
        # tpulint: disable=host-sync-in-hot-loop
        return np.asarray(ins, np.int32), np.asarray(outs, np.int32)

    def _train_label_pairs(self, idxs, alpha, label_rows) -> None:
        """DBOW-style label->word updates for a single sequence, dispatched
        immediately (used by ParagraphVectors.infer_vector, where the output
        tables are frozen between steps so buffering across calls would
        change semantics)."""
        ins, outs = self._label_pairs(idxs, label_rows)
        for s in range(0, len(ins), self._eff_batch):
            bi, bo = ins[s:s + self._eff_batch], outs[s:s + self._eff_batch]
            alphas = np.full(len(bi), alpha, np.float32)
            self._dispatch_sg(bi, bo, alphas)

    def _pad(self, bi: np.ndarray, bo: np.ndarray, alphas=None):
        """Pad a trailing partial batch to `batch_size` (static shapes for
        jit); returns pad mask (1 where padded). With `alphas` given, the
        per-pair lr array is padded too and returned before the mask."""
        pad = np.zeros(self._eff_batch, np.float32)
        if len(bi) < self._eff_batch:
            n = self._eff_batch - len(bi)
            pad[len(bi):] = 1.0
            bi = np.pad(bi, (0, n))
            bo = np.pad(bo, (0, n))
            if alphas is not None:
                alphas = np.pad(alphas, (0, n))
        if alphas is not None:
            return bi, bo, alphas.astype(np.float32), pad
        return bi, bo, pad

    def _sample_negatives(self, bo: np.ndarray):
        """Unigram-table negatives; col 0 is the positive word. Pad rows are
        zeroed inside the kernels via the `valid` mask."""
        K = self.negative
        B = len(bo)
        negs = self._table[self._rng.integers(0, len(self._table), (B, K))]
        targets = np.concatenate([bo[:, None], negs], axis=1).astype(np.int32)
        labels = np.zeros((B, K + 1), np.float32)
        labels[:, 0] = 1.0
        return targets, labels

    def __del__(self):
        up = getattr(self, "_uploader", None)
        if up is not None:
            up.shutdown(wait=False)

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> None:
        """Full-model save — vocab with counts/labels, huffman codes, syn0/
        syn1/syn1neg, trainer config AND rng state, in the reference's
        writeWord2VecModel zip layout (ref WordVectorSerializer.java:472-677)
        plus a trainer_state.json entry for exact mid-fit resume."""
        from deeplearning4j_tpu.nlp import serializer
        serializer.write_full_model(self, path)

    @classmethod
    def load(cls, path: str) -> "SequenceVectors":
        """Restore a model saved by save() — or a reference-written
        Word2Vec/ParagraphVectors zip (ref WordVectorSerializer
        readWord2Vec/readParagraphVectors :811-950)."""
        from deeplearning4j_tpu.nlp import serializer
        return serializer.read_full_model(path, cls=cls)

    # -- queries (ref: BasicModelUtils.java wordsNearest/similarity) -------
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        if i < 0:
            return None
        return np.asarray(self.syn0[i])

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = (np.linalg.norm(va) * np.linalg.norm(vb)) or 1e-12
        return float(va @ vb / denom)

    def words_nearest(self, word_or_vec, top_n: int = 10,
                      exclude: Sequence[str] = ()) -> List[str]:
        if isinstance(word_or_vec, str):
            v = self.get_word_vector(word_or_vec)
            exclude = list(exclude) + [word_or_vec]
            if v is None:
                return []
        else:
            v = np.asarray(word_or_vec, np.float32)
        syn0 = np.asarray(self.syn0)
        norms = np.linalg.norm(syn0, axis=1) + 1e-12
        sims = syn0 @ v / (norms * (np.linalg.norm(v) + 1e-12))
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at_index(int(i))
            vw = self.vocab.element_at_index(int(i))
            if w in exclude or (vw is not None and vw.is_label):
                continue
            out.append(w)
            if len(out) >= top_n:
                break
        return out
