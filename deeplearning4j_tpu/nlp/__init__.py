"""NLP stack: embeddings-as-XLA-ops with host-side text processing.

TPU-native equivalent of deeplearning4j-nlp-parent (SURVEY §2.6). The
reference trains embeddings with hogwild threads mutating syn0/syn1 arrays
through native aggregates (SkipGram.java, CBOW.java); here training pairs are
batched on host and a single jitted update step performs the gather /
scatter-add math on device — same objective, MXU/VPU-friendly execution.
"""

from deeplearning4j_tpu.nlp.tokenization import (
    Tokenizer, DefaultTokenizer, NGramTokenizer, TokenizerFactory,
    DefaultTokenizerFactory, NGramTokenizerFactory, CommonPreprocessor,
    EndingPreProcessor, StopWords,
)
from deeplearning4j_tpu.nlp.sentence import (
    SentenceIterator, CollectionSentenceIterator, BasicLineIterator,
    FileSentenceIterator, LabelledDocument, LabelAwareIterator,
    SimpleLabelAwareIterator, FileLabelAwareIterator,
)
from deeplearning4j_tpu.nlp.vocab import VocabWord, VocabCache, VocabConstructor
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.distributed import DistributedSequenceVectors
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.serializer import (
    write_word_vectors, read_word_vectors, write_word2vec_binary,
    read_word2vec_binary, write_full_model, read_full_model,
    write_word2vec_model, read_word2vec_model_full,
    write_paragraph_vectors, read_paragraph_vectors,
    write_sequence_vectors, read_sequence_vectors,
)
from deeplearning4j_tpu.nlp.bagofwords import (
    BagOfWordsVectorizer, TfidfVectorizer,
)
from deeplearning4j_tpu.nlp.cnn_sentence import CnnSentenceDataSetIterator
from deeplearning4j_tpu.nlp.annotation import (
    AnalysisEngine, AnnotatedDocument, Annotation, AnnotationSentenceIterator,
    AnnotationTokenizerFactory, PosFilterTokenizerFactory,
    StemmingPreprocessor, SWN3, porter_stem,
)
from deeplearning4j_tpu.nlp.trees import (
    Tree, ChunkTreeParser, TreeVectorizer, TreeIterator, HeadWordFinder,
)

__all__ = [
    "Tokenizer", "DefaultTokenizer", "NGramTokenizer", "TokenizerFactory",
    "DefaultTokenizerFactory", "NGramTokenizerFactory", "CommonPreprocessor",
    "EndingPreProcessor", "StopWords",
    "SentenceIterator", "CollectionSentenceIterator", "BasicLineIterator",
    "FileSentenceIterator", "LabelledDocument", "LabelAwareIterator",
    "SimpleLabelAwareIterator", "FileLabelAwareIterator",
    "VocabWord", "VocabCache", "VocabConstructor",
    "SequenceVectors", "Word2Vec", "ParagraphVectors", "Glove",
    "DistributedSequenceVectors",
    "write_word_vectors", "read_word_vectors", "write_word2vec_binary",
    "read_word2vec_binary", "write_full_model", "read_full_model",
    "write_word2vec_model", "read_word2vec_model_full",
    "write_paragraph_vectors", "read_paragraph_vectors",
    "write_sequence_vectors", "read_sequence_vectors",
    "BagOfWordsVectorizer", "TfidfVectorizer", "CnnSentenceDataSetIterator",
    "AnalysisEngine", "AnnotatedDocument", "Annotation",
    "AnnotationSentenceIterator", "AnnotationTokenizerFactory",
    "PosFilterTokenizerFactory", "StemmingPreprocessor", "SWN3",
    "porter_stem",
    "Tree", "ChunkTreeParser", "TreeVectorizer", "TreeIterator",
    "HeadWordFinder",
]
