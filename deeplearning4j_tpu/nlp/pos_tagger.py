"""Trained POS tagger: greedy averaged perceptron (Collins 2002).

The reference's UIMA annotator wraps a trained OpenNLP maxent model
(deeplearning4j-nlp-uima/src/main/java/org/deeplearning4j/text/annotator/
PoStagger.java:39-76 — loads en-pos-maxent.bin and tags per sentence);
this build is zero-egress, so the equivalent is trained in-repo on the
curated corpus in pos_data.py. Same contract: sentence in, one Penn tag
per token, trained weights rather than rules.

The model is the standard structured-perceptron feature set (word,
affixes, shape, previous tags, surrounding words) with weight averaging
for generalization; training is deterministic (fixed shuffle seed), so
every build produces identical weights. `default_tagger()` trains once
per process (<1 s on the bundled corpus) and caches.
"""

from __future__ import annotations

import json
import random
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple


def _shape(word: str) -> str:
    if word.isdigit():
        return "d"
    if any(ch.isdigit() for ch in word):
        return "mixd"
    if word.isupper():
        return "AA"
    if word[:1].isupper():
        return "Aa"
    if "-" in word:
        return "a-a"
    return "a"


def _features(i: int, word: str, context: Sequence[str],
              prev: str, prev2: str) -> List[str]:
    """Feature strings for token i. context is the padded word list
    (two leading/trailing sentinels)."""
    j = i + 2
    low = word.lower()
    feats = [
        "b",                            # bias
        "w=" + low,
        "suf3=" + low[-3:],
        "suf2=" + low[-2:],
        "suf1=" + low[-1:],
        "pre1=" + low[:1],
        "shape=" + _shape(word),
        "t1=" + prev,
        "t2=" + prev2,
        "t12=" + prev + "+" + prev2,
        "w-1=" + context[j - 1],
        "w-2=" + context[j - 2],
        "w+1=" + context[j + 1],
        "w+2=" + context[j + 2],
        "t1w=" + prev + "+" + low,
        "w-1suf3=" + context[j - 1][-3:],
        "w+1suf3=" + context[j + 1][-3:],
    ]
    return feats


class PerceptronPosTagger:
    """Greedy left-to-right averaged perceptron tagger."""

    START = ("-S1-", "-S2-")

    def __init__(self):
        self.weights: Dict[str, Dict[str, float]] = {}
        self.classes: List[str] = []
        self.tagdict: Dict[str, str] = {}   # unambiguous frequent words

    # -- inference ---------------------------------------------------------

    def _predict(self, feats: Sequence[str]) -> str:
        scores = defaultdict(float)
        for f in feats:
            w = self.weights.get(f)
            if not w:
                continue
            for tag, weight in w.items():
                scores[tag] += weight
        # ties broken by tag name for determinism
        return max(self.classes, key=lambda t: (scores[t], t))

    def tag(self, words: Sequence[str]) -> List[str]:
        prev, prev2 = self.START
        context = ["-C2-", "-C1-"] + [w.lower() for w in words] \
            + ["+C1+", "+C2+"]
        tags = []
        for i, word in enumerate(words):
            tag = self.tagdict.get(word.lower())
            if tag is None:
                tag = self._predict(_features(i, word, context, prev,
                                              prev2))
            tags.append(tag)
            prev2, prev = prev, tag
        return tags

    # -- training ----------------------------------------------------------

    def train(self, sentences: Sequence[Sequence[Tuple[str, str]]],
              iterations: int = 8, seed: int = 13) -> None:
        """Averaged-perceptron training with PREDICTED tag history —
        the prev/prev2 features see the model's own greedy guesses, the
        same regime inference runs in (gold history would train on
        contexts the tagger never sees at test time)."""
        self._make_tagdict(sentences)
        self.classes = sorted({t for s in sentences for _, t in s}
                              | set(self.tagdict.values()))
        totals: Dict[str, Dict[str, float]] = defaultdict(
            lambda: defaultdict(float))
        stamps: Dict[str, Dict[str, int]] = defaultdict(
            lambda: defaultdict(int))
        instance = 0
        rng = random.Random(seed)
        order = list(sentences)
        for _ in range(iterations):
            rng.shuffle(order)
            for sent in order:
                words = [w for w, _ in sent]
                gold = [t for _, t in sent]
                context = ["-C2-", "-C1-"] + [w.lower() for w in words] \
                    + ["+C1+", "+C2+"]
                prev, prev2 = self.START
                for i, word in enumerate(words):
                    instance += 1
                    dict_tag = self.tagdict.get(word.lower())
                    # update on EVERY token, tagdict-covered or not: on
                    # a small corpus, skipping dict words would leave
                    # their contexts untrained (e.g. t1=MD -> VB never
                    # gets weight when all template verbs are dict-
                    # covered), crippling generalization to unseen words
                    feats = _features(i, word, context, prev, prev2)
                    guess = self._predict(feats)
                    if guess != gold[i]:
                        for f in feats:
                            w = self.weights.setdefault(f, {})
                            self._upd(totals, stamps, instance, f,
                                      gold[i], w, 1.0)
                            self._upd(totals, stamps, instance, f,
                                      guess, w, -1.0)
                    # history tag mirrors the inference regime exactly:
                    # dict words contribute their dict tag, the rest the
                    # model's own greedy guess
                    prev2, prev = prev, (dict_tag if dict_tag is not None
                                         else guess)
        # average
        for f, w in self.weights.items():
            for tag in w:
                total = totals[f][tag] \
                    + (instance - stamps[f][tag]) * w[tag]
                avg = total / instance
                w[tag] = round(avg, 6)
        self.weights = {f: {t: v for t, v in w.items() if v}
                        for f, w in self.weights.items()}
        self.weights = {f: w for f, w in self.weights.items() if w}

    def _upd(self, totals, stamps, instance, f, tag, w, delta):
        totals[f][tag] += (instance - stamps[f][tag]) * w.get(tag, 0.0)
        stamps[f][tag] = instance
        w[tag] = w.get(tag, 0.0) + delta

    def _make_tagdict(self, sentences, min_count=4, ambiguity=0.99):
        """Frequent words that are (nearly) unambiguous bypass the model
        — the standard speed/stability trick."""
        counts: Dict[str, Dict[str, int]] = defaultdict(
            lambda: defaultdict(int))
        for sent in sentences:
            for word, tag in sent:
                counts[word.lower()][tag] += 1
        for word, tags in counts.items():
            tag, n = max(tags.items(), key=lambda kv: (kv[1], kv[0]))
            total = sum(tags.values())
            if total >= min_count and n / total >= ambiguity:
                self.tagdict[word] = tag

    # -- serialization -----------------------------------------------------

    def save(self, path: str) -> None:
        # atomic (tmp + fsync + rename): a crash mid-save must not tear
        # the only copy of the trained weights
        from deeplearning4j_tpu.resilience.durable import atomic_write_json
        atomic_write_json(path, {"weights": self.weights,
                                 "classes": self.classes,
                                 "tagdict": self.tagdict})

    @classmethod
    def load(cls, path: str) -> "PerceptronPosTagger":
        t = cls()
        with open(path) as f:
            blob = json.load(f)
        t.weights = blob["weights"]
        t.classes = blob["classes"]
        t.tagdict = blob["tagdict"]
        return t

    def accuracy(self, sentences) -> float:
        right = total = 0
        for sent in sentences:
            words = [w for w, _ in sent]
            gold = [t for _, t in sent]
            for g, p in zip(gold, self.tag(words)):
                right += g == p
                total += 1
        return right / max(total, 1)


_DEFAULT: Optional[PerceptronPosTagger] = None


def default_tagger() -> PerceptronPosTagger:
    """The in-repo tagger trained on the bundled corpus (cached per
    process; deterministic weights)."""
    global _DEFAULT
    if _DEFAULT is None:
        from deeplearning4j_tpu.nlp.pos_data import corpus
        t = PerceptronPosTagger()
        t.train(corpus())
        _DEFAULT = t
    return _DEFAULT
