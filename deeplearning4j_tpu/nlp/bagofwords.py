"""Bag-of-words and TF-IDF vectorizers.

Equivalent of deeplearning4j-nlp bagofwords/vectorizer/
(BagOfWordsVectorizer.java, TfidfVectorizer.java): fit a vocab over a
corpus, then transform texts into count / tf-idf vectors (and labelled
DataSets for classifier training).
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


class BagOfWordsVectorizer:
    """ref: BagOfWordsVectorizer.java — transform(text) -> count vector."""

    def __init__(self, tokenizer_factory: Optional[TokenizerFactory] = None,
                 min_word_frequency: int = 1,
                 stop_words: Sequence[str] = ()):
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = min_word_frequency
        self.stop_words = stop_words
        self.vocab: Optional[VocabCache] = None
        self.n_docs = 0
        self._doc_freq = {}

    def fit(self, texts: Iterable[str]) -> "BagOfWordsVectorizer":
        texts = list(texts)
        seqs = [self.tokenizer_factory.create(t).get_tokens() for t in texts]
        self.vocab = VocabConstructor(
            self.min_word_frequency, stop_words=self.stop_words,
            build_huffman_tree=False).build(seqs)
        self.n_docs = len(texts)
        for seq in seqs:
            for w in set(seq):
                if self.vocab.contains_word(w):
                    self._doc_freq[w] = self._doc_freq.get(w, 0) + 1
        return self

    def transform(self, text: str) -> np.ndarray:
        v = np.zeros(self.vocab.num_words(), np.float32)
        for tok in self.tokenizer_factory.create(text):
            i = self.vocab.index_of(tok)
            if i >= 0:
                v[i] += 1.0
        return v

    def vectorize(self, texts: Iterable[str],
                  labels: Optional[Sequence[int]] = None,
                  num_classes: Optional[int] = None) -> DataSet:
        """ref: vectorize() -> DataSet with one-hot labels."""
        X = np.stack([self.transform(t) for t in texts])
        if labels is None:
            return DataSet(X, np.zeros((len(X), 1), np.float32))
        k = num_classes or (max(labels) + 1)
        Y = np.zeros((len(X), k), np.float32)
        Y[np.arange(len(X)), np.asarray(labels)] = 1.0
        return DataSet(X, Y)


class TfidfVectorizer(BagOfWordsVectorizer):
    """ref: TfidfVectorizer.java — tf·idf weighting, idf = log(N/df)."""

    def transform(self, text: str) -> np.ndarray:
        counts = super().transform(text)
        total = counts.sum() or 1.0
        out = np.zeros_like(counts)
        nz = np.nonzero(counts)[0]
        for i in nz:
            w = self.vocab.word_at_index(int(i))
            df = self._doc_freq.get(w, 0)
            if df > 0:
                idf = math.log(self.n_docs / df)
                out[i] = (counts[i] / total) * idf
        return out
