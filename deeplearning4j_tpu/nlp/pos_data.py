"""Curated POS-annotated corpus (Penn Treebank tagset) for training the
in-repo perceptron tagger (pos_tagger.py).

The reference wraps real trained OpenNLP models
(deeplearning4j-nlp-uima/src/main/java/org/deeplearning4j/text/annotator/
PoStagger.java); this build is zero-egress, so the training data is
authored in-repo: a handwritten section covering irregular morphology,
questions, clauses and punctuation conventions, plus deterministic
template expansions that give exact tags for regular constructions at
volume. Sentences are (word, tag) lists; `train_test_split()` carves a
fixed held-out set (every 5th sentence) for the A/B in
tests/test_pos_tagger.py.
"""

from __future__ import annotations

from typing import List, Tuple

Tagged = List[Tuple[str, str]]

# ---------------------------------------------------------------------------
# handwritten sentences — irregulars, clauses, questions, punctuation
# ---------------------------------------------------------------------------

_H = [
    "The/DT old/JJ man/NN sat/VBD on/IN the/DT wooden/JJ bench/NN ./.",
    "She/PRP quickly/RB wrote/VBD a/DT long/JJ letter/NN to/TO her/PRP$ "
    "brother/NN ./.",
    "They/PRP have/VBP been/VBN waiting/VBG for/IN hours/NNS ./.",
    "He/PRP did/VBD not/RB know/VB what/WP to/TO say/VB ./.",
    "What/WP do/VBP you/PRP want/VB ?/.",
    "Where/WRB did/VBD the/DT children/NNS go/VB ?/.",
    "The/DT committee/NN has/VBZ approved/VBN the/DT new/JJ budget/NN ./.",
    "I/PRP think/VBP that/IN she/PRP is/VBZ right/JJ ./.",
    "Although/IN it/PRP was/VBD raining/VBG ,/, we/PRP went/VBD "
    "outside/RB ./.",
    "The/DT dog/NN that/WDT bit/VBD me/PRP ran/VBD away/RB ./.",
    "His/PRP$ answer/NN was/VBD better/JJR than/IN mine/PRP ./.",
    "This/DT is/VBZ the/DT best/JJS result/NN we/PRP have/VBP ever/RB "
    "seen/VBN ./.",
    "Can/MD you/PRP help/VB me/PRP with/IN this/DT problem/NN ?/.",
    "The/DT children/NNS were/VBD playing/VBG in/IN the/DT garden/NN ./.",
    "Nobody/NN knew/VBD why/WRB the/DT meeting/NN was/VBD cancelled/VBN ./.",
    "We/PRP will/MD probably/RB arrive/VB before/IN noon/NN ./.",
    "The/DT company/NN reported/VBD strong/JJ earnings/NNS last/JJ "
    "quarter/NN ./.",
    "Prices/NNS rose/VBD sharply/RB in/IN March/NNP ./.",
    "Mr./NNP Smith/NNP leads/VBZ the/DT research/NN team/NN ./.",
    "London/NNP and/CC Paris/NNP are/VBP large/JJ cities/NNS ./.",
    "My/PRP$ sister/NN teaches/VBZ mathematics/NN at/IN a/DT local/JJ "
    "school/NN ./.",
    "The/DT water/NN was/VBD too/RB cold/JJ for/IN swimming/NN ./.",
    "He/PRP gave/VBD her/PRP the/DT keys/NNS and/CC left/VBD ./.",
    "If/IN you/PRP see/VBP him/PRP ,/, tell/VB him/PRP to/TO call/VB "
    "me/PRP ./.",
    "Several/JJ students/NNS failed/VBD the/DT difficult/JJ exam/NN ./.",
    "The/DT results/NNS were/VBD surprisingly/RB good/JJ ./.",
    "She/PRP has/VBZ never/RB eaten/VBN sushi/NN before/RB ./.",
    "Both/DT teams/NNS played/VBD very/RB well/RB ./.",
    "It/PRP took/VBD three/CD years/NNS to/TO build/VB the/DT bridge/NN ./.",
    "The/DT first/JJ chapter/NN explains/VBZ the/DT basic/JJ ideas/NNS ./.",
    "Most/JJS people/NNS agree/VBP with/IN the/DT decision/NN ./.",
    "He/PRP was/VBD born/VBN in/IN 1985/CD in/IN Chicago/NNP ./.",
    "The/DT train/NN leaves/VBZ at/IN 10:30/CD every/DT morning/NN ./.",
    "Her/PRP$ latest/JJS novel/NN sold/VBD 50,000/CD copies/NNS ./.",
    "There/EX is/VBZ a/DT small/JJ shop/NN near/IN the/DT station/NN ./.",
    "There/EX were/VBD many/JJ reasons/NNS for/IN the/DT delay/NN ./.",
    "Who/WP wrote/VBD this/DT wonderful/JJ song/NN ?/.",
    "Whose/WP$ coat/NN is/VBZ hanging/VBG by/IN the/DT door/NN ?/.",
    "The/DT weather/NN has/VBZ been/VBN unusually/RB warm/JJ ./.",
    "You/PRP should/MD have/VB told/VBN me/PRP earlier/RBR ./.",
    "The/DT cat/NN slept/VBD while/IN the/DT mice/NNS played/VBD ./.",
    "Running/VBG every/DT day/NN keeps/VBZ him/PRP healthy/JJ ./.",
    "Broken/VBN windows/NNS were/VBD replaced/VBN immediately/RB ./.",
    "The/DT quickly/RB moving/VBG storm/NN caused/VBD damage/NN ./.",
    "I/PRP bought/VBD apples/NNS ,/, oranges/NNS and/CC bread/NN ./.",
    "Neither/DT answer/NN seems/VBZ correct/JJ to/TO me/PRP ./.",
    "The/DT book/NN on/IN the/DT table/NN belongs/VBZ to/TO John/NNP ./.",
    "Everyone/NN enjoyed/VBD the/DT performance/NN last/JJ night/NN ./.",
    "His/PRP$ decision/NN to/TO resign/VB shocked/VBD us/PRP all/DT ./.",
    "The/DT more/RBR you/PRP practice/VBP ,/, the/DT better/RBR you/PRP "
    "become/VBP ./.",
    "Scientists/NNS discovered/VBD a/DT new/JJ species/NN of/IN frog/NN ./.",
    "The/DT government/NN announced/VBD tax/NN cuts/NNS yesterday/NN ./.",
    "Interest/NN rates/NNS fell/VBD to/TO 3.5/CD %/NN last/JJ week/NN ./.",
    "She/PRP speaks/VBZ French/NNP fluently/RB ./.",
    "Do/VBP not/RB open/VB that/DT box/NN !/.",
    "Have/VBP you/PRP finished/VBN your/PRP$ homework/NN yet/RB ?/.",
    "The/DT river/NN flows/VBZ through/IN four/CD countries/NNS ./.",
    "An/DT honest/JJ answer/NN is/VBZ always/RB appreciated/VBN ./.",
    "They/PRP had/VBD already/RB gone/VBN when/WRB we/PRP arrived/VBD ./.",
    "The/DT fastest/JJS runner/NN won/VBD a/DT gold/NN medal/NN ./.",
    "Our/PRP$ neighbors/NNS are/VBP building/VBG a/DT new/JJ garage/NN ./.",
    "Some/DT birds/NNS cannot/MD fly/VB ./.",
    "The/DT museum/NN closes/VBZ at/IN five/CD on/IN Sundays/NNPS ./.",
    "A/DT sudden/JJ noise/NN woke/VBD the/DT sleeping/VBG baby/NN ./.",
    "I/PRP would/MD rather/RB stay/VB home/NN tonight/NN ./.",
    "The/DT teacher/NN explained/VBD the/DT lesson/NN again/RB ./.",
    "Workers/NNS demanded/VBD higher/JJR wages/NNS and/CC shorter/JJR "
    "hours/NNS ./.",
    "That/DT was/VBD the/DT funniest/JJS joke/NN I/PRP have/VBP "
    "heard/VBN ./.",
    "He/PRP carefully/RB placed/VBD the/DT vase/NN on/IN the/DT "
    "shelf/NN ./.",
    "The/DT old/JJ bridge/NN was/VBD torn/VBN down/RP in/IN 2010/CD ./.",
    "Children/NNS learn/VBP languages/NNS faster/RBR than/IN adults/NNS ./.",
    "She/PRP felt/VBD happier/JJR after/IN the/DT holiday/NN ./.",
    "The/DT committee/NN will/MD meet/VB again/RB next/JJ Tuesday/NNP ./.",
    "Its/PRP$ engine/NN makes/VBZ a/DT strange/JJ sound/NN ./.",
    "Nothing/NN could/MD stop/VB the/DT growing/VBG crowd/NN ./.",
    "The/DT recently/RB published/VBN report/NN criticizes/VBZ the/DT "
    "plan/NN ./.",
    "Tom/NNP 's/POS car/NN is/VBZ parked/VBN outside/RB ./.",
    "The/DT students/NNS '/POS projects/NNS impressed/VBD the/DT "
    "judges/NNS ./.",
    "We/PRP saw/VBD them/PRP leaving/VBG the/DT building/NN ./.",
    "It/PRP is/VBZ hard/JJ to/TO believe/VB his/PRP$ story/NN ./.",
    "The/DT sun/NN rises/VBZ in/IN the/DT east/NN ./.",
    "Why/WRB are/VBP you/PRP laughing/VBG ?/.",
    "Because/IN of/IN the/DT storm/NN ,/, flights/NNS were/VBD "
    "delayed/VBN ./.",
    "Each/DT player/NN receives/VBZ two/CD cards/NNS ./.",
    "Music/NN helps/VBZ me/PRP relax/VB after/IN work/NN ./.",
    "The/DT wounded/JJ soldier/NN slowly/RB recovered/VBD ./.",
    "Many/JJ visitors/NNS come/VBP here/RB every/DT summer/NN ./.",
    "A/DT loud/JJ argument/NN broke/VBD out/RP in/IN the/DT hall/NN ./.",
    "She/PRP turned/VBD off/RP the/DT lights/NNS and/CC left/VBD ./.",
    "He/PRP looked/VBD up/RP the/DT word/NN in/IN a/DT dictionary/NN ./.",
    "The/DT plane/NN took/VBD off/RP on/IN time/NN ./.",
    "Please/UH write/VB down/RP your/PRP$ name/NN ./.",
    "Well/UH ,/, that/DT went/VBD better/RBR than/IN expected/VBN ./.",
    "Oh/UH ,/, I/PRP nearly/RB forgot/VBD the/DT tickets/NNS ./.",
    "The/DT data/NNS show/VBP a/DT clear/JJ trend/NN ./.",
    "These/DT figures/NNS include/VBP all/DT overseas/JJ sales/NNS ./.",
    "However/RB ,/, the/DT plan/NN has/VBZ serious/JJ flaws/NNS ./.",
    "Meanwhile/RB ,/, the/DT crowd/NN grew/VBD restless/JJ ./.",
    "About/IN twenty/CD people/NNS attended/VBD the/DT lecture/NN ./.",
    "The/DT temperature/NN dropped/VBD below/IN zero/CD overnight/RB ./.",
    # modal questions with an interposed subject (MD ... VB)
    "Can/MD the/DT team/NN finish/VB the/DT project/NN ?/.",
    "Will/MD the/DT students/NNS pass/VB the/DT test/NN ?/.",
    "Should/MD the/DT committee/NN approve/VB the/DT plan/NN ?/.",
    "Could/MD your/PRP$ sister/NN drive/VB us/PRP home/NN ?/.",
    "Did/VBD the/DT driver/NN stop/VB at/IN the/DT light/NN ?/.",
    # adverb-final fragments + common time adverbs (unpunctuated ends
    # must cover non-verb finals too)
    "We/PRP should/MD leave/VB now/RB",
    "You/PRP must/MD stop/VB immediately/RB",
    "He/PRP will/MD arrive/VB soon/RB",
    "She/PRP might/MD come/VB later/RB",
    "They/PRP can/MD start/VB today/NN",
    "I/PRP will/MD call/VB you/PRP tomorrow/NN",
    "Do/VB it/PRP again/RB",
    "Come/VB here/RB",
    "The/DT store/NN is/VBZ open/JJ now/RB ./.",
    "He/PRP is/VBZ busy/JJ now/RB ,/, but/CC free/JJ later/RB ./.",
    "Everything/NN looks/VBZ fine/JJ so/RB far/RB ./.",
    # prenominal participles (CD/DT + VBN + NNS)
    "Three/CD stolen/VBN cars/NNS were/VBD found/VBN ./.",
    "The/DT fallen/VBN leaves/NNS covered/VBD the/DT path/NN ./.",
    "Two/CD broken/VBN chairs/NNS stood/VBD in/IN the/DT corner/NN ./.",
    "Five/CD injured/VBN players/NNS left/VBD the/DT game/NN ./.",
    "Several/JJ frozen/VBN pipes/NNS burst/VBD last/JJ winter/NN ./.",
]

# ---------------------------------------------------------------------------
# deterministic template expansions — regular morphology at volume
# ---------------------------------------------------------------------------

_DETS = [("the", "DT"), ("a", "DT"), ("every", "DT"), ("this", "DT")]
_ADJS = [("small", "JJ"), ("bright", "JJ"), ("quiet", "JJ"),
         ("heavy", "JJ"), ("modern", "JJ"), ("narrow", "JJ")]
_NOUNS = [("farmer", "NN"), ("engine", "NN"), ("village", "NN"),
          ("painter", "NN"), ("market", "NN"), ("garden", "NN"),
          ("teacher", "NN"), ("window", "NN")]
_NOUNS_PL = [("farmers", "NNS"), ("engines", "NNS"), ("villages", "NNS"),
             ("painters", "NNS"), ("markets", "NNS"), ("gardens", "NNS")]
_VERBS_D = [("opened", "VBD"), ("cleaned", "VBD"), ("repaired", "VBD"),
            ("watched", "VBD"), ("visited", "VBD"), ("painted", "VBD")]
_VERBS_Z = [("opens", "VBZ"), ("cleans", "VBZ"), ("repairs", "VBZ"),
            ("watches", "VBZ"), ("visits", "VBZ"), ("paints", "VBZ")]
_ADVS = [("slowly", "RB"), ("often", "RB"), ("rarely", "RB"),
         ("gently", "RB")]
_PREPS = [("near", "IN"), ("behind", "IN"), ("inside", "IN"),
          ("beyond", "IN")]
_MODALS = [("will", "MD"), ("might", "MD"), ("should", "MD"),
           ("can", "MD"), ("could", "MD"), ("must", "MD"),
           ("would", "MD")]
_PRONS = [("he", "PRP"), ("she", "PRP"), ("it", "PRP"),
          ("they", "PRP"), ("we", "PRP"), ("you", "PRP"), ("i", "PRP")]
_VERBS_B = [("open", "VB"), ("clean", "VB"), ("repair", "VB"),
            ("watch", "VB"), ("visit", "VB"), ("paint", "VB")]
_VERBS_G = [("opening", "VBG"), ("cleaning", "VBG"), ("repairing", "VBG"),
            ("watching", "VBG"), ("visiting", "VBG"), ("painting", "VBG")]


def _templates() -> List[Tagged]:
    out = []
    dot = (".", ".")
    # Det (Adj) Noun Verb-past Det Noun .
    for i in range(48):
        d1 = _DETS[i % len(_DETS)]
        a1 = _ADJS[i % len(_ADJS)]
        n1 = _NOUNS[i % len(_NOUNS)]
        v = _VERBS_D[(i * 5 + 1) % len(_VERBS_D)]
        d2 = _DETS[(i + 2) % len(_DETS)]
        n2 = _NOUNS[(i + 3) % len(_NOUNS)]
        out.append([d1, a1, n1, v, d2, n2, dot])
    # Det Noun Verb-s Adv .  /  Det Noun-pl Adv Verb-past .
    for i in range(36):
        d = _DETS[i % len(_DETS)]
        n = _NOUNS[(i * 3 + 1) % len(_NOUNS)]
        vz = _VERBS_Z[i % len(_VERBS_Z)]
        adv = _ADVS[i % len(_ADVS)]
        out.append([d, n, vz, adv, dot])
        npl = _NOUNS_PL[i % len(_NOUNS_PL)]
        vd = _VERBS_D[(i * 7 + 2) % len(_VERBS_D)]
        out.append([("the", "DT"), npl, adv, vd, dot])
    # Det Noun Modal Verb-base Prep Det Adj Noun .
    for i in range(36):
        d = _DETS[(i + 1) % len(_DETS)]
        n = _NOUNS[i % len(_NOUNS)]
        m = _MODALS[i % len(_MODALS)]
        vb = _VERBS_B[(i * 5 + 2) % len(_VERBS_B)]
        p = _PREPS[i % len(_PREPS)]
        a = _ADJS[(i + 3) % len(_ADJS)]
        n2 = _NOUNS[(i + 5) % len(_NOUNS)]
        out.append([d, n, m, vb, p, ("the", "DT"), a, n2, dot])
    # Pron Modal Verb-base (Det Noun) — every 3rd WITHOUT final punct
    # (an all-"./."-final corpus teaches `nothing-follows => .`, which
    # mis-tags the last word of unpunctuated fragments)
    for i in range(42):
        pr = _PRONS[i % len(_PRONS)]
        m = _MODALS[i % len(_MODALS)]
        vb = _VERBS_B[(i * 5 + 1) % len(_VERBS_B)]
        d = _DETS[i % len(_DETS)]
        n = _NOUNS[(i * 3 + 2) % len(_NOUNS)]
        sent = [pr, m, vb, d, n]
        if i % 3:
            sent.append(dot)
        out.append(sent)
    # Pron Modal Verb-base, UNPUNCTUATED 3-token fragments: without
    # these, no training sentence ever ENDS in a bare verb, so
    # `nothing-follows` + t1=MD still resolves to "." for unseen verbs
    # ("it can jump" -> jump/.)
    for i in range(21):
        pr = _PRONS[(i * 3 + 1) % len(_PRONS)]
        m = _MODALS[(i * 2 + 1) % len(_MODALS)]
        vb = _VERBS_B[i % len(_VERBS_B)]
        out.append([pr, m, vb])
    # Pron was/were Verb-ing Det Noun .  (PRP aux progressive)
    prons = [("he", "PRP"), ("she", "PRP"), ("it", "PRP"),
             ("they", "PRP"), ("we", "PRP")]
    for i in range(30):
        pr = prons[i % len(prons)]
        aux = ("were", "VBD") if pr[0] in ("they", "we") else ("was", "VBD")
        vg = _VERBS_G[i % len(_VERBS_G)]
        d = _DETS[i % len(_DETS)]
        n = _NOUNS[(i * 3 + 2) % len(_NOUNS)]
        out.append([pr, aux, vg, d, n, dot])
    # Proper-noun sentences: Name Verb-s Det Noun Prep Name .
    names = [("Anna", "NNP"), ("Berlin", "NNP"), ("Carter", "NNP"),
             ("Diana", "NNP"), ("Edward", "NNP"), ("Tokyo", "NNP")]
    for i in range(30):
        nm = names[i % len(names)]
        vz = _VERBS_Z[(i + 1) % len(_VERBS_Z)]
        d = _DETS[i % len(_DETS)]
        n = _NOUNS[(i * 5 + 3) % len(_NOUNS)]
        p = _PREPS[(i + 1) % len(_PREPS)]
        nm2 = names[(i + 2) % len(names)]
        out.append([nm, vz, d, n, p, nm2, dot])
    # Possessive: PRP$ Noun Verb-s/-d (Det Noun) .
    poss = [("my", "PRP$"), ("your", "PRP$"), ("his", "PRP$"),
            ("her", "PRP$"), ("its", "PRP$"), ("our", "PRP$"),
            ("their", "PRP$")]
    for i in range(35):
        ps = poss[i % len(poss)]
        n = _NOUNS[(i * 3 + 1) % len(_NOUNS)]
        if i % 2:
            v = _VERBS_Z[i % len(_VERBS_Z)]
        else:
            v = _VERBS_D[i % len(_VERBS_D)]
        d = _DETS[(i + 1) % len(_DETS)]
        n2 = _NOUNS[(i + 4) % len(_NOUNS)]
        out.append([ps, n, v, d, n2, dot])
    # Numeric: Det Noun Verb-d CD Noun-pl .
    nums = [("three", "CD"), ("seven", "CD"), ("40", "CD"), ("1,200", "CD")]
    for i in range(24):
        d = _DETS[i % len(_DETS)]
        n = _NOUNS[(i + 1) % len(_NOUNS)]
        v = _VERBS_D[i % len(_VERBS_D)]
        cd = nums[i % len(nums)]
        npl = _NOUNS_PL[(i + 2) % len(_NOUNS_PL)]
        out.append([d, n, v, cd, npl, dot])
    return out


def _parse(line: str) -> Tagged:
    toks = []
    for pair in line.split():
        word, _, tag = pair.rpartition("/")
        toks.append((word, tag))
    return toks


def corpus() -> List[Tagged]:
    """The full tagged corpus: handwritten + template expansions."""
    return [_parse(s) for s in _H] + _templates()


def train_test_split() -> Tuple[List[Tagged], List[Tagged]]:
    """Deterministic split: every 5th sentence held out."""
    sents = corpus()
    train = [s for i, s in enumerate(sents) if i % 5 != 0]
    test = [s for i, s in enumerate(sents) if i % 5 == 0]
    return train, test
