"""Vocabulary construction + Huffman coding.

Equivalent of deeplearning4j-nlp wordstore/ (SURVEY §2.6):
VocabConstructor.java:611 (frequency counting + min-freq pruning),
AbstractCache.java:478 (index/word/frequency store), and the Huffman tree in
models/word2vec/Huffman.java that assigns each word its hierarchical-softmax
code path. Host-side pure Python — the trained tables live on device as JAX
arrays (sequencevectors.py).
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np

MAX_CODE_LENGTH = 40  # ref: Huffman.java MAX_CODE_LENGTH


@dataclass
class VocabWord:
    """ref: word2vec/VocabWord.java — element frequency + HS code path."""
    word: str
    frequency: float = 1.0
    index: int = -1
    codes: List[int] = field(default_factory=list)      # huffman code bits
    points: List[int] = field(default_factory=list)     # inner-node indices
    is_label: bool = False                               # paravec doc labels

    def increment(self, by: float = 1.0) -> None:
        self.frequency += by


class VocabCache:
    """ref: wordstore/inmemory/AbstractCache.java — word<->index maps,
    frequencies, total counts."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._index: List[VocabWord] = []
        self.total_word_count: float = 0.0

    # -- construction ------------------------------------------------------
    def add_token(self, vw: VocabWord) -> VocabWord:
        existing = self._words.get(vw.word)
        if existing is not None:
            existing.increment(vw.frequency)
            return existing
        self._words[vw.word] = vw
        return vw

    def update_words_occurrences(self, count: float = 1.0) -> None:
        self.total_word_count += count

    def build_index(self, order_by_frequency: bool = True) -> None:
        words = list(self._words.values())
        if order_by_frequency:
            words.sort(key=lambda w: (-w.frequency, w.word))
        for i, w in enumerate(words):
            w.index = i
        self._index = words

    # -- queries (ref AbstractCache API names) -----------------------------
    def contains_word(self, word: str) -> bool:
        return word in self._words

    def word_frequency(self, word: str) -> float:
        w = self._words.get(word)
        return w.frequency if w else 0.0

    def index_of(self, word: str) -> int:
        w = self._words.get(word)
        return w.index if w else -1

    def word_at_index(self, index: int) -> Optional[str]:
        if 0 <= index < len(self._index):
            return self._index[index].word
        return None

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def element_at_index(self, index: int) -> Optional[VocabWord]:
        if 0 <= index < len(self._index):
            return self._index[index]
        return None

    def num_words(self) -> int:
        return len(self._index) or len(self._words)

    def words(self) -> List[str]:
        return [w.word for w in self._index] if self._index \
            else list(self._words)

    def vocab_words(self) -> List[VocabWord]:
        return list(self._index) if self._index else list(self._words.values())

    def remove(self, word: str) -> None:
        self._words.pop(word, None)

    def __len__(self) -> int:
        return self.num_words()


def build_huffman(cache: VocabCache) -> None:
    """Assign huffman codes/points to every vocab word
    (ref: models/word2vec/Huffman.java applyIndexes/build: classic word2vec
    two-min-heap merge; `points` are inner-node rows of syn1, `codes` the
    left/right bits along the root→leaf path)."""
    words = cache.vocab_words()
    n = len(words)
    if n == 0:
        return
    # heap of (frequency, tiebreak, node_id); leaves are 0..n-1,
    # inner nodes n..2n-2
    count = [w.frequency for w in words] + [0.0] * (n - 1)
    parent = [0] * (2 * n - 1)
    binary = [0] * (2 * n - 1)
    heap = [(words[i].frequency, i, i) for i in range(n)]
    heapq.heapify(heap)
    next_id = n
    while len(heap) > 1:
        f1, _, a = heapq.heappop(heap)
        f2, _, b = heapq.heappop(heap)
        count[next_id] = f1 + f2
        parent[a] = next_id
        parent[b] = next_id
        binary[b] = 1
        heapq.heappush(heap, (f1 + f2, next_id, next_id))
        next_id += 1
    root = 2 * n - 2
    for i, w in enumerate(words):
        codes: List[int] = []
        points: List[int] = []
        node = i
        while node != root:
            codes.append(binary[node])
            node = parent[node]
            points.append(node - n)  # inner-node row in syn1
        codes.reverse()
        points.reverse()
        w.codes = codes[:MAX_CODE_LENGTH]
        w.points = points[:MAX_CODE_LENGTH]


class VocabConstructor:
    """Builds a VocabCache from token sequences
    (ref: VocabConstructor.java:611 — addSource(iterator, minWordFrequency),
    buildJointVocabulary; parallel counting collapses to one pass here)."""

    def __init__(self, min_word_frequency: int = 1,
                 stop_words: Sequence[str] = (),
                 build_huffman_tree: bool = True,
                 vocab_limit: int = 0):
        self.min_word_frequency = min_word_frequency
        self.stop_words = frozenset(stop_words)
        self.build_huffman_tree = build_huffman_tree
        self.vocab_limit = vocab_limit

    def build(self, sequences: Iterable[Sequence[str]]) -> VocabCache:
        cache = VocabCache()
        for seq in sequences:
            for tok in seq:
                if not tok or tok in self.stop_words:
                    continue
                cache.add_token(VocabWord(tok))
                cache.update_words_occurrences()
        if self.min_word_frequency > 1:
            for w in list(cache._words.values()):
                if w.frequency < self.min_word_frequency and not w.is_label:
                    cache.remove(w.word)
        cache.build_index()
        if self.vocab_limit and cache.num_words() > self.vocab_limit:
            keep = cache.vocab_words()[:self.vocab_limit]
            cache._words = {w.word: w for w in keep}
            cache.build_index()
        if self.build_huffman_tree:
            build_huffman(cache)
        return cache


def make_unigram_table(cache: VocabCache, table_size: int = 1 << 20,
                       power: float = 0.75) -> np.ndarray:
    """Negative-sampling unigram table (ref: InMemoryLookupTable.java
    makeTable: index repeated proportionally to freq^0.75)."""
    n = cache.num_words()
    freqs = np.array([w.frequency for w in cache.vocab_words()], np.float64)
    probs = freqs ** power
    probs /= probs.sum()
    counts = np.maximum(1, np.round(probs * table_size)).astype(np.int64)
    table = np.repeat(np.arange(n), counts)
    return table.astype(np.int32)


def codes_points_arrays(cache: VocabCache):
    """Pad every word's huffman path to a fixed length for device-side HS:
    returns (codes [V,L] float32, points [V,L] int32, mask [V,L] float32)."""
    words = cache.vocab_words()
    maxlen = max((len(w.codes) for w in words), default=1)
    maxlen = max(maxlen, 1)
    V = len(words)
    codes = np.zeros((V, maxlen), np.float32)
    points = np.zeros((V, maxlen), np.int32)
    mask = np.zeros((V, maxlen), np.float32)
    for i, w in enumerate(words):
        L = len(w.codes)
        codes[i, :L] = w.codes
        points[i, :L] = w.points
        mask[i, :L] = 1.0
    return codes, points, mask
