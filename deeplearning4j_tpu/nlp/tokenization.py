"""Tokenizer / TokenizerFactory SPI + preprocessors + stopwords.

Equivalent of deeplearning4j-nlp text/tokenization/ (SURVEY §2.6): a
Tokenizer walks one string, a TokenizerFactory makes tokenizers (so vocab
construction and training can tokenize in parallel), and a TokenPreProcess
normalizes each token. Mirrors DefaultTokenizer/NGramTokenizerFactory/
CommonPreprocessor/EndingPreProcessor from the reference.
"""

from __future__ import annotations

import re
from typing import Callable, Iterable, List, Optional

_PUNCT_RE = re.compile(r"[\d.:,\"'()\[\]|/?!;]+")


class CommonPreprocessor:
    """Lowercase + strip punctuation/digits (ref: CommonPreprocessor.java)."""

    def pre_process(self, token: str) -> str:
        return _PUNCT_RE.sub("", token).lower()

    __call__ = pre_process


class EndingPreProcessor:
    """Crude English stemmer (ref: EndingPreProcessor.java: strips plural
    s/ed/ing/ly endings)."""

    def pre_process(self, token: str) -> str:
        if token.endswith("s") and not token.endswith("ss"):
            token = token[:-1]
        if token.endswith("ed"):
            token = token[:-2]
        if token.endswith("ing"):
            token = token[:-3]
        if token.endswith("ly"):
            token = token[:-2]
        return token

    __call__ = pre_process


class Tokenizer:
    """One pass over one string (ref: Tokenizer.java iface: hasMoreTokens/
    nextToken/getTokens/countTokens)."""

    def __init__(self, tokens: List[str],
                 preprocessor: Optional[Callable[[str], str]] = None):
        if preprocessor is not None:
            tokens = [preprocessor(t) for t in tokens]
            tokens = [t for t in tokens if t]
        self._tokens = tokens
        self._pos = 0

    def has_more_tokens(self) -> bool:
        return self._pos < len(self._tokens)

    def next_token(self) -> str:
        t = self._tokens[self._pos]
        self._pos += 1
        return t

    def count_tokens(self) -> int:
        return len(self._tokens)

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    def __iter__(self):
        return iter(self._tokens)


class DefaultTokenizer(Tokenizer):
    """Whitespace tokenizer (ref: DefaultTokenizer.java wraps Java
    StringTokenizer)."""

    def __init__(self, text: str,
                 preprocessor: Optional[Callable[[str], str]] = None):
        super().__init__(text.split(), preprocessor)


class NGramTokenizer(Tokenizer):
    """Emits n-grams (joined by space) from an underlying tokenizer
    (ref: NGramTokenizer.java, n-grams of min..max length)."""

    def __init__(self, base: Tokenizer, min_n: int, max_n: int):
        words = base.get_tokens()
        out: List[str] = []
        for n in range(min_n, max_n + 1):
            if n == 1:
                out.extend(words)
            else:
                out.extend(" ".join(words[i:i + n])
                           for i in range(len(words) - n + 1))
        super().__init__(out)


class TokenizerFactory:
    """ref: TokenizerFactory.java iface."""

    def __init__(self, preprocessor: Optional[Callable[[str], str]] = None):
        self._pre = preprocessor

    def set_token_pre_processor(self, pre: Callable[[str], str]) -> None:
        self._pre = pre

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    def create(self, text: str) -> Tokenizer:
        return DefaultTokenizer(text, self._pre)


class NGramTokenizerFactory(TokenizerFactory):
    def __init__(self, min_n: int = 1, max_n: int = 2,
                 preprocessor: Optional[Callable[[str], str]] = None):
        super().__init__(preprocessor)
        self.min_n, self.max_n = min_n, max_n

    def create(self, text: str) -> Tokenizer:
        return NGramTokenizer(DefaultTokenizer(text, self._pre),
                              self.min_n, self.max_n)


class StopWords:
    """English stopword list (ref: text/stopwords/StopWords.java loads
    stopwords resource file)."""

    _WORDS = frozenset("""a an and are as at be but by for if in into is it no
    not of on or such that the their then there these they this to was will
    with i me my we our you your he him his she her its who whom which what
    so than too very can just should now were been being have has had do does
    did doing would could from up down out over under again further once here
    all any both each few more most other some own same s t don shouldn
    """.split())

    @classmethod
    def get_stop_words(cls) -> frozenset:
        return cls._WORDS

    @classmethod
    def is_stop_word(cls, w: str) -> bool:
        return w.lower() in cls._WORDS
