"""GloVe: co-occurrence counting + AdaGrad weighted least-squares.

Equivalent of deeplearning4j-nlp models/glove/Glove.java:429 +
AbstractCoOccurrences.java:646 (window-weighted counts) +
learning/impl/elements/GloVe.java:406 (AdaGrad update with
f(X) = (X/xMax)^alpha weighting, xMax=100, alpha=0.75).

Counts are built on host (hash map — the reference shuffles shard files;
corpora here fit in memory); the factorization step is one jitted batch
update: gathers, per-pair dots, scatter-add of AdaGrad-scaled gradients.
"""

from __future__ import annotations

from collections import defaultdict
from functools import partial
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors


@partial(jax.jit, static_argnames=())
def _glove_step(w, b, hist_w, hist_b, rows_i, rows_j, logX, fX, valid, lr):
    """AdaGrad step on J = f(X)·(w_i·w_j + b_i + b_j − log X)² for a batch.
    Both word and context roles share one table (ref GloVe.java trains
    syn0 only, symmetric co-occurrences)."""
    wi, wj = w[rows_i], w[rows_j]                    # [B,D]
    diff = jnp.einsum("bd,bd->b", wi, wj) + b[rows_i] + b[rows_j] - logX
    fdiff = fX * diff * valid                        # [B]
    gi = fdiff[:, None] * wj                         # dJ/dwi
    gj = fdiff[:, None] * wi
    gb = fdiff
    # AdaGrad accumulators
    hist_w = hist_w.at[rows_i].add(gi * gi).at[rows_j].add(gj * gj)
    hist_b = hist_b.at[rows_i].add(gb * gb).at[rows_j].add(gb * gb)
    upd_i = lr * gi / jnp.sqrt(hist_w[rows_i] + 1e-8)
    upd_j = lr * gj / jnp.sqrt(hist_w[rows_j] + 1e-8)
    upd_bi = lr * gb / jnp.sqrt(hist_b[rows_i] + 1e-8)
    upd_bj = lr * gb / jnp.sqrt(hist_b[rows_j] + 1e-8)
    w = w.at[rows_i].add(-upd_i).at[rows_j].add(-upd_j)
    b = b.at[rows_i].add(-upd_bi).at[rows_j].add(-upd_bj)
    loss = 0.5 * jnp.sum(fX * diff * diff * valid)
    return w, b, hist_w, hist_b, loss


class Glove(SequenceVectors):
    """ref: Glove.java Builder — xMax :~, alpha, symmetric window counts."""

    def __init__(self, layer_size: int = 100, window: int = 5,
                 learning_rate: float = 0.05, x_max: float = 100.0,
                 alpha: float = 0.75, epochs: int = 5,
                 batch_size: int = 1024, min_word_frequency: int = 1,
                 symmetric: bool = True, shuffle: bool = True,
                 seed: int = 42, mesh=None, **kwargs):
        super().__init__(layer_size=layer_size, window=window,
                         learning_rate=learning_rate, epochs=epochs,
                         batch_size=batch_size,
                         min_word_frequency=min_word_frequency,
                         seed=seed, **kwargs)
        # GloVe factorizes co-occurrences directly — no HS/NS output tables,
        # so skip the Huffman build + syn1 allocation in _reset_weights
        self.use_hs = False
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric
        self.shuffle = shuffle
        self.bias = None
        self._hist_w = None         # AdaGrad accumulators persist across
        self._hist_b = None         # fit calls (and through save/load)
        self._cooc: Optional[Dict[Tuple[int, int], float]] = None
        self.loss_history: List[float] = []
        # mesh: run the factorization step SPMD across devices (the
        # dl4j-spark-nlp Glove-on-Spark role; see nlp/distributed.py)
        self.mesh = mesh
        self._dist_step = None

    # -- co-occurrences (ref AbstractCoOccurrences.java: 1/distance) -------
    def count_cooccurrences(self, sequences: Iterable[Sequence[str]]) -> None:
        cooc: Dict[Tuple[int, int], float] = defaultdict(float)
        for seq in sequences:
            idxs = [self.vocab.index_of(t) for t in seq]
            idxs = [i for i in idxs if i >= 0]
            n = len(idxs)
            for pos in range(n):
                for off in range(1, self.window + 1):
                    c = pos + off
                    if c >= n:
                        break
                    wgt = 1.0 / off
                    a, b_ = idxs[pos], idxs[c]
                    cooc[(a, b_)] += wgt
                    if self.symmetric:
                        cooc[(b_, a)] += wgt
        self._cooc = dict(cooc)

    def fit(self, sequences: Iterable[Sequence[str]],
            start_epoch: Optional[int] = None,
            stop_epoch: Optional[int] = None,
            resume: bool = False, **_) -> "Glove":
        """start_epoch/stop_epoch slice the epoch schedule for mid-fit
        checkpointing (see SequenceVectors.fit): the shuffle rng, bias and
        AdaGrad accumulators persist on the model (and through save/load),
        so fit(stop_epoch=k); save; load; fit(start_epoch=k) equals one
        uninterrupted fit."""
        seqs = sequences if isinstance(sequences, list) else list(sequences)
        if self.vocab is None:
            self.build_vocab(seqs)
        if self._cooc is None:
            self.count_cooccurrences(seqs)
        V, D = self.vocab.num_words(), self.layer_size
        rnd = np.random.default_rng(self.seed)
        if self.syn0 is None or self.syn0.shape != (V, D):
            self.syn0 = jnp.asarray(
                (rnd.random((V, D), np.float32) - 0.5) / D)
        if self.bias is None or self.bias.shape != (V,):
            self.bias = jnp.zeros((V,), jnp.float32)
        if self._hist_w is None or self._hist_w.shape != (V, D):
            self._hist_w = jnp.full((V, D), 1e-8, jnp.float32)
            self._hist_b = jnp.full((V,), 1e-8, jnp.float32)

        pairs = np.asarray(list(self._cooc.keys()), np.int32)
        counts = np.asarray(list(self._cooc.values()), np.float32)
        logX = np.log(counts)
        fX = np.minimum(1.0, (counts / self.x_max) ** self.alpha) \
            .astype(np.float32)
        n = len(pairs)
        B = self.batch_size
        step_fn = _glove_step
        if self.mesh is not None:
            from deeplearning4j_tpu.nlp.distributed import (
                make_distributed_glove_step,
            )
            ndev = int(np.prod(self.mesh.devices.shape))
            B = -(-B // ndev) * ndev  # mesh-divisible (pad rows masked)
            if self._dist_step is None:
                self._dist_step = make_distributed_glove_step(self.mesh)
            step_fn = self._dist_step
        order = np.arange(n)
        if start_epoch is None:
            e0 = self.epochs_trained if resume else 0
        else:
            e0 = int(start_epoch)
        e1 = self.epochs if stop_epoch is None else int(stop_epoch)
        for _ in range(e0, e1):
            if self.shuffle:
                # fresh permutation from the model's own rng each epoch
                # (saved/restored by the serializer): epoch k's order is a
                # function of rng state alone, so a mid-fit save resumes
                # with the identical visit order
                order = self._rng.permutation(n)
            total = 0.0
            for s in range(0, n, B):
                sel = order[s:s + B]
                valid = np.ones(B, np.float32)
                if len(sel) < B:
                    valid[len(sel):] = 0.0
                    sel = np.pad(sel, (0, B - len(sel)))
                # accumulators live on self so an interrupt mid-fit never
                # leaves weights and AdaGrad state out of step
                self.syn0, self.bias, self._hist_w, self._hist_b, loss = \
                    step_fn(self.syn0, self.bias, self._hist_w,
                            self._hist_b, jnp.asarray(pairs[sel, 0]),
                            jnp.asarray(pairs[sel, 1]),
                            jnp.asarray(logX[sel]), jnp.asarray(fX[sel]),
                            jnp.asarray(valid),
                            jnp.float32(self.learning_rate))
                # device-side accumulation: no per-batch host sync
                total = total + loss
            # one sync per EPOCH (bounded, feeds loss_history's floats)
            # tpulint: disable=host-sync-in-hot-loop
            self.loss_history.append(float(total) / max(1, n))
        self.epochs_trained = e1
        return self
