"""ParagraphVectors (doc2vec): DM and DBOW.

Equivalent of deeplearning4j-nlp models/paragraphvectors/
ParagraphVectors.java:1449 + learning/impl/sequence/{DM,DBOW}.java.
Doc labels live in the same lookup table as words (is_label rows);
DBOW trains label→word skip-gram pairs, DM folds the label vector into the
CBOW context average. inferVector trains ONLY a fresh row with the output
tables frozen (ref: ParagraphVectors.inferVector).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sentence import LabelAwareIterator, LabelledDocument
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory,
)


class ParagraphVectors(SequenceVectors):
    """sequence_learning_algorithm: "dbow" (default, ref DBOW.java) or
    "dm" (ref DM.java)."""

    def __init__(self, label_aware_iterator: Optional[LabelAwareIterator] = None,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 sequence_learning_algorithm: str = "dbow",
                 train_words: bool = False, **kwargs):
        algo = sequence_learning_algorithm.lower()
        if algo not in ("dbow", "dm"):
            raise ValueError(f"unknown sequence learning algorithm {algo!r}")
        kwargs.setdefault("elements_learning_algorithm",
                          "skipgram" if algo == "dbow" else "cbow")
        super().__init__(**kwargs)
        self.seq_algo = algo
        self.train_words = train_words
        self.label_aware_iterator = label_aware_iterator
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self._docs: List[LabelledDocument] = []

    # -- training ----------------------------------------------------------
    def fit(self, documents: Optional[Iterable[LabelledDocument]] = None,
            start_epoch: Optional[int] = None,
            stop_epoch: Optional[int] = None,
            resume: bool = False, **_) -> "ParagraphVectors":
        docs = list(documents) if documents is not None else \
            list(self.label_aware_iterator or [])
        if not docs:
            raise RuntimeError("no documents to fit")
        self._docs = docs
        seqs = [self.tokenizer_factory.create(d.content).get_tokens()
                for d in docs]
        labels = [d.labels for d in docs]
        all_labels = [l for ls in labels for l in ls]
        if self.vocab is None:
            self.build_vocab(seqs, extra_labels=all_labels)
        if self.seq_algo == "dbow":
            SequenceVectors.fit(self, seqs, labels_per_sequence=labels,
                                train_words=self.train_words,
                                train_labels=True,
                                start_epoch=start_epoch,
                                stop_epoch=stop_epoch, resume=resume)
        else:  # DM: label joins CBOW context; words co-train by nature
            SequenceVectors.fit(self, seqs, labels_per_sequence=labels,
                                start_epoch=start_epoch,
                                stop_epoch=stop_epoch, resume=resume)
        return self

    # -- queries -----------------------------------------------------------
    def get_label_vector(self, label: str) -> Optional[np.ndarray]:
        return self.get_word_vector(label)

    def nearest_labels(self, text_or_vec, top_n: int = 5) -> List[str]:
        if isinstance(text_or_vec, str):
            v = self.infer_vector(text_or_vec)
        else:
            v = np.asarray(text_or_vec, np.float32)
        labels = [w for w in self.vocab.vocab_words() if w.is_label]
        if not labels:
            return []
        syn0 = np.asarray(self.syn0)
        sims = []
        for vw in labels:
            u = syn0[vw.index]
            s = float(u @ v / ((np.linalg.norm(u) * np.linalg.norm(v)) + 1e-12))
            sims.append((s, vw.word))
        sims.sort(reverse=True)
        return [w for _, w in sims[:top_n]]

    def infer_vector(self, text: str, learning_rate: float = 0.01,
                     min_learning_rate: float = 0.001,
                     iterations: int = 5) -> np.ndarray:
        """Train a fresh doc row with word/output tables frozen
        (ref: ParagraphVectors.inferVector :~1050)."""
        toks = self.tokenizer_factory.create(text).get_tokens()
        # infer draws (subsampling, window shrink, negatives) from a
        # per-call seeded stream, NOT the training rng: inference is
        # deterministic and leaves the trainer's resumable stream untouched
        saved_rng = self._rng
        self._rng = np.random.default_rng(self.seed)
        idxs = self._to_indices(toks)
        if idxs.size == 0:
            self._rng = saved_rng
            return np.zeros(self.layer_size, np.float32)
        # append scratch row for the inferred doc (init drawn from the same
        # per-call stream — one rng, no correlated twin generator)
        row = self.syn0.shape[0]
        saved0, saved1, saved1n = self.syn0, self.syn1, self.syn1neg
        self.syn0 = jnp.concatenate(
            [self.syn0, jnp.asarray((self._rng.random((1, self.layer_size),
                                                      np.float32) - 0.5)
                                    / self.layer_size)], 0)
        if self.use_hs:
            pass  # syn1 indexed by inner nodes only — unchanged
        try:
            n_steps = max(1, iterations)
            for it in range(n_steps):
                alpha = max(min_learning_rate,
                            learning_rate * (1 - it / n_steps))
                before1, before1n = self.syn1, self.syn1neg
                self._train_label_pairs(idxs, alpha, [row])
                # freeze output tables: restore them after the step
                self.syn1, self.syn1neg = before1, before1n
            return np.asarray(self.syn0[row])
        finally:
            self.syn0, self.syn1, self.syn1neg = saved0, saved1, saved1n
            self._rng = saved_rng
