"""Word2Vec facade over SequenceVectors.

Equivalent of deeplearning4j-nlp models/word2vec/Word2Vec.java:621 — a
builder that wires a SentenceIterator + TokenizerFactory into the generic
SequenceVectors engine (SkipGram/CBOW, HS or negative sampling).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

from deeplearning4j_tpu.nlp.sentence import SentenceIterator
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory,
)


class Word2Vec(SequenceVectors):
    """ref: Word2Vec.java Builder — iterate(SentenceIterator),
    tokenizerFactory, then fit(). Defaults follow SequenceVectors.java
    :375-386 (lr .025, layerSize 100, window 5)."""

    def __init__(self, sentence_iterator: Optional[SentenceIterator] = None,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 min_word_frequency: int = 5, **kwargs):
        super().__init__(min_word_frequency=min_word_frequency, **kwargs)
        self.sentence_iterator = sentence_iterator
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()

    def _tokenize(self, sentences: Iterable[str]) -> List[List[str]]:
        tf = self.tokenizer_factory
        if type(tf) is DefaultTokenizerFactory and tf._pre is None:
            # fast path: DefaultTokenizer with no preprocessor IS
            # str.split — skip the per-sentence Tokenizer object + token
            # list copy (measured ~35% of host time at device speeds)
            return [s.split() for s in sentences]
        return [tf.create(s).get_tokens() for s in sentences]

    def _tokenized(self) -> List[List[str]]:
        if self.sentence_iterator is None:
            raise RuntimeError("no sentence iterator configured")
        return self._tokenize(self.sentence_iterator)

    def _coerce(self, sequences) -> List[List[str]]:
        """Accept token lists, sentence strings, or a SentenceIterator —
        strings are tokenized (iterating one directly would silently
        train a character vocab)."""
        seqs = list(sequences) if sequences is not None else self._tokenized()
        if seqs and isinstance(seqs[0], str):
            seqs = self._tokenize(seqs)
        return seqs

    def build_vocab(self, sequences=None, extra_labels=()) -> None:
        super().build_vocab(self._coerce(sequences), extra_labels)

    def fit(self, sequences: Optional[Iterable[Sequence[str]]] = None,
            **kwargs) -> "Word2Vec":
        seqs = self._coerce(sequences)
        if self.vocab is None:
            self.build_vocab(seqs)
        super().fit(seqs, **kwargs)
        return self

    # DL4J naming convenience
    def vec(self, word: str):
        return self.get_word_vector(word)
